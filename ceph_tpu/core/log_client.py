"""Daemon-side cluster log (reference ``src/common/LogClient.cc``).

Every daemon keeps a small local ring of clog entries and batches the
unsent tail to the monitor as an ``MLog`` message — the mon's
``LogMonitor`` commits them through paxos and serves
``ceph log last [n]``.  Transport failures are tolerated: entries
stay queued and ride the next flush (the reference resends
unacknowledged log entries the same way).
"""

from __future__ import annotations

import collections
import threading
import time

PRIO = ("debug", "info", "warn", "error")


class LogClient:
    """Ring + batched ``MLog`` uplink.

    ``send_fn`` takes one message (typically ``MonClient.send``); it
    may raise on a down mon — the batch is requeued.
    """

    def __init__(self, name: str, send_fn=None, *,
                 channel: str = "cluster", ring_size: int = 100):
        self.name = name
        self.channel = channel
        self.send_fn = send_fn
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(ring_size)))
        self._pending: list[dict] = []
        self._lock = threading.Lock()

    # -- producers ------------------------------------------------------

    def do_log(self, prio: str, text: str,
               channel: str | None = None) -> dict:
        entry = {"stamp": time.time(), "name": self.name,
                 "channel": channel or self.channel,
                 "prio": prio if prio in PRIO else "info",
                 "text": str(text)}
        with self._lock:
            self._ring.append(entry)
            self._pending.append(entry)
        return entry

    def debug(self, text: str) -> dict:
        return self.do_log("debug", text)

    def info(self, text: str) -> dict:
        return self.do_log("info", text)

    def warn(self, text: str) -> dict:
        return self.do_log("warn", text)

    def error(self, text: str) -> dict:
        return self.do_log("error", text)

    def audit(self, text: str, prio: str = "info") -> dict:
        """Entry on the ``audit`` channel (reference LogChannel
        ``audit`` — administrative actions, kept in the mon's
        separate audit ring)."""
        return self.do_log(prio, text, channel="audit")

    # -- uplink ---------------------------------------------------------

    def flush(self) -> int:
        """Send the pending batch; returns entries shipped (0 if the
        mon is unreachable — they stay pending)."""
        with self._lock:
            if not self._pending or self.send_fn is None:
                return 0
            batch, self._pending = self._pending, []
        from ..mon import messages as M      # lazy: core below mon
        try:
            self.send_fn(M.MLog(entries=batch))
        except (ConnectionError, OSError):
            with self._lock:
                self._pending = batch + self._pending
            return 0
        return len(batch)

    # -- inspection -----------------------------------------------------

    def last(self, n: int = 20) -> list[dict]:
        with self._lock:
            return list(self._ring)[-max(0, int(n)):]

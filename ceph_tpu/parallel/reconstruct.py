"""SPMD erasure-code pipeline: chunk-sharded encode + degraded-read
reconstruct under ``shard_map``.

Reference behavior being re-created TPU-natively (SURVEY.md §4.2-4.3):

- EC write: ``ECBackend::submit_transaction`` fans sub-writes of k+m chunks
  to k+m OSDs.  Here a stripe's chunk axis is sharded over the mesh's
  ``shard`` axis; computing parity requires combining contributions from
  data chunks on different devices — an XOR-reduction that rides ICI
  (implemented as an all-gather of local GF partial products + local XOR,
  exactly the collective the scaling-book recipe would pick for a small
  contraction axis).
- EC degraded read: ``objects_read_and_reconstruct`` gathers any k
  surviving shards from peer OSDs.  Here: ``jax.lax.all_gather`` of the
  surviving shard rows over ICI, then each device decodes its local stripe
  batch with the cached inverse submatrix.

Chunk ids are padded up to a multiple of the shard-axis size so every
device owns the same number of chunk rows (static shapes for XLA).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import rs
from ..ops.gf import gf_matmul
from ..utils.jaxcompat import enable_x64, shard_map
from ..ops.gf_jax import _bit_layout_matrix, gf_matmul_bits
from ..ops.gf_pallas2 import (_BIT_MASK, _gf_apply_words, block_diag4,
                              _word_operands)


class DecodePlan:
    """Survivor selection + recovery matrices for one erasure pattern.

    One plan answers every question a batched reconstruct needs about
    a (coding matrix, erasure set) pair:

    - ``survivors``: the first k surviving chunk ids in id order —
      the exact selection ``ErasureCode::_minimum_to_decode`` makes,
      so plan-driven decodes are byte-identical to the per-stripe
      path;
    - ``dm`` [k, k]: the decode matrix over those survivors;
    - ``parity_matrix`` [p, k] or None: for the p *erased parity*
      rows, the GF(2^8) composition ``coding[j] ∘ dm`` — parity
      straight from survivors, no decode-then-encode round trip
      (associativity makes the composition byte-exact);
    - ``matrix`` [k + p, k]: dm and parity_matrix stacked, so one
      fused matmul yields every recoverable row;
    - ``row_of``: chunk id → row in that fused output.
    """

    __slots__ = ("k", "m", "erasures", "survivors", "dm",
                 "parity_matrix", "matrix", "out_ids", "row_of")

    def __init__(self, coding: np.ndarray, k: int, m: int,
                 erasures: tuple[int, ...]):
        coding = np.asarray(coding, dtype=np.uint8)
        self.k, self.m = k, m
        self.erasures = tuple(sorted(erasures))
        self.survivors = tuple(
            i for i in range(k + m) if i not in self.erasures)[:k]
        self.dm = rs.decode_matrix(coding, k, list(self.erasures))
        miss_par = [j for j in range(m) if k + j in self.erasures]
        if miss_par:
            self.parity_matrix = gf_matmul(coding[miss_par], self.dm)
            self.matrix = np.vstack([self.dm, self.parity_matrix])
        else:
            self.parity_matrix = None
            self.matrix = self.dm
        self.out_ids = tuple(range(k)) + tuple(k + j for j in miss_par)
        self.row_of = {cid: r for r, cid in enumerate(self.out_ids)}


_PLAN_CACHE: dict = {}


def decode_plan(coding: np.ndarray, k: int, m: int,
                erasures, cache: dict | None = None) -> DecodePlan:
    """Cached :class:`DecodePlan` lookup.  Real clusters see a handful
    of erasure patterns at a time (reference: ECBackend caches decode
    tables per want/avail set), so plans persist for a whole recovery
    sweep — pass ``cache`` to scope the cache to an owner (the batch
    engine's reconstruct lane), default is process-wide."""
    key = (coding.tobytes(), k, m, tuple(sorted(erasures)))
    store = _PLAN_CACHE if cache is None else cache
    plan = store.get(key)
    if plan is None:
        plan = store[key] = DecodePlan(coding, k, m, key[3])
    return plan


class ShardedEC:
    """Erasure code over a (dp, shard) mesh.

    Layout: stripes [B, nchunks_padded, C] with spec P('dp', 'shard', None):
    stripe batches over dp, chunk ids over shard.

    ``word_native`` (auto: on for the TPU backend) switches the chunk
    payload dtype from uint8 [.., C] to int32 words [.., C/4] and the
    local GF multiply from the XLA bitmatrix path to the fused Pallas
    word kernel — the 10x-over-native encode path
    (`gf_pallas2.gf_matmul_words`); uint8 payloads on TPU pay a 4x
    sublane-padding tax per HBM read.  Host conversion is a free
    ``bytes.view("<i4")``.  The collectives are dtype-agnostic.
    """

    def __init__(self, coding: np.ndarray, k: int, m: int, mesh: Mesh,
                 word_native: bool | None = None):
        self.coding = np.asarray(coding, dtype=np.uint8)
        self.k, self.m = k, m
        self.mesh = mesh
        self.word_native = (jax.default_backend() == "tpu"
                            if word_native is None else word_native)
        self.payload_dtype = (np.int32 if self.word_native
                              else np.uint8)
        self.shard_n = mesh.shape["shard"]
        self.k_pad = -(-k // self.shard_n) * self.shard_n
        self.n_pad = -(-(k + m) // self.shard_n) * self.shard_n
        # coding matrix padded on the data axis [m, k_pad]
        cpad = np.zeros((m, self.k_pad), dtype=np.uint8)
        cpad[:, :k] = self.coding
        self._coding_pad = cpad
        self._decode_cache: dict[tuple[int, ...], object] = {}
        from .mesh import mesh_device_labels
        self._dev_labels = mesh_device_labels(mesh)

        self._encode = jax.jit(self._build_encode())

    # -- encode: data chunks sharded, XOR-combine partials over ICI --------
    def _build_encode(self):
        mesh = self.mesh
        shard_n = self.shard_n
        klocal = self.k_pad // shard_n
        m = self.m
        # bit-layout matrix of the padded coding [8m, 8*k_pad]: the
        # local multiply runs on the MXU bitmatrix path (the same math
        # GFLinear's production backend uses), not the table-gather.
        # Columns interleave as (bit s, chunk i) = s*k_pad + i, so a
        # device's chunk-column slice is strided — reshape to
        # [8m, 8, k_pad] and slice the chunk axis.
        bm_full = _bit_layout_matrix(self._coding_pad)
        bm3 = jnp.asarray(
            bm_full.reshape(8 * m, 8, self.k_pad))
        if self.word_native:
            # block-diag word matrix [32m, 32*k_pad]; columns factor as
            # ((b*8+s), chunk i) so the per-device chunk-column slice
            # is a dynamic_slice on the reshaped last axis
            bd4 = jnp.asarray(block_diag4(bm_full).reshape(
                32 * m, 32, self.k_pad))
            mrow_l = jnp.asarray(np.array(
                [_BIT_MASK[r // klocal] for r in range(32 * klocal)],
                dtype=np.int32).reshape(32 * klocal, 1))

        # Mosaic lowering of the fused word kernel requires a real TPU;
        # off-TPU (CPU equivalence tests, dev boxes) run it in Pallas
        # interpret mode instead of failing at lowering.
        interpret = jax.default_backend() != "tpu"

        def local_fn(data):  # data: [Bl, klocal, C] (or Cw words)
            idx = jax.lax.axis_index("shard")
            if self.word_native:
                cols = jax.lax.dynamic_slice_in_dim(
                    bd4, idx * klocal, klocal, axis=2).reshape(
                        32 * m, 32 * klocal)
                partial = _gf_apply_words(cols, mrow_l, data,
                                          k=klocal, m=m,
                                          interpret=interpret)
            else:
                cols3 = jax.lax.dynamic_slice_in_dim(
                    bm3, idx * klocal, klocal, axis=2)
                cols = cols3.reshape(8 * m, 8 * klocal)
                partial = gf_matmul_bits(cols, data, m)  # [Bl, m, C]
            # XOR-combine partials across the shard axis via all-gather
            # (ICI); every device ends with the full parity of its stripes.
            gathered = jax.lax.all_gather(partial, "shard", axis=0)
            parity = jax.lax.reduce(gathered,
                                    np.zeros((), gathered.dtype)[()],
                                    jax.lax.bitwise_xor, dimensions=(0,))
            return parity  # [Bl, m, C] replicated over shard

        def fn(data):  # [B, k_pad, C] sharded P('dp','shard',None)
            # out is replicated over 'shard' by construction (all_gather +
            # full XOR-reduce); the static VMA check can't see that.
            # Traced under x64=False: every dtype here is explicit, and
            # an embedding process with x64 on (the CRUSH mapper needs
            # it) otherwise widens internals — which also trips the
            # axon remote-compile helper on the word-native program.
            with enable_x64(False):
                return shard_map(
                    local_fn, mesh=mesh,
                    in_specs=P("dp", "shard", None),
                    out_specs=P("dp", None, None), check_vma=False)(data)

        return fn

    def pad_data(self, data: np.ndarray) -> np.ndarray:
        """[B, k, C] -> [B, k_pad, C] zero-padded (payload dtype kept:
        uint8 bytes or int32 words)."""
        B, k, C = data.shape
        assert k == self.k
        out = np.zeros((B, self.k_pad, C), dtype=data.dtype)
        out[:, :k] = data
        return out

    def to_payload(self, data: np.ndarray) -> np.ndarray:
        """Host bytes -> this instance's payload dtype (free view)."""
        if self.word_native:
            return np.ascontiguousarray(data).view("<i4")
        return data

    def payload_to_bytes(self, arr: np.ndarray) -> np.ndarray:
        if self.word_native:
            return np.ascontiguousarray(arr).view("<u1")
        return np.asarray(arr)

    def shard_array(self, arr: np.ndarray, spec: P) -> jax.Array:
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def encode(self, data_padded) -> jax.Array:
        """[B, k_pad, C] (sharded or host) -> parity [B, m, C]."""
        from ..core.device_profiler import DeviceProfiler
        nbytes = getattr(data_padded, "nbytes", 0)
        B = int(data_padded.shape[0])
        ln = DeviceProfiler.active().start(
            "sharded_encode", bytes_in=nbytes,
            rows=B * self.k_pad, rows_used=B * self.k,
            devices=self._dev_labels)
        try:
            out = self._encode(data_padded)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.finish(out=out, bytes_out=getattr(out, "nbytes", 0))
        return out

    # -- degraded read: all-gather survivors, decode locally ---------------
    def _decode_fn(self, erasures: tuple[int, ...]):
        # per-instance cache (an lru_cache on the method would pin self and
        # share one global budget across instances)
        cached = self._decode_cache.get(erasures)
        if cached is not None:
            return cached
        fn = self._build_decode_fn(erasures)
        self._decode_cache[erasures] = fn
        return fn

    def _build_decode_fn(self, erasures: tuple[int, ...]):
        mesh = self.mesh
        k, m = self.k, self.m
        # The plan's stacked [k + p, k] matrix covers parity-hole
        # patterns too: rows 0..k-1 are the decode matrix (data
        # chunks), rows k.. are the composed ``coding[j] ∘ dm`` for
        # each erased parity row — GF associativity makes parity
        # straight from survivors byte-exact, so the all-gather reduce
        # path emits every recoverable row in one launch instead of
        # bailing to single-chip whenever a parity row is erased.
        plan = decode_plan(self.coding, k, m, erasures)
        pbits_np = _bit_layout_matrix(plan.matrix)
        pbits = jnp.asarray(pbits_np)
        nrows = plan.matrix.shape[0]
        surv_idx = jnp.asarray(np.array(plan.survivors, dtype=np.int32))
        if self.word_native:
            wcache: dict = {}
            wbd, wmrow = _word_operands(pbits_np, k, wcache)
        interpret = jax.default_backend() != "tpu"  # see _build_encode

        def local_fn(chunks):  # [Bl, nlocal, C] — this device's chunk rows
            # gather every device's chunk rows over ICI (the sub-read fan-in)
            full = jax.lax.all_gather(chunks, "shard", axis=0)
            # full: [shard_n, Bl, nlocal, C]; chunk id = shard*nlocal + local
            full = jnp.moveaxis(full, 2, 1).reshape(
                -1, chunks.shape[0], chunks.shape[2])  # [n_pad, Bl, C]
            surv = full[surv_idx]                      # [k, Bl, C]
            surv = jnp.moveaxis(surv, 1, 0)            # [Bl, k, C]
            if self.word_native:
                # fused Pallas word kernel (the production decode path)
                data = _gf_apply_words(wbd, wmrow, surv,
                                       k=k, m=nrows,
                                       interpret=interpret)
            else:
                # MXU bitmatrix decode (byte-exact vs the oracle)
                data = gf_matmul_bits(pbits, surv, nrows)
            return data

        def fn(chunks):  # [B, n_pad, C] sharded P('dp','shard',None)
            # replicated over 'shard' by construction (decode after
            # gather); x64=False at trace time — see _build_encode
            with enable_x64(False):
                return shard_map(
                    local_fn, mesh=mesh,
                    in_specs=P("dp", "shard", None),
                    out_specs=P("dp", None, None), check_vma=False)(chunks)

        return jax.jit(fn)

    def reconstruct(self, chunks_padded, erasures: tuple[int, ...],
                    emit: str = "data") -> jax.Array:
        """[B, n_pad, C] chunk-sharded -> recovered rows.

        ``erasures`` lists erased chunk ids; their rows in the input are
        ignored (may be garbage/zeros).  ``emit`` selects the output
        rows: ``"data"`` (default) returns the k data chunks
        [B, k, C]; ``"plan"`` returns every recoverable row in the
        decode plan's ``out_ids`` order [B, k + p, C] — data chunks
        followed by the erased parity chunks, so parity-hole erasure
        patterns ride the mesh launch too (``DecodePlan.row_of`` maps
        chunk id → row).
        """
        from ..core.device_profiler import DeviceProfiler
        if emit not in ("data", "plan"):
            raise ValueError(f"emit must be 'data' or 'plan': {emit!r}")
        key = tuple(sorted(erasures))
        B = int(chunks_padded.shape[0])
        ln = DeviceProfiler.active().start(
            "sharded_reconstruct",
            bytes_in=getattr(chunks_padded, "nbytes", 0),
            rows=B * self.n_pad, rows_used=B * (self.k + self.m),
            cache_hit=key in self._decode_cache,
            devices=self._dev_labels)
        try:
            out = self._decode_fn(key)(chunks_padded)
            if emit == "data":
                out = out[:, :self.k]
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.finish(out=out, bytes_out=getattr(out, "nbytes", 0))
        return out

    def reconstruct_batch(self, groups: dict) -> dict:
        """Batched multi-pattern entry: ``{erasures: chunks_padded
        [B, n_pad, C]}`` → ``{erasures: data [B, k, C]}``.

        One shard_map launch per distinct erasure pattern; decode
        programs come from the per-instance ``_decode_cache``, so a
        recovery sweep that mixes patterns (different failed shards
        across PGs) compiles each pattern once and then replays
        executables.  Results stay on device (callers fence)."""
        return {tuple(sorted(er)): self.reconstruct(cp, er)
                for er, cp in groups.items()}

    def assemble_chunks(self, data_padded, parity) -> jnp.ndarray:
        """Lay out the [B, n_pad, C] chunk array `_decode_fn` expects:
        data rows 0..k-1, parity rows k..k+m-1, zero padding to n_pad.
        The single definition of that implicit layout contract — the
        bench and the multichip dryrun build their inputs through it
        too."""
        B = data_padded.shape[0]
        C = data_padded.shape[2]
        parity = jnp.asarray(parity)
        return jnp.concatenate(
            [data_padded[:, :self.k], parity,
             jnp.zeros((B, self.n_pad - self.k - self.m, C),
                       parity.dtype)], axis=1)

    # -- the full pipeline step (flagship "train step") --------------------
    def pipeline_step(self, data_padded, erasures: tuple[int, ...]):
        """Encode, then reconstruct with ``erasures`` erased, returning
        (parity, recovered_data).  The compiled graph contains both the
        XOR-combine and the all-gather collectives — this is the program
        `__graft_entry__.dryrun_multichip` compiles over an N-device mesh.
        """
        parity = self._encode(data_padded)
        recovered = self._decode_fn(tuple(sorted(erasures)))(
            self.assemble_chunks(data_padded, parity))[:, :self.k]
        return parity, recovered

"""Mesh helpers: lay out available devices as a (dp, shard) grid.

``dp`` partitions independent stripes (pure data parallelism — the analog
of PGs being independent); ``shard`` partitions the chunk axis of a stripe
(the analog of EC shards living on k+m different OSDs), so collectives on
``shard`` ride ICI exactly where the reference sends MOSDECSubOp* messages.
"""

from __future__ import annotations

import threading

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None, shard: int | None = None) -> Mesh:
    """Build a (dp, shard) mesh over the first ``n_devices`` devices.

    ``shard`` defaults to the largest power-of-two divisor of n_devices
    capped at 8 (a typical k+m fits in 8-16 shards); dp gets the rest.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if shard is None:
        shard = 1
        while shard * 2 <= min(n_devices, 8) and n_devices % (shard * 2) == 0:
            shard *= 2
    if n_devices % shard != 0:
        raise ValueError(f"{n_devices} devices not divisible by shard={shard}")
    dp = n_devices // shard
    arr = np.array(devices).reshape(dp, shard)
    return Mesh(arr, ("dp", "shard"))


_cluster_mesh: Mesh | None = None
_cluster_lock = threading.Lock()


def cluster_mesh() -> Mesh:
    """The process-wide cluster mesh over ALL visible devices.

    Every batch-engine lane (write encode+CRC megabatches, recovery
    reconstructs, comp fingerprint scans) shards over this one mesh, so
    one OSD host drives all chips instead of one.  Built lazily on
    first use and shared for the process lifetime — devices don't hot
    plug, and a single mesh keeps every lane's sharded executable
    cache coherent.
    """
    global _cluster_mesh
    m = _cluster_mesh
    if m is None:
        with _cluster_lock:
            if _cluster_mesh is None:
                _cluster_mesh = make_mesh()
            m = _cluster_mesh
    return m


def mesh_device_labels(mesh: Mesh) -> tuple[str, ...]:
    """Stable per-device labels for profiler attribution."""
    return tuple(str(d) for d in mesh.devices.flat)

"""Device-mesh parallelism: the TPU-native replacement for the reference's
OSD<->OSD sub-read/sub-write fan-out (``src/osd/ECBackend.cc``; SURVEY.md
§3.2, §4.3).

- `mesh`        — mesh construction helpers (dp x shard axes).
- `reconstruct` — SPMD erasure-code pipeline under `shard_map`: chunk-sharded
  encode (XOR-reduce across the shard axis) and degraded-read reconstruct
  (ICI all-gather of surviving shards + local decode).
"""

from .mesh import make_mesh  # noqa: F401
from .reconstruct import ShardedEC  # noqa: F401

"""Small mgr modules: status, iostat, crash, telemetry.

Reference behavior re-created (``src/pybind/mgr/<module>/module.py``
each; SURVEY.md §3.10 "mgr modules"):

- **status**: ``ceph -s``-shaped cluster summary assembled mgr-side
  from the mon's status + pg stats (the reference renders fs/osd
  status tables from the same aggregates);
- **iostat**: cluster-wide IOPS read off consecutive ``pg dump``
  osd_stat op-counter deltas (the reference differentiates PGMap
  counters the same way);
- **crash**: crash-report archive — daemons (or operators) post
  crash dumps, ``crash ls``/``info``/``rm`` browse them; stored in
  RADOS-backed mon config-key storage analog (here: module-local
  store persisted via mon config-key commands);
- **telemetry**: an anonymized cluster report (counts and versions,
  never names/keys) assembled on demand, ``telemetry show`` style.
"""

from __future__ import annotations

import hashlib
import json
import time

from ..core.flight_recorder import CRASH_KEY_PREFIX, crash_id_for
from .daemon import MgrModule


class StatusModule(MgrModule):
    """`ceph -s` aggregation (reference ``pybind/mgr/status``)."""

    NAME = "status"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self.last: dict = {}

    def serve_tick(self):
        rc, _, st = self.ctx.mon_command({"prefix": "status"})
        if rc == 0 and st:
            self.last = st

    def render(self) -> str:
        """The human `ceph -s` panel, from the last aggregate."""
        st = self.last
        if not st:
            return "status: no data yet"
        lines = [
            f"  health: {st.get('health')}",
            "",
            "  services:",
            f"    mon: quorum {st.get('quorum')} "
            f"(leader {st.get('leader')})",
            f"    osd: {st.get('num_up_osds')}/{st.get('num_osds')} up",
            "",
            "  data:",
            f"    pools:   {len(st.get('pools', []))}",
            f"    objects: {st.get('num_objects')}",
            f"    pgs:     {st.get('num_pgs')} " + " ".join(
                f"{n} {s};" for s, n in
                sorted(st.get("pg_states", {}).items())),
        ]
        for chk in st.get("checks", []):
            lines.insert(1, f"    {chk['code']}: {chk['summary']}")
        return "\n".join(lines)


class IostatModule(MgrModule):
    """Cluster IOPS from osd_stat op-counter deltas (reference
    ``pybind/mgr/iostat``)."""

    NAME = "iostat"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self._prev: tuple[float, dict] | None = None
        self.rates = {"op_per_sec": 0.0, "write_op_per_sec": 0.0,
                      "read_op_per_sec": 0.0}

    def _totals(self) -> dict | None:
        # osd_stats counters only: `pg summary` carries them without
        # a per-PG dump (fall back for mons that don't serve it)
        rc, _, dump = self.ctx.mon_command({"prefix": "pg summary"})
        if rc != 0 or not dump or "osd_stats" not in dump:
            rc, _, dump = self.ctx.mon_command({"prefix": "pg dump"})
        if rc != 0 or not dump:
            return None
        tot = {"op": 0.0, "op_w": 0.0, "op_r": 0.0}
        for st in (dump.get("osd_stats") or {}).values():
            for k in tot:
                tot[k] += float(st.get(k, 0))
        return tot

    def serve_tick(self):
        now = time.monotonic()
        tot = self._totals()
        if tot is None:
            return
        if self._prev is not None:
            t0, prev = self._prev
            dt = max(now - t0, 1e-6)
            # counters are cumulative; an OSD restart can step one
            # backwards — clamp at 0 rather than reporting negatives
            self.rates = {
                "op_per_sec": max(0.0, (tot["op"] - prev["op"]) / dt),
                "write_op_per_sec":
                    max(0.0, (tot["op_w"] - prev["op_w"]) / dt),
                "read_op_per_sec":
                    max(0.0, (tot["op_r"] - prev["op_r"]) / dt),
            }
        self._prev = (now, tot)


class CrashModule(MgrModule):
    """Crash-report archive (reference ``pybind/mgr/crash``): posts
    are keyed by crash id (timestamp + entity hash), persisted through
    the mon's config-key store so they survive mgr failover.  Daemons
    post directly (an OSD revive writes the config-key itself — the
    ceph-crash agent path), so the store, not this module, is the
    source of truth; archiving stamps ``archived`` into the stored
    JSON, which the mon-side RECENT_CRASH evaluator honors."""

    NAME = "crash"
    TICK = 30.0
    _PREFIX = CRASH_KEY_PREFIX

    def post(self, report: dict) -> str:
        """`ceph crash post` — report must carry entity + backtrace."""
        if "entity" not in report:
            raise ValueError("crash report requires 'entity'")
        stamp = report.setdefault("timestamp", time.time())
        crash_id = crash_id_for(report["entity"], stamp)
        report["crash_id"] = crash_id
        self.ctx.mon_command({
            "prefix": "config-key put",
            "key": self._PREFIX + crash_id,
            "val": json.dumps(report)})
        return crash_id

    def _keys(self) -> list[str]:
        rc, _, keys = self.ctx.mon_command({
            "prefix": "config-key ls"})
        if rc != 0 or not keys:
            return []
        return sorted(k for k in keys if k.startswith(self._PREFIX))

    def ls(self, new_only: bool = False) -> list[dict]:
        out = []
        for k in self._keys():
            rc, _, val = self.ctx.mon_command({
                "prefix": "config-key get", "key": k})
            if rc != 0 or not val:
                continue
            rep = json.loads(val)
            if new_only and rep.get("archived"):
                continue
            out.append({
                # daemon-posted reports carry no crash_id field; the
                # key suffix IS the id either way
                "crash_id": rep.get("crash_id",
                                    k[len(self._PREFIX):]),
                "entity": rep.get("entity", "?"),
                "timestamp": rep.get("timestamp"),
                "crash_point": rep.get("crash_point"),
                "archived": rep.get("archived")})
        return out

    def info(self, crash_id: str) -> dict | None:
        rc, _, val = self.ctx.mon_command({
            "prefix": "config-key get",
            "key": self._PREFIX + crash_id})
        return json.loads(val) if rc == 0 and val else None

    def rm(self, crash_id: str):
        self.ctx.mon_command({
            "prefix": "config-key del", "key": self._PREFIX + crash_id})

    def archive(self, crash_id: str) -> bool:
        """Silence one report: RECENT_CRASH skips archived entries."""
        rep = self.info(crash_id)
        if rep is None:
            return False
        rep["archived"] = time.time()
        self.ctx.mon_command({
            "prefix": "config-key put",
            "key": self._PREFIX + crash_id,
            "val": json.dumps(rep)})
        return True

    def archive_all(self) -> int:
        n = 0
        for row in self.ls(new_only=True):
            if self.archive(row["crash_id"]):
                n += 1
        return n

    def handle_command(self, cmd: dict):
        """`ceph crash ls|ls-new|info|post|rm|archive|archive-all`."""
        prefix = cmd.get("prefix", "")
        if prefix in ("crash ls", "crash ls-new"):
            return 0, "", self.ls(new_only=prefix.endswith("-new"))
        if prefix == "crash info":
            rep = self.info(str(cmd.get("id", "")))
            if rep is None:
                return -2, f"no crash {cmd.get('id')!r}", None
            return 0, "", rep
        if prefix == "crash post":
            try:
                cid = self.post(dict(cmd.get("report") or {}))
            except ValueError as e:
                return -22, str(e), None
            return 0, cid, {"crash_id": cid}
        if prefix == "crash rm":
            self.rm(str(cmd.get("id", "")))
            return 0, "", {"removed": cmd.get("id")}
        if prefix == "crash archive":
            if not self.archive(str(cmd.get("id", ""))):
                return -2, f"no crash {cmd.get('id')!r}", None
            return 0, "", {"archived": cmd.get("id")}
        if prefix == "crash archive-all":
            return 0, "", {"archived": self.archive_all()}
        return None


class TelemetryModule(MgrModule):
    """Anonymized cluster report (reference ``pybind/mgr/telemetry``):
    aggregate counts only — never pool/host/entity names, never keys;
    the cluster id is a salted hash, as upstream sends a UUID."""

    NAME = "telemetry"
    TICK = 60.0

    def compile_report(self) -> dict:
        rc, _, st = self.ctx.mon_command({"prefix": "status"})
        st = st if rc == 0 and st else {}
        rc, _, keys = self.ctx.mon_command({"prefix": "config-key ls"})
        crashes = len([k for k in (keys or [])
                       if k.startswith(CrashModule._PREFIX)]) \
            if rc == 0 else 0
        cluster_id = hashlib.sha256(
            f"ceph-tpu-{sorted(st.get('quorum') or [])}".encode()
        ).hexdigest()[:32]
        return {
            "cluster_id": cluster_id,
            "report_timestamp": time.time(),
            "mon": {"count": len(st.get("quorum") or [])},
            "osd": {"count": st.get("num_osds", 0),
                    "up": st.get("num_up_osds", 0)},
            "pools": {"count": len(st.get("pools", []))},
            "pgs": {"count": st.get("num_pgs", 0),
                    "states": st.get("pg_states", {})},
            "objects": {"count": st.get("num_objects", 0)},
            "health": st.get("health"),
            "crashes": crashes,
        }

"""Small mgr modules: status, iostat, crash, telemetry.

Reference behavior re-created (``src/pybind/mgr/<module>/module.py``
each; SURVEY.md §3.10 "mgr modules"):

- **status**: ``ceph -s``-shaped cluster summary assembled mgr-side
  from the mon's status + pg stats (the reference renders fs/osd
  status tables from the same aggregates);
- **iostat**: cluster-wide IOPS read off consecutive ``pg dump``
  osd_stat op-counter deltas (the reference differentiates PGMap
  counters the same way);
- **crash**: crash-report archive — daemons (or operators) post
  crash dumps, ``crash ls``/``info``/``rm`` browse them; stored in
  RADOS-backed mon config-key storage analog (here: module-local
  store persisted via mon config-key commands);
- **telemetry**: an anonymized cluster report (counts and versions,
  never names/keys) assembled on demand, ``telemetry show`` style.
"""

from __future__ import annotations

import hashlib
import json
import time

from .daemon import MgrModule


class StatusModule(MgrModule):
    """`ceph -s` aggregation (reference ``pybind/mgr/status``)."""

    NAME = "status"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self.last: dict = {}

    def serve_tick(self):
        rc, _, st = self.ctx.mon_command({"prefix": "status"})
        if rc == 0 and st:
            self.last = st

    def render(self) -> str:
        """The human `ceph -s` panel, from the last aggregate."""
        st = self.last
        if not st:
            return "status: no data yet"
        lines = [
            f"  health: {st.get('health')}",
            "",
            "  services:",
            f"    mon: quorum {st.get('quorum')} "
            f"(leader {st.get('leader')})",
            f"    osd: {st.get('num_up_osds')}/{st.get('num_osds')} up",
            "",
            "  data:",
            f"    pools:   {len(st.get('pools', []))}",
            f"    objects: {st.get('num_objects')}",
            f"    pgs:     {st.get('num_pgs')} " + " ".join(
                f"{n} {s};" for s, n in
                sorted(st.get("pg_states", {}).items())),
        ]
        for chk in st.get("checks", []):
            lines.insert(1, f"    {chk['code']}: {chk['summary']}")
        return "\n".join(lines)


class IostatModule(MgrModule):
    """Cluster IOPS from osd_stat op-counter deltas (reference
    ``pybind/mgr/iostat``)."""

    NAME = "iostat"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self._prev: tuple[float, dict] | None = None
        self.rates = {"op_per_sec": 0.0, "write_op_per_sec": 0.0,
                      "read_op_per_sec": 0.0}

    def _totals(self) -> dict | None:
        # osd_stats counters only: `pg summary` carries them without
        # a per-PG dump (fall back for mons that don't serve it)
        rc, _, dump = self.ctx.mon_command({"prefix": "pg summary"})
        if rc != 0 or not dump or "osd_stats" not in dump:
            rc, _, dump = self.ctx.mon_command({"prefix": "pg dump"})
        if rc != 0 or not dump:
            return None
        tot = {"op": 0.0, "op_w": 0.0, "op_r": 0.0}
        for st in (dump.get("osd_stats") or {}).values():
            for k in tot:
                tot[k] += float(st.get(k, 0))
        return tot

    def serve_tick(self):
        now = time.monotonic()
        tot = self._totals()
        if tot is None:
            return
        if self._prev is not None:
            t0, prev = self._prev
            dt = max(now - t0, 1e-6)
            # counters are cumulative; an OSD restart can step one
            # backwards — clamp at 0 rather than reporting negatives
            self.rates = {
                "op_per_sec": max(0.0, (tot["op"] - prev["op"]) / dt),
                "write_op_per_sec":
                    max(0.0, (tot["op_w"] - prev["op_w"]) / dt),
                "read_op_per_sec":
                    max(0.0, (tot["op_r"] - prev["op_r"]) / dt),
            }
        self._prev = (now, tot)


class CrashModule(MgrModule):
    """Crash-report archive (reference ``pybind/mgr/crash``): posts
    are keyed by crash id (timestamp + entity hash), persisted through
    the mon's config-key store so they survive mgr failover."""

    NAME = "crash"
    TICK = 30.0
    _PREFIX = "mgr/crash/"

    def post(self, report: dict) -> str:
        """`ceph crash post` — report must carry entity + backtrace."""
        if "entity" not in report:
            raise ValueError("crash report requires 'entity'")
        stamp = report.setdefault("timestamp", time.time())
        crash_id = "%s_%s" % (
            time.strftime("%Y-%m-%d_%H:%M:%S", time.gmtime(stamp)),
            hashlib.sha1(
                f"{report['entity']}{stamp}".encode()).hexdigest()[:12])
        report["crash_id"] = crash_id
        self.ctx.mon_command({
            "prefix": "config-key put",
            "key": self._PREFIX + crash_id,
            "val": json.dumps(report)})
        return crash_id

    def _keys(self) -> list[str]:
        rc, _, keys = self.ctx.mon_command({
            "prefix": "config-key ls"})
        if rc != 0 or not keys:
            return []
        return sorted(k for k in keys if k.startswith(self._PREFIX))

    def ls(self) -> list[dict]:
        out = []
        for k in self._keys():
            rc, _, val = self.ctx.mon_command({
                "prefix": "config-key get", "key": k})
            if rc == 0 and val:
                rep = json.loads(val)
                out.append({"crash_id": rep["crash_id"],
                            "entity": rep["entity"],
                            "timestamp": rep["timestamp"]})
        return out

    def info(self, crash_id: str) -> dict | None:
        rc, _, val = self.ctx.mon_command({
            "prefix": "config-key get",
            "key": self._PREFIX + crash_id})
        return json.loads(val) if rc == 0 and val else None

    def rm(self, crash_id: str):
        self.ctx.mon_command({
            "prefix": "config-key del", "key": self._PREFIX + crash_id})


class TelemetryModule(MgrModule):
    """Anonymized cluster report (reference ``pybind/mgr/telemetry``):
    aggregate counts only — never pool/host/entity names, never keys;
    the cluster id is a salted hash, as upstream sends a UUID."""

    NAME = "telemetry"
    TICK = 60.0

    def compile_report(self) -> dict:
        rc, _, st = self.ctx.mon_command({"prefix": "status"})
        st = st if rc == 0 and st else {}
        rc, _, keys = self.ctx.mon_command({"prefix": "config-key ls"})
        crashes = len([k for k in (keys or [])
                       if k.startswith(CrashModule._PREFIX)]) \
            if rc == 0 else 0
        cluster_id = hashlib.sha256(
            f"ceph-tpu-{sorted(st.get('quorum') or [])}".encode()
        ).hexdigest()[:32]
        return {
            "cluster_id": cluster_id,
            "report_timestamp": time.time(),
            "mon": {"count": len(st.get("quorum") or [])},
            "osd": {"count": st.get("num_osds", 0),
                    "up": st.get("num_up_osds", 0)},
            "pools": {"count": len(st.get("pools", []))},
            "pgs": {"count": st.get("num_pgs", 0),
                    "states": st.get("pg_states", {})},
            "objects": {"count": st.get("num_objects", 0)},
            "health": st.get("health"),
            "crashes": crashes,
        }

"""mgr telemetry spine — counter time-series with derived rates.

Everything before this module was point-in-time: perf counters are
cumulative totals, ``pg dump`` a snapshot.  The spine turns the
osd_stats beacon into **history**: every tick it ingests the selected
counters + device-profiler aggregates each OSD ships, keeps a
fixed-size downsampling ring per (daemon, counter) — when a ring
fills it decimates by two and doubles its sampling stride, so memory
stays bounded while the window keeps growing (the classic RRD
trade) — and derives

* **rates** from consecutive cumulative samples (ops/s, B/s,
  launches/s), clamped at zero across daemon restarts,
* **rolling p50/p99** launch times from the *delta* of the log2
  launch histograms over the retained window (not lifetime), and
* **device-plane ratios** straight off the profiler aggregates:
  dispatch overhead (host dispatch time / total device wall time —
  ROADMAP item 1's target), batch occupancy (useful rows / padded
  rows) and the average device idle gap.

``ceph iostat`` and ``ceph osd perf`` are served from here
(reference: the mgr's ``iostat`` module and ``osd perf`` reading
osd_stat_t fields the OSDs beacon via MPGStats).

Workload attribution rides the same beacon: each OSD ships its
space-saving top-K sketches (clients/pools/pgs) and the slowest-op
trace exemplars per latency bucket; the spine merges sketches
cluster-wide for ``ceph osd top`` and serves ``ceph tracing
exemplar`` lookups straight off the ingested state.
"""

from __future__ import annotations

import collections
import time

import numpy as np

from ..core import topk as _topk
from .daemon import MgrModule

# counters lifted verbatim off each osd_stats beacon into rings
_COUNTERS = ("op", "op_w", "op_r", "op_in_bytes")


class SeriesRing:
    """Fixed-capacity (t, value) ring: when full, decimate by two and
    double the sampling stride — old history thins, recent stays.

    Backed by one preallocated ``[capacity+1, 2]`` float64 buffer so
    a telemetry spine tracking thousands of daemons never churns
    per-sample Python tuples: appends are two scalar stores,
    decimation is a strided copy, and ``rate()`` reads the tail
    directly."""

    __slots__ = ("capacity", "_buf", "_len", "_stride", "_pending")

    def __init__(self, capacity: int = 256):
        self.capacity = max(4, int(capacity))
        # +1: the overflowing sample lands before decimation
        self._buf = np.empty((self.capacity + 1, 2), dtype=np.float64)
        self._len = 0
        self._stride = 1
        self._pending = 0

    def append(self, t: float, v: float):
        self._pending += 1
        if self._pending < self._stride:
            return
        self._pending = 0
        self._buf[self._len, 0] = t
        self._buf[self._len, 1] = v
        self._len += 1
        if self._len > self.capacity:
            kept = self._buf[:self._len:2].copy()
            self._len = len(kept)
            self._buf[:self._len] = kept
            self._stride *= 2

    @property
    def samples(self) -> list[tuple[float, float]]:
        """Materialized (t, value) tuples — the legacy list shape for
        dump/test surfaces; hot paths read the buffer directly."""
        return [(float(t), float(v))
                for t, v in self._buf[:self._len]]

    def array(self) -> np.ndarray:
        """The live [n, 2] window (no copy) for vectorized consumers."""
        return self._buf[:self._len]

    def last(self) -> tuple[float, float] | None:
        if self._len == 0:
            return None
        t, v = self._buf[self._len - 1]
        return (float(t), float(v))

    def rate(self) -> float:
        """Per-second rate from the two most recent samples of a
        cumulative counter (>= 0: restarts step counters backwards)."""
        if self._len < 2:
            return 0.0
        t0, v0 = self._buf[self._len - 2]
        t1, v1 = self._buf[self._len - 1]
        dt = t1 - t0
        if dt <= 0:
            return 0.0
        return max(0.0, float((v1 - v0) / dt))

    def __len__(self):
        return self._len


def hist_quantile(counts, q: float) -> float:
    """Approximate quantile of a log2-bucketed histogram (bucket i
    holds values in [2^i - 1, 2^(i+1) - 1)): returns the upper bound
    of the bucket where the cumulative count crosses q — one
    cumsum + searchsorted instead of a Python scan."""
    c = np.asarray(counts, dtype=np.float64)
    total = float(c.sum())
    if total <= 0:
        return 0.0
    cum = np.cumsum(c)
    i = int(np.searchsorted(cum, q * total, side="left"))
    i = min(i, len(c) - 1)
    return float((1 << (i + 1)) - 1)


def _hist_delta(new, old) -> np.ndarray:
    n = np.asarray(new, dtype=np.int64)
    if old is None or len(old) != len(n):
        return n
    d = n - np.asarray(old, dtype=np.int64)
    # a reset profiler steps buckets backwards: fall back to lifetime
    return n if bool((d < 0).any()) else d


class TelemetrySpine(MgrModule):
    """Per-(daemon, counter) rings + derived rates/percentiles."""

    NAME = "telemetry_spine"
    TICK = 1.0
    RING_CAPACITY = 256
    HIST_WINDOW = 64           # (t, hist) snapshots kept per daemon

    def __init__(self, ctx):
        super().__init__(ctx)
        self.series: dict[str, dict[str, SeriesRing]] = {}
        self.profiler: dict[str, dict] = {}      # latest aggregate
        self._hists: dict[str, collections.deque] = {}
        self._latency: dict[str, SeriesRing] = {}  # op_latency sum ring
        self._lat_count: dict[str, SeriesRing] = {}
        # latest SLO-harness report per scenario ("slo ingest")
        self.slo: dict[str, dict] = {}
        # latest attribution sketches / trace exemplars per daemon
        self.topk: dict[str, dict] = {}
        self.exemplars: dict[str, dict] = {}

    # -- ingest ------------------------------------------------------------

    def _ring(self, daemon: str, counter: str) -> SeriesRing:
        return self.series.setdefault(daemon, {}).setdefault(
            counter, SeriesRing(self.RING_CAPACITY))

    def serve_tick(self):
        # only the osd_stats beacons are ingested — `pg summary`
        # carries them without materializing a per-PG dump; fall back
        # to `pg dump` for mons that don't serve it
        try:
            rc, _, dump = self.ctx.mon_command({"prefix": "pg summary"})
            if rc != 0 or not dump or "osd_stats" not in dump:
                rc, _, dump = self.ctx.mon_command(
                    {"prefix": "pg dump"})
        except Exception:       # noqa: BLE001 — mon churn: next tick
            return
        if rc != 0 or not dump:
            return
        now = time.monotonic()
        for osd, st in (dump.get("osd_stats") or {}).items():
            daemon = f"osd.{osd}"
            for c in _COUNTERS:
                if c in st:
                    self._ring(daemon, c).append(now, float(st[c]))
            lat = st.get("op_latency")
            if isinstance(lat, dict):
                self._latency.setdefault(
                    daemon, SeriesRing(self.RING_CAPACITY)).append(
                        now, float(lat.get("sum", 0.0)))
                self._lat_count.setdefault(
                    daemon, SeriesRing(self.RING_CAPACITY)).append(
                        now, float(lat.get("count", 0)))
            comp = st.get("comp")
            if isinstance(comp, dict):
                # storage-efficiency lane counters → per-lane byte
                # rates (compress in/out, decompress, fingerprint)
                for c in ("bytes_in", "bytes_out",
                          "decompress_bytes", "fingerprint_bytes"):
                    self._ring(daemon, f"comp_{c}").append(
                        now, float(comp.get(c, 0)))
            prof = st.get("profiler")
            if isinstance(prof, dict):
                self.profiler[daemon] = prof
                tot = prof.get("totals") or {}
                self._ring(daemon, "device_launches").append(
                    now, float(tot.get("launches", 0)))
                self._ring(daemon, "device_bytes").append(
                    now, float(tot.get("bytes_in", 0)
                               + tot.get("bytes_out", 0)))
                hist = prof.get("launch_hist_us")
                if hist:
                    dq = self._hists.setdefault(
                        daemon,
                        collections.deque(maxlen=self.HIST_WINDOW))
                    dq.append((now, list(hist)))
            tk = st.get("topk")
            if isinstance(tk, dict):
                self.topk[daemon] = tk
            ex = st.get("exemplars")
            if isinstance(ex, dict):
                self.exemplars[daemon] = ex

    # -- derived views -----------------------------------------------------

    def daemon_rates(self, daemon: str) -> dict:
        rings = self.series.get(daemon, {})
        if daemon.startswith("slo."):
            # SLO pseudo-daemons carry cumulative harness aggregates;
            # their rate view is one windowed per-second number per
            # ring — the same numbers ``telemetry series`` reports
            return {f"{c}_per_s": ring.rate()
                    for c, ring in sorted(rings.items())}

        def r(c):
            ring = rings.get(c)
            return ring.rate() if ring is not None else 0.0
        return {
            "ops_per_sec": r("op"),
            "write_ops_per_sec": r("op_w"),
            "read_ops_per_sec": r("op_r"),
            "bytes_per_sec": r("op_in_bytes"),
            "launches_per_sec": r("device_launches"),
            "device_bytes_per_sec": r("device_bytes"),
            "compress_bytes_per_sec": r("comp_bytes_in"),
            "compressed_bytes_per_sec": r("comp_bytes_out"),
            "decompress_bytes_per_sec": r("comp_decompress_bytes"),
            "fingerprint_bytes_per_sec": r("comp_fingerprint_bytes"),
        }

    def commit_latency_ms(self, daemon: str) -> float:
        """Windowed client-op commit latency: delta(sum)/delta(count)
        of the op_latency pair over the last two beacons."""
        s, c = self._latency.get(daemon), self._lat_count.get(daemon)
        if s is None or c is None or len(s) < 2 or len(c) < 2:
            return 0.0
        sv, cv = s.array()[:, 1], c.array()[:, 1]
        ds = float(sv[-1] - sv[-2])
        dc = float(cv[-1] - cv[-2])
        if dc <= 0:
            # nothing completed this window: lifetime average instead
            tot_s, tot_c = float(sv[-1]), float(cv[-1])
            return 1000.0 * tot_s / tot_c if tot_c > 0 else 0.0
        return 1000.0 * max(ds, 0.0) / dc

    def launch_percentiles(self, daemon: str) -> dict:
        """Rolling p50/p99 launch wall time (us) over the retained
        histogram window."""
        dq = self._hists.get(daemon)
        if not dq:
            return {"p50_us": 0.0, "p99_us": 0.0}
        newest = dq[-1][1]
        oldest = dq[0][1] if len(dq) > 1 else [0] * len(newest)
        delta = _hist_delta(newest, oldest)
        if sum(delta) <= 0:
            delta = newest      # idle window: lifetime distribution
        return {"p50_us": hist_quantile(delta, 0.50),
                "p99_us": hist_quantile(delta, 0.99)}

    def device_summary(self, daemon: str) -> dict:
        prof = self.profiler.get(daemon) or {}
        tot = prof.get("totals") or {}
        launches = int(tot.get("launches", 0))
        disp, comp = tot.get("dispatch_s", 0.0), tot.get("compute_s", 0.0)
        out = {
            "launches": launches,
            "dispatch_ms_avg":
                1000.0 * disp / launches if launches else 0.0,
            "compute_ms_avg":
                1000.0 * comp / launches if launches else 0.0,
            "dispatch_overhead_ratio":
                float(prof.get("dispatch_overhead_ratio", 0.0)),
            "occupancy_ratio": float(prof.get("occupancy_ratio", 1.0)),
            "idle_gap_avg_s": float(prof.get("idle_gap_avg_s", 0.0)),
        }
        out.update(self.launch_percentiles(daemon))
        return out

    def iostat(self) -> dict:
        """`ceph iostat` payload: cluster totals + per-OSD rates."""
        osds = sorted((d for d in self.series if d.startswith("osd.")),
                      key=lambda d: int(d.split(".", 1)[1]))
        per = {d: self.daemon_rates(d) for d in osds}
        keys = ("ops_per_sec", "write_ops_per_sec",
                "read_ops_per_sec", "bytes_per_sec",
                "launches_per_sec", "device_bytes_per_sec",
                "compress_bytes_per_sec", "compressed_bytes_per_sec",
                "decompress_bytes_per_sec",
                "fingerprint_bytes_per_sec")
        cluster = ({k: sum(v[k] for v in per.values()) for k in keys}
                   if per else {k: 0.0 for k in keys})
        return {"cluster": cluster, "osds": per}

    def osd_perf(self) -> dict:
        """`ceph osd perf` payload: commit latency + device-launch
        breakdown per OSD."""
        osds = sorted(set(self.series) | set(self.profiler))
        out = {}
        for d in osds:
            if not d.startswith("osd."):
                continue
            out[d] = {
                "commit_latency_ms": self.commit_latency_ms(d),
                "apply_latency_ms": self.commit_latency_ms(d),
                "device": self.device_summary(d),
            }
        return {"osd_perf": out}

    def _ingest_slo_series(self, scenario: str, report: dict):
        """Thread each report's violation/goodput aggregates into
        per-scenario rings (pseudo-daemon ``slo.<scenario>``) so the
        autotuner and ``telemetry series`` see pressure *history*, not
        just the latest point sample kept in ``self.slo``."""
        now = time.monotonic()
        daemon = f"slo.{scenario}"
        violation_s = 0.0
        lanes_in_violation = 0.0
        for lanes in (report.get("tenants") or {}).values():
            for lane in (lanes or {}).values():
                violation_s += float(lane.get("violation_s", 0.0))
                lanes_in_violation += bool(lane.get("in_violation"))
        self._ring(daemon, "violation_s").append(now, violation_s)
        self._ring(daemon, "goodput_ops").append(
            now, float(report.get("goodput_ops", 0.0)))
        self._ring(daemon, "lanes_in_violation").append(
            now, lanes_in_violation)
        self._ring(daemon, "offered_rate").append(
            now, float(report.get("offered_rate", 0.0)))

    def slo_pressure(self) -> dict:
        """Windowed violation pressure for the autotuner: per scenario
        the *rate* of cumulative time-in-violation (seconds of
        violation per wall second, clamped to [0,1] — 1 means every
        moment of the window was in violation somewhere), plus the
        latest goodput and worst lane p99 from the retained reports."""
        per = {}
        for daemon, rings in self.series.items():
            if not daemon.startswith("slo."):
                continue
            scenario = daemon.split(".", 1)[1]
            ring = rings.get("violation_s")
            rate = ring.rate() if ring is not None else 0.0
            good = rings.get("goodput_ops")
            per[scenario] = {
                "pressure": min(1.0, rate),
                "goodput_ops": (float(good.samples[-1][1])
                                if good is not None and len(good)
                                else 0.0),
            }
        worst_p99 = 0.0
        for report in self.slo.values():
            for lanes in (report.get("tenants") or {}).values():
                for lane in (lanes or {}).values():
                    worst_p99 = max(worst_p99,
                                    float(lane.get("p99_ms", 0.0)))
        return {
            "pressure": max((s["pressure"] for s in per.values()),
                            default=0.0),
            "goodput_ops": sum(s["goodput_ops"]
                               for s in per.values()),
            "worst_p99_ms": worst_p99,
            "scenarios": per,
        }

    @staticmethod
    def _windowed(ring: SeriesRing) -> list[tuple[float, float]]:
        """Cumulative ring → per-second windowed samples (successive
        deltas, clamped at zero; the first sample has no window).  The
        tail equals ``ring.rate()`` so every surface derived from this
        ring reports the same number."""
        out: list[tuple[float, float]] = []
        prev = None
        for t, v in ring.array():
            if prev is None or t <= prev[0]:
                out.append((float(t), 0.0))
            else:
                out.append((float(t),
                            max(0.0, float(v - prev[1])
                                / float(t - prev[0]))))
            prev = (t, v)
        return out

    def series_dump(self, daemon: str | None = None) -> dict:
        """History surface for tests/tools: raw (t, value) samples —
        except slo.* rings, which surface as the windowed per-second
        numbers ``daemon_rates`` reports (raw cumulative
        harness aggregates were a trap: the two surfaces disagreed)."""
        src = (self.series if daemon is None
               else {daemon: self.series.get(daemon, {})})
        out = {}
        for d, rings in src.items():
            if d.startswith("slo."):
                out[d] = {f"{c}_per_s": self._windowed(r)
                          for c, r in rings.items()}
            else:
                out[d] = {c: list(r.samples) for c, r in rings.items()}
        return out

    def osd_top(self, dim: str = "clients", by: str = "ops",
                count: int = 10) -> dict:
        """``ceph osd top``: merge every OSD's sketch for one
        dimension into a cluster-wide top-K with error bounds."""
        dumps = [t[dim] for t in self.topk.values()
                 if isinstance(t.get(dim), dict)]
        merged = _topk.merge_sketches(dumps)
        return {"dim": dim, "by": by,
                "osds": sorted(self.topk),
                "err_floor": int(merged.get("min", 0)),
                "rows": _topk.rank(merged, by=by, n=count)}

    def exemplar_lookup(self, metric: str | None = None,
                        bucket: int | None = None) -> list[dict]:
        """Ingested trace exemplars, filtered by metric/bucket, worst
        (largest observed value) first — each row names the daemon
        whose histogram kept the trace."""
        rows = []
        for daemon in sorted(self.exemplars):
            for counter, buckets in sorted(
                    self.exemplars[daemon].items()):
                if metric is not None and counter != metric:
                    continue
                for b, ex in (buckets or {}).items():
                    if bucket is not None and int(b) != int(bucket):
                        continue
                    rows.append({"daemon": daemon, "metric": counter,
                                 "bucket": int(b), **dict(ex)})
        rows.sort(key=lambda r: (-float(r.get("value", 0.0)),
                                 r["daemon"], r["bucket"]))
        return rows

    def export_view(self) -> dict:
        """What the prometheus exporter consumes: latest profiler
        aggregate + derived rates per daemon (slo.* included, as
        windowed per-second numbers) + the last SLO-harness reports
        + the merged attribution top-K."""
        return {"profiler": dict(self.profiler),
                "rates": {d: self.daemon_rates(d)
                          for d in self.series},
                "slo": dict(self.slo),
                "slo_pressure": self.slo_pressure(),
                "topk": {dim: self.osd_top(dim)["rows"]
                         for dim in _topk.TopKSet.DIMS}}

    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix in ("iostat", "iostat json"):
            return 0, "", self.iostat()
        if prefix == "osd perf":
            return 0, "", self.osd_perf()
        if prefix == "telemetry series":
            return 0, "", self.series_dump(cmd.get("daemon"))
        if prefix == "osd top":
            dim = str(cmd.get("dim") or "clients")
            if dim not in _topk.TopKSet.DIMS:
                return (-22, "osd top: dim must be one of "
                        + "|".join(_topk.TopKSet.DIMS), None)
            by = str(cmd.get("by") or "ops")
            if by not in ("ops", "bytes", "p99"):
                return -22, "osd top: --by ops|bytes|p99", None
            return 0, "", self.osd_top(
                dim, by, int(cmd.get("count") or 10))
        if prefix == "tracing exemplar":
            metric = cmd.get("metric")
            bucket = cmd.get("bucket")
            rows = self.exemplar_lookup(
                str(metric) if metric is not None else None,
                int(bucket) if bucket is not None else None)
            return 0, "", {"exemplars": rows}
        if prefix == "slo ingest":
            report = cmd.get("report")
            if not isinstance(report, dict):
                return -22, "", "slo ingest needs a report dict"
            scenario = str(cmd.get("scenario") or "default")
            self.slo[scenario] = report
            self._ingest_slo_series(scenario, report)
            return 0, "", ""
        if prefix == "slo report":
            scenario = cmd.get("scenario")
            if scenario is not None:
                return 0, "", self.slo.get(str(scenario), {})
            return 0, "", dict(self.slo)
        return None

"""mgr volumes — CephFS subvolume management.

Reference behavior re-created (``src/pybind/mgr/volumes``; SURVEY.md
§3.10): subvolumes are managed directories under
``/volumes/<group>/<name>`` in a filesystem, created/listed/removed
through the mgr so orchestration never hand-rolls paths.  The module
mounts a CephFS client lazily (only when a filesystem with an active
MDS exists) and serves:

- ``subvolume_create(fs, name, group="_nogroup")``
- ``subvolume_ls(fs, group)``
- ``subvolume_rm(fs, name, group)`` (recursive)
- ``subvolume_getpath(fs, name, group)``
"""

from __future__ import annotations

from .daemon import MgrModule

VOLUMES_ROOT = "/volumes"


class VolumesModule(MgrModule):
    NAME = "volumes"
    TICK = 30.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self._mounts: dict[str, object] = {}

    def shutdown(self):
        for fs in list(self._mounts.values()):
            try:
                fs.unmount()
            except Exception:
                pass
        self._mounts.clear()

    def _fs(self, fs_name: str):
        fs = self._mounts.get(fs_name)
        if fs is None:
            from ..cephfs.client import CephFS
            fs = CephFS(self.ctx._d.monmap, fs_name=fs_name,
                        auth=getattr(self.ctx._d, "auth", None)).mount()
            self._mounts[fs_name] = fs
        return fs

    @staticmethod
    def _dir(group: str, name: str = "") -> str:
        base = f"{VOLUMES_ROOT}/{group}"
        return f"{base}/{name}" if name else base

    def subvolume_create(self, fs_name: str, name: str,
                         group: str = "_nogroup") -> str:
        fs = self._fs(fs_name)
        path = self._dir(group, name)
        fs.mkdirs(path)
        return path

    def subvolume_ls(self, fs_name: str,
                     group: str = "_nogroup") -> list[str]:
        fs = self._fs(fs_name)
        try:
            return [n for n, rec in fs.readdir(self._dir(group))
                    if rec["type"] == "dir"]
        except Exception:
            return []

    def subvolume_getpath(self, fs_name: str, name: str,
                          group: str = "_nogroup") -> str:
        fs = self._fs(fs_name)
        path = self._dir(group, name)
        fs.stat(path)           # raises if absent
        return path

    def subvolume_rm(self, fs_name: str, name: str,
                     group: str = "_nogroup"):
        fs = self._fs(fs_name)
        self._rmtree(fs, self._dir(group, name))

    def _rmtree(self, fs, path: str):
        for entry, rec in fs.readdir(path):
            child = f"{path}/{entry}"
            if rec["type"] == "dir":
                self._rmtree(fs, child)
            else:
                fs.unlink(child)
        fs.rmdir(path)

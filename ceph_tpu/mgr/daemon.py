"""ceph-mgr daemon — active/standby module host.

Reference behavior re-created (``src/mgr/Mgr.cc``, ``MgrStandby.cc``,
``ActivePyModules.cc``; SURVEY.md §3.10): the mgr beacons to the mon
cluster; the MgrMonitor elects one active (the rest standby) and a
beacon timeout fails over.  The ACTIVE mgr hosts the management
modules — here the upmap **balancer**, the **pg_autoscaler**, and the
**prometheus** exporter — each driven from a periodic serve tick with
a module context exposing mon commands and cluster maps (the
reference's MgrModule API surface, narrowed to what the modules use).
"""

from __future__ import annotations

import threading
import time

from ..mon import messages as MM
from ..mon.client import MonClient
from ..msg import Dispatcher, Messenger
from ..osd.osdmap import OSDMap, PGid
from ..tools.osdmaptool import osdmap_from_dict
from .balancer import UpmapBalancer
from .exporter import Exporter, ExporterService


class MgrModuleContext:
    """What a module sees (reference MgrModule: get_osdmap, mon
    command access, logging)."""

    def __init__(self, daemon: "MgrDaemon"):
        self._d = daemon

    def mon_command(self, cmd: dict):
        return self._d.monc.command(cmd)

    def get_osdmap(self) -> OSDMap | None:
        d = self._d.monc.osdmap_dict
        return osdmap_from_dict(d) if d else None


class MgrModule:
    NAME = "module"
    TICK = 1.0

    def __init__(self, ctx: MgrModuleContext):
        self.ctx = ctx

    def serve_tick(self):
        """One periodic step; exceptions are logged-and-survived."""

    def shutdown(self):
        pass


class BalancerModule(MgrModule):
    """Upmap balancer (reference ``pybind/mgr/balancer`` upmap mode):
    every tick evaluates each replicated pool's placement on the
    batched mapper and applies a bounded set of pg-upmap-items."""

    NAME = "balancer"
    TICK = 2.0
    MAX_CHANGES_PER_TICK = 8

    def serve_tick(self):
        m = self.ctx.get_osdmap()
        if m is None:
            return
        for pid, pool in m.pools.items():
            if pool.is_erasure():
                continue
            try:
                bal = UpmapBalancer(m, pid)
                proposals = bal.optimize(
                    max_changes=self.MAX_CHANGES_PER_TICK)
            except Exception:   # noqa: BLE001 — unbalanceable rule
                continue
            for pgid, items in proposals.items():
                self.ctx.mon_command({
                    "prefix": "osd pg-upmap-items", "pgid": str(pgid),
                    "mappings": [[a, b] for a, b in items]})


class PgAutoscalerModule(MgrModule):
    """pg_num autoscaler (reference ``pybind/mgr/pg_autoscaler``):
    grows pools toward ~TARGET_PGS_PER_OSD replica-slots per OSD,
    doubling pg_num per step; pgp_num follows one tick later so the
    split settles colocated before placement rebalances (the
    reference's split-then-move pacing)."""

    NAME = "pg_autoscaler"
    TARGET_PGS_PER_OSD = 100
    MAX_POOL_PG_NUM = 256

    def serve_tick(self):
        m = self.ctx.get_osdmap()
        if m is None or not m.pools:
            return
        n_osds = max(1, m.num_in_osds())
        budget = self.TARGET_PGS_PER_OSD * n_osds
        share = budget // max(1, len(m.pools))
        for pid, pool in m.pools.items():
            name = next((n for n, i in m.pool_name.items()
                         if i == pid), None)
            if name is None:
                continue
            if pool.pgp_num < pool.pg_num:
                # previous split step: let placement catch up now
                self.ctx.mon_command({
                    "prefix": "osd pool set", "pool": name,
                    "var": "pgp_num", "val": str(pool.pg_num)})
                continue
            ideal = share // max(1, pool.size)
            ideal = min(ideal, self.MAX_POOL_PG_NUM)
            # grow only when under half the ideal (reference threshold
            # 3x; halved here because steps double), one doubling at
            # a time
            if ideal >= pool.pg_num * 2:
                self.ctx.mon_command({
                    "prefix": "osd pool set", "pool": name,
                    "var": "pg_num", "val": str(pool.pg_num * 2)})


class PrometheusModule(MgrModule):
    """Scrape endpoint (reference ``pybind/mgr/prometheus``)."""

    NAME = "prometheus"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.service = ExporterService(
            Exporter(ctx._d.monc, ctx._d.asok_paths,
                     progress_events=self._progress_events,
                     telemetry=self._telemetry,
                     autotune=self._autotune,
                     alerts=self._alerts)).start()
        self.port = self.service.port

    def _progress_events(self):
        # lazy lookup: module construction order is undefined, so the
        # progress module may not exist yet at our __init__
        mod = self.ctx._d.modules.get("progress")
        return mod.snapshot() if mod is not None else []

    def _telemetry(self):
        mod = self.ctx._d.modules.get("telemetry_spine")
        return mod.export_view() if mod is not None else {}

    def _autotune(self):
        mod = self.ctx._d.modules.get("autotune")
        return mod.export_view() if mod is not None else {}

    def _alerts(self):
        mod = self.ctx._d.modules.get("alerts")
        return mod.export_view() if mod is not None else {}

    def shutdown(self):
        self.service.shutdown()


def _default_modules():
    # late import: modules.py subclasses MgrModule from this file
    from .alerts import AlertsModule
    from .autotune import AutotuneModule
    from .dashboard import DashboardModule
    from .modules import (CrashModule, IostatModule, StatusModule,
                          TelemetryModule)
    from .devicehealth import DeviceHealthModule
    from .orchestrator import OrchestratorModule
    from .progress import ProgressModule
    from .rbd_support import RbdSupportModule
    from .telemetry import TelemetrySpine
    from .volumes import VolumesModule
    return (BalancerModule, PgAutoscalerModule, PrometheusModule,
            ProgressModule, StatusModule, IostatModule, CrashModule,
            TelemetryModule, TelemetrySpine, AutotuneModule,
            AlertsModule, DashboardModule, VolumesModule,
            OrchestratorModule, DeviceHealthModule, RbdSupportModule)


class _MgrCommandServer(Dispatcher):
    """Serves MMonCommand frames arriving on the mgr's own
    messenger (reference DaemonServer handling `ceph tell mgr` /
    orchestrator commands).  Modules answer via handle_command."""

    def __init__(self, daemon: "MgrDaemon"):
        self.d = daemon

    def ms_dispatch(self, msg) -> bool:
        if not isinstance(msg, MM.MMonCommand):
            return False
        cmd = msg.cmd if isinstance(msg.cmd, dict) else {}
        rc, outs, outb = -22, f"unknown mgr command "                               f"{cmd.get('prefix')!r}", None
        if self.d.state != "active":
            rc, outs = -11, "mgr not active"
        else:
            # NB: deliberately NOT under self.d.lock — a slow module
            # command would stall the loop thread at its lock acquire
            # and starve beacons (mon demotes us mid-command).
            # Modules doing slow work serialize internally
            # (OrchestratorModule defers deploys to a worker).
            for mod in list(self.d.modules.values()):
                handler = getattr(mod, "handle_command", None)
                if handler is None:
                    continue
                try:
                    res = handler(cmd)
                except Exception as e:      # noqa: BLE001 — module
                    res = (-22, f"module error: {e!r}", None)
                if res is not None:
                    rc, outs, outb = res
                    break
        try:
            msg.connection.send_message(MM.MMonCommandReply(
                tid=msg.tid, rc=rc, outs=outs, outb=outb))
        except ConnectionError:
            pass
        return True


class MgrDaemon:
    def __init__(self, name: str, monmap, *,
                 beacon_interval: float = 0.4,
                 modules=None,
                 asok_paths: dict[str, str] | None = None,
                 auth=None,
                 admin_socket_path: str | None = None):
        self.name = name
        self.monmap = monmap
        self.auth = auth
        self.beacon_interval = beacon_interval
        self.module_classes = (modules if modules is not None
                               else _default_modules())
        self.asok_paths = dict(asok_paths or {})
        self.monc = MonClient(monmap, entity=f"mgr.{name}",
                              auth=auth)
        # the mgr's own command server (reference DaemonServer): the
        # `ceph orch ...` / `ceph tell mgr` path connects HERE, found
        # via the mgrmap's active_addr
        self.msgr = Messenger(
            f"mgr.{name}",
            **(auth.msgr_kwargs(f"mgr.{name}") if auth else {}))
        self.msgr.add_dispatcher(_MgrCommandServer(self))
        self.addr = None
        # observability (reference: the mgr serves its own asok)
        from ..core.admin_socket import AdminSocket, default_path
        self.admin_socket = AdminSocket(
            admin_socket_path or default_path(f"mgr.{name}"))
        self.admin_socket.register(
            "status", lambda c: {
                "name": self.name, "state": self.state,
                "modules": sorted(self.modules),
                # real TCP port of the active exporter (procs-mode
                # parents discover the /metrics endpoint here) + the
                # clock pair for cross-process timeline alignment
                "prometheus_port": getattr(
                    self.modules.get("prometheus"), "port", None),
                "clock": {"wall": time.time(),
                          "mono": time.monotonic()}},
            "daemon status")
        self.admin_socket.register(
            "mgr module ls", lambda c: sorted(self.modules),
            "loaded modules")
        self.state = "boot"           # boot / standby / active
        self.modules: dict[str, MgrModule] = {}
        self.running = False
        self._seq = 0
        self._thread: threading.Thread | None = None
        # _on_mgrmap runs on the MonClient messenger thread, which
        # also delivers command replies — it must NEVER block on this
        # lock or a module tick awaiting a reply deadlocks the whole
        # client.  The push only flips _want_active; the loop thread
        # owns every state transition.
        self._want_active = False
        self.lock = threading.RLock()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self.running = True
        self.addr = self.msgr.bind()
        self.admin_socket.start()
        self.monc.on_mgrmap = self._on_mgrmap
        self.monc.sub_want("mgrmap", 0)
        self.monc.sub_want("osdmap", 0)
        self._send_beacon()
        self.state = "standby"
        self._thread = threading.Thread(
            target=self._loop, name=f"mgr.{self.name}", daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        self.running = False
        self.admin_socket.shutdown()
        with self.lock:
            self._stop_modules()
        self.msgr.shutdown()
        self.monc.shutdown()

    def kill(self):
        """Abrupt stop (failover fixture) — mgrs never deregister with
        the mon either way; the MgrMonitor beacon timeout is what
        promotes a standby, so kill IS shutdown."""
        self.shutdown()

    def _send_beacon(self):
        self._seq += 1
        addr = [self.addr.host, self.addr.port] if self.addr else []
        self.monc.send(MM.MMgrBeacon(name=self.name, addr=addr,
                                     seq=self._seq))

    # -- map handling ------------------------------------------------------
    def _on_mgrmap(self, epoch: int, mgrmap: dict):
        self._want_active = mgrmap.get("active_name") == self.name

    def _start_modules(self):
        ctx = MgrModuleContext(self)
        for cls in self.module_classes:
            try:
                self.modules[cls.NAME] = cls(ctx)
            except Exception:   # noqa: BLE001 — one bad module must
                pass            # not take the mgr down
        self._last_tick: dict[str, float] = {}

    def _stop_modules(self):
        for mod in self.modules.values():
            try:
                mod.shutdown()
            except Exception:   # noqa: BLE001
                pass
        self.modules.clear()

    def _loop(self):
        while self.running:
            self._send_beacon()
            with self.lock:
                if not self.running:
                    return
                if self._want_active and self.state != "active":
                    # modules first, THEN announce: the command
                    # server answers -11 (retryable "not active")
                    # until the module table is fully built, instead
                    # of -22 "unknown command" for a module that is
                    # mid-construction
                    self._start_modules()
                    self.state = "active"
                elif not self._want_active and self.state == "active":
                    self.state = "standby"
                    self._stop_modules()
                if self.state == "active":
                    now = time.monotonic()
                    for name, mod in list(self.modules.items()):
                        if now - self._last_tick.get(name, 0.0) \
                                < mod.TICK:
                            continue
                        self._last_tick[name] = now
                        try:
                            mod.serve_tick()
                        except Exception:   # noqa: BLE001
                            pass
            time.sleep(self.beacon_interval)

"""mgr ``progress`` module — recovery/backfill/scrub progress events.

Reference behavior re-created (``src/pybind/mgr/progress/module.py``;
SURVEY.md §3.10): watch PGMap deltas and the OSDMap out-set to open,
advance and close **progress events** — "Rebalancing after osd.3
marked out — 42%" — with the fraction derived from outstanding
recovery work (missing objects + backfill remainder) against the
worst backlog seen since the event opened, so it advances
monotonically.  Open events serve ``ceph progress`` /
``ceph progress json`` and the ``ceph_progress_event`` exporter
gauge; every open/advance/close is also published to the mon event
stream (``progress publish``) so ``ceph -w`` narrates it live.
"""

from __future__ import annotations

import json
import time

from .daemon import MgrModule


class ProgressModule(MgrModule):
    NAME = "progress"
    TICK = 1.0
    MAX_COMPLETED = 20
    # an event that never saw work (stats lag, or nothing actually
    # moved) closes quietly after this long
    CLEAN_GRACE = 10.0
    # config-key slot the open events + baselines persist under, so a
    # promoted standby resumes half-done events instead of restarting
    # every fraction at 0% (reference: the module's kv-store state)
    STORE_KEY = "mgr/progress/state"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.events: dict[str, dict] = {}       # open, by id
        self.completed: list[dict] = []          # bounded, oldest first
        self._baselines: dict[str, int] = {}     # id → worst backlog
        self._prev_out: set[int] | None = None
        self._dirty: list[dict] = []             # pending publishes
        self._loaded = False

    # -- failover persistence (mon config-key store) ----------------------

    def _load_state(self):
        """One-shot restore on the first tick after promotion: mgr
        module instances are rebuilt from scratch on failover, so the
        open events and their worst-seen backlogs must come back from
        the mon or every in-flight rebalance restarts at 0%."""
        self._loaded = True
        try:
            rc, _, out = self.ctx.mon_command(
                {"prefix": "config-key get", "key": self.STORE_KEY})
        except Exception:       # noqa: BLE001 — mon churn: stay empty
            return
        if rc != 0 or not out:
            return
        try:
            state = json.loads(out if isinstance(out, str)
                               else out.get("value", ""))
        except (ValueError, AttributeError):
            return
        self.events = dict(state.get("events") or {})
        self._baselines = {k: int(v) for k, v in
                           (state.get("baselines") or {}).items()}
        self.completed = list(state.get("completed") or [])

    def _save_state(self):
        blob = json.dumps({"events": self.events,
                           "baselines": self._baselines,
                           "completed": self.completed})
        try:
            self.ctx.mon_command({"prefix": "config-key put",
                                  "key": self.STORE_KEY, "val": blob})
        except Exception:       # noqa: BLE001 — retried next change
            pass

    # -- event bookkeeping -----------------------------------------------

    def _open(self, eid: str, message: str, now: float) -> dict:
        ev = {"id": eid, "message": message, "progress": 0.0,
              "started_at": now, "updated_at": now}
        self.events[eid] = ev
        self._dirty.append(dict(ev, state="open"))
        return ev

    def _close(self, eid: str, now: float):
        ev = self.events.pop(eid, None)
        if ev is None:
            return
        self._baselines.pop(eid, None)
        ev["progress"] = 1.0
        ev["updated_at"] = now
        self.completed.append(ev)
        del self.completed[:-self.MAX_COMPLETED]
        self._dirty.append(dict(ev, state="complete"))

    def _advance(self, ev: dict, frac: float, now: float):
        if frac > ev["progress"] + 1e-9:         # monotonic only
            ev["progress"] = min(1.0, frac)
            ev["updated_at"] = now
            self._dirty.append(dict(ev, state="update"))

    # -- the tick ----------------------------------------------------------

    def serve_tick(self):
        m = self.ctx.get_osdmap()
        if m is None:
            return
        if not self._loaded:
            self._load_state()
        now = time.time()
        out = {o for o in range(m.max_osd)
               if m.exists(o) and m.is_out(o)}
        prev, self._prev_out = self._prev_out, out
        # `pg summary` serves the recovery/scrub totals and the
        # sparse mid-flight chunk positions as mon-side reductions —
        # O(pools + scrubbing PGs) instead of a full per-PG dump.
        # Fall back to `pg dump` against mons (or test fakes) that
        # don't serve it.
        try:
            rc, _, summ = self.ctx.mon_command(
                {"prefix": "pg summary"})
        except Exception:       # noqa: BLE001 — mon churn: next tick
            return
        if rc == 0 and summ and "missing" in summ:
            work = int(summ.get("missing", 0)) \
                + int(summ.get("backfill_remaining", 0))
            scrubbing = int(summ.get("scrubbing_pgs", 0))
            scrub_pos = {pgid: (int(d), int(t)) for pgid, (d, t)
                         in (summ.get("scrubbing") or {}).items()}
        else:
            try:
                rc, _, dump = self.ctx.mon_command(
                    {"prefix": "pg dump"})
            except Exception:   # noqa: BLE001 — mon churn: next tick
                return
            if rc != 0 or not dump:
                return
            pg_stats = dump.get("pg_stats") or {}
            work = sum(int(st.get("missing", 0))
                       + int(st.get("backfill_remaining", 0))
                       for st in pg_stats.values())
            scrubbing = sum(1 for st in pg_stats.values()
                            if "scrubbing" in str(st.get("state", "")))
            scrub_pos = {}
            for pgid, st in pg_stats.items():
                total = int(st.get("scrub_chunks_total") or 0)
                if "scrubbing" in str(st.get("state", "")) \
                        and total > 0:
                    scrub_pos[pgid] = (
                        int(st.get("scrub_chunks_done") or 0), total)

        if prev is not None:
            for o in sorted(out - prev):
                self._open(f"osd.{o}-out",
                           f"Rebalancing after osd.{o} marked out",
                           now)
            for o in sorted(prev - out):
                self._open(f"osd.{o}-in",
                           f"Rebalancing after osd.{o} marked in",
                           now)

        recovery = [e for e in self.events.values()
                    if e["id"] != "scrub-sweep"
                    and not e["id"].startswith("pg_scrub/")]
        if work > 0 and not recovery:
            # degradation with no attributable map change (osd crash,
            # lost objects): one generic recovery event
            recovery = [self._open("recovery",
                                   "Recovering degraded objects", now)]
        for ev in list(recovery):
            eid = ev["id"]
            base = max(self._baselines.get(eid, 0), work)
            self._baselines[eid] = base
            if base <= 0:
                if work == 0 and \
                        now - ev["started_at"] > self.CLEAN_GRACE:
                    self._close(eid, now)
                continue
            self._advance(ev, 1.0 - work / base, now)
            if work == 0:
                self._close(eid, now)

        # per-PG scrub sweeps: the primary reports its chunk position
        # (scrub maps gathered vs. the acting set) in pg_stats while a
        # scrub is mid-flight — one `pg_scrub/<pgid>` event each, so
        # `ceph progress` narrates individual sweeps, not just the
        # cluster-wide scrub-sweep aggregate below
        seen: set[str] = set()
        for pgid, (done, total) in scrub_pos.items():
            eid = f"pg_scrub/{pgid}"
            seen.add(eid)
            ev = self.events.get(eid)
            if ev is None:
                ev = self._open(eid, f"Scrubbing pg {pgid}", now)
            self._advance(ev, done / total, now)
        for eid in [e for e in self.events
                    if e.startswith("pg_scrub/") and e not in seen]:
            self._close(eid, now)

        sweep = self.events.get("scrub-sweep")
        if sweep is None and scrubbing > 0:
            sweep = self._open("scrub-sweep",
                               "Deep scrub sweep in progress", now)
        if sweep is not None:
            base = max(self._baselines.get("scrub-sweep", 0),
                       scrubbing)
            self._baselines["scrub-sweep"] = base
            if base > 0:
                self._advance(sweep, 1.0 - scrubbing / base, now)
            if scrubbing == 0:
                self._close("scrub-sweep", now)

        if self._dirty:
            batch, self._dirty = self._dirty, []
            try:
                self.ctx.mon_command({"prefix": "progress publish",
                                      "events": batch})
            except Exception:   # noqa: BLE001 — re-publish next time
                self._dirty = batch + self._dirty
            # state changed (open/advance/close) — checkpoint it for
            # the next mgr; piggybacked here so an idle cluster never
            # writes the key
            self._save_state()

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """Open events, oldest first (exporter + CLI share this)."""
        return sorted((dict(e) for e in self.events.values()),
                      key=lambda e: e["started_at"])

    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix in ("progress", "progress json"):
            return 0, "", {"events": self.snapshot(),
                           "completed": [dict(e)
                                         for e in self.completed]}
        return None

"""Self-tuning data plane — the mgr autotuner that closes the
telemetry→knobs loop.

Everything the controller needs already existed in isolation: the
telemetry spine's device-plane signals (batch occupancy, idle gap,
dispatch-overhead %, rolling launch p99, windowed commit latency),
the SLO harness's violation pressure (``slo ingest`` reports, ringed
per scenario), and the live-retunable knob surface (``osd_batch_*``,
recovery/comp lane equivalents, size-bucket floor,
``osd_wal_sync_mode``, ``osd_mclock_scheduler_*``, scrub/recovery
pacing — all observer-wired, no OSD restart).  This module is the
feedback loop on top (reference shape: mgr modules like ``balancer``
and ``pg_autoscaler`` that continuously actuate cluster state from
observed load).

Design rules, in order of importance:

1. **Deterministic.**  Every decision is a pure function of
   ``(seed, signal trace)`` — the fault-fabric testing pattern.  The
   engine keeps the trace it consumed; replaying it through a fresh
   engine with the same seed reproduces the decision journal
   byte-for-byte (``journal_digest`` is the acceptance hook).  No
   wall-clock, no ambient randomness: logical ticks only.
2. **Guarded.**  One decision in flight at a time.  Each knob has
   hard bounds (inside the Option's declared min/max — the knob lint
   enforces this), a post-decision evaluation window, automatic
   rollback when the objective regresses, a cooldown after every
   move (longer after a rollback), and a per-direction "that hurt"
   memory so a rolled-back move is not retried immediately.
3. **Paxos-free.**  The decision journal lives in the active mgr
   only.  A failover loses it (a fresh engine starts from the
   registry's initial values) — knob state is reconstructable and
   the journal is telemetry, not truth, so it does not rate a
   quorum round-trip.

Actuation rides the existing per-daemon admin sockets: one
``config set`` per OSD per decision, landing in the option observers
each daemon already registers.  Surfaces: ``ceph autotune
status|history|enable|disable|pin|unpin``, the ``ceph iostat`` panel,
and the exporter's ``ceph_autotune_*`` gauges.
"""

from __future__ import annotations

import hashlib
import json

from ..core.admin_socket import admin_command
from .daemon import MgrModule

DEFAULT_SEED = 0xA070


def objective(signals: dict) -> float:
    """The scalar the controller climbs: device-plane throughput plus
    SLO goodput, minus a steep penalty for time-in-violation.  Pure
    arithmetic over the signal dict — replay-stable."""
    osd = signals.get("osd") or {}
    slo = signals.get("slo") or {}
    return (float(osd.get("bytes_per_sec", 0.0)) / 1e6
            + float(slo.get("goodput_ops", 0.0))
            - 100.0 * float(slo.get("pressure", 0.0)))


class Knob:
    """One guarded controller: bounds, step rule, decide() guard.

    ``kind``:
      - ``"ladder"`` — hysteresis hill-climb over a fixed value
        ladder (direction moves one rung);
      - ``"aimd"`` — additive increase (``+ step``), multiplicative
        decrease (``* decrease``), clamped to ``[lo, hi]``.

    ``decide(signals, value)`` → ``(direction, reason)`` or ``None``;
    it must be a pure function of its arguments (determinism)."""

    def __init__(self, name: str, *, decide, cast=float,
                 kind: str = "ladder", ladder=None, initial=None,
                 step: float = 0.0, decrease: float = 0.5,
                 lo=None, hi=None):
        self.name = name
        self.decide = decide
        self.cast = cast
        self.kind = kind
        self.ladder = list(ladder) if ladder is not None else None
        if kind == "ladder":
            if not self.ladder:
                raise ValueError(f"{name}: ladder knob needs a ladder")
            self.lo, self.hi = self.ladder[0], self.ladder[-1]
        else:
            self.lo, self.hi = lo, hi
        self.step = step
        self.decrease = decrease
        self.initial = (initial if initial is not None
                        else (self.ladder[0] if self.ladder else lo))

    def move(self, value, direction: int):
        """One guarded step from ``value``; returns the clamped new
        value (== value when already at the bound)."""
        if self.kind == "ladder":
            try:
                i = self.ladder.index(value)
            except ValueError:
                # pinned/foreign value off the ladder: snap to the
                # nearest rung first (strings compare by position 0)
                i = 0
                if not isinstance(value, str):
                    i = min(range(len(self.ladder)),
                            key=lambda j: abs(self.ladder[j] - value))
            i = max(0, min(len(self.ladder) - 1, i + direction))
            return self.ladder[i]
        if direction > 0:
            new = self.cast(value + self.step)
        else:
            new = self.cast(value * self.decrease)
        if self.lo is not None:
            new = max(self.lo, new)
        if self.hi is not None:
            new = min(self.hi, new)
        return self.cast(new)


# -- decide() guards --------------------------------------------------------
# Each reads the aggregated signal dict:
#   osd: occupancy, idle_gap_s, dispatch_overhead, launch_p99_us,
#        commit_ms, bytes_per_sec, launches_per_sec
#   slo: pressure (windowed time-in-violation rate), goodput_ops,
#        worst_p99_ms
#   degraded: fraction of PGs not active+clean


def _osd(s):
    return s.get("osd") or {}


def _slo(s):
    return s.get("slo") or {}


def _decide_flush(s, v):
    if _slo(s).get("pressure", 0.0) > 0.25 \
            or _osd(s).get("commit_ms", 0.0) > 50.0:
        return -1, "latency pressure: shrink the batch window"
    if _osd(s).get("dispatch_overhead", 0.0) > 0.25 \
            and _slo(s).get("pressure", 0.0) < 0.05:
        return +1, "dispatch-bound: widen the batch window"
    return None


def _decide_ceiling(s, v):
    if _slo(s).get("pressure", 0.0) > 0.25:
        return -1, "latency pressure: lower the batch ceiling"
    if _osd(s).get("occupancy", 1.0) > 0.85 \
            and _osd(s).get("dispatch_overhead", 0.0) > 0.2:
        return +1, "batches run full while dispatch-bound: raise ceiling"
    return None


def _decide_bucket_floor(s, v):
    if _osd(s).get("occupancy", 1.0) < 0.35:
        return -1, "padding waste: lower the size-bucket floor"
    if _osd(s).get("dispatch_overhead", 0.0) > 0.3 \
            and _osd(s).get("launches_per_sec", 0.0) > 50.0:
        return +1, "many small launches: merge size buckets upward"
    return None


def _decide_wal_sync(s, v):
    if _slo(s).get("pressure", 0.0) > 0.2 and v == "always":
        return -1, "violation pressure: group-commit instead of " \
                   "per-op fsync"
    if _slo(s).get("pressure", 0.0) < 0.01 \
            and _osd(s).get("commit_ms", 0.0) < 5.0 \
            and _osd(s).get("bytes_per_sec", 0.0) < 1e5 and \
            v == "batch":
        return +1, "near-idle with headroom: buy per-op durability"
    return None


def _decide_recovery_lim(s, v):
    if s.get("degraded", 0.0) > 0.0 \
            and _slo(s).get("pressure", 0.0) > 0.15:
        return -1, "clients violating during recovery: cut its feed"
    if s.get("degraded", 0.0) > 0.0 \
            and _slo(s).get("pressure", 0.0) < 0.02:
        return +1, "recovery pending, clients healthy: feed it"
    return None


def _decide_scrub_lim(s, v):
    if _slo(s).get("pressure", 0.0) > 0.3:
        return -1, "violation pressure: throttle scrub ops"
    if _slo(s).get("pressure", 0.0) < 0.01 and v < 100.0:
        return +1, "pressure gone: restore scrub budget"
    return None


def _decide_scrub_interval(s, v):
    if _slo(s).get("pressure", 0.0) > 0.3:
        return +1, "violation pressure: defer periodic scrubs"
    if _slo(s).get("pressure", 0.0) < 0.01 and v > 86400.0:
        return -1, "pressure gone: restore the scrub cadence"
    return None


def _decide_recovery_active(s, v):
    if _slo(s).get("pressure", 0.0) > 0.25:
        return -1, "violation pressure: fewer in-flight pushes"
    if s.get("degraded", 0.0) > 0.05 \
            and _slo(s).get("pressure", 0.0) < 0.05:
        return +1, "backlog with client headroom: push harder"
    return None


# The actuation registry — every knob the controller may touch.  The
# knob-registry lint walks this: each name must be a declared Option
# with a live observer (or an explicit live-read waiver), the bounds
# must sit inside the Option's min/max, and ``initial`` must equal
# the Option default (so a disabled autotuner changes nothing).
KNOBS: dict[str, Knob] = {k.name: k for k in (
    Knob("osd_batch_flush_ms", decide=_decide_flush, cast=float,
         ladder=[0.0, 0.5, 1.0, 2.0, 4.0], initial=0.0),
    Knob("osd_batch_max_ops", decide=_decide_ceiling, cast=int,
         ladder=[32, 64, 128, 256, 512], initial=64),
    Knob("osd_batch_max_bytes", decide=_decide_ceiling, cast=int,
         ladder=[2 << 20, 4 << 20, 8 << 20, 16 << 20, 32 << 20],
         initial=8 << 20),
    Knob("osd_recovery_batch_flush_ms", decide=_decide_flush,
         cast=float, ladder=[0.0, 0.5, 1.0, 2.0, 4.0], initial=0.0),
    Knob("osd_recovery_batch_max_ops", decide=_decide_ceiling,
         cast=int, ladder=[32, 64, 128, 256, 512], initial=64),
    Knob("osd_compress_batch_flush_ms", decide=_decide_flush,
         cast=float, ladder=[0.0, 0.5, 1.0, 2.0, 4.0], initial=0.0),
    Knob("osd_compress_batch_max_ops", decide=_decide_ceiling,
         cast=int, ladder=[32, 64, 128, 256, 512], initial=64),
    Knob("osd_batch_bucket_floor", decide=_decide_bucket_floor,
         cast=int, ladder=[32, 64, 128, 256, 512, 1024, 2048, 4096],
         initial=32),
    # durability ladder deliberately excludes "none": the autotuner
    # may trade fsync granularity, never ack-without-durability
    Knob("osd_wal_sync_mode", decide=_decide_wal_sync, cast=str,
         ladder=["batch", "always"], initial="batch"),
    Knob("osd_mclock_scheduler_recovery_lim",
         decide=_decide_recovery_lim, cast=float, kind="aimd",
         step=50.0, decrease=0.5, lo=25.0, hi=2000.0, initial=200.0),
    Knob("osd_mclock_scheduler_scrub_lim", decide=_decide_scrub_lim,
         cast=float, kind="aimd", step=10.0, decrease=0.5, lo=5.0,
         hi=500.0, initial=100.0),
    Knob("osd_scrub_interval", decide=_decide_scrub_interval,
         cast=float, kind="aimd", step=43200.0, decrease=0.5,
         lo=3600.0, hi=1209600.0, initial=86400.0),
    Knob("osd_recovery_max_active", decide=_decide_recovery_active,
         cast=int, ladder=[1, 2, 4, 8, 16], initial=8),
)}


class AutotuneEngine:
    """The seeded decision core — no cluster, no clock, no I/O.

    ``step(signals)`` consumes one tick's aggregated signal dict and
    returns the decisions to actuate (``action`` in ``adjust`` /
    ``rollback``).  The consumed trace and the journal are both
    retained; ``AutotuneEngine(seed)`` re-stepped over the same trace
    emits the identical journal (``journal_digest()``)."""

    EVAL_TICKS = 2          # ticks between a move and its verdict
    COOLDOWN = 4            # ticks a knob rests after a kept move
    ROLLBACK_COOLDOWN = 10  # ticks a knob rests after a rollback
    BAD_DIR_TICKS = 20      # ticks a rolled-back direction is barred
    REGRESS_REL = 0.10      # objective drop fraction that trips rollback
    REGRESS_ABS = 1.0       # ... with this absolute floor
    TRACE_CAP = 4096        # retained signal ticks (journal is smaller)

    def __init__(self, seed: int = DEFAULT_SEED,
                 knobs: dict[str, Knob] | None = None):
        self.seed = int(seed)
        self.knobs = dict(knobs if knobs is not None else KNOBS)
        self.values = {n: k.initial for n, k in self.knobs.items()}
        self.pinned: dict[str, bool] = {}
        self.tick = 0
        self.trace: list[dict] = []
        self.journal: list[dict] = []
        self.decisions_total = 0
        self.rollbacks_total = 0
        self._obj_ema: float | None = None
        self._pending: dict | None = None    # one decision in flight
        self._cooldown_until: dict[str, int] = {}
        self._bad_dir: dict[tuple[str, int], int] = {}

    # -- determinism helpers ------------------------------------------------

    def _scan_start(self, n: int) -> int:
        """Seeded, tick-rotated scan offset: same (seed, tick) ⇒ same
        knob exploration order — the only 'randomness' in the loop."""
        h = (self.seed ^ (self.tick * 0x9E3779B1)) * 0x85EBCA6B
        return (h & 0xFFFFFFFF) % max(1, n)

    def journal_digest(self) -> str:
        blob = json.dumps(self.journal, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    # -- control-plane state (not journaled: pins are operator intent,
    #    decisions are controller output) -----------------------------------

    def pin(self, knob: str, value=None):
        if knob not in self.knobs:
            raise KeyError(knob)
        self.pinned[knob] = True
        if value is not None:
            k = self.knobs[knob]
            v = k.cast(value)
            if k.lo is not None and not isinstance(v, str):
                v = max(k.lo, min(k.hi, v))
            self.values[knob] = v
        return self.values[knob]

    def unpin(self, knob: str):
        self.pinned.pop(knob, None)

    # -- the loop ------------------------------------------------------------

    def step(self, signals: dict) -> list[dict]:
        """One logical tick.  Returns journal entries that need
        actuation (adjust/rollback); commit entries are bookkeeping."""
        # JSON round-trip: the retained trace is exactly what a
        # replayer will feed back, so replay floats are bit-identical
        sig = json.loads(json.dumps(signals, sort_keys=True))
        self.tick += 1
        self.trace.append(sig)
        if len(self.trace) > self.TRACE_CAP:
            del self.trace[:len(self.trace) - self.TRACE_CAP]
        obj = objective(sig)
        self._obj_ema = (obj if self._obj_ema is None
                         else 0.5 * self._obj_ema + 0.5 * obj)
        out: list[dict] = []
        verdict = self._evaluate_pending(obj)
        if verdict is not None:
            out.append(verdict)
        if self._pending is None:
            adj = self._consider(sig, obj)
            if adj is not None:
                out.append(adj)
        return out

    def _journal(self, entry: dict) -> dict:
        entry["seq"] = len(self.journal)
        entry["tick"] = self.tick
        self.journal.append(entry)
        return entry

    def _evaluate_pending(self, obj: float) -> dict | None:
        p = self._pending
        if p is None or self.tick < p["eval_at"]:
            return None
        self._pending = None
        knob, old, new = p["knob"], p["old"], p["new"]
        before = p["obj_before"]
        bar = before - max(self.REGRESS_ABS,
                           self.REGRESS_REL * abs(before))
        if self._obj_ema < bar:
            self.values[knob] = old
            self._cooldown_until[knob] = \
                self.tick + self.ROLLBACK_COOLDOWN
            self._bad_dir[(knob, p["dir"])] = \
                self.tick + self.BAD_DIR_TICKS
            self.rollbacks_total += 1
            return self._journal({
                "action": "rollback", "knob": knob,
                "old": new, "new": old, "dir": -p["dir"],
                "objective_before": before, "objective": self._obj_ema,
                "reason": "objective regressed past tolerance"})
        self._cooldown_until[knob] = self.tick + self.COOLDOWN
        self._journal({
            "action": "commit", "knob": knob, "value": new,
            "objective_before": before, "objective": self._obj_ema})
        return None     # commits change no knob: nothing to actuate

    def _consider(self, sig: dict, obj: float) -> dict | None:
        names = sorted(self.knobs)
        start = self._scan_start(len(names))
        for i in range(len(names)):
            name = names[(start + i) % len(names)]
            if self.pinned.get(name):
                continue
            if self.tick < self._cooldown_until.get(name, 0):
                continue
            knob = self.knobs[name]
            got = knob.decide(sig, self.values[name])
            if got is None:
                continue
            direction, reason = got
            if self.tick < self._bad_dir.get((name, direction), 0):
                continue
            old = self.values[name]
            new = knob.move(old, direction)
            if new == old:
                continue        # already at the bound
            self.values[name] = new
            self.decisions_total += 1
            self._pending = {
                "knob": name, "old": old, "new": new,
                "dir": direction, "obj_before": self._obj_ema,
                "eval_at": self.tick + self.EVAL_TICKS}
            return self._journal({
                "action": "adjust", "knob": name, "old": old,
                "new": new, "dir": direction, "reason": reason,
                "objective": self._obj_ema})
        return None

    # -- replay (the fault-fabric acceptance hook) ---------------------------

    @classmethod
    def replay(cls, seed: int, trace: list[dict],
               knobs: dict[str, Knob] | None = None) -> "AutotuneEngine":
        """Fresh engine stepped over a recorded signal trace; its
        journal is byte-identical to the recorder's."""
        eng = cls(seed=seed, knobs=knobs)
        for sig in trace:
            eng.step(sig)
        return eng


class AutotuneModule(MgrModule):
    """The mgr host: gathers signals from the telemetry spine, steps
    the engine, actuates decisions over the per-OSD admin sockets.
    Ships disabled — ``ceph autotune enable`` arms it."""

    NAME = "autotune"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self.engine = AutotuneEngine()
        self.enabled = False
        self.applied: dict[str, object] = {}
        self.apply_errors = 0

    # -- signal aggregation --------------------------------------------------

    def _gather(self) -> dict | None:
        spine = self.ctx._d.modules.get("telemetry_spine")
        if spine is None:
            return None
        osds = sorted(d for d in (set(spine.series)
                                  | set(spine.profiler))
                      if d.startswith("osd."))
        if not osds:
            return None
        occ = gap = dov = 0.0
        p99 = commit = bps = lps = 0.0
        for d in osds:
            dev = spine.device_summary(d)
            occ += float(dev.get("occupancy_ratio", 1.0))
            gap += float(dev.get("idle_gap_avg_s", 0.0))
            dov += float(dev.get("dispatch_overhead_ratio", 0.0))
            p99 = max(p99, float(dev.get("p99_us", 0.0)))
            commit = max(commit, spine.commit_latency_ms(d))
            rates = spine.daemon_rates(d)
            bps += float(rates.get("bytes_per_sec", 0.0))
            lps += float(rates.get("launches_per_sec", 0.0))
        n = float(len(osds))
        pressure = (spine.slo_pressure()
                    if hasattr(spine, "slo_pressure") else {})
        degraded = 0.0
        try:
            rc, _, st = self.ctx.mon_command({"prefix": "status"})
            if rc == 0 and st:
                states = st.get("pg_states") or {}
                total = float(sum(states.values()) or 0)
                clean = float(states.get("active+clean", 0))
                degraded = ((total - clean) / total) if total else 0.0
        except Exception:   # noqa: BLE001 — mon churn: signal stays 0
            pass
        return {
            "osd": {
                "occupancy": occ / n, "idle_gap_s": gap / n,
                "dispatch_overhead": dov / n, "launch_p99_us": p99,
                "commit_ms": commit, "bytes_per_sec": bps,
                "launches_per_sec": lps,
            },
            "slo": {
                "pressure": float(pressure.get("pressure", 0.0)),
                "goodput_ops": float(pressure.get("goodput_ops", 0.0)),
                "worst_p99_ms": float(pressure.get("worst_p99_ms",
                                                   0.0)),
            },
            "degraded": degraded,
        }

    # -- actuation -----------------------------------------------------------

    def _apply(self, knob: str, value):
        """One ``config set`` per OSD admin socket; the daemons' own
        option observers do the live retune."""
        for daemon, path in sorted(self.ctx._d.asok_paths.items()):
            if not daemon.startswith("osd."):
                continue
            try:
                admin_command(path, "config set", timeout=5.0,
                              key=knob, value=value)
            except Exception:   # noqa: BLE001 — daemon down: next tick
                self.apply_errors += 1
        self.applied[knob] = value

    def serve_tick(self):
        if not self.enabled:
            return
        signals = self._gather()
        if signals is None:
            return
        for dec in self.engine.step(signals):
            if dec.get("action") in ("adjust", "rollback"):
                self._apply(dec["knob"], dec["new"])

    # -- surfaces ------------------------------------------------------------

    def status(self) -> dict:
        eng = self.engine
        knobs = {}
        for name in sorted(eng.knobs):
            k = eng.knobs[name]
            last = next((e for e in reversed(eng.journal)
                         if e.get("knob") == name), None)
            knobs[name] = {
                "value": eng.values[name],
                "lo": k.lo, "hi": k.hi, "kind": k.kind,
                "pinned": bool(eng.pinned.get(name)),
                "cooldown_ticks": max(
                    0, eng._cooldown_until.get(name, 0) - eng.tick),
                "last_action": (last or {}).get("action"),
            }
        return {
            "enabled": self.enabled, "seed": eng.seed,
            "tick": eng.tick,
            "decisions_total": eng.decisions_total,
            "rollbacks_total": eng.rollbacks_total,
            "apply_errors": self.apply_errors,
            "journal_digest": eng.journal_digest(),
            "knobs": knobs,
        }

    def export_view(self) -> dict:
        """What the prometheus exporter consumes."""
        return {
            "enabled": self.enabled,
            "decisions_total": self.engine.decisions_total,
            "rollbacks_total": self.engine.rollbacks_total,
            "knobs": dict(self.engine.values),
        }

    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if not prefix.startswith("autotune"):
            return None
        verb = (prefix.split(maxsplit=1)[1:] or ["status"])[0]
        if verb == "status":
            return 0, "", self.status()
        if verb == "history":
            n = int(cmd.get("count") or 0)
            decisions = (self.engine.journal[-n:] if n
                         else list(self.engine.journal))
            out = {"seed": self.engine.seed,
                   "decisions": decisions,
                   "decisions_total": self.engine.decisions_total,
                   "rollbacks_total": self.engine.rollbacks_total,
                   "journal_digest": self.engine.journal_digest()}
            if cmd.get("trace"):
                out["trace"] = list(self.engine.trace)
            return 0, "", out
        if verb == "enable":
            if "seed" in cmd:
                self.engine = AutotuneEngine(seed=int(cmd["seed"]))
                self.applied.clear()
            self.enabled = True
            return 0, "", {"enabled": True, "seed": self.engine.seed}
        if verb == "disable":
            self.enabled = False
            return 0, "", {"enabled": False}
        if verb in ("pin", "unpin"):
            knob = cmd.get("knob")
            if not knob or knob not in self.engine.knobs:
                return -22, "", f"autotune {verb} needs a known knob " \
                                f"(got {knob!r})"
            if verb == "unpin":
                self.engine.unpin(knob)
                return 0, "", {"knob": knob, "pinned": False}
            try:
                v = self.engine.pin(knob, cmd.get("value"))
            except (TypeError, ValueError) as e:
                return -22, "", f"autotune pin: bad value: {e}"
            if cmd.get("value") is not None:
                self._apply(knob, v)
            return 0, "", {"knob": knob, "pinned": True, "value": v}
        return -22, "", ("usage: autotune status|history|enable"
                         "|disable|pin <knob> [value]|unpin <knob>")

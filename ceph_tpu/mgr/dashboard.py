"""mgr dashboard — operational web UI + REST API.

Reference behavior re-created (``src/pybind/mgr/dashboard``; SURVEY.md
§3.10): the REST controllers plus a self-contained operational
frontend (the reference ships an Angular app; here a single
server-rendered page with auto-refreshing panels fetches the same API
— the API shape and the operator workflows are the parity surface):

- ``GET /api/health``      → health status + checks
- ``GET /api/summary``     → the `ceph -s` aggregate
- ``GET /api/osd``         → per-OSD rows (up/in, pgs, usage)
- ``GET /api/osd/tree``    → the CRUSH tree
- ``GET /api/pool``        → per-pool rows (pg_num, objects, bytes)
- ``GET /api/pg``          → pg state counts
- ``GET /api/mon``         → quorum / leader
- ``GET /api/mgr``         → active + standbys
- ``GET /api/fs``          → filesystems + MDS ranks
- ``GET /api/log``         → recent cluster log
- ``GET /api/crash``       → archived crash reports
- ``GET /api/device``      → device health verdicts (devicehealth)
- ``GET /api/rbd/task``    → background task queue (rbd_support)
- ``GET /api/orch``        → declared services (orchestrator)
- ``GET /``                → the dashboard page

Runs on the ACTIVE mgr like the prometheus exporter; standbys don't
bind (reference: the dashboard fails over with the active mgr).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .daemon import MgrModule


class DashboardModule(MgrModule):
    NAME = "dashboard"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        module = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body: bytes,
                       ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    route = self.path.split("?", 1)[0].rstrip("/")
                    if route == "":
                        return self._reply(
                            200, module.render_html().encode(),
                            ctype="text/html")
                    if route.startswith("/api/"):
                        out = module.api(route[len("/api/"):])
                        if out is None:
                            return self._reply(
                                404, b'{"error": "no such route"}')
                        return self._reply(200, json.dumps(
                            out, default=str).encode())
                    return self._reply(404, b"not found",
                                       ctype="text/plain")
                except Exception as e:   # noqa: BLE001 — a mon
                    # hiccup must return 503, not kill the server
                    return self._reply(503, json.dumps(
                        {"error": repr(e)}).encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="mgr-dashboard",
            daemon=True)
        self._thread.start()

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- data --------------------------------------------------------------
    def _status(self) -> dict:
        rc, _, st = self.ctx.mon_command({"prefix": "status"})
        return st if rc == 0 and st else {}

    def _mon(self, cmd: str) -> dict | list:
        rc, _, out = self.ctx.mon_command({"prefix": cmd})
        return out if rc == 0 and out is not None else {}

    def _sibling(self, name: str):
        """Another module hosted by this mgr (shared instances)."""
        return self.ctx._d.modules.get(name)

    def api(self, route: str):
        if route == "health":
            st = self._status()
            return {"status": st.get("health"),
                    "checks": st.get("checks", [])}
        if route == "summary":
            return self._status()
        if route == "osd":
            out = self._mon("osd df")
            return out.get("nodes", []) if isinstance(out, dict) \
                else []
        if route == "osd/tree":
            return self._mon("osd tree")
        if route == "pool":
            out = self._mon("df")
            return out.get("pools", []) if isinstance(out, dict) \
                else []
        if route == "pg":
            st = self._status()
            return {"num_pgs": st.get("num_pgs", 0),
                    "states": st.get("pg_states", {})}
        if route == "mon":
            st = self._status()
            return {"quorum": st.get("quorum"),
                    "leader": st.get("leader")}
        if route == "mgr":
            return self._mon("mgr dump")
        if route == "fs":
            return self._mon("fs dump")
        if route == "log":
            rc, _, entries = self.ctx.mon_command(
                {"prefix": "log last", "num": 20})
            return entries if rc == 0 else []
        if route == "crash":
            mod = self._sibling("crash")
            if mod is None:
                from .modules import CrashModule
                mod = CrashModule(self.ctx)
            return mod.ls()
        if route == "device":
            # the module's LAST verdicts — a dashboard poll must not
            # trigger scrapes, config-key writes, or clog warnings
            mod = self._sibling("devicehealth")
            return mod.last_verdicts() if mod is not None else []
        if route == "rbd/task":
            mod = self._sibling("rbd_support")
            if mod is None:
                return []
            res = mod.handle_command({"prefix": "rbd task list"})
            return res[2] if res else []
        if route == "orch":
            mod = self._sibling("orchestrator")
            if mod is None:
                return []
            res = mod.handle_command({"prefix": "orch ls"})
            return res[2] if res else []
        return None

    # -- frontend ----------------------------------------------------------
    def render_html(self) -> str:
        """One self-contained page: server renders the shell, a small
        script polls the API and fills the panels (the reference's
        Angular SPA, minus the build system)."""
        return """<!doctype html><html><head>
<title>ceph_tpu dashboard</title>
<style>
body{font-family:system-ui,sans-serif;margin:0;background:#f4f5f7}
header{background:#24303c;color:#fff;padding:10px 16px}
header b{font-size:1.1em}
#health{padding:2px 10px;border-radius:10px;margin-left:10px}
.ok{background:#0a6b2c}.warn{background:#a87000}.err{background:#a00}
main{display:grid;grid-template-columns:repeat(auto-fit,minmax(340px,
1fr));gap:12px;padding:12px}
section{background:#fff;border-radius:6px;padding:10px 14px;
box-shadow:0 1px 3px rgba(0,0,0,.15)}
h2{font-size:.95em;margin:2px 0 8px;color:#445}
table{border-collapse:collapse;width:100%;font-size:.85em}
td,th{text-align:left;padding:2px 8px 2px 0;border-bottom:1px solid
#eee}
#log td{font-family:monospace;font-size:.8em}
.muted{color:#888}
</style></head><body>
<header><b>ceph_tpu</b> dashboard
<span id="health" class="ok">...</span>
<span id="svc" class="muted"></span></header>
<main>
<section><h2>Health checks</h2><ul id="checks"></ul></section>
<section><h2>PGs</h2><div id="pgs"></div></section>
<section><h2>OSDs</h2><table id="osds"><thead><tr><th>id</th>
<th>status</th><th>pgs</th><th>ops</th></tr></thead>
<tbody></tbody></table></section>
<section><h2>Pools</h2><table id="pools"><thead><tr><th>pool</th>
<th>objects</th><th>bytes</th></tr></thead>
<tbody></tbody></table></section>
<section><h2>Filesystems</h2><div id="fs"></div></section>
<section><h2>Devices</h2><table id="devices"><thead><tr>
<th>device</th><th>osd</th><th>verdict</th></tr></thead>
<tbody></tbody></table></section>
<section><h2>Orchestrator services</h2><table id="orch"><thead><tr>
<th>service</th><th>target</th><th>running</th></tr></thead>
<tbody></tbody></table></section>
<section><h2>RBD tasks</h2><table id="tasks"><thead><tr><th>id</th>
<th>task</th><th>image</th><th>status</th></tr></thead>
<tbody></tbody></table></section>
<section style="grid-column:1/-1"><h2>Cluster log</h2>
<table id="log"><tbody></tbody></table></section>
</main>
<script>
async function j(r){const x=await fetch('/api/'+r);
  return x.ok?x.json():null}
function esc(v){return String(v??'').replace(/[&<>"']/g,
  c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',
       "'":'&#39;'}[c]))}
function rows(el,data,f){const b=document.querySelector(el+' tbody');
  if(!b||!data)return;b.innerHTML=data.map(f).join('')}
async function refresh(){
  const s=await j('summary');if(s){
    const h=document.getElementById('health');
    h.textContent=s.health||'?';
    h.className=s.health==='HEALTH_OK'?'ok':
      (s.health==='HEALTH_WARN'?'warn':'err');
    document.getElementById('svc').textContent=
      ' mon quorum '+JSON.stringify(s.quorum)+' | osd '+
      s.num_up_osds+'/'+s.num_osds+' up | '+
      (s.pools?s.pools.length:0)+' pools | '+
      s.num_objects+' objects';
    document.getElementById('checks').innerHTML=
      (s.checks&&s.checks.length)?s.checks.map(c=>'<li>'+
        esc(c.code)+': '+esc(c.summary)+'</li>').join(''):
        '<li class="muted">none</li>';
    const pg=await j('pg');
    document.getElementById('pgs').textContent=
      pg?pg.num_pgs+' pgs: '+Object.entries(pg.states||{}).map(
        ([k,v])=>v+' '+k).join(', '):'';}
  rows('#osds',await j('osd'),n=>'<tr><td>osd.'+esc(n.osd)+
    '</td><td>'+(n.up?'up':'down')+'</td><td>'+esc(n.num_pgs)+
    '</td><td>'+esc(n.ops)+'</td></tr>');
  rows('#pools',await j('pool'),p=>'<tr><td>'+esc(p.name)+
    '</td><td>'+esc(p.objects)+'</td><td>'+esc(p.bytes_used)+
    '</td></tr>');
  const fs=await j('fs');
  document.getElementById('fs').textContent=
    fs&&fs.filesystems?Object.values(fs.filesystems).map(
      f=>f.name+' (max_mds '+f.max_mds+')').join(', ')||'none':
      'none';
  rows('#devices',await j('device'),d=>'<tr><td>'+esc(d.devid)+
    '</td><td>'+esc(d.osd)+'</td><td>'+esc(d.life_expectancy)+
    '</td></tr>');
  rows('#orch',await j('orch'),s=>'<tr><td>'+esc(s.service_type)+
    '</td><td>'+esc(s.count)+'</td><td>'+esc(s.running)+
    '</td></tr>');
  rows('#tasks',await j('rbd/task'),t=>'<tr><td>'+esc(t.id)+
    '</td><td>'+esc(t.task)+'</td><td>'+esc(t.image)+'</td><td>'+
    esc(t.status)+'</td></tr>');
  rows('#log',await j('log'),e=>'<tr><td>'+
    new Date(e.stamp*1000).toISOString()+' '+esc(e.text)+
    '</td></tr>');
}
refresh();setInterval(refresh,3000);
</script></body></html>"""

"""mgr dashboard — REST API + HTML cluster status page.

Reference behavior re-created (``src/pybind/mgr/dashboard``; SURVEY.md
§3.10), reduced to the read-side REST controllers and a single status
page (the reference's Angular frontend is out of scope — the API
shape is the parity surface):

- ``GET /api/health``      → health status + checks
- ``GET /api/summary``     → the `ceph -s` aggregate
- ``GET /api/osd``         → per-OSD rows (up/in, pgs, ops)
- ``GET /api/pool``        → per-pool rows (pg_num, objects, bytes)
- ``GET /api/pg``          → pg state counts
- ``GET /api/crash``       → archived crash reports
- ``GET /``                → minimal HTML status page

Runs on the ACTIVE mgr like the prometheus exporter; standbys don't
bind (reference: the dashboard fails over with the active mgr).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .daemon import MgrModule


class DashboardModule(MgrModule):
    NAME = "dashboard"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        module = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code, body: bytes,
                       ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    route = self.path.split("?", 1)[0].rstrip("/")
                    if route == "":
                        return self._reply(
                            200, module.render_html().encode(),
                            ctype="text/html")
                    if route.startswith("/api/"):
                        out = module.api(route[len("/api/"):])
                        if out is None:
                            return self._reply(
                                404, b'{"error": "no such route"}')
                        return self._reply(200, json.dumps(
                            out, default=str).encode())
                    return self._reply(404, b"not found",
                                       ctype="text/plain")
                except Exception as e:   # noqa: BLE001 — a mon
                    # hiccup must return 503, not kill the server
                    return self._reply(503, json.dumps(
                        {"error": repr(e)}).encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="mgr-dashboard",
            daemon=True)
        self._thread.start()

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    # -- data --------------------------------------------------------------
    def _status(self) -> dict:
        rc, _, st = self.ctx.mon_command({"prefix": "status"})
        return st if rc == 0 and st else {}

    def api(self, route: str):
        if route == "health":
            st = self._status()
            return {"status": st.get("health"),
                    "checks": st.get("checks", [])}
        if route == "summary":
            return self._status()
        if route == "osd":
            rc, _, dump = self.ctx.mon_command({"prefix": "osd df"})
            return dump.get("nodes", []) if rc == 0 and dump else []
        if route == "pool":
            rc, _, df = self.ctx.mon_command({"prefix": "df"})
            return df.get("pools", []) if rc == 0 and df else []
        if route == "pg":
            st = self._status()
            return {"num_pgs": st.get("num_pgs", 0),
                    "states": st.get("pg_states", {})}
        if route == "crash":
            # reuse the daemon's registered crash module (it shares
            # this module host) rather than wiring a second instance
            mod = self.ctx._d.modules.get("crash")
            if mod is None:
                from .modules import CrashModule
                mod = CrashModule(self.ctx)
            return mod.ls()
        return None

    def render_html(self) -> str:
        st = self._status()
        checks = "".join(
            f"<li>{c['code']}: {c['summary']}</li>"
            for c in st.get("checks", []))
        pgs = ", ".join(f"{n} {s}" for s, n in
                        sorted(st.get("pg_states", {}).items()))
        color = {"HEALTH_OK": "#0a0", "HEALTH_WARN": "#a80",
                 "HEALTH_ERR": "#a00"}.get(st.get("health"), "#888")
        return f"""<!doctype html><html><head>
<title>ceph_tpu dashboard</title></head><body>
<h1>Cluster status</h1>
<p>Health: <b style="color:{color}">{st.get('health', '?')}</b></p>
<ul>{checks}</ul>
<p>mon quorum {st.get('quorum')} &middot;
osd {st.get('num_up_osds')}/{st.get('num_osds')} up &middot;
{len(st.get('pools', []))} pools &middot;
{st.get('num_objects')} objects</p>
<p>pgs: {pgs}</p>
<p>API: /api/health /api/summary /api/osd /api/pool /api/pg
/api/crash</p>
</body></html>"""

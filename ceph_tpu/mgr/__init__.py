"""Management plane (reference ``src/mgr`` + ``src/pybind/mgr`` —
SURVEY.md §3.10): Python modules that observe cluster maps and steer
them through mon commands.  First resident: the upmap balancer."""

from .balancer import UpmapBalancer  # noqa: F401
from .exporter import Exporter, ExporterService  # noqa: F401

"""Management plane (reference ``src/mgr`` + ``src/pybind/mgr`` —
SURVEY.md §3.10): the active/standby mgr daemon hosts modules that
observe cluster maps and steer them through mon commands — the upmap
balancer, the pg_autoscaler, and the prometheus exporter."""

from .balancer import UpmapBalancer  # noqa: F401
from .daemon import (BalancerModule, MgrDaemon, MgrModule,  # noqa: F401
                     PgAutoscalerModule, PrometheusModule)
from .exporter import Exporter, ExporterService  # noqa: F401

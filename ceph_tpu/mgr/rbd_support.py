"""mgr rbd_support — background RBD task queue + snapshot schedules.

Reference behavior re-created (``src/pybind/mgr/rbd_support``;
SURVEY.md §3.10): long-running image maintenance (flatten, remove,
migration execute) is queued with ``rbd task add ...`` and executed by
the module's worker so clients don't block; ``rbd snapshot schedule``
takes periodic snapshots of an image.  State (queue + schedules)
lives in the mon config-key store and survives mgr failover.

Commands (via the mgr command server):
- ``rbd task add`` {task: flatten|remove|migration execute,
  image: pool/name} — enqueue
- ``rbd task list`` — queue with statuses
- ``rbd snapshot schedule add`` {image, interval} / ``remove`` /
  ``list``
"""

from __future__ import annotations

import json
import threading
import time

from .daemon import MgrModule

TASKS_KEY = "rbd_support/tasks"
SCHED_KEY = "rbd_support/schedules"
TASK_KINDS = ("flatten", "remove", "migration execute")


class RbdSupportModule(MgrModule):
    NAME = "rbd_support"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self._rados = None
        self._lock = threading.Lock()
        self._worker: threading.Thread | None = None
        self._kick = threading.Event()
        self._stop = False
        self._last_snap: dict[str, float] = {}

    # -- persistence -------------------------------------------------------
    def _load(self, key: str) -> list[dict]:
        rc, _, blob = self.ctx.mon_command(
            {"prefix": "config-key get", "key": key})
        return json.loads(blob) if rc == 0 and blob else []

    def _store(self, key: str, rows: list[dict]):
        self.ctx.mon_command({"prefix": "config-key put", "key": key,
                              "val": json.dumps(rows)})

    # -- worker ------------------------------------------------------------
    def _get_rados(self):
        if self._rados is None:
            from ..osdc.librados import Rados
            d = self.ctx._d
            self._rados = Rados(
                d.monmap, name=f"client.rbd-support-{d.name}",
                auth=getattr(d, "auth", None)).connect()
        return self._rados

    def _split_image(self, spec: str):
        pool, _, image = spec.partition("/")
        if not pool or not image:
            raise ValueError(f"image must be pool/name, got {spec!r}")
        return pool, image

    def _run_task(self, task: dict):
        from ..rbd import RBD, Image
        pool, image = self._split_image(task["image"])
        io = self._get_rados().open_ioctx(pool)
        rbd = RBD()
        kind = task["task"]
        if kind == "flatten":
            with Image(io, image) as im:
                im.flatten()
        elif kind == "remove":
            from ..rbd import ImageNotFound
            try:
                rbd.remove(io, image)
            except ImageNotFound:
                if not task.get("_adopted"):
                    raise
                # an adopted (failover-requeued) remove may find the
                # image already gone: the task succeeded
        elif kind == "migration execute":
            while rbd.migration_execute(io, image):
                pass
        else:
            raise ValueError(f"unknown task kind {kind!r}")

    def _worker_loop(self):
        while not self._stop:
            self._kick.wait(timeout=1.0)
            self._kick.clear()
            if self._stop:
                return
            with self._lock:
                tasks = self._load(TASKS_KEY)
                # "running" tasks are adopted too: they were in
                # flight when a previous active mgr died and nothing
                # else will ever finish them (single worker, so no
                # double-execution within one mgr)
                pending = [t for t in tasks
                           if t["status"] in ("pending", "running")]
            for task in pending:
                task["_adopted"] = task["status"] == "running"
                task["status"] = "running"
                self._update_task(task)
                try:
                    self._run_task(task)
                    task["status"] = "complete"
                except Exception as e:      # noqa: BLE001
                    task["status"] = "failed"
                    task["error"] = str(e)[:200]
                task.pop("_adopted", None)
                self._update_task(task)
            self._snapshot_pass()

    def _update_task(self, task: dict):
        with self._lock:
            tasks = self._load(TASKS_KEY)
            for i, t in enumerate(tasks):
                if t["id"] == task["id"]:
                    tasks[i] = task
                    break
            self._store(TASKS_KEY, tasks)

    def _snapshot_pass(self):
        from ..rbd import Image
        now = time.time()
        for sched in self._load(SCHED_KEY):
            spec = sched["image"]
            last = self._last_snap.get(spec, 0.0)
            if now - last < float(sched["interval"]):
                continue
            try:
                pool, image = self._split_image(spec)
                io = self._get_rados().open_ioctx(pool)
                with Image(io, image) as im:
                    im.create_snap(
                        f"scheduled-{int(now)}")
                self._last_snap[spec] = now
            except Exception:   # noqa: BLE001 — retried next pass
                pass

    def _kick_worker(self):
        # check-and-start under the lock: the tick thread and the
        # command-dispatch thread both call this, and two workers
        # would run the same task twice
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._worker_loop, name="rbd-support",
                    daemon=True)
                self._worker.start()
        self._kick.set()

    # -- commands ----------------------------------------------------------
    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix == "rbd task add":
            kind = cmd.get("task")
            if kind not in TASK_KINDS:
                return (-22, f"unknown task {kind!r} (supported: "
                             f"{', '.join(TASK_KINDS)})", None)
            try:
                self._split_image(cmd.get("image", ""))
            except ValueError as e:
                return -22, str(e), None
            with self._lock:
                tasks = self._load(TASKS_KEY)
                task = {"id": (max((t["id"] for t in tasks),
                                   default=0) + 1),
                        "task": kind, "image": cmd["image"],
                        "status": "pending",
                        "created": time.time()}
                tasks.append(task)
                self._store(TASKS_KEY, tasks)
            self._kick_worker()
            return 0, f"queued task {task['id']}", task
        if prefix == "rbd task list":
            return 0, "", self._load(TASKS_KEY)
        if prefix == "rbd snapshot schedule add":
            import math
            try:
                self._split_image(cmd.get("image", ""))
                interval = float(cmd["interval"])
            except (ValueError, KeyError, TypeError) as e:
                return -22, f"bad schedule: {e}", None
            if not math.isfinite(interval) or interval <= 0:
                return -22, "interval must be a positive number", None
            with self._lock:
                scheds = [s for s in self._load(SCHED_KEY)
                          if s["image"] != cmd["image"]]
                scheds.append({"image": cmd["image"],
                               "interval": interval})
                self._store(SCHED_KEY, scheds)
            self._kick_worker()
            return 0, "schedule added", None
        if prefix == "rbd snapshot schedule remove":
            with self._lock:
                scheds = [s for s in self._load(SCHED_KEY)
                          if s["image"] != cmd.get("image")]
                self._store(SCHED_KEY, scheds)
            return 0, "schedule removed", None
        if prefix == "rbd snapshot schedule list":
            return 0, "", self._load(SCHED_KEY)
        return None

    def serve_tick(self):
        self._kick_worker()

    def shutdown(self):
        self._stop = True
        self._kick.set()
        if self._worker is not None:
            self._worker.join(timeout=5)
        if self._rados is not None:
            try:
                self._rados.shutdown()
            except Exception:   # noqa: BLE001
                pass
            self._rados = None

"""mgr orchestrator — declarative service specs reconciled into the
deployer.

Reference behavior re-created (``src/pybind/mgr/orchestrator/`` +
``src/pybind/mgr/cephadm/``; SURVEY.md §3.10): ``ceph orch apply``
declares a service's desired shape, the module persists the spec in
the mon's config-key store and continuously reconciles reality toward
it through a deployment backend; ``ceph orch ls`` shows declared vs
running, ``ceph orch ps`` lists daemons.  The command transport is the
mgr's own command server (reference DaemonServer), reached via the
mgrmap's active_addr — exactly the `ceph orch` → mon → mgr → cephadm
round trip, minus the ssh/container layer (our deployment unit is the
in-process daemon, as in ``tools/cephadm.py``).

Spec shape: ``{"service_type": "mds"|"rgw"|"osd", "count": N}``.
Orchestrator-managed daemons are named ``orch-<type>-<i>`` so
reconciliation only ever removes what it created.
"""

from __future__ import annotations

import json
import threading

from .daemon import MgrModule

SPEC_PREFIX = "orch/spec/"          # config-key namespace
MANAGED = ("mds", "rgw", "osd")


class OrchestratorModule(MgrModule):
    NAME = "orchestrator"
    TICK = 1.0

    def __init__(self, ctx):
        super().__init__(ctx)
        # the deployment backend (reference: the cephadm module's ssh
        # connection pool; here: a MiniCluster wrapper) is injected by
        # whoever owns the deployment — no backend ⇒ specs are stored
        # and listed but reconciliation reports itself paused
        self.backend = getattr(ctx._d, "orch_backend", None)
        self._specs: dict[str, dict] | None = None
        # deploys run on a dedicated worker (reference: the cephadm
        # module's serve thread): starting an OSD/MDS blocks for
        # seconds, which must stall neither the mgr tick loop (beacon
        # starvation ⇒ spurious failover) nor the command server.
        # _rec_lock serializes reconciles so a command-triggered pass
        # can't double-deploy against the worker's
        self._rec_lock = threading.Lock()
        self._kick = threading.Event()
        self._stop = False
        self._worker: threading.Thread | None = None

    # -- spec store (mon config-key; survives mgr failover) ----------------
    def _load_specs(self) -> dict[str, dict]:
        if self._specs is None:
            specs = {}
            rc, _, keys = self.ctx.mon_command(
                {"prefix": "config-key ls"})
            for k in (keys or []) if rc == 0 else []:
                if not k.startswith(SPEC_PREFIX):
                    continue
                rc2, _, val = self.ctx.mon_command(
                    {"prefix": "config-key get", "key": k})
                if rc2 == 0 and val:
                    specs[k[len(SPEC_PREFIX):]] = json.loads(val)
            self._specs = specs
        return self._specs

    def _store_spec(self, stype: str, spec: dict):
        self.ctx.mon_command({
            "prefix": "config-key put",
            "key": f"{SPEC_PREFIX}{stype}",
            "val": json.dumps(spec)})
        self._load_specs()[stype] = spec

    def _drop_spec(self, stype: str):
        self.ctx.mon_command({
            "prefix": "config-key del",
            "key": f"{SPEC_PREFIX}{stype}"})
        self._load_specs().pop(stype, None)

    # -- command surface (reference `ceph orch ...`) -----------------------
    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix == "orch apply":
            stype = cmd.get("service_type")
            if stype not in MANAGED:
                return (-22, f"unsupported service_type {stype!r} "
                             f"(supported: {', '.join(MANAGED)})",
                        None)
            try:
                count = int(cmd.get("count", 1))
            except (TypeError, ValueError):
                return -22, "count must be an integer", None
            if count < 0:
                return -22, "count must be >= 0", None
            spec = {"service_type": stype, "count": count}
            self._store_spec(stype, spec)
            self._kick_worker()
            return 0, f"Scheduled {stype} update: count {count}" + \
                ("" if self.backend is not None
                 else " (no backend: deferred)"), spec
        if prefix == "orch ls":
            out = []
            for stype, spec in sorted(self._load_specs().items()):
                out.append({
                    "service_type": stype,
                    "count": spec.get("count", 0),
                    "running": self._running_count(stype),
                })
            return 0, "", out
        if prefix == "orch ps":
            if self.backend is None:
                return 0, "no backend attached", []
            return 0, "", self.backend.daemon_inventory()
        if prefix == "orch rm":
            stype = cmd.get("service_type")
            if stype not in self._load_specs():
                return -2, f"no spec for {stype!r}", None
            self._drop_spec(stype)
            return 0, f"Removed service spec {stype}", None
        return None

    # -- reconciliation ----------------------------------------------------
    def _running_count(self, stype: str) -> int:
        if self.backend is None:
            return 0
        return sum(1 for d in self.backend.daemon_inventory()
                   if d["type"] == stype)

    def _reconcile(self) -> bool:
        """Move reality toward the declared specs; → False when no
        backend is attached (specs stay pending)."""
        if self.backend is None:
            return False
        with self._rec_lock:
            # snapshot: handle_command (messenger thread) mutates the
            # spec dict mid-pass, and a changed-size RuntimeError
            # would kill the worker outside the per-spec try
            for stype, spec in list(self._load_specs().items()):
                try:
                    self.backend.ensure(stype,
                                        int(spec.get("count", 0)))
                except Exception:   # noqa: BLE001 — retried next pass
                    pass
        return True

    def _kick_worker(self):
        if self.backend is None:
            return
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, name="orch-reconcile",
                daemon=True)
            self._worker.start()
        self._kick.set()

    def _worker_loop(self):
        while not self._stop:
            self._kick.wait(timeout=2.0)
            self._kick.clear()
            if self._stop:
                return
            self._reconcile()

    def serve_tick(self):
        # non-blocking: the tick (which runs under the mgr-wide lock)
        # only nudges the worker
        self._kick_worker()

    def shutdown(self):
        self._stop = True
        self._kick.set()
        if self._worker is not None:
            self._worker.join(timeout=5)


class MiniClusterBackend:
    """Deployment backend over a MiniCluster — the in-process analog
    of the cephadm module's ssh/container deployer.  Only daemons it
    created (``orch-*`` names / OSD ids it added) are ever removed."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._rgw = None
        self._rados = None
        self._added_osds: list[int] = []

    def daemon_inventory(self) -> list[dict]:
        out = []
        for r in range(len(self.cluster.mons)):
            out.append({"name": f"mon.{r}", "type": "mon",
                        "status": "running"})
        for i in self.cluster.osds:
            out.append({"name": f"osd.{i}", "type": "osd",
                        "status": "running"})
        for name, mds in self.cluster.mdss.items():
            out.append({"name": f"mds.{name}", "type": "mds",
                        "status": mds.state})
        for name in self.cluster.mgrs:
            out.append({"name": f"mgr.{name}", "type": "mgr",
                        "status": "running"})
        if self._rgw is not None:
            out.append({"name": "rgw.orch-0", "type": "rgw",
                        "status": "running",
                        "endpoint":
                            f"http://127.0.0.1:{self._rgw.port}"})
        return sorted(out, key=lambda d: d["name"])

    def ensure(self, stype: str, count: int):
        if stype == "mds":
            self._ensure_mds(count)
        elif stype == "rgw":
            self._ensure_rgw(count)
        elif stype == "osd":
            self._ensure_osd(count)

    def _ensure_mds(self, count: int):
        running = list(self.cluster.mdss)
        if len(running) < count:
            taken = set(running)
            i = 0
            while len(self.cluster.mdss) < count:
                name = f"orch-mds-{i}"
                i += 1
                if name in taken:
                    continue
                self.cluster.start_mds(name)
        elif len(running) > count:
            # shrink only what we created, newest first
            managed = sorted((n for n in running
                              if n.startswith("orch-mds-")),
                             reverse=True)
            for name in managed[:len(running) - count]:
                self.cluster.kill_mds(name)

    def _ensure_rgw(self, count: int):
        if count > 0 and self._rgw is None:
            from ..rgw import RGWService
            if self._rados is None:
                self._rados = self.cluster.rados()
            self._rgw = RGWService(self._rados).start()
        elif count == 0 and self._rgw is not None:
            self._rgw.shutdown()
            self._rgw = None

    def _ensure_osd(self, count: int):
        cur = len(self.cluster.osds)
        if cur < count:
            next_id = max(self.cluster.osds, default=-1) + 1
            for i in range(next_id, next_id + (count - cur)):
                self.cluster.start_osd(i)
                self._added_osds.append(i)
        # shrink is deliberately unsupported: draining an OSD needs
        # rebalancing orchestration (reference `ceph orch osd rm`
        # drains first); report-only here

    def shutdown(self):
        if self._rgw is not None:
            self._rgw.shutdown()
            self._rgw = None

"""mgr alerts — multi-window burn-rate rules + anomaly detection.

The telemetry spine gave the cluster *history*; this module gives it
*judgement* (reference shape: ``pybind/mgr/alerts`` + the
prometheus/SRE multi-window multi-burn-rate recipe).  Two rule
families evaluate every tick over the spine's rings:

* **SLO burn rate** — per scenario, the rate at which the error
  budget is being spent: ``burn = Δviolation_s / window / budget``.
  A rule fires only when BOTH its short window and its 12x long
  confirmation window exceed the threshold (the SRE pairing: fast
  5m/1h at 14.4 pages, slow 30m/6h at 6.0 tickets) — the long window
  filters blips, the short window makes the alert clear promptly
  once the spend stops.
* **Telemetry anomaly** — a seeded, deterministic detector over
  device-plane rate series: the newest windowed rate is scored with
  a robust z (0.6745·|x − median| / MAD, both over the prior
  samples); MAD-based so a single spike can't drag its own baseline.

Firing alerts post into **mon health** as ``SLO_BURN_RATE`` /
``TELEMETRY_ANOMALY`` checks through the config-key store (the
RECENT_CRASH pattern) — so ``ceph health``, mutes/TTLs, ``ceph -w``
transitions and the history ring all work on alerts for free.

Determinism is the autotune contract verbatim: the engine is a pure
function of ``(seed, rules, signal trace)``; it retains the consumed
trace, journals every fire/clear, and ``replay()`` over the same
trace reproduces ``journal_digest()`` byte-for-byte.  No wall clock
inside the engine — logical ticks only (the module stamps wall time
only on the records it posts to the mon).

Surfaces: ``ceph alerts status|history|rules|silence``, mon health
checks, and the exporter's ``ceph_alert_*`` gauges.
"""

from __future__ import annotations

import hashlib
import json
import time

from .daemon import MgrModule

DEFAULT_SEED = 0xA1E7

# robust-z of a zero-MAD series with any deviation: effectively
# infinite, kept finite so journals stay strict-JSON
_Z_SATURATED = 1e9

# rule knob → (Option name, default).  The defaults here are
# hardcoded on purpose (mgr modules don't read ConfigProxy — the
# autotune KNOBS precedent); the observability lint asserts each
# matches its declared Option so they cannot drift apart.
RULES = {
    "slo_budget": ("mgr_alerts_slo_budget", 0.01),
    "fast_window_s": ("mgr_alerts_fast_window_s", 300.0),
    "slow_window_s": ("mgr_alerts_slow_window_s", 1800.0),
    "fast_burn": ("mgr_alerts_fast_burn", 14.4),
    "slow_burn": ("mgr_alerts_slow_burn", 6.0),
    "anomaly_z": ("mgr_alerts_anomaly_z", 6.0),
    "anomaly_min_samples": ("mgr_alerts_anomaly_min_samples", 8),
    "history_size": ("mgr_alerts_history_size", 256),
}

# the two long confirmation windows are 12x their short window (5m→1h,
# 30m→6h) — a ratio, not a knob, per the SRE recipe
LONG_WINDOW_FACTOR = 12.0


def default_rules() -> dict:
    return {name: default for name, (_opt, default) in RULES.items()}


def mad_z(values: list[float]) -> float:
    """Robust z-score of the LAST sample against the prior ones:
    0.6745·|x − median| / MAD.  Pure arithmetic (sorted medians, no
    numpy) so replays are bit-identical."""
    if len(values) < 2:
        return 0.0
    prior = sorted(float(v) for v in values[:-1])
    x = float(values[-1])

    def med(s):
        n = len(s)
        m = n // 2
        return s[m] if n % 2 else 0.5 * (s[m - 1] + s[m])

    center = med(prior)
    mad = med(sorted(abs(v - center) for v in prior))
    dev = abs(x - center)
    if mad <= 0.0:
        return 0.0 if dev <= 0.0 else _Z_SATURATED
    return 0.6745 * dev / mad


def window_burn(samples, window: float, budget: float) -> float:
    """Burn rate over one lookback window of a cumulative
    violation-seconds series: Δviolation / window / budget.  With
    less history than the window the delta still divides by the FULL
    window (partial data under-reports — conservative, like a
    prometheus ``increase()`` without extrapolation)."""
    if len(samples) < 2 or window <= 0 or budget <= 0:
        return 0.0
    t1, v1 = samples[-1]
    target = float(t1) - float(window)
    v0 = samples[0][1]
    for t, v in samples:
        if t > target:
            break
        v0 = v
    return max(0.0, float(v1) - float(v0)) / float(window) \
        / float(budget)


class AlertEngine:
    """The seeded decision core — no cluster, no clock, no I/O.

    ``step(signals)`` consumes one tick's signal dict::

        {"slo": {scenario: {"burn": {"fast": b, "fast_long": b,
                                     "slow": b, "slow_long": b}}},
         "series": {daemon: {counter: [windowed rates...]}}}

    and returns fire/clear events.  Trace and journal are retained;
    ``replay(seed, trace, rules=...)`` over the same trace (and the
    same rules — rule edits mid-run are the operator changing the
    experiment) reproduces the journal byte-for-byte."""

    TRACE_CAP = 4096

    def __init__(self, seed: int = DEFAULT_SEED,
                 rules: dict | None = None):
        self.seed = int(seed)
        self.rules = dict(default_rules())
        if rules:
            self.rules.update(rules)
        self.tick = 0
        self.trace: list[dict] = []
        self.journal: list[dict] = []
        self._seq = 0           # monotonic across journal trimming
        # alert name -> {"check","severity","summary","since_tick",
        #                "value"}
        self.firing: dict[str, dict] = {}
        self.fired_total = 0
        self.cleared_total = 0

    def journal_digest(self) -> str:
        blob = json.dumps(self.journal, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    # -- the loop ------------------------------------------------------------

    def step(self, signals: dict) -> list[dict]:
        """One logical tick; returns the fire/clear transitions."""
        # JSON round-trip: the retained trace is exactly what a
        # replayer feeds back, so replay floats are bit-identical
        sig = json.loads(json.dumps(signals, sort_keys=True))
        self.tick += 1
        self.trace.append(sig)
        if len(self.trace) > self.TRACE_CAP:
            del self.trace[:len(self.trace) - self.TRACE_CAP]
        want: dict[str, dict] = {}
        self._eval_burn(sig, want)
        self._eval_anomaly(sig, want)
        out: list[dict] = []
        for name in sorted(want):
            rec = want[name]
            cur = self.firing.get(name)
            if cur is None:
                rec["since_tick"] = self.tick
                self.firing[name] = rec
                self.fired_total += 1
                out.append(self._journal({"event": "fire",
                                          "name": name, **rec}))
            else:
                # refresh the measured value, keep since_tick
                cur["value"] = rec["value"]
                cur["summary"] = rec["summary"]
        for name in sorted(set(self.firing) - set(want)):
            rec = self.firing.pop(name)
            self.cleared_total += 1
            out.append(self._journal({"event": "clear",
                                      "name": name, **rec}))
        return out

    def _journal(self, entry: dict) -> dict:
        entry["seq"] = self._seq
        self._seq += 1
        entry["tick"] = self.tick
        self.journal.append(entry)
        # the history_size rule is the journal's ring bound; seq
        # stays monotonic so trimming is visible in the record
        cap = int(self.rules.get("history_size") or 0)
        if cap > 0 and len(self.journal) > cap:
            del self.journal[:len(self.journal) - cap]
        return entry

    def _eval_burn(self, sig: dict, want: dict):
        r = self.rules
        for scenario in sorted(sig.get("slo") or {}):
            burn = (sig["slo"][scenario] or {}).get("burn") or {}
            pairs = (
                ("fast", "fast_long", float(r["fast_burn"]), "ERR",
                 f"{r['fast_window_s']:g}s/"
                 f"{LONG_WINDOW_FACTOR * r['fast_window_s']:g}s"),
                ("slow", "slow_long", float(r["slow_burn"]), "WARN",
                 f"{r['slow_window_s']:g}s/"
                 f"{LONG_WINDOW_FACTOR * r['slow_window_s']:g}s"),
            )
            for short, long_, threshold, severity, windows in pairs:
                bs = float(burn.get(short, 0.0))
                bl = float(burn.get(long_, 0.0))
                if bs < threshold or bl < threshold:
                    continue
                name = f"slo-burn-{short}:{scenario}"
                want[name] = {
                    "check": "SLO_BURN_RATE",
                    "severity": severity,
                    "value": bs,
                    "summary": (
                        f"scenario '{scenario}' burning error budget "
                        f"at {bs:.1f}x (threshold {threshold:g}, "
                        f"windows {windows})")}

    def _eval_anomaly(self, sig: dict, want: dict):
        r = self.rules
        min_n = int(r["anomaly_min_samples"])
        threshold = float(r["anomaly_z"])
        series = sig.get("series") or {}
        for daemon in sorted(series):
            for counter in sorted(series[daemon] or {}):
                values = series[daemon][counter] or []
                if len(values) < min_n:
                    continue
                z = mad_z(values)
                if z < threshold:
                    continue
                want[f"anomaly:{daemon}:{counter}"] = {
                    "check": "TELEMETRY_ANOMALY",
                    "severity": "WARN",
                    "value": z,
                    "summary": (
                        f"{daemon} {counter} rate "
                        f"{float(values[-1]):.1f}/s is a "
                        f"z={min(z, 999.0):.1f} outlier against its "
                        f"own history")}

    # -- replay (the fault-fabric acceptance hook) ---------------------------

    @classmethod
    def replay(cls, seed: int, trace: list[dict],
               rules: dict | None = None) -> "AlertEngine":
        """Fresh engine stepped over a recorded signal trace; its
        journal is byte-identical to the recorder's."""
        eng = cls(seed=seed, rules=rules)
        for sig in trace:
            eng.step(sig)
        return eng


class AlertsModule(MgrModule):
    """The mgr host: derives burn/anomaly signals from the telemetry
    spine's rings, steps the engine, and reconciles firing alerts
    into the mon config-key store where the health checks read them.
    Ships enabled (``mgr_alerts_enable`` default)."""

    NAME = "alerts"
    TICK = 1.0
    # device-plane rate series the anomaly detector watches
    ANOMALY_COUNTERS = ("op", "device_launches", "device_bytes")
    ANOMALY_TAIL = 64           # rate samples fed per series

    def __init__(self, ctx):
        super().__init__(ctx)
        self.engine = AlertEngine()
        self.enabled = True
        self.silences: dict[str, dict] = {}   # name -> {"expires",...}
        self._posted: set[str] = set()
        self.post_errors = 0

    # -- signal derivation ---------------------------------------------------

    def _spine(self):
        return self.ctx._d.modules.get("telemetry_spine")

    def _gather(self) -> dict:
        """Always returns a full signal dict — empty when the spine
        is missing or its rings are, so the engine still steps and
        alerts whose signal vanished clear instead of sticking."""
        slo: dict[str, dict] = {}
        series: dict[str, dict] = {}
        spine = self._spine()
        if spine is None:
            return {"slo": slo, "series": series}
        rules = self.engine.rules
        for daemon, rings in sorted(spine.series.items()):
            if daemon.startswith("slo."):
                ring = rings.get("violation_s")
                if ring is None or len(ring) < 2:
                    continue
                samples = [(float(t), float(v))
                           for t, v in ring.array()]
                fw = float(rules["fast_window_s"])
                sw = float(rules["slow_window_s"])
                budget = float(rules["slo_budget"])
                slo[daemon.split(".", 1)[1]] = {"burn": {
                    "fast": window_burn(samples, fw, budget),
                    "fast_long": window_burn(
                        samples, LONG_WINDOW_FACTOR * fw, budget),
                    "slow": window_burn(samples, sw, budget),
                    "slow_long": window_burn(
                        samples, LONG_WINDOW_FACTOR * sw, budget),
                }}
                continue
            if not daemon.startswith("osd."):
                continue
            per = {}
            for counter in self.ANOMALY_COUNTERS:
                ring = rings.get(counter)
                if ring is None or len(ring) < 2:
                    continue
                rates = [v for _t, v in spine._windowed(ring)]
                # drop the windowless leading zero, keep the tail
                per[counter] = rates[1:][-self.ANOMALY_TAIL:]
            if per:
                series[daemon] = per
        return {"slo": slo, "series": series}

    # -- mon health reconciliation -------------------------------------------

    def _reap_silences(self, now: float):
        for name, s in list(self.silences.items()):
            expires = float(s.get("expires") or 0)
            if expires and now >= expires:
                del self.silences[name]

    def _post(self, name: str, rec: dict, now: float):
        from ..mon.health import ALERT_KEY_PREFIX
        try:
            rc, _, _ = self.ctx.mon_command({
                "prefix": "config-key put",
                "key": ALERT_KEY_PREFIX + name,
                "val": json.dumps({
                    "name": name, "check": rec["check"],
                    "severity": rec["severity"],
                    "summary": rec["summary"],
                    "value": rec.get("value"),
                    "firing": True, "since": now})})
            if rc != 0:
                raise OSError(rc)
            self._posted.add(name)
        except Exception:   # noqa: BLE001 — mon churn: next tick
            self.post_errors += 1

    def _unpost(self, name: str):
        from ..mon.health import ALERT_KEY_PREFIX
        try:
            rc, _, _ = self.ctx.mon_command({
                "prefix": "config-key del",
                "key": ALERT_KEY_PREFIX + name})
            if rc != 0:
                raise OSError(rc)
            self._posted.discard(name)
        except Exception:   # noqa: BLE001 — mon churn: next tick
            self.post_errors += 1

    def _reconcile(self, now: float):
        """Make the mon's alerts/ namespace match (firing −
        silenced); idempotent, so a lost put is repaired next tick."""
        want = {n for n in self.engine.firing if n not in self.silences}
        for name in sorted(want - self._posted):
            self._post(name, self.engine.firing[name], now)
        for name in sorted(self._posted - want):
            self._unpost(name)

    def serve_tick(self):
        if not self.enabled:
            return
        signals = self._gather()
        now = time.time()
        self._reap_silences(now)
        self.engine.step(signals)
        self._reconcile(now)

    # -- surfaces ------------------------------------------------------------

    def status(self) -> dict:
        eng = self.engine
        return {
            "enabled": self.enabled, "seed": eng.seed,
            "tick": eng.tick,
            "firing": {n: dict(r)
                       for n, r in sorted(eng.firing.items())},
            "silences": {n: dict(s)
                         for n, s in sorted(self.silences.items())},
            "fired_total": eng.fired_total,
            "cleared_total": eng.cleared_total,
            "post_errors": self.post_errors,
            "rules": dict(eng.rules),
            "journal_digest": eng.journal_digest(),
        }

    def export_view(self) -> dict:
        """What the prometheus exporter consumes."""
        return {
            "enabled": self.enabled,
            "fired_total": self.engine.fired_total,
            "cleared_total": self.engine.cleared_total,
            "firing": {n: dict(r)
                       for n, r in self.engine.firing.items()},
        }

    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if not prefix.startswith("alerts"):
            return None
        verb = (prefix.split(maxsplit=1)[1:] or ["status"])[0]
        if verb == "status":
            return 0, "", self.status()
        if verb == "history":
            n = int(cmd.get("count") or 0)
            events = (self.engine.journal[-n:] if n
                      else list(self.engine.journal))
            out = {"seed": self.engine.seed, "events": events,
                   "fired_total": self.engine.fired_total,
                   "cleared_total": self.engine.cleared_total,
                   "journal_digest": self.engine.journal_digest()}
            if cmd.get("trace"):
                out["trace"] = list(self.engine.trace)
            return 0, "", out
        if verb == "rules":
            knob = cmd.get("knob")
            if knob is None:
                return 0, "", {"rules": dict(self.engine.rules),
                               "options": {k: opt for k, (opt, _d)
                                           in RULES.items()}}
            if knob not in RULES:
                return -22, "", f"alerts rules: unknown rule knob " \
                                f"{knob!r} (have {sorted(RULES)})"
            if cmd.get("value") is None:
                return 0, "", {knob: self.engine.rules[knob]}
            cast = type(RULES[knob][1])
            try:
                self.engine.rules[knob] = cast(cmd["value"])
            except (TypeError, ValueError) as e:
                return -22, "", f"alerts rules: bad value: {e}"
            return 0, "", {knob: self.engine.rules[knob]}
        if verb == "silence":
            name = cmd.get("name")
            if not name:
                return -22, "", "alerts silence needs an alert name"
            if cmd.get("off"):
                self.silences.pop(name, None)
                self._reconcile(time.time())
                return 0, "", {"name": name, "silenced": False}
            ttl = float(cmd.get("ttl") or 3600.0)
            now = time.time()
            self.silences[name] = {"expires": now + ttl, "ttl": ttl}
            self._reconcile(now)
            return 0, "", {"name": name, "silenced": True,
                           "expires": now + ttl}
        if verb == "enable":
            if "seed" in cmd:
                self.engine = AlertEngine(seed=int(cmd["seed"]),
                                          rules=self.engine.rules)
            self.enabled = True
            return 0, "", {"enabled": True, "seed": self.engine.seed}
        if verb == "disable":
            self.enabled = False
            for name in sorted(self._posted):
                self._unpost(name)
            return 0, "", {"enabled": False}
        return -22, "", ("usage: alerts status|history|rules "
                         "[knob [value]]|silence <name> [ttl|off]"
                         "|enable|disable")

"""Prometheus exporter — cluster + daemon metrics over HTTP.

Reference behavior re-created (``src/pybind/mgr/prometheus/
module.py``; SURVEY.md §3.10): scrape-on-demand ``GET /metrics`` in
the Prometheus text exposition format, fed from the mon's
health/status/PGMap (cluster health, osd up/in counts, PG states,
object counts) and from live daemons' PerfCounters via their admin
sockets (op counts, latency sums, recovery/scrub counters).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..core.admin_socket import admin_command

_HEALTH_VAL = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}


def _san(name: str) -> str:
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


def _esc_label(v) -> str:
    """Prometheus label-value escaping: backslash, double quote and
    newline (exposition format spec)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class Exporter:
    def __init__(self, monc, asok_paths: dict[str, str] | None = None,
                 progress_events=None, telemetry=None, autotune=None,
                 alerts=None):
        """monc: a MonClient; asok_paths: daemon name → admin socket
        (scraped for perf counters); progress_events: nullary callable
        → open mgr progress events (ceph_progress_event gauge);
        telemetry: nullary callable → the telemetry spine's export
        view (device-plane series + derived byte rates + merged
        attribution top-K); autotune: nullary callable → the autotune
        module's export view (decision counters + current knob
        values); alerts: nullary callable → the alerts module's
        export view (firing alerts + fire/clear counters)."""
        self.monc = monc
        self.asok_paths = dict(asok_paths or {})
        self.progress_events = progress_events
        self.telemetry = telemetry
        self.autotune = autotune
        self.alerts = alerts

    def collect(self) -> str:
        lines: list[str] = []
        # one `# TYPE`/`# HELP` per metric family, no matter how many
        # instances emit into it (scrapers reject duplicates)
        typed: set[str] = set()
        helped: set[str] = set()

        def emit_type(name, typ):
            if name not in typed:
                typed.add(name)
                lines.append(f"# TYPE {name} {typ}")

        def emit(name, value, labels=None, help_=None, typ="gauge",
                 exemplar=None):
            if help_ and name not in helped:
                helped.add(name)
                lines.append(f"# HELP {name} {help_}")
                emit_type(name, typ)
            lab = ""
            if labels:
                lab = "{" + ",".join(
                    f'{k}="{_esc_label(v)}"'
                    for k, v in labels.items()) + "}"
            line = f"{name}{lab} {value}"
            if exemplar:
                # OpenMetrics exemplar suffix on _bucket lines: the
                # trace id of the slowest op that landed in the bucket
                line += (' # {trace_id="'
                         f'{_esc_label(exemplar.get("trace_id", ""))}'
                         f'"}} {exemplar.get("value", 0)}'
                         f' {exemplar.get("ts", 0)}')
            lines.append(line)

        try:
            rc, _, st = self.monc.command({"prefix": "status"})
        except Exception:
            rc, st = -1, None
        if rc == 0 and st:
            emit("ceph_health_status",
                 _HEALTH_VAL.get(st.get("health"), 2),
                 help_="cluster health (0=OK 1=WARN 2=ERR)")
            emit("ceph_osd_up", st.get("num_up_osds", 0),
                 help_="OSDs up")
            emit("ceph_osd_total", st.get("num_osds", 0),
                 help_="OSDs known")
            emit("ceph_mon_quorum_count",
                 len(st.get("quorum") or []),
                 help_="mons in quorum")
            emit("ceph_pg_total", st.get("num_pgs", 0),
                 help_="placement groups")
            emit("ceph_objects_total", st.get("num_objects", 0),
                 help_="objects (primary-reported)")
            first = True
            for state, n in sorted(
                    (st.get("pg_states") or {}).items()):
                emit("ceph_pg_state", n,
                     labels={"state": state},
                     help_="PGs by state" if first else None)
                first = False

        # per-check health + mute gauges (reference
        # ceph_health_detail): one series per active check code,
        # valued by severity, plus one per muted code
        try:
            rc, _, rep = self.monc.command({"prefix": "health"})
        except Exception:
            rc, rep = -1, None
        if rc == 0 and rep:
            first = True
            for chk in rep.get("checks") or []:
                sev = 2 if chk.get("severity") == "ERR" else 1
                emit("ceph_health_check", sev,
                     labels={"code": chk.get("code", "")},
                     help_="active health checks (1=WARN 2=ERR)"
                     if first else None)
                first = False
            first = True
            for chk in rep.get("muted") or []:
                emit("ceph_health_mute", 1,
                     labels={"code": chk.get("code", "")},
                     help_="muted health checks" if first else None)
                first = False

        # open mgr progress events (reference ceph_progress_event)
        if self.progress_events is not None:
            try:
                events = self.progress_events() or []
            except Exception:
                events = []
            first = True
            for ev in events:
                emit("ceph_progress_event",
                     round(float(ev.get("progress", 0.0)), 4),
                     labels={"id": ev.get("id", ""),
                             "message": ev.get("message", "")},
                     help_="progress event completion fraction"
                     if first else None)
                first = False

        # cluster-wide scrub totals + per-pool/per-state PG gauges
        # from the mon's array PGMap: ONE `pg summary` reply of
        # masked reductions per scrape — never a per-PG dump, so
        # scrape time stays flat as PG count grows.  `pg dump` is the
        # fallback for mons (or test fakes) that don't serve it.
        try:
            rc, _, summ = self.monc.command({"prefix": "pg summary"})
        except Exception:
            rc, summ = -1, None
        if rc != 0 or not summ or "scrub_errors" not in summ:
            summ = self._summary_from_dump()
        if summ is not None:
            emit("ceph_pg_scrub_errors", summ["scrub_errors"],
                 help_="scrub inconsistencies outstanding")
            emit("ceph_pg_inconsistent_objects",
                 summ["inconsistent_objects"],
                 help_="objects flagged by list-inconsistent-obj")
            first = True
            for pid, pool in sorted(
                    (summ.get("pools") or {}).items()):
                lab = {"name": str(pool.get("name", "")),
                       "pool_id": str(pid)}
                emit("ceph_pool_pg_total", pool.get("pgs", 0),
                     labels=lab,
                     help_="reported PGs per pool" if first else None)
                emit("ceph_pool_objects", pool.get("objects", 0),
                     labels=lab,
                     help_="objects per pool" if first else None)
                for state, n in sorted(
                        (pool.get("by_state") or {}).items()):
                    emit("ceph_pool_pgs_by_state", n,
                         labels={**lab, "state": state},
                         help_="PGs per pool and state"
                         if first else None)
                first = False
            # slow-op gauges (reference ceph_healthcheck_slow_ops +
            # per-daemon slow op counts): fed from the osd_stats each
            # OSD reports out of its op tracker
            osd_stats = summ.get("osd_stats") or {}
            total_slow, worst_age = 0, 0.0
            first = True
            for name, st in sorted(osd_stats.items()):
                s = st.get("slow_ops") or {}
                count = int(s.get("count", 0))
                age = float(s.get("oldest_age", 0.0))
                total_slow += count
                worst_age = max(worst_age, age)
                emit("ceph_osd_slow_ops", count,
                     labels={"ceph_daemon": f"osd.{name}"},
                     help_="slow ops in flight (per OSD)"
                     if first else None)
                first = False
            emit("ceph_cluster_slow_ops", total_slow,
                 help_="slow ops in flight (cluster total)")
            emit("ceph_cluster_slow_ops_oldest_age_seconds", worst_age,
                 help_="age of the oldest slow op")

        # storage-efficiency gauges per pool (reference prometheus
        # module's ceph_pool_* compression family): stored vs logical
        # bytes and the derived ratios from `df`
        try:
            rc, _, df = self.monc.command({"prefix": "df"})
        except Exception:
            rc, df = -1, None
        if rc == 0 and df:
            first = True
            for p in df.get("pools") or []:
                lab = {"name": p.get("name", ""),
                       "pool_id": str(p.get("id", ""))}
                emit("ceph_pool_stored_bytes",
                     p.get("bytes_used", 0), labels=lab,
                     help_="physical pool bytes (post-compression)"
                     if first else None)
                emit("ceph_pool_logical_bytes",
                     p.get("bytes_logical", 0), labels=lab,
                     help_="logical pool bytes (client view)"
                     if first else None)
                emit("ceph_pool_compress_ratio",
                     round(float(p.get("compress_ratio", 1.0)), 4),
                     labels=lab,
                     help_="logical/stored compression ratio"
                     if first else None)
                if "dedup_ratio" in p:
                    emit("ceph_pool_dedup_ratio",
                         round(float(p["dedup_ratio"]), 4),
                         labels=lab,
                         help_="referenced/stored dedup ratio")
                first = False
            ded = df.get("dedup") or {}
            if ded:
                emit("ceph_dedup_chunks", ded.get("chunks", 0),
                     help_="unique dedup chunks stored")
                emit("ceph_dedup_stored_bytes",
                     ded.get("stored_bytes", 0),
                     help_="dedup chunk bytes stored once")
                emit("ceph_dedup_referenced_bytes",
                     ded.get("referenced_bytes", 0),
                     help_="bytes the chunk store logically serves")

        # device-plane series from the mgr telemetry spine (profiler
        # aggregates + derived rates the OSDs beacon via osd_stats)
        if self.telemetry is not None:
            try:
                view = self.telemetry() or {}
            except Exception:
                view = {}
            self._emit_device_series(emit, emit_type, view)
            self._emit_slo_series(emit, view)
            self._emit_topk(emit, view)

        # firing alerts + fire/clear counters
        if self.alerts is not None:
            try:
                alview = self.alerts() or {}
            except Exception:
                alview = {}
            self._emit_alerts(emit, alview)

        # autotuner decision counters + actuated knob values
        if self.autotune is not None:
            try:
                aview = self.autotune() or {}
            except Exception:
                aview = {}
            self._emit_autotune(emit, aview)

        for daemon, path in sorted(self.asok_paths.items()):
            try:
                dump = admin_command(path, "perf dump")
            except Exception:
                continue        # daemon down: skip its series
            try:
                schema = admin_command(path, "perf schema")
            except Exception:
                schema = {}     # older daemon: untyped series only
            # one metric FAMILY per counter, instance in the
            # ceph_daemon label (reference prometheus module's
            # shape) — sum(ceph_osd_op) must aggregate across OSDs
            dtype = _san(daemon.split(".", 1)[0])
            for pcname, counters in dump.items():
                kinds = schema.get(pcname) or {}
                for cname, val in counters.items():
                    base = f"ceph_{dtype}_{_san(cname)}"
                    lab = {"ceph_daemon": daemon}
                    kind = (kinds.get(cname) or {}).get("type")
                    if isinstance(val, dict):
                        if "avgcount" in val:
                            emit(base + "_sum", val.get("sum", 0),
                                 labels=lab)
                            emit(base + "_count",
                                 val.get("avgcount", 0), labels=lab)
                        elif "values" in val:
                            self._emit_histogram(
                                emit, emit_type, base, lab, val)
                    else:
                        if kind == "u64":
                            # monotonic counters get the proper
                            # prometheus type (rate() needs it)
                            emit_type(base, "counter")
                        emit(base, val, labels=lab)
        return "\n".join(lines) + "\n"

    def _summary_from_dump(self) -> dict | None:
        """`pg summary`-shaped totals rebuilt from a legacy
        `pg dump` (compat path for old mons / test doubles)."""
        try:
            rc, _, dump = self.monc.command({"prefix": "pg dump"})
        except Exception:
            rc, dump = -1, None
        if rc != 0 or not dump:
            return None
        pg_stats = (dump.get("pg_stats") or {}).values()
        return {
            "scrub_errors": sum(st.get("scrub_errors", 0)
                                for st in pg_stats),
            "inconsistent_objects": sum(
                len(st.get("inconsistent_objects") or [])
                for st in pg_stats),
            "pools": {},
            "osd_stats": dump.get("osd_stats") or {},
        }

    @staticmethod
    def _emit_device_series(emit, emit_type, view):
        """Telemetry-spine export view → the device observability
        families: a per-daemon launch wall-time histogram (buckets in
        seconds, converted from the profiler's log2-µs histogram) and
        the dispatch-overhead / occupancy / byte-rate gauges."""
        profs = view.get("profiler") or {}
        rates = view.get("rates") or {}
        first = True
        for daemon in sorted(profs):
            prof = profs[daemon] or {}
            lab = {"ceph_daemon": daemon}
            hist = prof.get("launch_hist_us") or []
            if hist:
                emit_type("ceph_device_launch_seconds", "histogram")
                cum = 0
                approx_sum = 0.0
                for i, n in enumerate(hist):
                    cum += n
                    approx_sum += n * (2 ** i - 1) * 1e-6
                    le = "+Inf" if i == len(hist) - 1 \
                        else f"{(2 ** (i + 1) - 1) * 1e-6:g}"
                    emit("ceph_device_launch_seconds_bucket", cum,
                         labels={**lab, "le": le})
                emit("ceph_device_launch_seconds_sum",
                     f"{approx_sum:g}", labels=lab)
                emit("ceph_device_launch_seconds_count", cum,
                     labels=lab)
            emit("ceph_device_dispatch_overhead_ratio",
                 round(float(prof.get("dispatch_overhead_ratio",
                                      0.0)), 6),
                 labels=lab,
                 help_="host dispatch time / total device wall time"
                 if first else None)
            emit("ceph_device_occupancy_ratio",
                 round(float(prof.get("occupancy_ratio", 1.0)), 6),
                 labels=lab,
                 help_="useful rows / padded rows per launch"
                 if first else None)
            first = False
        first = True
        for daemon in sorted(rates):
            if daemon.startswith("slo."):
                continue    # slo pseudo-daemons: _emit_slo_series
            r = rates[daemon] or {}
            emit("ceph_osd_bytes_rate",
                 round(float(r.get("bytes_per_sec", 0.0)), 3),
                 labels={"ceph_daemon": daemon},
                 help_="client write bytes per second (windowed)"
                 if first else None)
            first = False

    @staticmethod
    def _emit_slo_series(emit, view):
        """SLO-harness reports ("slo ingest" → export_view()["slo"])
        → per-tenant/per-op-class gauges.  The workload scenarios push
        whole reports; here each (scenario, tenant, op_class) lane
        becomes one labeled series so dashboards can plot victim vs
        aggressor p99 side by side."""
        slo = view.get("slo") or {}
        first = True
        for scenario in sorted(slo):
            rep = slo[scenario] or {}
            emit("ceph_slo_offered_rate",
                 round(float(rep.get("offered_rate", 0.0)), 3),
                 labels={"scenario": scenario},
                 help_="open-loop offered ops per second"
                 if first else None)
            emit("ceph_slo_goodput_ops",
                 round(float(rep.get("goodput_ops", 0.0)), 3),
                 labels={"scenario": scenario},
                 help_="ops/s completed OK and within SLO target"
                 if first else None)
            for tenant in sorted(rep.get("tenants") or {}):
                lanes = rep["tenants"][tenant] or {}
                for klass in sorted(lanes):
                    lane = lanes[klass] or {}
                    lab = {"scenario": scenario, "tenant": tenant,
                           "op_class": klass}
                    for q in ("p50_ms", "p99_ms", "p999_ms"):
                        emit(f"ceph_slo_latency_{q}",
                             round(float(lane.get(q, 0.0)), 3),
                             labels=lab)
                    emit("ceph_slo_ops_total",
                         int(lane.get("count", 0)), labels=lab)
                    emit("ceph_slo_throttled_total",
                         int(lane.get("throttled", 0)), labels=lab)
                    emit("ceph_slo_errors_total",
                         int(lane.get("errors", 0)), labels=lab)
                    emit("ceph_slo_in_violation",
                         int(bool(lane.get("in_violation"))),
                         labels=lab)
                    emit("ceph_slo_violation_seconds",
                         round(float(lane.get("violation_s", 0.0)),
                               3), labels=lab)
            first = False
        # windowed per-second numbers off the slo.* rings — the same
        # values `telemetry series` and daemon_rates report
        first = True
        for daemon in sorted(view.get("rates") or {}):
            if not daemon.startswith("slo."):
                continue
            scenario = daemon.split(".", 1)[1]
            for counter, v in sorted(
                    (view["rates"][daemon] or {}).items()):
                emit("ceph_slo_rate", round(float(v), 6),
                     labels={"scenario": scenario,
                             "counter": counter},
                     help_="windowed per-second rate of an SLO "
                     "harness aggregate" if first else None)
                first = False

    @staticmethod
    def _emit_topk(emit, view):
        """Merged attribution top-K → ceph_topk_* gauges: one series
        per (dimension, key) for ops (with its space-saving error
        bound), bytes and p99 latency."""
        topk = view.get("topk") or {}
        firsts = {}
        for dim in sorted(topk):
            for row in topk[dim] or []:
                lab = {"dim": dim, "key": str(row.get("key", ""))}
                for fam, field, help_ in (
                        ("ceph_topk_ops", "ops",
                         "ops attributed to a heavy-hitter key "
                         "(space-saving sketch, overestimate)"),
                        ("ceph_topk_ops_err", "err",
                         "overestimation bound on ceph_topk_ops"),
                        ("ceph_topk_bytes", "bytes",
                         "bytes attributed to a heavy-hitter key"),
                        ("ceph_topk_p99_ms", "p99_ms",
                         "p99 op latency of a heavy-hitter key")):
                    emit(fam, row.get(field, 0), labels=lab,
                         help_=help_ if not firsts.get(fam) else None)
                    firsts[fam] = True

    @staticmethod
    def _emit_alerts(emit, view):
        """Alerts export view → ceph_alert_* families: an armed
        flag, fire/clear counters, and one series per firing alert
        valued by its measured burn rate / z-score."""
        if not view:
            return
        emit("ceph_alerts_enabled", int(bool(view.get("enabled"))),
             help_="alert rules evaluated each mgr tick (1=yes)")
        emit("ceph_alerts_fired_total",
             int(view.get("fired_total", 0)),
             help_="alert fire transitions since mgr start",
             typ="counter")
        emit("ceph_alerts_cleared_total",
             int(view.get("cleared_total", 0)),
             help_="alert clear transitions since mgr start",
             typ="counter")
        first = True
        for name in sorted(view.get("firing") or {}):
            rec = view["firing"][name] or {}
            emit("ceph_alert_firing",
                 round(float(rec.get("value", 1.0)), 6),
                 labels={"name": name,
                         "check": str(rec.get("check", "")),
                         "severity": str(rec.get("severity", ""))},
                 help_="firing alerts, valued by the measured "
                 "burn rate / z-score" if first else None)
            first = False

    @staticmethod
    def _emit_autotune(emit, view):
        """Autotune export view → ceph_autotune_* families: the
        decision/rollback counters, an armed flag, and one
        ceph_autotune_knob_value series per numeric knob (string
        knobs — e.g. osd_wal_sync_mode — become an info-style series
        with the value in a label)."""
        if not view:
            return
        emit("ceph_autotune_enabled",
             int(bool(view.get("enabled"))),
             help_="autotuner actively actuating knobs (1=yes)")
        emit("ceph_autotune_decisions_total",
             int(view.get("decisions_total", 0)),
             help_="knob adjustments made since (re)seed",
             typ="counter")
        emit("ceph_autotune_rollbacks_total",
             int(view.get("rollbacks_total", 0)),
             help_="adjustments undone after objective regression",
             typ="counter")
        num_first = info_first = True
        for knob in sorted(view.get("knobs") or {}):
            value = view["knobs"][knob]
            if isinstance(value, bool) or not isinstance(
                    value, (int, float)):
                emit("ceph_autotune_knob_info", 1,
                     labels={"knob": knob, "value": str(value)},
                     help_="current value of a non-numeric knob"
                     if info_first else None)
                info_first = False
            else:
                emit("ceph_autotune_knob_value", value,
                     labels={"knob": knob},
                     help_="current value of an actuated knob"
                     if num_first else None)
                num_first = False

    @staticmethod
    def _emit_histogram(emit, emit_type, base, lab, val):
        """LogHistogram dump → prometheus histogram series.

        The 2-D log2 histogram collapses its y axis; x-bucket i holds
        observations v with int(log2(v+1)) == i, so its upper bound
        is 2^(i+1)-1 (the last bucket is +Inf).  `_sum` is
        approximated from bucket lower bounds — the source histogram
        stores counts only.  Buckets that kept a metric→trace
        exemplar carry it as an OpenMetrics exemplar suffix."""
        rows = val.get("values") or []
        if not rows:
            return
        exemplars = val.get("exemplars") or {}
        nx = len(rows[0])
        per_x = [sum(r[i] for r in rows) for i in range(nx)]
        emit_type(base, "histogram")
        cum = 0
        approx_sum = 0.0
        for i, n in enumerate(per_x):
            cum += n
            approx_sum += n * float(2 ** i - 1)
            le = "+Inf" if i == nx - 1 else f"{float(2 ** (i + 1) - 1):g}"
            emit(base + "_bucket", cum, labels={**lab, "le": le},
                 exemplar=exemplars.get(str(i)))
        emit(base + "_sum", approx_sum, labels=lab)
        emit(base + "_count", cum, labels=lab)


class ExporterService:
    """HTTP frontend: GET /metrics (reference module's scrape port)."""

    def __init__(self, exporter: Exporter, host: str = "127.0.0.1",
                 port: int = 0):
        ex = exporter

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                from urllib.parse import urlsplit
                if urlsplit(self.path).path.rstrip("/") not in \
                        ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = ex.collect().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="mgr-exporter",
            daemon=True)

    def start(self):
        self._thread.start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()

"""mgr devicehealth — device inventory, SMART-style scraping, life
expectancy.

Reference behavior re-created (``src/pybind/mgr/devicehealth``;
SURVEY.md §3.10): every OSD reports the device backing it; the module
scrapes health metrics on a cadence, stores the time series, computes
a life-expectancy verdict, and raises a cluster-log warning when a
device is expected to fail.  Real SMART comes from smartctl on the
host; here each OSD serves a ``smart`` admin-socket command whose
counters tests (and fault injection) can steer — the module logic
(scrape → store → predict → warn) is the same.

Commands (via the mgr command server, i.e. ``ceph device ...``):
- ``device ls`` — inventory with health verdicts
- ``device info`` {devid} — stored metric history
- ``device check-health`` — scrape + evaluate now
"""

from __future__ import annotations

import json
import time

from .daemon import MgrModule

import threading

STORE_PREFIX = "devicehealth/"
# media-error thresholds for the verdicts (reference uses a life
# expectancy model over SMART attributes; the shape is what matters)
WARN_ERRORS = 10
FAIL_ERRORS = 100
HISTORY_KEPT = 24


class DeviceHealthModule(MgrModule):
    NAME = "devicehealth"
    TICK = 5.0

    def __init__(self, ctx):
        super().__init__(ctx)
        self._last_scrape = 0.0
        self.scrape_interval = 60.0
        # single-flight: the tick thread and the command thread must
        # not interleave the config-key read-modify-write (lost
        # history entries, duplicated clog warnings)
        self._scrape_lock = threading.Lock()
        # None = never scraped; [] is a valid "no devices" result and
        # must not make every 'device ls' poll re-scrape
        self._verdicts: list[dict] | None = None

    # -- scraping ----------------------------------------------------------
    def _osd_asoks(self) -> dict[str, str]:
        return {name: path
                for name, path in self.ctx._d.asok_paths.items()
                if name.startswith("osd.")}

    def _scrape_one(self, osd_name: str, asok: str) -> dict | None:
        from ..core.admin_socket import admin_command
        try:
            return admin_command(asok, "smart", timeout=5.0)
        except Exception:   # noqa: BLE001 — daemon down; next pass
            return None

    def scrape(self) -> dict[str, dict]:
        """Scrape every OSD's device → {devid: reading}; store."""
        readings = {}
        for osd_name, asok in self._osd_asoks().items():
            r = self._scrape_one(osd_name, asok)
            if r is None:
                continue
            devid = r.get("devid", f"dev-{osd_name}")
            r = dict(r, osd=osd_name, stamp=time.time())
            readings[devid] = r
            key = f"{STORE_PREFIX}{devid}"
            rc, _, blob = self.ctx.mon_command(
                {"prefix": "config-key get", "key": key})
            hist = json.loads(blob) if rc == 0 and blob else []
            hist.append(r)
            self.ctx.mon_command({
                "prefix": "config-key put", "key": key,
                "val": json.dumps(hist[-HISTORY_KEPT:])})
        return readings

    # -- evaluation --------------------------------------------------------
    @staticmethod
    def life_expectancy(reading: dict) -> str:
        errs = int(reading.get("media_errors", 0))
        if errs >= FAIL_ERRORS:
            return "failing"
        if errs >= WARN_ERRORS:
            return "warning"
        return "good"

    def check_health(self) -> list[dict]:
        """Scrape now, evaluate, clog-warn on bad devices; → verdicts."""
        out = []
        with self._scrape_lock:
            readings = self.scrape()
        for devid, r in sorted(readings.items()):
            verdict = self.life_expectancy(r)
            out.append({"devid": devid, "osd": r.get("osd"),
                        "life_expectancy": verdict,
                        "media_errors": r.get("media_errors", 0)})
            if verdict != "good":
                self.ctx.mon_command({
                    "prefix": "log",
                    "logtext": f"DEVICE_HEALTH {devid} "
                               f"({r.get('osd')}): {verdict} "
                               f"({r.get('media_errors', 0)} media "
                               f"errors)"})
        self._verdicts = out
        return out

    def last_verdicts(self) -> list[dict]:
        """Most recent check_health result — a side-effect-free read
        for dashboards/pollers."""
        return list(self._verdicts or [])

    # -- commands ----------------------------------------------------------
    def handle_command(self, cmd: dict):
        prefix = cmd.get("prefix", "")
        if prefix == "device ls":
            # inventory is a read: serve the last verdicts (scrape
            # only before the first scrape ever) so dashboard polls
            # don't re-scrape every OSD and duplicate clog warnings
            if self._verdicts is None:
                return 0, "", self.check_health()
            return 0, "", self.last_verdicts()
        if prefix == "device check-health":
            bad = [d for d in self.check_health()
                   if d["life_expectancy"] != "good"]
            return 0, f"{len(bad)} device(s) unhealthy", bad
        if prefix == "device info":
            key = f"{STORE_PREFIX}{cmd.get('devid', '')}"
            rc, _, blob = self.ctx.mon_command(
                {"prefix": "config-key get", "key": key})
            if rc != 0 or not blob:
                return -2, f"no device {cmd.get('devid')!r}", None
            return 0, "", json.loads(blob)
        return None

    def serve_tick(self):
        # scrape OFF the loop thread: serve_tick runs under the mgr
        # lock on the beacon-sending thread, and a slow daemon would
        # starve beacons into a spurious failover.  The asok timeout
        # bounds the worker; the single-flight lock keeps it from
        # overlapping a command-triggered scrape.
        now = time.monotonic()
        if now - self._last_scrape >= self.scrape_interval:
            self._last_scrape = now
            threading.Thread(target=self._safe_check,
                             name="devicehealth-scrape",
                             daemon=True).start()

    def _safe_check(self):
        try:
            self.check_health()
        except Exception:   # noqa: BLE001 — next cadence retries
            pass

"""Upmap balancer — evens per-OSD PG load with pg_upmap_items.

Reference behavior re-created (``src/pybind/mgr/balancer/module.py``
upmap mode + ``OSDMap::calc_pg_upmaps`` in ``src/osd/OSDMap.cc``):
compute every PG's placement, find overfull/underfull OSDs against
their CRUSH-weight-proportional targets, and propose pg_upmap_items
exceptions moving single replicas from the fullest OSD to compatible
underfull ones — never violating the rule's failure domain.

TPU-first: the full-pool placement matrix comes from ONE BatchMapper
launch (`tools.osdmaptool.map_pool_pgs`) instead of the reference's
per-PG scalar loop — this module is crush_tpu's first in-system
consumer: every optimize() round is a batched what-if evaluation of
the whole pool.

Apply through the mon: ``{"prefix": "osd pg-upmap-items", "pgid":
"<p.s>", "mappings": [[from, to], ...]}`` (same command the reference
balancer issues).
"""

from __future__ import annotations

import numpy as np

from ..crush.map import CRUSH_ITEM_NONE
from ..osd.osdmap import OSDMap, PGid


class UpmapBalancer:
    def __init__(self, osdmap: OSDMap, pool_id: int,
                 use_jax: bool = True, require_batched: bool = False):
        from ..utils.platform import ensure_x64
        if use_jax:
            ensure_x64()        # BatchMapper needs 64-bit straw2 draws
        self.use_jax = use_jax
        self.require_batched = require_batched
        self.m = osdmap
        self.pool = osdmap.pools[pool_id]
        self.rule = osdmap.crush.rule_by_id(self.pool.crush_rule)
        # failure-domain type of the rule's choose step (0 = osd)
        self.domain_type = 0
        for s in self.rule.steps:
            if s.op.startswith(("choose_firstn", "chooseleaf_firstn",
                                "choose_indep", "chooseleaf_indep")):
                self.domain_type = s.arg2
        self._domain_of = self._build_domain_index()

    def _build_domain_index(self) -> dict[int, int]:
        """osd → ancestor bucket id of the failure-domain type."""
        dom: dict[int, int] = {}
        if self.domain_type == 0:
            return dom
        crush = self.m.crush

        def walk(bid: int, domain: int | None):
            b = crush.bucket(bid)
            d = bid if b.type == self.domain_type else domain
            for it in b.items:
                if it >= 0:
                    if d is not None:
                        dom[it] = d
                else:
                    walk(it, d)

        children = {it for b in crush.buckets if b is not None
                    for it in b.items if it < 0}
        for b in crush.buckets:
            if b is not None and b.type > self.domain_type and \
                    b.id not in children:
                walk(b.id, None)
        return dom

    # -- placement snapshot ------------------------------------------------
    def _placements(self) -> dict[PGid, list[int]]:
        from ..tools.osdmaptool import map_pool_pgs
        raw = map_pool_pgs(self.m, self.pool, use_jax=self.use_jax,
                           require_batched=self.require_batched)
        place: dict[PGid, list[int]] = {}
        for seed in range(self.pool.pg_num):
            pgid = PGid(self.pool.id, seed)
            row = [o for o in raw[seed] if o != CRUSH_ITEM_NONE]
            row = self.m._apply_upmap(pgid, row)
            place[pgid] = [o for o in row
                           if o != CRUSH_ITEM_NONE and self.m.is_up(o)]
        return place

    def pg_counts(self, place=None) -> np.ndarray:
        place = place if place is not None else self._placements()
        counts = np.zeros(self.m.max_osd, dtype=np.int64)
        for osds in place.values():
            for o in osds:
                counts[o] += 1
        return counts

    def _targets(self) -> np.ndarray:
        """Per-OSD target load ∝ CRUSH device weight (in OSDs only)."""
        w = np.zeros(self.m.max_osd, dtype=np.float64)
        crush = self.m.crush
        for b in crush.buckets:
            if b is None:
                continue
            for it, bw in zip(b.items, b.weights):
                if it >= 0 and not self.m.is_out(it) \
                        and self.m.is_up(it):
                    w[it] = bw
        total_slots = self.pool.pg_num * self.pool.size
        if w.sum() == 0:
            return np.zeros_like(w)
        return total_slots * w / w.sum()

    # -- optimization ------------------------------------------------------
    def optimize(self, max_changes: int = 10,
                 deviation_stop: float = 1.0
                 ) -> dict[PGid, list[tuple[int, int]]]:
        """Propose up to max_changes pg_upmap_items changes.  Greedy
        per-round: move one replica off the currently fullest OSD to
        the most underfull compatible OSD (reference calc_pg_upmaps'
        retry loop, simplified to single-replica swaps)."""
        place = self._placements()
        counts = self.pg_counts(place).astype(np.float64)
        targets = self._targets()
        proposals: dict[PGid, list[tuple[int, int]]] = {}
        pgs_by_osd: dict[int, set[PGid]] = {}
        for pgid, osds in place.items():
            for o in osds:
                pgs_by_osd.setdefault(o, set()).add(pgid)

        for _ in range(max_changes):
            dev = counts - targets
            # ignore out/down osds entirely
            for o in range(self.m.max_osd):
                if not self.m.is_up(o) or self.m.is_out(o):
                    dev[o] = 0
            omax = int(np.argmax(dev))
            if dev[omax] <= deviation_stop:
                break
            under = sorted(
                (o for o in range(self.m.max_osd)
                 if self.m.is_up(o) and not self.m.is_out(o)
                 and dev[o] < -0.5),
                key=lambda o: dev[o])
            moved = False
            for pgid in sorted(pgs_by_osd.get(omax, ()),
                               key=lambda p: p.seed):
                others = [o for o in place[pgid] if o != omax]
                used_domains = {self._domain_of.get(o) for o in others} \
                    if self.domain_type else set()
                for ou in under:
                    if ou in place[pgid]:
                        continue
                    if self.domain_type and \
                            self._domain_of.get(ou) in used_domains:
                        continue
                    # the PG may sit on omax only VIA an existing
                    # upmap pair (raw→omax): rewrite that pair's
                    # target instead of appending a no-op (omax, ou)
                    # that _apply_upmap would ignore
                    items = []
                    rewired = False
                    for a, b in self.m.pg_upmap_items.get(pgid, []):
                        if b == omax and not rewired:
                            items.append((a, ou))
                            rewired = True
                        else:
                            items.append((a, b))
                    if not rewired:
                        items.append((omax, ou))
                    proposals[pgid] = items
                    # apply locally for subsequent rounds
                    self.m.pg_upmap_items[pgid] = items
                    place[pgid] = [ou if o == omax else o
                                   for o in place[pgid]]
                    pgs_by_osd[omax].discard(pgid)
                    pgs_by_osd.setdefault(ou, set()).add(pgid)
                    counts[omax] -= 1
                    counts[ou] += 1
                    moved = True
                    break
                if moved:
                    break
            if not moved:
                break
        return proposals

    def stddev(self) -> float:
        counts = self.pg_counts().astype(np.float64)
        live = [o for o in range(self.m.max_osd)
                if self.m.is_up(o) and not self.m.is_out(o)]
        return float(np.std(counts[live]))

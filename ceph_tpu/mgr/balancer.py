"""Upmap balancer — evens per-OSD PG load with pg_upmap_items.

Reference behavior re-created (``src/pybind/mgr/balancer/module.py``
upmap mode + ``OSDMap::calc_pg_upmaps`` in ``src/osd/OSDMap.cc``):
compute every PG's placement, find overfull/underfull OSDs against
their CRUSH-weight-proportional targets, and propose pg_upmap_items
exceptions moving single replicas from the fullest OSD to compatible
underfull ones — never violating the rule's failure domain.

TPU-first: the full-pool placement matrix comes from ONE BatchMapper
launch (`tools.osdmaptool.map_pool_pgs`) instead of the reference's
per-PG scalar loop, and — since the array control-plane refactor —
the optimize round itself is array-native: per-OSD PG counts are a
scatter-add over the placement matrix, overfull→underfull candidates
come from sorted deviation arrays, and the domain-conflict check is
one boolean [pgs-on-omax, underfull] eligibility matrix per round
instead of a per-PG dict walk.  ``optimize(use_arrays=False)`` keeps
the original per-PG walk as the equality oracle; both paths propose
byte-identical moves.

Apply through the mon: ``{"prefix": "osd pg-upmap-items", "pgid":
"<p.s>", "mappings": [[from, to], ...]}`` (same command the reference
balancer issues).
"""

from __future__ import annotations

import numpy as np

from ..crush.map import CRUSH_ITEM_NONE
from ..osd.osdmap import UP, OSDMap, PGid

# domain sentinel for placement slots that must not contribute a
# used-domain (holes, the overfull OSD itself).  Must sit outside the
# whole domain-value space: bucket ids are negative and "osd has no
# domain" is -1 (which DOES collide with a domain-less candidate,
# matching the legacy None-vs-None check) — so a large positive.
_DOM_IGNORE = 1 << 62


class UpmapBalancer:
    def __init__(self, osdmap: OSDMap, pool_id: int,
                 use_jax: bool = True, require_batched: bool = False,
                 placements: np.ndarray | None = None):
        """``placements``: optional precomputed [pg_num, size] raw
        CRUSH matrix (CRUSH_ITEM_NONE holes) — the scale harness
        injects synthetic or cached placements so a million-PG round
        doesn't recompute the mapping."""
        from ..utils.platform import ensure_x64
        if use_jax and placements is None:
            ensure_x64()        # BatchMapper needs 64-bit straw2 draws
        self.use_jax = use_jax
        self.require_batched = require_batched
        self.m = osdmap
        self.pool = osdmap.pools[pool_id]
        self.rule = osdmap.crush.rule_by_id(self.pool.crush_rule)
        self._raw_placements = placements
        # failure-domain type of the rule's choose step (0 = osd)
        self.domain_type = 0
        for s in self.rule.steps:
            if s.op.startswith(("choose_firstn", "chooseleaf_firstn",
                                "choose_indep", "chooseleaf_indep")):
                self.domain_type = s.arg2
        self._domain_of = self._build_domain_index()

    def _build_domain_index(self) -> dict[int, int]:
        """osd → ancestor bucket id of the failure-domain type."""
        dom: dict[int, int] = {}
        if self.domain_type == 0:
            return dom
        crush = self.m.crush

        def walk(bid: int, domain: int | None):
            b = crush.bucket(bid)
            d = bid if b.type == self.domain_type else domain
            for it in b.items:
                if it >= 0:
                    if d is not None:
                        dom[it] = d
                else:
                    walk(it, d)

        children = {it for b in crush.buckets if b is not None
                    for it in b.items if it < 0}
        for b in crush.buckets:
            if b is not None and b.type > self.domain_type and \
                    b.id not in children:
                walk(b.id, None)
        return dom

    # -- placement snapshot ------------------------------------------------
    def _raw_matrix(self) -> np.ndarray:
        if self._raw_placements is not None:
            return self._raw_placements
        from ..tools.osdmaptool import map_pool_pgs
        return map_pool_pgs(self.m, self.pool, use_jax=self.use_jax,
                            require_batched=self.require_batched)

    def _placements(self) -> dict[PGid, list[int]]:
        raw = self._raw_matrix()
        place: dict[PGid, list[int]] = {}
        for seed in range(self.pool.pg_num):
            pgid = PGid(self.pool.id, seed)
            row = [o for o in raw[seed] if o != CRUSH_ITEM_NONE]
            row = self.m._apply_upmap(pgid, row)
            place[pgid] = [o for o in row
                           if o != CRUSH_ITEM_NONE and self.m.is_up(o)]
        return place

    def _placement_matrix(self) -> np.ndarray:
        """[pg_num, size] int64 placement with upmaps applied and
        invalid (hole / not-up) slots as CRUSH_ITEM_NONE — the
        array-round state.  Upmap overrides are sparse, so only those
        rows take the per-PG path; everything else is two vectorized
        masks over the raw CRUSH matrix."""
        raw = np.asarray(self._raw_matrix(), dtype=np.int64)
        mat = raw.copy()
        pg_num, size = self.pool.pg_num, self.pool.size
        override = {p.seed for p in self.m.pg_upmap
                    if p.pool == self.pool.id and p.seed < pg_num}
        override |= {p.seed for p in self.m.pg_upmap_items
                     if p.pool == self.pool.id and p.seed < pg_num}
        for seed in override:
            pgid = PGid(self.pool.id, seed)
            row = [o for o in raw[seed] if o != CRUSH_ITEM_NONE]
            row = list(self.m._apply_upmap(pgid, row))[:size]
            mat[seed] = CRUSH_ITEM_NONE
            mat[seed, :len(row)] = row
        # mask holes and down/nonexistent OSDs in one pass
        valid = (mat >= 0) & (mat < self.m.max_osd)
        up = np.asarray(self.m.osd_state, dtype=np.int64) & UP != 0
        live = np.zeros_like(mat, dtype=bool)
        live[valid] = up[mat[valid]]
        mat[~live] = CRUSH_ITEM_NONE
        return mat

    def pg_counts(self, place=None) -> np.ndarray:
        if place is None:
            mat = self._placement_matrix()
            flat = mat[mat != CRUSH_ITEM_NONE]
            return np.bincount(flat, minlength=self.m.max_osd
                               ).astype(np.int64)
        counts = np.zeros(self.m.max_osd, dtype=np.int64)
        for osds in place.values():
            for o in osds:
                counts[o] += 1
        return counts

    def _targets(self) -> np.ndarray:
        """Per-OSD target load ∝ CRUSH device weight (in OSDs only)."""
        w = np.zeros(self.m.max_osd, dtype=np.float64)
        crush = self.m.crush
        for b in crush.buckets:
            if b is None:
                continue
            for it, bw in zip(b.items, b.weights):
                if it >= 0 and not self.m.is_out(it) \
                        and self.m.is_up(it):
                    w[it] = bw
        total_slots = self.pool.pg_num * self.pool.size
        if w.sum() == 0:
            return np.zeros_like(w)
        return total_slots * w / w.sum()

    def _live_mask(self) -> np.ndarray:
        st = np.asarray(self.m.osd_state, dtype=np.int64)
        wt = np.asarray(self.m.osd_weight, dtype=np.int64)
        return ((st & UP) != 0) & (wt != 0)

    def _rewire_items(self, pgid: PGid, omax: int,
                      ou: int) -> list[tuple[int, int]]:
        """The PG may sit on omax only VIA an existing upmap pair
        (raw→omax): rewrite that pair's target instead of appending a
        no-op (omax, ou) that _apply_upmap would ignore."""
        items = []
        rewired = False
        for a, b in self.m.pg_upmap_items.get(pgid, []):
            if b == omax and not rewired:
                items.append((a, ou))
                rewired = True
            else:
                items.append((a, b))
        if not rewired:
            items.append((omax, ou))
        return items

    # -- optimization ------------------------------------------------------
    def optimize(self, max_changes: int = 10,
                 deviation_stop: float = 1.0,
                 use_arrays: bool = True
                 ) -> dict[PGid, list[tuple[int, int]]]:
        """Propose up to max_changes pg_upmap_items changes.  Greedy
        per-round: move one replica off the currently fullest OSD to
        the most underfull compatible OSD (reference calc_pg_upmaps'
        retry loop, simplified to single-replica swaps).  The default
        array path and the legacy per-PG walk
        (``use_arrays=False``) propose identical moves."""
        if not use_arrays:
            return self._optimize_legacy(max_changes, deviation_stop)
        max_osd = self.m.max_osd
        mat = self._placement_matrix()
        flat = mat[mat != CRUSH_ITEM_NONE]
        counts = np.bincount(flat, minlength=max_osd
                             ).astype(np.float64)
        targets = self._targets()
        live = self._live_mask()
        # osd → failure-domain as an array (-1: no domain recorded)
        dom = np.full(max_osd, -1, dtype=np.int64)
        for o, d in self._domain_of.items():
            if 0 <= o < max_osd:
                dom[o] = d
        proposals: dict[PGid, list[tuple[int, int]]] = {}

        for _ in range(max_changes):
            dev = counts - targets
            dev[~live] = 0      # ignore out/down osds entirely
            omax = int(np.argmax(dev))
            if dev[omax] <= deviation_stop:
                break
            cand = np.nonzero(live & (dev < -0.5))[0]
            # stable sort keeps ascending-osd tie order, matching the
            # legacy sorted(..., key=dev) walk
            order = cand[np.argsort(dev[cand], kind="stable")]
            rows = np.nonzero((mat == omax).any(axis=1))[0]
            if order.size == 0 or rows.size == 0:
                break
            sub = mat[rows]                          # [P, S]
            # candidate already holds a replica of the PG?
            member = (sub[:, None, :] ==
                      order[None, :, None]).any(axis=2)      # [P, U]
            elig = ~member
            if self.domain_type:
                dsub = dom[np.clip(sub, 0, max_osd - 1)]
                invalid = (sub == omax) | (sub < 0) | (sub >= max_osd)
                dsub = np.where(invalid, _DOM_IGNORE, dsub)  # [P, S]
                d_ou = dom[order]                            # [U]
                conflict = (dsub[:, None, :] ==
                            d_ou[None, :, None]).any(axis=2)
                elig &= ~conflict
            hit = elig.any(axis=1)
            if not hit.any():
                break
            # first PG in seed order with a compatible candidate,
            # then its most-underfull compatible candidate — the
            # exact pair the legacy nested loops pick
            r = int(np.argmax(hit))
            ou = int(order[int(np.argmax(elig[r]))])
            seed = int(rows[r])
            pgid = PGid(self.pool.id, seed)
            items = self._rewire_items(pgid, omax, ou)
            proposals[pgid] = items
            # apply locally for subsequent rounds
            self.m.pg_upmap_items[pgid] = items
            mat[seed][mat[seed] == omax] = ou
            counts[omax] -= 1
            counts[ou] += 1
        return proposals

    def _optimize_legacy(self, max_changes: int = 10,
                         deviation_stop: float = 1.0
                         ) -> dict[PGid, list[tuple[int, int]]]:
        """The original per-PG dict walk, kept verbatim as the
        equality oracle for the array round."""
        place = self._placements()
        counts = self.pg_counts(place).astype(np.float64)
        targets = self._targets()
        proposals: dict[PGid, list[tuple[int, int]]] = {}
        pgs_by_osd: dict[int, set[PGid]] = {}
        for pgid, osds in place.items():
            for o in osds:
                pgs_by_osd.setdefault(o, set()).add(pgid)

        for _ in range(max_changes):
            dev = counts - targets
            # ignore out/down osds entirely
            for o in range(self.m.max_osd):
                if not self.m.is_up(o) or self.m.is_out(o):
                    dev[o] = 0
            omax = int(np.argmax(dev))
            if dev[omax] <= deviation_stop:
                break
            under = sorted(
                (o for o in range(self.m.max_osd)
                 if self.m.is_up(o) and not self.m.is_out(o)
                 and dev[o] < -0.5),
                key=lambda o: dev[o])
            moved = False
            for pgid in sorted(pgs_by_osd.get(omax, ()),
                               key=lambda p: p.seed):
                others = [o for o in place[pgid] if o != omax]
                used_domains = {self._domain_of.get(o) for o in others} \
                    if self.domain_type else set()
                for ou in under:
                    if ou in place[pgid]:
                        continue
                    if self.domain_type and \
                            self._domain_of.get(ou) in used_domains:
                        continue
                    items = self._rewire_items(pgid, omax, ou)
                    proposals[pgid] = items
                    # apply locally for subsequent rounds
                    self.m.pg_upmap_items[pgid] = items
                    place[pgid] = [ou if o == omax else o
                                   for o in place[pgid]]
                    pgs_by_osd[omax].discard(pgid)
                    pgs_by_osd.setdefault(ou, set()).add(pgid)
                    counts[omax] -= 1
                    counts[ou] += 1
                    moved = True
                    break
                if moved:
                    break
            if not moved:
                break
        return proposals

    def stddev(self) -> float:
        counts = self.pg_counts().astype(np.float64)
        live = self._live_mask()
        return float(np.std(counts[live]))

"""Process-parallel cluster runtime — real daemons, real ``kill -9``.

The threaded ``MiniCluster`` runs every daemon inside one interpreter,
so "power loss" is a simulation (truncate + cold remount) and a knee
measurement measures the GIL.  This module is the other half: a daemon
described by a serializable :class:`DaemonSpec` is spawned as its own
OS process (``python -m ceph_tpu.procs <spec.json>``), joins the
cluster over the existing TCP messenger, and can be killed with a
genuine SIGKILL — nothing in the dead process gets a chance to flush,
truncate, or tidy up.  The parent talks to it only through what real
operators have: the wire, the admin socket (a Unix socket, so it
crosses the process boundary), the readiness file, and signals.

Contracts:

- **Boot spec**: everything a child needs rides one JSON blob —
  entity kind + ident, the monmap (ports pre-allocated by the
  parent), the WAL path, osd_config, the fault seed, and pre-assigned
  asok/readiness paths.  No pickling, no inherited Python state.
- **Readiness**: the child writes ``{"pid", "ident"}`` atomically to
  ``spec.ready_path`` only once the daemon is actually serving (an
  OSD after ``start(wait_for_up=True)`` returns).  ``spawn_daemon``
  polls ready-file vs process-exit vs deadline, and retries a failed
  spawn before raising :class:`ProcSpawnError` with the log tail.
- **Orphan reaping**: every spawn registers in a module-level PID
  table; ``reap_orphans()`` SIGKILLs + waits anything still alive and
  runs from ``atexit`` always — a crashed test cannot strand daemons.
  ``tests/conftest.py`` additionally asserts the table is empty at
  session teardown so a leak fails the run loudly.
- **kill -9 semantics**: children run with ``CEPH_TPU_PROC_DAEMON=1``
  in the environment, which arms the ``kill9`` crash point in
  ``WALStore`` to deliver a real ``os.kill(getpid(), SIGKILL)``.
  Because the store flushes the WAL per append, the OS page cache
  holds every appended record at the instant of death — SIGKILL
  loses *process* state, not *written* state — while a simulated
  power cut keeps only the fsynced prefix.  Both are one revive away:
  a fresh process cold-remounts the same WAL file.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field

DAEMON_KINDS = ("mon", "osd", "mgr", "workload", "msgr_victim")

# child-process marker: WALStore's kill9 crash point delivers a real
# SIGKILL only when this is set (threaded mode degrades it to the
# pre_append simulated power cut)
PROC_ENV = "CEPH_TPU_PROC_DAEMON"


class ProcSpawnError(RuntimeError):
    """A daemon process failed to come up within its retry budget."""


# -- orphan registry ------------------------------------------------------
# pid → ProcHandle for every child THIS process spawned.  The atexit
# sweep is the backstop; conftest.py's session fixture is the loud
# version that fails the test run on a leak.
_SPAWNED: dict[int, "ProcHandle"] = {}
_REG_LOCK = threading.Lock()


def register_pid(handle: "ProcHandle") -> None:
    with _REG_LOCK:
        _SPAWNED[handle.pid] = handle


def unregister_pid(pid: int) -> None:
    with _REG_LOCK:
        _SPAWNED.pop(pid, None)


def live_pids() -> list[int]:
    """PIDs of spawned children still alive (reaps exited ones)."""
    with _REG_LOCK:
        handles = list(_SPAWNED.values())
    return [h.pid for h in handles if h.alive()]


def reap_orphans() -> list[int]:
    """SIGKILL + wait every tracked child still alive; → reaped PIDs."""
    reaped = []
    with _REG_LOCK:
        handles = list(_SPAWNED.values())
        _SPAWNED.clear()
    for h in handles:
        if h.alive():
            reaped.append(h.pid)
            try:
                h.proc.kill()
            except OSError:
                pass
        try:
            h.proc.wait(timeout=10)
        except Exception:   # noqa: BLE001 — best-effort at teardown
            pass
    return reaped


atexit.register(reap_orphans)


# -- boot spec ------------------------------------------------------------
@dataclass
class DaemonSpec:
    """Serializable boot description for one daemon process."""

    kind: str                        # one of DAEMON_KINDS
    ident: str                       # "0" for mon.0/osd.0, mgr name …
    monmap: dict | None = None       # MonMap.to_dict()
    wal_path: str | None = None      # OSD: durable backing (walstore)
    osd_config: dict = field(default_factory=dict)
    fault_seed: int | None = None
    asok_path: str | None = None     # pre-assigned admin socket
    ready_path: str | None = None    # readiness-file handshake
    extra: dict = field(default_factory=dict)   # kind-specific knobs

    def __post_init__(self):
        if self.kind not in DAEMON_KINDS:
            raise ValueError(
                f"unknown daemon kind {self.kind!r}; "
                f"one of {DAEMON_KINDS}")

    def to_dict(self) -> dict:
        return {"kind": self.kind, "ident": self.ident,
                "monmap": self.monmap, "wal_path": self.wal_path,
                "osd_config": dict(self.osd_config),
                "fault_seed": self.fault_seed,
                "asok_path": self.asok_path,
                "ready_path": self.ready_path,
                "extra": dict(self.extra)}

    @classmethod
    def from_dict(cls, d: dict) -> "DaemonSpec":
        return cls(kind=d["kind"], ident=str(d["ident"]),
                   monmap=d.get("monmap"), wal_path=d.get("wal_path"),
                   osd_config=dict(d.get("osd_config") or {}),
                   fault_seed=d.get("fault_seed"),
                   asok_path=d.get("asok_path"),
                   ready_path=d.get("ready_path"),
                   extra=dict(d.get("extra") or {}))

    @property
    def name(self) -> str:
        return f"{self.kind}.{self.ident}"


class ProcHandle:
    """Parent-side handle on one spawned daemon process."""

    def __init__(self, spec: DaemonSpec, proc: subprocess.Popen,
                 log_path: str):
        self.spec = spec
        self.proc = proc
        self.log_path = log_path

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def signal(self, sig: int) -> None:
        os.kill(self.pid, sig)

    def kill9(self) -> None:
        """True process death: SIGKILL, then reap the zombie."""
        try:
            self.proc.kill()
        except OSError:
            pass
        self.wait(timeout=10)

    def terminate(self) -> None:
        try:
            self.proc.terminate()
        except OSError:
            pass

    def wait(self, timeout: float | None = None) -> int | None:
        try:
            rc = self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None
        unregister_pid(self.pid)
        return rc

    def stop(self, timeout: float = 10.0) -> int | None:
        """Clean shutdown: SIGTERM, escalate to SIGKILL at timeout."""
        if not self.alive():
            return self.wait(timeout=timeout)
        self.terminate()
        rc = self.wait(timeout=timeout)
        if rc is None:
            self.kill9()
            rc = self.proc.returncode
        return rc

    def log_tail(self, n: int = 40) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"

    def __repr__(self):
        state = "alive" if self.alive() else \
            f"exit={self.proc.returncode}"
        return f"ProcHandle({self.spec.name}, pid={self.pid}, {state})"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_daemon(spec: DaemonSpec, *, retries: int = 2,
                 timeout: float = 30.0,
                 run_dir: str | None = None) -> ProcHandle:
    """Spawn one daemon process from its boot spec and wait for the
    readiness file.  A failed attempt (exit before ready, or deadline)
    is killed, reaped, and retried; exhaustion raises
    :class:`ProcSpawnError` carrying the last log tail."""
    if run_dir is None:
        run_dir = tempfile.mkdtemp(prefix="ceph-tpu-procs-")
    if spec.ready_path is None:
        spec.ready_path = os.path.join(
            run_dir, f"{spec.name}.ready")
    spec_path = os.path.join(run_dir, f"{spec.name}.spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec.to_dict(), f)
    log_path = os.path.join(run_dir, f"{spec.name}.log")
    env = dict(os.environ)
    env[PROC_ENV] = "1"
    env["PYTHONPATH"] = _repo_root() + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    last_err = "never attempted"
    attempts = 1 + max(0, int(retries))
    for attempt in range(attempts):
        try:
            os.unlink(spec.ready_path)
        except FileNotFoundError:
            pass
        with open(log_path, "ab") as logf:
            logf.write(
                f"--- spawn attempt {attempt + 1}/{attempts} "
                f"{spec.name} ---\n".encode())
            logf.flush()
            proc = subprocess.Popen(
                [sys.executable, "-m", "ceph_tpu.procs", spec_path],
                stdout=logf, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL, env=env,
                start_new_session=True)
        handle = ProcHandle(spec, proc, log_path)
        register_pid(handle)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if os.path.exists(spec.ready_path):
                try:
                    with open(spec.ready_path) as f:
                        ready = json.load(f)
                except (OSError, ValueError):
                    time.sleep(0.01)    # racing the atomic rename
                    continue
                if int(ready.get("pid", -1)) == proc.pid:
                    return handle
                # stale ready file from a previous incarnation on the
                # same path: ignore it and keep waiting for ours
            if proc.poll() is not None:
                last_err = (f"exited rc={proc.returncode} before "
                            f"ready: {handle.log_tail()}")
                break
            time.sleep(0.02)
        else:
            last_err = f"not ready in {timeout}s: {handle.log_tail()}"
        handle.kill9()
    raise ProcSpawnError(
        f"{spec.name}: spawn failed after {attempts} attempt(s): "
        f"{last_err}")


def write_ready(spec: DaemonSpec) -> None:
    """Atomic readiness handshake (child side): tmp + rename so the
    parent never reads a torn file.  The wall/mono clock pair is this
    process's monotonic-to-wall alignment — the parent rebases the
    child's span starts and black-box stamps with it when merging
    cross-process timelines (asok dump headers carry the same pair,
    fresher; the readiness file is the fallback that survives the
    daemon's death)."""
    if not spec.ready_path:
        return
    tmp = spec.ready_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": os.getpid(), "ident": spec.ident,
                   "kind": spec.kind, "wall": time.time(),
                   "mono": time.monotonic()}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, spec.ready_path)


# -- open-loop rados ramp (shared by bench threaded leg + workload child)
def run_rados_ramp(monmap, *, seed: int = 0, pool: str = "ramp",
                   pool_size: int = 2, pg_num: int = 8,
                   rates=(50, 100, 200, 400, 800, 1600),
                   step_duration: float = 2.0,
                   slo_p99_ms: float = 250.0,
                   object_kb: int = 16, n_objects: int = 64,
                   workers: int = 8) -> dict:
    """Rados-level ramp-to-collapse: step the offered rate through a
    geometric ladder of seeded open-loop write/read mixes and find the
    knee — the last rate where p99 holds the SLO, goodput keeps ≥90%
    of offered, and no op errors.  Same knee definition as
    ``workload.scenarios.ramp_to_collapse`` but driven straight at
    librados (no RGW front door), so it runs identically in-process
    (threaded leg) and as a ``workload`` daemon process (procs leg).
    """
    import random as _random

    from .mon.monitor import MonMap
    from .osdc.librados import Rados
    from .workload.generator import (RBD_READ, RBD_WRITE, LoadGenerator,
                                     OpMix, TenantProfile)
    from .workload.slo import SLOTracker

    if isinstance(monmap, dict):
        monmap = MonMap.from_dict(monmap)
    r = Rados(monmap, name=f"client.ramp{seed}").connect()
    try:
        if pool not in r.list_pools():
            r.create_pool(pool, pg_num=pg_num, size=pool_size)
        io = r.open_ioctx(pool)
        payload = _random.Random(seed).randbytes(object_kb << 10)
        for i in range(n_objects):
            io.write_full(f"ramp-{i}", payload)

        def execute(op):
            oid = f"ramp-{op.seq % n_objects}"
            if op.op_class == RBD_WRITE:
                io.write_full(oid, payload)
            else:
                io.read(oid)

        mix = OpMix({RBD_WRITE: 1, RBD_READ: 1})
        steps, knee, collapse = [], None, None
        for rate in rates:
            tracker = SLOTracker({"*": slo_p99_ms})
            prof = TenantProfile("ramp", rate, kind="poisson",
                                 mix=mix, size=object_kb << 10,
                                 seed=seed)
            gen = LoadGenerator([prof], execute,
                                duration=step_duration,
                                workers=workers, tracker=tracker)
            stop = threading.Event()

            def _tick():
                while not stop.wait(0.25):
                    tracker.evaluate()
            t = threading.Thread(target=_tick, daemon=True)
            t.start()
            open_loop = gen.run()
            stop.set()
            t.join(timeout=2)
            rep = tracker.report()
            p99 = max((lane["p99_ms"]
                       for t_ in rep["tenants"].values()
                       for lane in t_.values()), default=0.0)
            holds = (p99 <= slo_p99_ms
                     and rep["goodput_ops"]
                     >= 0.9 * rep["offered_rate"]
                     and open_loop["errors"] == 0)
            steps.append({"rate": rate, "p99_ms": round(p99, 2),
                          "goodput_ops": round(rep["goodput_ops"], 1),
                          "offered_rate":
                              round(rep["offered_rate"], 1),
                          "errors": open_loop["errors"],
                          "drift_pct":
                              round(open_loop["drift_pct"], 2),
                          "holds": holds})
            if holds:
                knee = rate
            else:
                collapse = rate
                break
        return {"seed": seed, "slo_p99_ms": slo_p99_ms,
                "knee_ops_per_sec": knee,
                "collapse_ops_per_sec": collapse, "steps": steps}
    finally:
        r.shutdown()


# -- child entrypoint -----------------------------------------------------
def _force_cpu_jax() -> None:
    """Pin jax to CPU NOW, before any daemon code imports it lazily:
    the TPU plugin force-overrides platform selection at import, and a
    procs-mode OSD grabbing the real chip under a CPU test run is a
    hang, not a failure."""
    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:   # noqa: BLE001 — jax-free paths must still run
        pass


def _seed_faults(msgr, fault_seed) -> None:
    if fault_seed is None:
        return
    import random as _random
    msgr.faults.seed = int(fault_seed)
    msgr.faults.rng = _random.Random(int(fault_seed))


def _build_mon(spec: DaemonSpec):
    from .mon.monitor import MonMap, Monitor
    mon = Monitor(int(spec.ident), MonMap.from_dict(spec.monmap),
                  admin_socket_path=spec.asok_path)
    _seed_faults(mon.msgr, spec.fault_seed)
    mon.start()
    return mon


def _build_osd(spec: DaemonSpec):
    from .mon.monitor import MonMap
    from .os_store import CrashInjector, WALStore
    from .osd.daemon import OSDaemon

    whoami = int(spec.ident)
    cfg = None
    if spec.osd_config:
        from .core.config import ConfigProxy
        from .core.options import build_options
        cfg = ConfigProxy(build_options())
        for k, v in spec.osd_config.items():
            cfg.set(k, v)
    store = None
    if spec.wal_path and spec.osd_config.get(
            "osd_objectstore", "walstore") == "walstore":
        inj = CrashInjector(seed=int(spec.fault_seed or 0),
                            osd=f"osd.{whoami}")
        for point, prob in (spec.extra.get("crash_probs")
                            or {}).items():
            inj.set_prob(point, float(prob))
        store = WALStore(
            spec.wal_path,
            sync_mode=spec.osd_config.get("osd_wal_sync_mode",
                                          "batch"),
            name=f"osd.{whoami}", crash=inj,
            compact_min_records=int(spec.osd_config.get(
                "osd_wal_compact_min_records", 0)))
    osd = OSDaemon(whoami, MonMap.from_dict(spec.monmap),
                   store=store, config=cfg,
                   admin_socket_path=spec.asok_path)
    _seed_faults(osd.msgr, spec.fault_seed)
    osd.start(wait_for_up=True,
              timeout=float(spec.extra.get("boot_timeout", 30.0)))
    return osd


def _build_mgr(spec: DaemonSpec):
    import importlib

    from .mgr.daemon import MgrDaemon
    from .mon.monitor import MonMap
    modules = None
    if spec.extra.get("modules"):
        # dotted "pkg.mod:Class" strings — classes don't serialize
        modules = []
        for path in spec.extra["modules"]:
            modname, _, clsname = path.partition(":")
            modules.append(
                getattr(importlib.import_module(modname), clsname))
    mgr = MgrDaemon(spec.ident, MonMap.from_dict(spec.monmap),
                    modules=tuple(modules) if modules else None,
                    asok_paths=spec.extra.get("asok_paths"),
                    admin_socket_path=spec.asok_path)
    _seed_faults(mgr.msgr, spec.fault_seed)
    mgr.start()
    return mgr


def _run_workload(spec: DaemonSpec) -> int:
    """Open-loop generator as its own process: ready first (the parent
    tracks the PID), then drive the ramp, then write the report JSON
    and exit 0 — the parent collects via wait() + result file."""
    write_ready(spec)
    params = dict(spec.extra.get("ramp") or {})
    result_path = spec.extra.get("result_path")
    report = run_rados_ramp(spec.monmap,
                            seed=int(spec.fault_seed or 0), **params)
    if result_path:
        tmp = result_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f)
        os.replace(tmp, result_path)
    return 0


def _build_msgr_victim(spec: DaemonSpec):
    """Accept-side messenger that records every MGenericReply.result
    (one int per line, flushed) to extra["out_path"] — the kill-the-
    accepting-end-mid-stream target for tests/test_msgr.py.  Stays
    jax-free: the msg import chain never touches numpy or jax."""
    from .msg import Dispatcher, MGenericReply, Messenger

    out = open(spec.extra["out_path"], "a", buffering=1)

    class _Sink(Dispatcher):
        def ms_dispatch(self, msg):
            if isinstance(msg, MGenericReply):
                out.write(f"{msg.result}\n")
                return True
            return False

    msgr = Messenger(spec.extra.get("entity", "osd.victim"))
    msgr.add_dispatcher(_Sink())
    msgr.bind("127.0.0.1", int(spec.extra["port"]))
    return msgr


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: python -m ceph_tpu.procs <spec.json>",
              file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        spec = DaemonSpec.from_dict(json.load(f))
    if spec.kind != "msgr_victim":
        # daemons lazily import jax (batch-engine lanes); pin the
        # platform before any of that can run.  The victim skips it to
        # keep the tier-1 messenger test spawn cheap.
        _force_cpu_jax()
    stop = threading.Event()

    def _on_sigterm(signum, frame):   # noqa: ARG001
        stop.set()

    signal.signal(signal.SIGTERM, _on_sigterm)
    signal.signal(signal.SIGINT, _on_sigterm)
    if spec.kind == "workload":
        return _run_workload(spec)
    builders = {"mon": _build_mon, "osd": _build_osd,
                "mgr": _build_mgr, "msgr_victim": _build_msgr_victim}
    daemon = builders[spec.kind](spec)
    write_ready(spec)
    stop.wait()
    try:
        daemon.shutdown()
    except Exception:   # noqa: BLE001 — exiting anyway
        pass
    # skip interpreter teardown: daemon threads mid-poll segfault-free
    os._exit(0)


if __name__ == "__main__":
    sys.exit(main())

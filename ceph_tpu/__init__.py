"""ceph_tpu — a TPU-native re-design of the capabilities of Ceph.

This is NOT a port of the reference (``alvinsunalvin/ceph``, a fork of
``ceph/ceph``).  It is a from-scratch framework, architected for JAX / XLA /
Pallas on TPU, that re-creates the reference's capability surface:

- ``ceph_tpu.ops``      — GF(2^8) arithmetic, Reed-Solomon matrix math,
  rjenkins hashing, and the CRUSH fixed-point ``ln`` tables, each with a
  NumPy oracle (bit-exactness standard) and a vectorised JAX form.
- ``ceph_tpu.ec``       — the erasure-code subsystem: plugin registry,
  jerasure/isa/lrc/shec/clay-equivalent plugins, and the TPU batch engine
  (reference: ``src/erasure-code/``).
- ``ceph_tpu.crush``    — CRUSH placement: map model, rule VM oracle, and
  the TPU batch mapper (reference: ``src/crush/``).
- ``ceph_tpu.osd``      — OSDMap analog and the EC backend stripe math
  (reference: ``src/osd/OSDMap.cc``, ``src/osd/ECUtil.h``).
- ``ceph_tpu.parallel`` — device-mesh sharding and the multi-chip
  degraded-read reconstruct path (ICI all-gather).
- ``ceph_tpu.utils``    — runtime substrate: buffers, versioned encoding,
  config options, perf counters (reference: ``src/common/``).
- ``ceph_tpu.tools``    — CLI parity tools: ``ec_bench``, ``osdmaptool``,
  ``crushtool`` equivalents.

Provenance note: the reference mount was empty during the survey (see
SURVEY.md §0); compatibility target is "upstream Ceph, vintage unknown".
Bit-exactness claims in this tree are therefore between the documented
upstream algorithms (re-implemented independently), the NumPy/C++ oracles
in this repo, and the TPU kernels — all cross-checked in tests/.
"""

__version__ = "0.1.0"

"""Object classes — server-side compute on objects.

Reference behavior re-created (``src/osd/ClassHandler.cc`` +
``src/cls/``; SURVEY.md §3.5): clients invoke named methods that run
ON the primary inside the op pipeline with read access to the object
and the ability to stage mutations — the mechanism behind rbd/rgw
metadata ops and advisory locking.  The reference dlopens
``libcls_*.so``; here classes are Python modules registered in-process
(`register`, `method`), the idiomatic analog of the plugin registry.

Built-ins: ``lock`` (advisory shared/exclusive locks with cookies —
reference ``src/cls/lock``) and ``version`` (monotonic object version
stamps — reference ``src/cls/version``).
"""

from __future__ import annotations

import json


class ClsError(Exception):
    def __init__(self, rc: int, msg: str = ""):
        super().__init__(msg or f"cls error rc={rc}")
        self.rc = rc


class ClsContext:
    """What a class method sees (reference cls_method_context_t):
    reads against the object's current state, staged writes that join
    the surrounding op's transaction."""

    def __init__(self, read_xattr, exists, read_omap=None):
        self._read_xattr = read_xattr
        self._exists = exists
        self._read_omap = read_omap
        self.staged_ops: list[dict] = []

    # -- reads -------------------------------------------------------------
    def exists(self) -> bool:
        return self._exists()

    def get_xattr(self, name: str) -> bytes | None:
        return self._read_xattr(name)

    def get_omap(self) -> dict[str, bytes]:
        if self._read_omap is None:
            return {}
        return self._read_omap()

    # -- staged writes ------------------------------------------------------
    def set_xattr(self, name: str, value: bytes):
        self.staged_ops.append({"op": "setxattr", "name": name,
                                "data": value.hex()})

    def rm_xattr(self, name: str):
        self.staged_ops.append({"op": "rmxattr", "name": name})

    def set_omap(self, kv: dict[str, bytes]):
        self.staged_ops.append({"op": "omap_set", "kv": {
            k: v.hex() for k, v in kv.items()}})

    def rm_omap(self, keys: list[str]):
        self.staged_ops.append({"op": "omap_rm", "keys": list(keys)})

    def create(self):
        """Ensure the object exists (zero-length write)."""
        if not self.exists():
            self.staged_ops.append({"op": "write_full", "data": ""})


_REGISTRY: dict[str, dict[str, object]] = {}


def register(cls_name: str):
    _REGISTRY.setdefault(cls_name, {})


def method(cls_name: str, name: str):
    """Decorator: fn(ctx, input_bytes) -> output_bytes (raise
    ClsError(-errno) to fail the op)."""
    register(cls_name)

    def deco(fn):
        _REGISTRY[cls_name][name] = fn
        return fn
    return deco


def call(cls_name: str, method_name: str, ctx: ClsContext,
         inp: bytes) -> bytes:
    cls = _REGISTRY.get(cls_name)
    if cls is None:
        raise ClsError(-95, f"no class {cls_name!r}")      # EOPNOTSUPP
    fn = cls.get(method_name)
    if fn is None:
        raise ClsError(-95, f"no method {cls_name}.{method_name}")
    out = fn(ctx, inp)
    return out if out is not None else b""


# --------------------------------------------------------------------------
# cls_lock — advisory locking (reference src/cls/lock/cls_lock.cc)
# --------------------------------------------------------------------------
_LOCK_XATTR = "lock.%s"


def _load_lock(ctx: ClsContext, name: str) -> dict:
    raw = ctx.get_xattr(_LOCK_XATTR % name)
    return json.loads(bytes(raw)) if raw else {"type": "", "lockers": {}}


@method("lock", "lock")
def _lock_lock(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    name = req["name"]
    ltype = req.get("type", "exclusive")
    cookie = req["cookie"]
    entity = req.get("entity", "")
    st = _load_lock(ctx, name)
    holders = st["lockers"]
    mine = f"{entity}/{cookie}"
    if holders:
        if st["type"] == "exclusive" or ltype == "exclusive":
            if list(holders) != [mine]:
                raise ClsError(-16, "lock held")           # EBUSY
    st["type"] = ltype
    holders[mine] = {"entity": entity, "cookie": cookie, "type": ltype}
    ctx.create()
    ctx.set_xattr(_LOCK_XATTR % name, json.dumps(st).encode())
    return b""


@method("lock", "unlock")
def _lock_unlock(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    name = req["name"]
    mine = f"{req.get('entity', '')}/{req['cookie']}"
    st = _load_lock(ctx, name)
    if mine not in st["lockers"]:
        raise ClsError(-2, "no such lock holder")          # ENOENT
    del st["lockers"][mine]
    if st["lockers"]:
        ctx.set_xattr(_LOCK_XATTR % name, json.dumps(st).encode())
    else:
        ctx.rm_xattr(_LOCK_XATTR % name)
    return b""


@method("lock", "info")
def _lock_info(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode()) if inp else {}
    st = _load_lock(ctx, req.get("name", ""))
    return json.dumps(st).encode()


# --------------------------------------------------------------------------
# cls_version — monotonic object versions (reference src/cls/version)
# --------------------------------------------------------------------------
@method("version", "inc")
def _version_inc(ctx: ClsContext, inp: bytes) -> bytes:
    raw = ctx.get_xattr("cls.version")
    cur = int(bytes(raw)) if raw else 0
    ctx.create()
    ctx.set_xattr("cls.version", str(cur + 1).encode())
    return str(cur + 1).encode()


@method("version", "read")
def _version_read(ctx: ClsContext, inp: bytes) -> bytes:
    raw = ctx.get_xattr("cls.version")
    return bytes(raw) if raw else b"0"


# --------------------------------------------------------------------------
# cls_log — time-indexed log entries in omap (reference src/cls/log)
# --------------------------------------------------------------------------
# Keys sort by (timestamp, sub-second counter) so `list` pages in time
# order; `trim` drops everything up to a marker — the structure RGW
# multisite mdlog/datalog shards are built on.

def _log_key(ts: float, seq: int) -> str:
    return f"log.{ts:020.6f}.{seq:08d}"


@method("log", "add")
def _log_add(ctx: ClsContext, inp: bytes) -> bytes:
    import time as _time
    req = json.loads(inp.decode())
    entries = req["entries"] if "entries" in req else [req]
    rows = {}
    existing = ctx.get_omap()
    # persisted MONOTONIC counter: deriving seq from a key count
    # would re-mint a surviving key's seq after a partial trim and
    # silently overwrite its entry
    seq = int(existing.get("log_seq", b"0"))
    for e in entries:
        ts = float(e.get("timestamp", _time.time()))
        rows[_log_key(ts, seq)] = json.dumps(
            {"timestamp": ts, "section": e.get("section", ""),
             "name": e.get("name", ""),
             "data": e.get("data", "")}).encode()
        seq += 1
    rows["log_seq"] = str(seq).encode()
    ctx.create()
    ctx.set_omap(rows)
    return b""


@method("log", "list")
def _log_list(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode()) if inp else {}
    marker = req.get("marker", "")
    limit = int(req.get("max_entries", 100))
    rows = ctx.get_omap()
    keys = sorted(k for k in rows if k.startswith("log.")
                  and k > marker)
    page = keys[:limit]
    out = {"entries": [dict(json.loads(bytes(rows[k])), key=k)
                       for k in page],
           "truncated": len(keys) > limit,
           "marker": page[-1] if page else marker}
    return json.dumps(out).encode()


@method("log", "trim")
def _log_trim(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    upto = req["to_marker"]
    rows = ctx.get_omap()
    dead = [k for k in rows if k.startswith("log.") and k <= upto]
    if not dead:
        raise ClsError(-2, "nothing to trim")
    ctx.rm_omap(dead)
    return b""

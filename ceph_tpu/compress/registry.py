"""Codec registry — the compressor analog of ``ec/registry.py``.

The reference registers compressor plugins by name
(``src/compressor/Compressor.cc``: ``Compressor::create`` switches on
the pool's ``compression_algorithm``).  Codecs register in-process
here the same way EC plugins do; pool options and the mon's
``osd pool set`` validation resolve through ``list_codecs``.
"""

from __future__ import annotations

import threading
from typing import Callable

from .codec import Codec, CodecError

_CODECS: dict[str, Callable[[], Codec]] = {}
_BUILTINS_LOADED = False
_LOAD_LOCK = threading.Lock()


def register_codec(name: str, factory: Callable[[], Codec]):
    _CODECS[name] = factory


def list_codecs() -> list[str]:
    _load_builtin()
    return sorted(_CODECS)


def _load_builtin():
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:
        return
    # same double-checked pattern as ec.registry: many OSD threads hit
    # their first compress at once — the flag flips only after every
    # builtin is registered
    with _LOAD_LOCK:
        if _BUILTINS_LOADED:
            return
        from .codec import PassthroughCodec, RleCodec, ZlibCodec
        register_codec("none", PassthroughCodec)
        register_codec("rle", RleCodec)
        # the device-batched hybrid under its framework name, the way
        # "jax_tpu" aliases jerasure in the EC registry
        register_codec("rle_jax", RleCodec)
        register_codec("zlib", ZlibCodec)
        _BUILTINS_LOADED = True


def create_codec(name: str) -> Codec:
    _load_builtin()
    factory = _CODECS.get(name)
    if factory is None:
        raise CodecError(f"unknown compression codec {name!r}"
                         f" (available: {sorted(_CODECS)})")
    return factory()

"""Dedup index conventions — the ``os_store`` refcount layer.

The reference implements dedup with a chunk pool + ``cls_refcount``
objects (RGW dedup / the tiering-based dedup work PAPER.md cites):
each stored object becomes a *manifest* of chunk fingerprints, chunk
payloads live once under refcount.  Here the chunk store is one
collection per OSD (``dedup``) holding ``chunk_<fp>`` objects, with
refcounts in the omap of a single index object — and the conditional
ingest/release themselves are **transaction opcodes**
(``Transaction.dedup_ingest`` / ``dedup_release``), so they ride the
same replicated txn as the manifest write and every acting member
applies them against its *own* local index (apply-time conditionals
keep replicas consistent without the primary knowing their state).

Balance invariant (checked by ``verify_refcounts``, wired into
MiniCluster teardown): for every store, each fingerprint's refcount
equals the number of live manifest entries naming it, and refcounts
that reach zero have removed their chunk — deletes balance to zero.

Dedup is a replicated-pool feature: chunks replicate with the object
(each acting member keeps its own chunk copy, exactly like replica
data bytes).  EC pools refuse ``dedup_enable`` at the mon — an EC
manifest would need a separately-coded chunk pool to beat replication,
which is the reference's architecture and out of scope here.
"""

from __future__ import annotations

import collections
import json

DEDUP_COLL = "dedup"
DEDUP_INDEX_OID = "_dedup_index"
CHUNK_PREFIX = "chunk_"


def chunk_oid(fp: str) -> str:
    return CHUNK_PREFIX + fp


# -- chunk frames -----------------------------------------------------------
# A chunk object's stored bytes are self-describing: a 1-byte tag, then
# either the raw chunk or a compression header + blob.  Self-description
# matters because ingest is conditional — the FIRST writer of a
# fingerprint decides the stored form, and later manifests referencing
# the same chunk may have been written under different pool compression
# settings.  Any reader can expand any frame.

def frame_raw(chunk: bytes) -> bytes:
    return b"\x00" + bytes(chunk)


def frame_sealed(blob: bytes, header: dict) -> bytes:
    hdr = json.dumps(header, separators=(",", ":")).encode()
    return b"\x01" + len(hdr).to_bytes(4, "big") + hdr + bytes(blob)


def unframe(frame: bytes) -> tuple[bytes, dict | None]:
    """→ (payload, header).  header None ⇒ payload IS the raw chunk;
    otherwise payload is a compressed blob to expand with header."""
    frame = bytes(frame)
    if not frame:
        raise ValueError("empty dedup chunk frame")
    if frame[0] == 0:
        return frame[1:], None
    if frame[0] != 1:
        raise ValueError(f"bad dedup chunk frame tag {frame[0]}")
    n = int.from_bytes(frame[1:5], "big")
    header = json.loads(frame[5:5 + n].decode())
    return frame[5 + n:], header


def manifest_entries(meta: dict | None) -> list:
    """The ``[[fp, length], ...]`` manifest from an object's "_" meta
    (empty when the object is not dedup-sealed)."""
    if not meta:
        return []
    return list(meta.get("dedup") or [])


def index_refcounts(store) -> dict[str, int]:
    """fp → live refcount from a store's dedup index."""
    try:
        omap = store.omap_get(DEDUP_COLL, DEDUP_INDEX_OID)
    except KeyError:
        return {}
    return {fp: int(bytes(v)) for fp, v in omap.items()}


def dedup_stats(store) -> dict:
    """Physical vs referenced (logical) bytes of a store's chunk set."""
    refs = index_refcounts(store)
    stored = 0
    referenced = 0
    for fp, n in refs.items():
        try:
            size = store.stat(DEDUP_COLL, chunk_oid(fp))["size"]
        except KeyError:
            size = 0
        stored += size
        referenced += size * n
    return {"chunks": len(refs), "refs": sum(refs.values()),
            "stored_bytes": stored, "referenced_bytes": referenced}


def expected_refcounts(store) -> collections.Counter:
    """fp → reference count implied by every live manifest in the
    store (all collections, all objects) — the ground truth the index
    must match."""
    expect: collections.Counter = collections.Counter()
    for cid in store.list_collections():
        if cid == DEDUP_COLL:
            continue
        for oid in store.list_objects(cid):
            try:
                meta = json.loads(bytes(store.getattr(cid, oid, "_")))
            except (KeyError, ValueError):
                continue
            for fp, _ln in manifest_entries(meta):
                expect[fp] += 1
    return expect


def verify_refcounts(store) -> list[str]:
    """Leak check: [] when the index exactly matches the live
    manifests and no orphan chunk objects remain."""
    problems = []
    refs = index_refcounts(store)
    expect = expected_refcounts(store)
    for fp in sorted(set(refs) | set(expect)):
        have, want = refs.get(fp, 0), expect.get(fp, 0)
        if have != want:
            problems.append(
                f"fp {fp}: refcount {have} != {want} live references")
    try:
        objs = store.list_objects(DEDUP_COLL)
    except KeyError:
        objs = []
    for oid in objs:
        if oid == DEDUP_INDEX_OID:
            continue
        fp = oid[len(CHUNK_PREFIX):]
        if refs.get(fp, 0) <= 0:
            problems.append(f"orphan chunk object {oid}")
    return problems

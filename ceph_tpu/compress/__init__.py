"""Storage-efficiency subsystem — inline compression + dedup lanes.

ROADMAP item 4: the pluggable codec registry (``registry``), the
device-batched RLE+entropy hybrid codec (``codec``), gear-hash
content-defined chunking with batched CRC fingerprints (``chunker``),
and the os_store refcount conventions for the dedup index (``dedup``).
The batch engine's compression/fingerprint lanes
(``osd.batch_engine``) and the pool options (``compression_mode``,
``compression_algorithm``, ``dedup_enable``) are the consumers.
"""

from .codec import Codec, CodecError
from .registry import create_codec, list_codecs, register_codec
from .chunker import Chunker, fingerprint, fingerprints_batch
from . import dedup

__all__ = ["Codec", "CodecError", "create_codec", "list_codecs",
           "register_codec", "Chunker", "fingerprint",
           "fingerprints_batch", "dedup"]

"""Device-batched compression codecs.

The reference ships BlueStore inline compression behind a compressor
plugin interface (``src/compressor/Compressor.h``: zlib/snappy/lz4/
zstd selected per pool via ``compression_algorithm``).  This module is
the same seam with a codec family that fits the repo's device idiom:
the expensive full-payload *scan* runs as a jitted kernel over a
size-bucketed ``[rows, length]`` uint8 megabatch (one launch for a
whole batch-engine flush), and only the compact run descriptors are
finalized on the host.

``rle`` — the built-in LZ-class hybrid — is run-length coding with an
entropy second stage: the device scan marks run boundaries
(``x[i] != x[i-1]``, a single vectorized compare across the whole
megabatch), the host compacts them into ``(count, byte)`` pairs with
pure numpy (``flatnonzero``/``diff``/``repeat`` — no per-byte Python),
and when the run alphabet fits in 16 symbols the pairs are re-coded as
a nibble-packed dictionary stream (the entropy stage; worth ~25% on
top of RLE for low-entropy payloads).  Decompression is a single
``np.repeat`` gather — exact, and cheap enough to stay on the host.

Round trips are bit-identical by construction and asserted in
tests/test_compress.py on empty/tiny/incompressible/oversized corpora;
callers (the batch engine's compression lane) fall back to
pass-through storage when a blob does not shrink.
"""

from __future__ import annotations

import functools
import struct
import zlib

import numpy as np


class CodecError(Exception):
    pass


_MODE_RLE8 = 1      # (count u8, byte u8) pairs
_MODE_RLE4 = 2      # nibble-packed dictionary symbols + count stream


class Codec:
    """One compression algorithm (reference ``Compressor``).

    ``compress``/``decompress`` are the host reference semantics;
    codecs that can batch expose ``scan_batch`` (a jitted device pass
    over a padded ``[rows, length]`` uint8 megabatch) plus
    ``compress_from_scan`` to finalize one member from the scan
    output — **bit-identical** to ``compress`` by construction.
    """

    name = "?"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, blob: bytes, out_len: int) -> bytes:
        raise NotImplementedError

    # device-batched entry points (None ⇒ host-only codec: the lane
    # still coalesces accounting but finalizes each member on host)
    scan_batch = None

    def compress_from_scan(self, row: np.ndarray, length: int,
                           scan_row: np.ndarray) -> bytes:
        raise NotImplementedError


class PassthroughCodec(Codec):
    """``none``: stores bytes verbatim (the pool-mode-off reference)."""

    name = "none"

    def compress(self, data: bytes) -> bytes:
        return bytes(data)

    def decompress(self, blob: bytes, out_len: int) -> bytes:
        if len(blob) != out_len:
            raise CodecError(f"passthrough length {len(blob)} != "
                             f"{out_len}")
        return bytes(blob)


@functools.lru_cache(maxsize=None)
def _boundary_kernel(length: int):
    """[rows, length] uint8 → bool run-start mask, one fused launch.

    Cached per bucket length like ``crc32c_jax._batch_kernel`` so the
    jit cache stays bounded by the engine's pow2 size buckets.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kern(batch):
        cur = batch.astype(jnp.int16)
        prev = jnp.concatenate(
            [jnp.full((batch.shape[0], 1), -1, jnp.int16),
             cur[:, :-1]], axis=1)
        return cur != prev

    return kern


def _run_starts_host(row: np.ndarray) -> np.ndarray:
    """Host mirror of ``_boundary_kernel`` for one row — the
    bit-identity reference for the unbatched path."""
    mask = np.empty(len(row), dtype=bool)
    if len(row):
        mask[0] = True
        np.not_equal(row[1:], row[:-1], out=mask[1:])
    return mask


class RleCodec(Codec):
    """RLE + nibble-dictionary entropy hybrid (the ``rle`` builtin)."""

    name = "rle"

    @property
    def scan_batch(self):
        return self._scan_batch

    @staticmethod
    def _scan_batch(batch: np.ndarray):
        return _boundary_kernel(batch.shape[1])(batch)

    def compress(self, data: bytes) -> bytes:
        row = np.frombuffer(bytes(data), dtype=np.uint8)
        return self.compress_from_scan(row, len(row),
                                       _run_starts_host(row))

    def compress_from_scan(self, row: np.ndarray, length: int,
                           scan_row: np.ndarray) -> bytes:
        if length == 0:
            return bytes([_MODE_RLE8])
        starts = np.flatnonzero(np.asarray(scan_row[:length]))
        lens = np.diff(np.append(starts, length))
        syms = row[starts]
        # runs longer than 255 split into u8-countable pieces; the
        # count stream stays fixed-width so decode is one reshape
        pieces = (lens + 254) // 255
        total = int(pieces.sum())
        out_syms = np.repeat(syms, pieces)
        counts = np.full(total, 255, dtype=np.int64)
        counts[np.cumsum(pieces) - 1] = lens - (pieces - 1) * 255
        counts = counts.astype(np.uint8)
        pairs = np.empty((total, 2), dtype=np.uint8)
        pairs[:, 0] = counts
        pairs[:, 1] = out_syms
        rle8 = bytes([_MODE_RLE8]) + pairs.tobytes()
        alphabet = np.unique(out_syms)
        if len(alphabet) > 16:
            return rle8
        # entropy stage: symbols become 4-bit dictionary indices
        idx = np.searchsorted(alphabet, out_syms).astype(np.uint8)
        if total % 2:
            idx = np.append(idx, np.uint8(0))
        packed = (idx[0::2] << 4) | idx[1::2]
        rle4 = (bytes([_MODE_RLE4, len(alphabet)]) + alphabet.tobytes()
                + struct.pack("<I", total) + packed.tobytes()
                + counts.tobytes())
        return rle4 if len(rle4) < len(rle8) else rle8

    def decompress(self, blob: bytes, out_len: int) -> bytes:
        if not blob:
            raise CodecError("empty rle blob")
        mode = blob[0]
        if mode == _MODE_RLE8:
            pairs = np.frombuffer(blob, dtype=np.uint8, offset=1)
            if len(pairs) % 2:
                raise CodecError("truncated rle8 stream")
            pairs = pairs.reshape(-1, 2)
            out = np.repeat(pairs[:, 1], pairs[:, 0])
        elif mode == _MODE_RLE4:
            nsym = blob[1]
            alphabet = np.frombuffer(blob, np.uint8, nsym, offset=2)
            (total,) = struct.unpack_from("<I", blob, 2 + nsym)
            off = 6 + nsym
            npack = (total + 1) // 2
            packed = np.frombuffer(blob, np.uint8, npack, offset=off)
            counts = np.frombuffer(blob, np.uint8, total,
                                   offset=off + npack)
            idx = np.empty(npack * 2, dtype=np.uint8)
            idx[0::2] = packed >> 4
            idx[1::2] = packed & 0x0F
            out = np.repeat(alphabet[idx[:total]], counts)
        else:
            raise CodecError(f"unknown rle mode {mode}")
        if len(out) != out_len:
            raise CodecError(
                f"rle expanded to {len(out)} bytes, expected {out_len}")
        return out.tobytes()


class ZlibCodec(Codec):
    """Host reference codec (the upstream default compressor); no
    device scan — the lane batches its accounting only."""

    name = "zlib"

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(bytes(data), 6)

    def decompress(self, blob: bytes, out_len: int) -> bytes:
        out = zlib.decompress(bytes(blob))
        if len(out) != out_len:
            raise CodecError(
                f"zlib expanded to {len(out)} bytes, expected {out_len}")
        return out

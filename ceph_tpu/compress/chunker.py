"""Content-defined chunking + batched fingerprints (the dedup front).

The reference's RGW dedup and the CDC literature cut chunk boundaries
where a rolling hash of the trailing window hits a mask — so identical
content yields identical chunks regardless of byte offset.  We use the
*gear* hash: ``h_i = Σ_{j<W} GEAR[x_{i-j}] << j`` — unlike the
recurrence form ``h = (h<<1) + GEAR[b]`` it has **no sequential
dependency**, so the whole ``[rows, length]`` megabatch evaluates as
W shifted adds in one jitted launch (the "rolling-hash boundaries as
a jitted scan" of ROADMAP item 4).  The two forms are identical
because the recurrence telescopes: after W steps the shifted-out bits
of older terms have left the 32-bit window.

Boundary candidates are positions where ``h & (avg-1) == 0``; the
host pass enforces min/max chunk bounds on the (sparse) candidate
list.  Fingerprints are two independent CRC polynomials + the length
— CRC-32C through the ``scrub.crc32c_jax`` bit-matrix batch kernel
(one launch digests every chunk of a flush, pow2-padded and corrected
with ``crc32c_zero_unpad``) and host CRC-32 (zlib) as the second
opinion.  A collision needs simultaneous 64-bit agreement at equal
length; corruption from a false dedup hit additionally requires the
lengths to match.  This is the standard fingerprint-trust tradeoff —
documented here rather than hidden.
"""

from __future__ import annotations

import functools
import zlib

import numpy as np

from ..scrub.crc32c_jax import crc32c, _batch_kernel, crc32c_zero_unpad

_WINDOW = 32
# deterministic gear table: chunk boundaries must agree across every
# OSD and every process lifetime, or dedup silently stops matching
_GEAR = np.random.default_rng(0x43455048).integers(
    0, 1 << 32, size=256, dtype=np.uint32)


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


@functools.lru_cache(maxsize=None)
def _gear_kernel(length: int, mesh=None):
    """[rows, length] uint8 → uint32 gear hashes, one fused launch of
    W=32 shifted adds (cached per pow2 bucket length).

    With ``mesh`` (hashable — jax Mesh instances are) the megabatch is
    sharded over the row axis across every mesh device: each row's
    hash is independent, so the comp lane's fingerprint scan is pure
    data parallelism with the gear table replicated."""
    import jax
    import jax.numpy as jnp

    gear = np.asarray(_GEAR)

    def kern(batch):
        g = jnp.asarray(gear)[batch.astype(jnp.int32)]
        padded = jnp.pad(g, ((0, 0), (_WINDOW - 1, 0)))
        acc = jnp.zeros_like(g)
        for j in range(_WINDOW):
            acc = acc + (padded[:, _WINDOW - 1 - j:
                                _WINDOW - 1 - j + length]
                         << jnp.uint32(j))
        return acc

    if mesh is None:
        return jax.jit(kern)
    from jax.sharding import NamedSharding, PartitionSpec
    rows_sharded = NamedSharding(
        mesh, PartitionSpec(tuple(mesh.axis_names), None))
    return jax.jit(kern, in_shardings=(rows_sharded,),
                   out_shardings=rows_sharded)


def gear_hashes_host(row: np.ndarray) -> np.ndarray:
    """Host mirror of ``_gear_kernel`` for one row — the bit-identity
    reference for the unbatched path and the tests."""
    g = _GEAR[row.astype(np.intp)]
    padded = np.pad(g, (_WINDOW - 1, 0))
    acc = np.zeros(len(row), dtype=np.uint32)
    for j in range(_WINDOW):
        acc += padded[_WINDOW - 1 - j:
                      _WINDOW - 1 - j + len(row)] << np.uint32(j)
    return acc


def fingerprint(chunk: bytes) -> str:
    """24 hex chars: crc32c ‖ crc32 ‖ length (host reference)."""
    chunk = bytes(chunk)
    return (f"{crc32c(chunk):08x}"
            f"{zlib.crc32(chunk) & 0xFFFFFFFF:08x}"
            f"{len(chunk):08x}")


class Chunker:
    """CDC parameters + the boundary/fingerprint passes.

    ``avg_size`` must be a power of two (it becomes the hash mask);
    chunks are clamped to ``[min_size, max_size]`` with a forced cut
    at ``max_size`` — forced cuts are the only content-independent
    boundaries, the standard CDC escape hatch for pathological data.
    """

    def __init__(self, avg_size: int = 4096, min_size: int | None = None,
                 max_size: int | None = None):
        self.avg = _next_pow2(max(int(avg_size), 64))
        self.min = int(min_size) if min_size else max(self.avg // 4, 64)
        self.max = int(max_size) if max_size else self.avg * 4
        if not self.min <= self.avg <= self.max:
            raise ValueError("need min <= avg <= max chunk size")
        self.mask = np.uint32(self.avg - 1)

    def key(self) -> tuple:
        """Engine group key: one launch shape family per parameter set."""
        return ("cdc", self.avg, self.min, self.max)

    def hash_batch(self, batch: np.ndarray, mesh=None):
        """Device gear hashes for a padded megabatch; with ``mesh``
        (and rows divisible by its device count) the scan is sharded
        data-parallel over the row axis."""
        if mesh is not None and batch.shape[0] % mesh.size == 0:
            return _gear_kernel(batch.shape[1], mesh)(batch)
        return _gear_kernel(batch.shape[1])(batch)

    def cuts_from_hashes(self, hashes: np.ndarray,
                         length: int) -> list[int]:
        """Exclusive chunk end offsets from a (possibly padded) hash
        row; deterministic given the bytes alone."""
        if length == 0:
            return []
        h = np.asarray(hashes[:length])
        cand = np.flatnonzero((h & self.mask) == 0) + 1
        cuts: list[int] = []
        last = 0
        for c in cand:
            c = int(c)
            while c - last > self.max:
                last += self.max
                cuts.append(last)
            if c - last >= self.min and c < length:
                cuts.append(c)
                last = c
        while length - last > self.max:
            last += self.max
            cuts.append(last)
        cuts.append(length)
        return cuts

    def chunks(self, data: bytes) -> list[tuple[int, int]]:
        """(offset, length) spans for ``data`` — host path."""
        row = np.frombuffer(bytes(data), dtype=np.uint8)
        cuts = self.cuts_from_hashes(gear_hashes_host(row), len(row))
        out = []
        last = 0
        for c in cuts:
            out.append((last, c - last))
            last = c
        return out


def fingerprints_batch(chunks: list[bytes]) -> list[str]:
    """Digest many chunks in one CRC-32C launch: stack pow2-padded,
    run the bit-matrix batch kernel, strip each row's zero pad with
    the GF(2) unpad algebra — identical to host ``fingerprint`` per
    chunk, asserted in tests."""
    if not chunks:
        return []
    import jax.numpy as jnp
    bucket = _next_pow2(max(max(len(c) for c in chunks), 32))
    rows = len(chunks)
    batch = np.zeros((rows, bucket), dtype=np.uint8)
    for i, c in enumerate(chunks):
        batch[i, :len(c)] = np.frombuffer(c, dtype=np.uint8)
    crcs = np.asarray(_batch_kernel(bucket)(
        jnp.asarray(batch), jnp.zeros(rows, jnp.uint32)))
    out = []
    for i, c in enumerate(chunks):
        crc = crc32c_zero_unpad(int(crcs[i]), bucket - len(c))
        out.append(f"{crc:08x}{zlib.crc32(c) & 0xFFFFFFFF:08x}"
                   f"{len(c):08x}")
    return out

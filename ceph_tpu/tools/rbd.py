"""rbd CLI — block-image management + bench (reference ``src/tools/
rbd`` and ``rbd bench``; SURVEY.md §3.10).

    rbd -m HOST:PORT[,...] -p POOL create NAME --size BYTES
        [--order N] [--journaling]
    rbd ... ls | info NAME | rm NAME | resize NAME --size BYTES
    rbd ... snap create NAME@SNAP | snap ls NAME | snap rm NAME@SNAP
    rbd ... export NAME FILE | import FILE NAME
    rbd ... bench NAME --io-type write|read [--io-size N]
        [--io-total N] [--seconds S]
    rbd ... mirror promote NAME | mirror demote NAME

`bench` reports ops/sec and MB/s like the reference's
``rbd bench --io-type write`` summary.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..osdc.librados import Rados
from ..rbd.image import RBD, Image
from .rados import _monmap_from_addrs


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rbd", description=__doc__)
    p.add_argument("-m", "--mon", required=True)
    p.add_argument("-p", "--pool", default="rbd")
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("create")
    c.add_argument("name")
    c.add_argument("--size", type=int, required=True)
    c.add_argument("--order", type=int, default=22)
    c.add_argument("--journaling", action="store_true")
    c.add_argument("--mirror-snapshot", action="store_true",
                   help="enable snapshot-based mirroring mode")

    sub.add_parser("ls")
    for name in ("info", "rm"):
        s = sub.add_parser(name)
        s.add_argument("name")

    s = sub.add_parser("resize")
    s.add_argument("name")
    s.add_argument("--size", type=int, required=True)

    s = sub.add_parser("snap")
    s.add_argument("op", choices=["create", "ls", "rm", "protect",
                                  "unprotect"])
    s.add_argument("spec", help="NAME or NAME@SNAP")

    s = sub.add_parser("clone")
    s.add_argument("parent_spec", help="PARENT@SNAP")
    s.add_argument("child")
    s = sub.add_parser("flatten")
    s.add_argument("name")
    s = sub.add_parser("children")
    s.add_argument("parent_spec", help="PARENT@SNAP")

    s = sub.add_parser("export")
    s.add_argument("name")
    s.add_argument("path")
    s = sub.add_parser("export-diff")
    s.add_argument("name", help="NAME or NAME@SNAP (diff endpoint)")
    s.add_argument("path")
    s.add_argument("--from-snap", default=None)
    s = sub.add_parser("import-diff")
    s.add_argument("path")
    s.add_argument("name")
    s = sub.add_parser("import")
    s.add_argument("path")
    s.add_argument("name")

    s = sub.add_parser("bench")
    s.add_argument("name")
    s.add_argument("--io-type", choices=["write", "read"],
                   default="write")
    s.add_argument("--io-size", type=int, default=4096)
    s.add_argument("--io-total", type=int, default=4 << 20)
    s.add_argument("--seconds", type=float, default=10.0)

    s = sub.add_parser("mirror")
    s.add_argument("op", choices=["promote", "demote", "snapshot",
                                  "status"])
    s.add_argument("name")
    return p


def _bench(img: Image, a) -> dict:
    """Sequential-with-wrap I/O loop, reference obj_bencher-style
    summary."""
    import random
    rng = random.Random(0)
    size = img.size()
    if size < a.io_size:
        raise SystemExit("image smaller than --io-size")
    payload = bytes(rng.randrange(256) for _ in range(a.io_size))
    deadline = time.monotonic() + a.seconds
    done = 0
    t0 = time.monotonic()
    offset = 0
    while done < a.io_total and time.monotonic() < deadline:
        if offset + a.io_size > size:
            offset = 0
        if a.io_type == "write":
            img.write(offset, payload)
        else:
            img.read(offset, a.io_size)
        offset += a.io_size
        done += a.io_size
    dt = max(time.monotonic() - t0, 1e-9)
    ios = done // a.io_size
    return {"io_type": a.io_type, "io_size": a.io_size,
            "bytes": done, "seconds": round(dt, 3),
            "ops_per_sec": round(ios / dt, 2),
            "mb_per_sec": round(done / dt / 1e6, 3)}


def main(argv=None) -> int:
    a = build_parser().parse_args(argv)
    r = Rados(_monmap_from_addrs(a.mon)).connect()
    try:
        try:
            io = r.open_ioctx(a.pool)
        except Exception:
            if a.cmd not in ("create", "import"):
                raise SystemExit(f"rbd: pool {a.pool!r} not found")
            # image creation bootstraps its pool (vstart convenience;
            # read-side commands must never create pools as a side
            # effect of a typo)
            r.create_pool(a.pool, pg_num=8)
            io = r.open_ioctx(a.pool)
        rbd = RBD()
        if a.cmd == "create":
            rbd.create(io, a.name, a.size, order=a.order,
                       journaling=a.journaling,
                       mirror_snapshot=a.mirror_snapshot)
            return 0
        if a.cmd == "ls":
            print("\n".join(rbd.list(io)))
            return 0
        if a.cmd == "info":
            with Image(io, a.name, read_only=True) as img:
                print(json.dumps(img.stat(), indent=2))
            return 0
        if a.cmd == "rm":
            rbd.remove(io, a.name)
            return 0
        if a.cmd == "resize":
            with Image(io, a.name) as img:
                img.resize(a.size)
            return 0
        if a.cmd == "snap":
            if a.op == "ls":
                with Image(io, a.spec, read_only=True) as img:
                    for s in img.list_snaps():
                        print(f"{s['id']:>4} {s['name']} "
                              f"{s['size']}")
                return 0
            name, _, snap = a.spec.partition("@")
            if not snap:
                raise SystemExit("snap ops want NAME@SNAP")
            with Image(io, name) as img:
                if a.op == "create":
                    img.create_snap(snap)
                elif a.op == "rm":
                    img.remove_snap(snap)
                elif a.op == "protect":
                    img.protect_snap(snap)
                else:
                    img.unprotect_snap(snap)
            return 0
        if a.cmd == "clone":
            parent, _, snap = a.parent_spec.partition("@")
            if not snap:
                raise SystemExit("clone wants PARENT@SNAP CHILD")
            rbd.clone(io, parent, snap, a.child)
            return 0
        if a.cmd == "flatten":
            with Image(io, a.name) as img:
                img.flatten()
            return 0
        if a.cmd == "children":
            parent, _, snap = a.parent_spec.partition("@")
            print("\n".join(rbd.children(io, parent, snap)))
            return 0
        if a.cmd == "export":
            name, _, snap = a.name.partition("@")
            with Image(io, name, snapshot=snap or None,
                       read_only=True) as img:
                data = img.read(0, img.size())
            with open(a.path, "wb") as f:
                f.write(data)
            print(f"exported {len(data)} bytes")
            return 0
        if a.cmd == "export-diff":
            name, _, snap = a.name.partition("@")
            with Image(io, name, snapshot=snap or None,
                       read_only=True) as img:
                diff = img.export_diff(from_snap=a.from_snap)
            with open(a.path, "w") as f:
                json.dump(diff, f)
            nb = sum(len(e["data"]) // 2 for e in diff["extents"])
            print(f"exported diff: {len(diff['extents'])} extents, "
                  f"{nb} bytes")
            return 0
        if a.cmd == "import-diff":
            with open(a.path) as f:
                diff = json.load(f)
            with Image(io, a.name) as img:
                img.import_diff(diff)
            print("applied diff")
            return 0
        if a.cmd == "import":
            with open(a.path, "rb") as f:
                data = f.read()
            rbd.create(io, a.name, len(data))
            with Image(io, a.name) as img:
                img.write(0, data)
            print(f"imported {len(data)} bytes")
            return 0
        if a.cmd == "bench":
            with Image(io, a.name) as img:
                rep = _bench(img, a)
            print(json.dumps(rep))
            return 0
        if a.cmd == "mirror":
            if a.op == "snapshot":
                # reference `rbd mirror image snapshot`: stamp one
                with Image(io, a.name) as img:
                    print(img.mirror_snapshot_create())
                return 0
            with Image(io, a.name, read_only=True) as img:
                if a.op == "status":
                    print(json.dumps({
                        "mode": img.mirror_mode(),
                        "primary": img.is_primary(),
                        "mirror_snapshots": img.mirror_snapshots(),
                        "peer_synced": img.mirror_snap_committed()}))
                else:
                    (img.promote() if a.op == "promote"
                     else img.demote())
            return 0
        return 1
    finally:
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())

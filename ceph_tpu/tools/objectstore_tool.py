"""ceph-objectstore-tool — offline surgery on an OSD's object store.

Reference behavior re-created (``src/tools/ceph_objectstore_tool.cc``;
SURVEY.md §3.10): mount a **stopped** OSD's store directly (no daemon,
no cluster) and inspect or repair it.  Supported operations::

    --data-path <wal> --op list-pgs
    --data-path <wal> --op list [--pgid <pgid>]
    --data-path <wal> --op info --pgid <pgid>
    --data-path <wal> --op log --pgid <pgid>
    --data-path <wal> --op export --pgid <pgid> --file <out>
    --data-path <wal> --op import --file <in>
    --data-path <wal> --op remove --pgid <pgid>
    --data-path <wal> --op fsck [--truncate-tail]
    --data-path <wal> <pgid> <oid> dump|get-bytes|remove

The export file is a self-describing JSON snapshot of the PG's
collection (objects with data/xattrs/omap, including the ``_meta``
info+log rows) — the analog of the reference's PG export container
used to re-home a PG onto another OSD (``--op export`` / ``import``).
Imports refuse to clobber an existing collection, like the reference.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..os_store import MemStore, WALStore, walog
from ..os_store.objectstore import Transaction

EXPORT_VERSION = 1


def _mount(path: str) -> WALStore:
    store = WALStore(path)
    store.mount()
    return store


def _pg_collections(store: WALStore, pgid: str | None = None):
    """All collection ids, optionally filtered to one PG (an EC PG's
    shards are ``<pgid>s<n>`` collections; replicated is bare)."""
    out = []
    for cid in sorted(store.list_collections()):
        if pgid is None or cid == pgid or cid.startswith(f"{pgid}s"):
            out.append(cid)
    return out


def export_pg(store: WALStore, pgid: str) -> dict:
    colls = _pg_collections(store, pgid)
    if not colls:
        raise SystemExit(f"PG {pgid} does not exist in this store")
    dump = {"version": EXPORT_VERSION, "pgid": pgid, "collections": {}}
    for cid in colls:
        objs = {}
        for oid in store.list_objects(cid):
            objs[oid] = {
                "data": bytes(store.read(cid, oid)).hex(),
                "xattrs": {k: v.hex()
                           for k, v in store.getattrs(cid, oid).items()},
                "omap": {k: v.hex()
                         for k, v in store.omap_get(cid, oid).items()},
            }
        dump["collections"][cid] = objs
    return dump


def import_pg(store: WALStore, dump: dict):
    if dump.get("version") != EXPORT_VERSION:
        raise SystemExit("unrecognized export file version")
    for cid, objs in dump["collections"].items():
        if store.collection_exists(cid):
            raise SystemExit(f"collection {cid} already exists — "
                             "remove it first (--op remove)")
    for cid, objs in dump["collections"].items():
        t = Transaction().create_collection(cid)
        for oid, o in objs.items():
            t.touch(cid, oid)
            data = bytes.fromhex(o["data"])
            if data:
                t.write(cid, oid, 0, data)
            xattrs = {k: bytes.fromhex(v)
                      for k, v in o["xattrs"].items()}
            if xattrs:
                t.setattrs(cid, oid, xattrs)
            omap = {k: bytes.fromhex(v) for k, v in o["omap"].items()}
            if omap:
                t.omap_setkeys(cid, oid, omap)
        store.queue_transaction(t)


def remove_pg(store: WALStore, pgid: str):
    colls = _pg_collections(store, pgid)
    if not colls:
        raise SystemExit(f"PG {pgid} does not exist in this store")
    for cid in colls:
        t = Transaction()
        for oid in store.list_objects(cid):
            t.remove(cid, oid)
        t.remove_collection(cid)
        store.queue_transaction(t)


def fsck(path: str, truncate_tail: bool = False) -> dict:
    """Offline consistency check of a WALStore file.

    Non-destructive by default: walks the CRC-framed log directly with
    :mod:`walog` (NOT ``WALStore.mount``, which repairs torn tails as a
    side effect), replays every intact record into a throwaway
    :class:`MemStore`, and verifies invariants on the reconstructed
    state — the analog of ``ceph-objectstore-tool --op fsck`` over
    BlueStore's fsck.  With ``truncate_tail=True`` a torn/corrupt tail
    is cut back to the last intact record (the same repair a mount
    would perform).

    Checks:
      * per-record framing + CRC32C (implicit in the log scan);
      * every record decodes as JSON and replays as a valid transaction;
      * dedup chunk refcounts match live manifests, no orphan chunks;
      * each collection's ``_meta`` info/log omap rows parse as JSON.
    """
    import os

    payloads, good_off, tail = walog.scan_path(path)
    try:
        file_size = os.path.getsize(path)
    except OSError:
        file_size = 0
    issues: list[str] = []
    if tail["status"] != "clean":
        issues.append(
            f"{tail['status']} tail at offset {good_off}: "
            f"{tail['error']} ({tail['lost_bytes']} bytes lost)")

    shadow = MemStore()
    shadow.mount()
    replayed = 0
    for i, payload in enumerate(payloads):
        try:
            txn = Transaction.from_dict(json.loads(payload.decode()))
            shadow.queue_transaction(txn)
            replayed += 1
        except Exception as exc:  # noqa: BLE001 — report, keep walking
            issues.append(f"record {i}: replay failed: {exc!r}")

    from ..compress import dedup
    for problem in dedup.verify_refcounts(shadow):
        issues.append(f"dedup: {problem}")
    for cid in sorted(shadow.list_collections()):
        try:
            rows = shadow.omap_get(cid, "_meta")
        except KeyError:
            continue
        for k in ("info", "log", "missing"):
            if k not in rows:
                continue
            try:
                json.loads(rows[k])
            except Exception as exc:  # noqa: BLE001
                issues.append(f"{cid}/_meta[{k}]: unparseable: {exc!r}")

    truncated = False
    if truncate_tail and tail["status"] != "clean" and file_size:
        walog.truncate_tail(path, good_off)
        truncated = True
    n_colls = len(shadow.list_collections())
    shadow.umount()
    return {
        "path": path,
        "file_size": file_size,
        "records": len(payloads),
        "records_replayed": replayed,
        "good_off": good_off,
        "tail": tail,
        "collections": n_colls,
        "issues": issues,
        "truncated": truncated,
    }


def _meta(store: WALStore, cid: str) -> dict:
    try:
        rows = store.omap_get(cid, "_meta")
    except KeyError:
        return {}
    out = {}
    for k in ("info", "log"):
        if k in rows:
            out[k] = json.loads(rows[k])
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ceph-objectstore-tool",
                                description=__doc__)
    p.add_argument("--data-path", required=True,
                   help="the OSD's WALStore file")
    p.add_argument("--op", choices=["list-pgs", "list", "info", "log",
                                    "export", "import", "remove",
                                    "fsck"])
    p.add_argument("--pgid")
    p.add_argument("--file", help="export/import file")
    p.add_argument("--truncate-tail", action="store_true",
                   help="with --op fsck: repair a torn/corrupt tail by "
                        "truncating to the last intact record")
    p.add_argument("positional", nargs="*",
                   help="<pgid> <oid> dump|get-bytes|remove")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.op == "fsck":
        # fsck never mounts — mounting repairs torn tails, and an fsck
        # must observe (not destroy) the evidence unless asked.
        report = fsck(args.data_path,
                      truncate_tail=args.truncate_tail)
        print(json.dumps(report, indent=1, sort_keys=True))
        bad = report["issues"]
        if report["truncated"]:  # tail damage was just repaired
            bad = [i for i in bad
                   if not i.startswith(("torn tail", "corrupt tail"))]
        return 1 if bad else 0
    store = _mount(args.data_path)
    try:
        if args.op == "list-pgs":
            seen = []
            for cid in _pg_collections(store):
                base = cid.split("s", 1)[0] if "s" in cid else cid
                if base not in seen:
                    seen.append(base)
            print("\n".join(seen))
            return 0
        if args.op == "list":
            for cid in _pg_collections(store, args.pgid):
                for oid in sorted(store.list_objects(cid)):
                    print(json.dumps([cid, oid]))
            return 0
        if args.op == "info":
            if not args.pgid:
                raise SystemExit("--op info requires --pgid")
            for cid in _pg_collections(store, args.pgid):
                m = _meta(store, cid)
                if "info" in m:
                    print(json.dumps(m["info"], indent=1,
                                     sort_keys=True))
                    return 0
            raise SystemExit(f"no info for PG {args.pgid}")
        if args.op == "log":
            if not args.pgid:
                raise SystemExit("--op log requires --pgid")
            for cid in _pg_collections(store, args.pgid):
                m = _meta(store, cid)
                if "log" in m:
                    print(json.dumps(m["log"], indent=1,
                                     sort_keys=True))
                    return 0
            raise SystemExit(f"no log for PG {args.pgid}")
        if args.op == "export":
            if not (args.pgid and args.file):
                raise SystemExit("--op export requires --pgid --file")
            dump = export_pg(store, args.pgid)
            with open(args.file, "w") as f:
                json.dump(dump, f)
            n = sum(len(o) for o in dump["collections"].values())
            print(f"Export successful: {args.pgid} "
                  f"({n} objects)")
            return 0
        if args.op == "import":
            if not args.file:
                raise SystemExit("--op import requires --file")
            with open(args.file) as f:
                dump = json.load(f)
            import_pg(store, dump)
            print(f"Import successful: {dump['pgid']}")
            return 0
        if args.op == "remove":
            if not args.pgid:
                raise SystemExit("--op remove requires --pgid")
            remove_pg(store, args.pgid)
            print(f"Remove successful: {args.pgid}")
            return 0
        # object-level positional form
        if len(args.positional) == 3:
            pgid, oid, cmd = args.positional
            cids = [c for c in _pg_collections(store, pgid)
                    if store.exists(c, oid)]
            if not cids:
                raise SystemExit(f"object {oid!r} not found in {pgid}")
            cid = cids[0]
            if cmd == "dump":
                print(json.dumps({
                    "cid": cid, "oid": oid,
                    "size": store.stat(cid, oid)["size"],
                    "xattrs": {k: v.hex() for k, v in
                               store.getattrs(cid, oid).items()},
                    "omap_keys": sorted(store.omap_get(cid, oid)),
                }, indent=1, sort_keys=True))
            elif cmd == "get-bytes":
                sys.stdout.buffer.write(bytes(store.read(cid, oid)))
            elif cmd == "remove":
                store.queue_transaction(
                    Transaction().remove(cid, oid))
                print(f"removed {cid}/{oid}")
            else:
                raise SystemExit(f"unknown object command {cmd!r}")
            return 0
        raise SystemExit("nothing to do (see --help)")
    finally:
        store.umount()


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:      # e.g. `... --op list | head`
        sys.exit(141)

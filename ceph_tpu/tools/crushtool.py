"""crushtool — compile/decompile/test CRUSH maps.

Reference: ``src/tools/crushtool.cc`` (SURVEY.md §3.10); the
``--test --show-mappings`` output is the second north-star CRUSH harness
(SURVEY.md §4.5) and the golden-capture source for mapping tests.

Usage::

    crushtool -c map.txt -o map.json          # compile text → map
    crushtool -d map.json [-o map.txt]        # decompile → text
    crushtool -i map.json --test --rule 0 --num-rep 3 \
        --min-x 0 --max-x 1023 --show-mappings
    crushtool -i map.json --test --show-utilization
    crushtool --build --num-osds 64 host straw2 4 rack straw2 4 \
        root straw2 0 -o map.json

Mapping batches run through `BatchMapper` (TPU/JAX path) when the rule
shape supports it, falling back to the scalar oracle — results are
identical either way (tests/test_crush_jax.py enforces bit-equality).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..crush import mapper
from ..crush.compiler import (compile_crushmap, crushmap_from_dict,
                              crushmap_to_dict, decompile_crushmap,
                              weight_to_float)
from ..crush.map import CRUSH_ITEM_NONE, Bucket, CrushMap, Rule, Step


def load_map(path: str) -> CrushMap:
    with open(path) as f:
        text = f.read()
    stripped = text.lstrip()
    if stripped.startswith("{"):
        return crushmap_from_dict(json.loads(text))
    return compile_crushmap(text)


def save_map(cmap: CrushMap, path: str):
    with open(path, "w") as f:
        json.dump(crushmap_to_dict(cmap), f, indent=1)
        f.write("\n")


def batch_map(cmap: CrushMap, rule: Rule, xs, num_rep: int,
              weights=None, require_batched: bool = False,
              engines: list | None = None) -> list[list[int]]:
    """Map a batch of inputs; JAX path with a LOUD scalar fallback
    (or a hard error under --require-batched)."""
    from ._engine import fallback
    try:
        from ..crush.jax_mapper import BatchMapper
        bm = BatchMapper(cmap, rule, result_max=num_rep)
        res = bm(xs, weights)
        if engines is not None:
            engines.append("tpu-batched")
        return [[int(o) for o in row] for row in res]
    except (NotImplementedError, ValueError, RuntimeError) as e:
        fallback("crushtool", f"rule {rule.id} ({rule.name})", e,
                 require_batched)
    if engines is not None:
        engines.append("scalar-oracle")
    wl = list(weights) if weights is not None else None
    return [mapper.do_rule(cmap, rule, int(x), num_rep, wl) for x in xs]


def build_hierarchy_args(num_osds: int, layers: list[tuple[str, str, int]],
                         ) -> CrushMap:
    """--build: bottom-up layered topology. Each layer (typename, alg,
    fanout); fanout 0 = one bucket holding everything below."""
    cmap = CrushMap(types={0: "osd"}, max_devices=num_osds)
    for i in range(num_osds):
        cmap.names[i] = f"osd.{i}"
    cur = list(range(num_osds))
    cur_w = [0x10000] * num_osds
    next_bid = -1
    for li, (tname, alg, fanout) in enumerate(layers, start=1):
        cmap.types[li] = tname
        if fanout <= 0:
            groups = [cur]
        else:
            groups = [cur[i:i + fanout] for i in range(0, len(cur), fanout)]
        nxt, nxt_w = [], []
        for gi, grp in enumerate(groups):
            ws = [cur_w[cur.index(it)] for it in grp]
            b = Bucket(id=next_bid, type=li, alg=alg, items=list(grp),
                       weights=ws)
            cmap.add_bucket(b)
            cmap.names[next_bid] = (tname if len(groups) == 1
                                    else f"{tname}{gi}")
            nxt.append(next_bid)
            nxt_w.append(b.weight)
            next_bid -= 1
        cur, cur_w = nxt, nxt_w
    # default rule: chooseleaf over the layer under the root (the failure
    # domain), or straight to devices for a single-layer build
    top_type = len(layers)
    domain = top_type - 1 if top_type >= 2 else 0
    cmap.rules.append(Rule(id=0, name="replicated_rule", steps=[
        Step("take", cur[0]),
        Step("chooseleaf_firstn", 0, domain),
        Step("emit")]))
    return cmap


def cmd_test(cmap: CrushMap, args) -> int:
    rules = [r for r in cmap.rules
             if args.rule is None or r.id == args.rule]
    if not rules:
        print(f"rule {args.rule} not found", file=sys.stderr)
        return 1
    weights = None
    if args.weight:
        weights = [0x10000] * cmap.max_devices
        for spec in args.weight:
            osd, w = spec.split(":") if ":" in spec else spec.split(",")
            weights[int(osd)] = int(float(w) * 0x10000)
    min_x, max_x = args.min_x, args.max_x
    xs = list(range(min_x, max_x + 1))
    engines: list[str] = []
    for rule in rules:
        reps = ([args.num_rep] if args.num_rep
                else list(range(rule.min_size, rule.max_size + 1)))
        for num_rep in reps:
            rows = batch_map(cmap, rule, xs, num_rep, weights,
                             require_batched=args.require_batched,
                             engines=engines)
            if args.show_mappings:
                for x, row in zip(xs, rows):
                    shown = [o for o in row if o != CRUSH_ITEM_NONE] \
                        if rule.steps and _is_firstn(rule) else \
                        ["NONE" if o == CRUSH_ITEM_NONE else o for o in row]
                    print(f"CRUSH rule {rule.id} x {x} {shown}")
            if args.show_utilization:
                counts: dict[int, int] = {}
                placed = 0
                for row in rows:
                    for o in row:
                        if o != CRUSH_ITEM_NONE:
                            counts[o] = counts.get(o, 0) + 1
                            placed += 1
                n_dev = max(cmap.max_devices, 1)
                avg = placed / n_dev
                print(f"rule {rule.id} ({rule.name}) num_rep {num_rep} "
                      f"result size == {placed / len(xs):.2f}\tok for all x")
                for o in sorted(counts):
                    print(f"  device {o}:\t\t stored : {counts[o]}\t "
                          f"expected : {avg:.2f}")
            if args.show_statistics:
                sizes: dict[int, int] = {}
                for row in rows:
                    got = sum(1 for o in row if o != CRUSH_ITEM_NONE)
                    sizes[got] = sizes.get(got, 0) + 1
                for got in sorted(sizes):
                    print(f"rule {rule.id} ({rule.name}) num_rep {num_rep} "
                          f"result size == {got}:\t{sizes[got]}/{len(xs)}")
    from ._engine import announce
    announce("crushtool", "+".join(sorted(set(engines)))
             if engines else "scalar-oracle")
    return 0


def _is_firstn(rule: Rule) -> bool:
    return any(s.op.endswith("firstn") for s in rule.steps)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="crushtool", description=__doc__)
    p.add_argument("-c", "--compile", metavar="FILE",
                   help="compile text map FILE")
    p.add_argument("-d", "--decompile", metavar="FILE",
                   help="decompile map FILE to text")
    p.add_argument("-i", "--in-file", metavar="FILE", help="input map")
    p.add_argument("-o", "--out-file", metavar="FILE", help="output path")
    p.add_argument("--build", action="store_true")
    p.add_argument("--num-osds", type=int, default=0)
    p.add_argument("layers", nargs="*", default=[],
                   help="--build layers: NAME ALG SIZE triples")
    p.add_argument("--test", action="store_true")
    p.add_argument("--rule", type=int, default=None)
    p.add_argument("--num-rep", type=int, default=None)
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1023)
    p.add_argument("--weight", action="append", default=[],
                   metavar="OSD:W", help="reweight device (repeatable)")
    p.add_argument("--show-mappings", action="store_true")
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-statistics", action="store_true")
    p.add_argument("--require-batched", action="store_true",
                   help="error instead of falling back to the scalar "
                        "oracle when the batched mapper declines a rule")
    return p


def _run_test(cmap: CrushMap, args) -> int:
    from ._engine import BatchedRequired
    try:
        return cmd_test(cmap, args)
    except BatchedRequired as e:
        print(e, file=sys.stderr)
        return 2


def main(argv=None) -> int:
    from ..utils import honor_jax_platforms_env
    from ..utils.platform import ensure_x64
    honor_jax_platforms_env()
    args = build_parser().parse_args(argv)
    if args.test:
        from ..utils.platform import enable_compile_cache
        ensure_x64()       # BatchMapper needs 64-bit straw2 draws
        enable_compile_cache()
    if args.compile:
        with open(args.compile) as f:
            cmap = compile_crushmap(f.read())
        save_map(cmap, args.out_file or args.compile + ".json")
        return 0
    if args.decompile:
        text = decompile_crushmap(load_map(args.decompile))
        if args.out_file:
            with open(args.out_file, "w") as f:
                f.write(text)
        else:
            sys.stdout.write(text)
        return 0
    if args.build:
        if args.num_osds <= 0 or len(args.layers) % 3:
            print("--build needs --num-osds and NAME ALG SIZE triples",
                  file=sys.stderr)
            return 1
        layers = [(args.layers[i], args.layers[i + 1],
                   int(args.layers[i + 2]))
                  for i in range(0, len(args.layers), 3)]
        cmap = build_hierarchy_args(args.num_osds, layers)
        if args.out_file:
            save_map(cmap, args.out_file)
        if args.test:
            return _run_test(cmap, args)
        return 0
    if args.test:
        if not args.in_file:
            print("--test needs -i MAP", file=sys.stderr)
            return 1
        return _run_test(load_map(args.in_file), args)
    build_parser().print_usage()
    return 1


if __name__ == "__main__":
    sys.exit(main())

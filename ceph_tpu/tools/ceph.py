"""ceph CLI — mon command dispatch (reference ``src/ceph.in``).

    ceph -m HOST:PORT[,...] status|-s | health | df | osd df
    ceph -m ... -w [--count N] [--timeout S] [--filter CODE]
        (live event stream; --filter narrows to one health code)
    ceph -m ... health detail | health history
    ceph -m ... health mute CODE [TTL_SECONDS] [--sticky]
    ceph -m ... health unmute CODE
    ceph -m ... crash ls|ls-new|archive-all | crash info|rm|archive ID
        (mgr crash archive — post-mortems from revived daemons)
    ceph -m ... progress [json]   (mgr progress events)
    ceph -m ... iostat [json]     (live rates from the telemetry spine)
    ceph -m ... osd perf [json]   (commit latency + device launches)
    ceph -m ... osd top [clients|pools|pgs] [--by ops|bytes|p99]
        [--count N] [json]   (cluster-merged heavy hitters)
    ceph -m ... alerts [status|history|rules [KNOB [VAL]]|
        silence NAME [TTL|--off]|enable [SEED]|disable]
    ceph -m ... tracing exemplar [METRIC [BUCKET]]
        (slowest-op trace id per latency histogram bucket)
    ceph -m ... pg stat | pg dump
    ceph -m ... osd tree | osd dump | osd stat | osd pool ls
    ceph -m ... osd pool create NAME [--pg-num N] [--size N] [--type T]
        [--compression-mode M] [--compression-algorithm A] [--dedup]
    ceph -m ... osd pool set POOL VAR VAL | osd pool get POOL [VAR]
        (VAR incl. compression_mode|compression_algorithm|dedup_enable)
    ceph -m ... osd out ID | osd in ID | osd down ID
    ceph -m ... osd reweight ID WEIGHT
    ceph -m ... osd pool mksnap POOL SNAP | rmsnap POOL SNAP
    ceph -m ... osd pg-upmap-items PGID FROM TO [FROM TO ...]
    ceph -m ... log last [N] [cluster|audit] | log MESSAGE...
    ceph -m ... daemon SOCK_PATH COMMAND [k=v ...]
        (e.g. daemon <asok> dump_tracing [format=otlp] |
         trace start|stop|clear | profiler dump|reset |
         dump_historic_ops_by_duration | perf histogram dump)
        (e.g. daemon <asok> injectargs args="op_complaint_time=5",
         daemon <asok> fault show | fault set dst=osd.1 drop=0.3 |
         fault partition dst=osd.2 | fault heal — the seeded
         network-chaos injector, see msg/fault.py)

Free-form: any unrecognized argument list is sent as
{"prefix": "<joined words>"} — the same pass-through the reference CLI
does with its command descriptions."""

from __future__ import annotations

import argparse
import json
import sys

from ..core.admin_socket import admin_command
from ..mon.client import MonClient
from .rados import _monmap_from_addrs


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    p = argparse.ArgumentParser(prog="ceph", add_help=False)
    p.add_argument("-m", "--mon")
    args, rest = p.parse_known_args(argv)
    if not rest:
        print(__doc__)
        return 1

    try:
        return _dispatch(args, rest)
    except (IndexError, ValueError):
        print(__doc__)
        return 1


def _run_mgr_command(mc, cmd: dict) -> int:
    """Send one mgr-hosted command and print the standard output
    shape (shared by the orch and device branches)."""
    rc, outs, outb = mc.mgr_command(cmd)
    if outb is not None:
        print(json.dumps(outb, indent=2, default=str))
    if outs:
        print(outs, file=sys.stderr)
    return 0 if rc == 0 else 1


def _dispatch(args, rest) -> int:
    if rest[0] == "daemon":
        # `ceph daemon <asok> <cmd> [k=v ...]` — local admin socket
        sock, words, kvs = rest[1], [], {}
        toks = rest[2:]
        i = 0
        while i < len(toks):
            tok = toks[i]
            if tok.startswith("--") and "=" in tok:
                k, v = tok[2:].split("=", 1)
                kvs[k] = v
            elif tok.startswith("--") and i + 1 < len(toks):
                kvs[tok[2:]] = toks[i + 1]
                i += 1
            elif "=" in tok:
                k, v = tok.split("=", 1)
                kvs[k] = v
            else:
                words.append(tok)
            i += 1
        out = admin_command(sock, " ".join(words), **kvs)
        print(json.dumps(out, indent=2, default=str))
        return 0

    if rest[0] in ("-w", "--watch", "watch"):
        # `ceph -w` — live event stream (health transitions, clog,
        # progress); --count/--timeout bound it for scripting;
        # --filter CODE prints only events about that health check
        # (repeatable — any match passes)
        sub = argparse.ArgumentParser(prog="ceph -w")
        sub.add_argument("--count", type=int, default=0)
        sub.add_argument("--timeout", type=float, default=0.0)
        sub.add_argument("--filter", action="append", default=[])
        a = sub.parse_args(rest[1:])
        if not args.mon:
            raise SystemExit("ceph: -m HOST:PORT required")
        mc = MonClient(_monmap_from_addrs(args.mon))
        try:
            return _watch(mc, count=a.count, timeout=a.timeout,
                          codes=[c.upper() for c in a.filter])
        finally:
            mc.shutdown()

    if not args.mon:
        raise SystemExit("ceph: -m HOST:PORT required")
    mc = MonClient(_monmap_from_addrs(args.mon))
    try:
        if rest[0] == "device" and len(rest) >= 2:
            # mgr-hosted devicehealth commands
            cmd = {"prefix": f"device {rest[1]}"}
            if rest[1] == "info" and len(rest) > 2:
                cmd["devid"] = rest[2]
            return _run_mgr_command(mc, cmd)
        if rest[0] == "crash":
            # mgr-hosted crash archive (reference `ceph crash ...`)
            usage = ("usage: ceph crash ls|ls-new|archive-all | "
                     "crash info|rm|archive ID")
            verb = rest[1] if len(rest) > 1 else "ls"
            if verb not in ("ls", "ls-new", "info", "rm", "archive",
                            "archive-all"):
                print(usage, file=sys.stderr)
                return 1
            cmd = {"prefix": f"crash {verb}"}
            if verb in ("info", "rm", "archive"):
                if len(rest) < 3:
                    print(usage, file=sys.stderr)
                    return 1
                cmd["id"] = rest[2]
            return _run_mgr_command(mc, cmd)
        if rest[0] == "orch":
            # mgr-hosted orchestrator commands (reference `ceph orch`
            # → mon → active mgr → cephadm); transport: mgr_command
            usage = ("usage: ceph orch ls|ps | "
                     "orch apply TYPE [COUNT] | orch rm TYPE")
            if len(rest) < 2 or rest[1] not in ("ls", "ps", "apply",
                                                "rm"):
                print(usage, file=sys.stderr)
                return 1
            cmd = {"prefix": f"orch {rest[1]}"}
            if rest[1] == "apply":
                if len(rest) < 3 or (len(rest) > 3
                                     and not rest[3].isdigit()):
                    print(usage, file=sys.stderr)
                    return 1
                cmd["service_type"] = rest[2]
                if len(rest) > 3:
                    cmd["count"] = int(rest[3])
            elif rest[1] == "rm":
                if len(rest) < 3:
                    print(usage, file=sys.stderr)
                    return 1
                cmd["service_type"] = rest[2]
            return _run_mgr_command(mc, cmd)
        cmd: dict = {}
        if rest[0] == "osd" and rest[1:2] == ["pool"] and \
                rest[2:3] == ["create"]:
            sub = argparse.ArgumentParser()
            sub.add_argument("name")
            sub.add_argument("--pg-num", type=int, default=32)
            sub.add_argument("--size", type=int, default=3)
            sub.add_argument("--type", default="replicated")
            sub.add_argument("--profile", default="")
            sub.add_argument("--compression-mode", default=None)
            sub.add_argument("--compression-algorithm", default=None)
            sub.add_argument("--dedup", action="store_true",
                             default=None)
            a = sub.parse_args(rest[3:])
            cmd = {"prefix": "osd pool create", "pool": a.name,
                   "pg_num": a.pg_num, "size": a.size,
                   "pool_type": a.type}
            if a.profile:
                cmd["erasure_code_profile"] = a.profile
            if a.compression_mode is not None:
                cmd["compression_mode"] = a.compression_mode
            if a.compression_algorithm is not None:
                cmd["compression_algorithm"] = a.compression_algorithm
            if a.dedup is not None:
                cmd["dedup_enable"] = a.dedup
        elif rest[0] == "osd" and rest[1:2] == ["pool"] and \
                rest[2:3] in (["mksnap"], ["rmsnap"]):
            cmd = {"prefix": f"osd pool {rest[2]}", "pool": rest[3],
                   "snap": rest[4]}
        elif rest[0] == "osd" and rest[1:2] == ["pg-upmap-items"]:
            pairs = [[int(a), int(b)]
                     for a, b in zip(rest[3::2], rest[4::2])]
            cmd = {"prefix": "osd pg-upmap-items", "pgid": rest[2],
                   "mappings": pairs}
        elif rest[0] == "osd" and rest[1:2] in (["out"], ["in"],
                                                ["down"]):
            cmd = {"prefix": f"osd {rest[1]}", "ids": [int(rest[2])]}
        elif rest[0] == "osd" and rest[1:2] in (["set"], ["unset"]) \
                and len(rest) == 3:
            cmd = {"prefix": f"osd {rest[1]}", "key": rest[2]}
        elif rest[0] == "osd" and rest[1:2] == ["pool"] and \
                rest[2:3] == ["set"] and len(rest) == 6:
            # `ceph osd pool set POOL VAR VAL` — the mon coerces the
            # string val per var (pg-num ints, efficiency enums/bools)
            cmd = {"prefix": "osd pool set", "pool": rest[3],
                   "var": rest[4], "val": rest[5]}
        elif rest[0] == "osd" and rest[1:2] == ["pool"] and \
                rest[2:3] == ["get"] and len(rest) >= 4:
            cmd = {"prefix": "osd pool get", "pool": rest[3]}
            if len(rest) > 4:
                cmd["var"] = rest[4]
        elif rest[0] == "osd" and rest[1:2] == ["pool"] and \
                rest[2:3] == ["set-quota"]:
            cmd = {"prefix": "osd pool set-quota", "pool": rest[3],
                   "field": rest[4], "val": rest[5]}
        elif rest[0] == "pg" and rest[1:2] in (["scrub"], ["deep-scrub"],
                                               ["repair"]):
            cmd = {"prefix": f"pg {rest[1]}", "pgid": rest[2]}
        elif rest[0] == "pg" and rest[1:2] == ["list-inconsistent-obj"]:
            cmd = {"prefix": "pg list-inconsistent-obj",
                   "pgid": rest[2]}
        elif rest[0] == "fs" and rest[1:2] == ["set"]:
            cmd = {"prefix": "fs set", "fs_name": rest[2],
                   "var": rest[3], "val": rest[4]}
        elif rest[0] == "fs" and rest[1:2] == ["new"]:
            cmd = {"prefix": "fs new", "fs_name": rest[2],
                   "metadata": rest[3], "data": rest[4]}
        elif rest[0] == "osd" and rest[1:2] == ["tier"]:
            verb = rest[2]
            if verb in ("add", "remove"):
                cmd = {"prefix": f"osd tier {verb}",
                       "pool": rest[3], "tierpool": rest[4]}
            elif verb == "cache-mode":
                cmd = {"prefix": "osd tier cache-mode",
                       "pool": rest[3], "mode": rest[4]}
            elif verb == "set-overlay":
                cmd = {"prefix": "osd tier set-overlay",
                       "pool": rest[3], "overlaypool": rest[4]}
            elif verb == "remove-overlay":
                cmd = {"prefix": "osd tier remove-overlay",
                       "pool": rest[3]}
            else:
                raise ValueError(verb)
        elif rest[0] == "osd" and rest[1:2] == ["reweight"]:
            cmd = {"prefix": "osd reweight", "id": int(rest[2]),
                   "weight": float(rest[3])}
        elif rest[0] == "health" and rest[1:2] == ["mute"]:
            # `ceph health mute CODE [TTL] [--sticky]`
            cmd = {"prefix": "health mute", "code": rest[2]}
            for tok in rest[3:]:
                if tok == "--sticky":
                    cmd["sticky"] = True
                else:
                    cmd["ttl"] = float(tok)
        elif rest[0] == "health" and rest[1:2] == ["unmute"]:
            cmd = {"prefix": "health unmute", "code": rest[2]}
        elif rest[0] == "progress":
            # mgr-hosted progress events
            return _run_mgr_command(mc, {"prefix": "progress"})
        elif rest[0] == "iostat":
            # mgr telemetry spine: live rates from osd_stats deltas
            rc, outs, outb = mc.mgr_command({"prefix": "iostat"})
            if rc == 0 and outb is not None and "json" not in rest[1:]:
                print(_render_iostat(outb))
                # autotune panel rides along when the module is loaded
                arc, _, aout = mc.mgr_command(
                    {"prefix": "autotune status"})
                if arc == 0 and aout:
                    print(_render_autotune(aout))
                return 0
            if outb is not None:
                print(json.dumps(outb, indent=2, default=str))
            if outs:
                print(outs, file=sys.stderr)
            return 0 if rc == 0 else 1
        elif rest[0] == "autotune":
            # mgr autotuner: status|history|enable|disable|pin|unpin
            verb = rest[1] if len(rest) > 1 else "status"
            cmd = {"prefix": f"autotune {verb}"}
            json_out = False
            pos = []
            for tok in rest[2:]:
                if tok == "json":
                    json_out = True
                elif "=" in tok:
                    k, v = tok.split("=", 1)
                    cmd[k] = int(v) if v.lstrip("-").isdigit() else v
                else:
                    pos.append(tok)
            if verb in ("pin", "unpin") and pos:
                cmd["knob"] = pos[0]
                if verb == "pin" and len(pos) > 1:
                    cmd["value"] = pos[1]
            elif verb == "enable" and pos:
                cmd["seed"] = int(pos[0])
            rc, outs, outb = mc.mgr_command(cmd)
            if rc == 0 and verb == "status" and outb and not json_out:
                print(_render_autotune(outb))
                return 0
            if outb is not None:
                print(json.dumps(outb, indent=2, default=str))
            if outs:
                print(outs, file=sys.stderr)
            return 0 if rc == 0 else 1
        elif rest[0] == "osd" and rest[1:2] == ["top"]:
            # `ceph osd top [clients|pools|pgs] [--by ops|bytes|p99]
            #  [--count N] [json]` — cluster-merged heavy hitters
            sub = argparse.ArgumentParser(prog="ceph osd top")
            sub.add_argument("dim", nargs="?", default="clients",
                             choices=("clients", "pools", "pgs"))
            sub.add_argument("--by", default="ops",
                             choices=("ops", "bytes", "p99"))
            sub.add_argument("--count", type=int, default=10)
            # "json" is a bare token, not a positional — argparse
            # refuses positionals after interleaved optionals
            json_out = "json" in rest[2:]
            a = sub.parse_args([t for t in rest[2:] if t != "json"])
            rc, outs, outb = mc.mgr_command(
                {"prefix": "osd top", "dim": a.dim, "by": a.by,
                 "count": a.count})
            if rc == 0 and outb is not None and not json_out:
                print(_render_osd_top(outb))
                return 0
            if outb is not None:
                print(json.dumps(outb, indent=2, default=str))
            if outs:
                print(outs, file=sys.stderr)
            return 0 if rc == 0 else 1
        elif rest[0] == "tracing" and rest[1:2] == ["exemplar"]:
            # `ceph tracing exemplar [METRIC [BUCKET]]` — metric→trace
            # lookup: the slowest-op trace id per histogram bucket
            cmd = {"prefix": "tracing exemplar"}
            if len(rest) > 2:
                cmd["metric"] = rest[2]
            if len(rest) > 3:
                cmd["bucket"] = int(rest[3])
            return _run_mgr_command(mc, cmd)
        elif rest[0] == "alerts":
            # mgr alert rules: status|history|rules|silence|enable|
            # disable
            verb = rest[1] if len(rest) > 1 else "status"
            cmd = {"prefix": f"alerts {verb}"}
            json_out = False
            pos = []
            for tok in rest[2:]:
                if tok == "json":
                    json_out = True
                elif tok == "--off":
                    cmd["off"] = True
                elif "=" in tok:
                    k, v = tok.split("=", 1)
                    cmd[k] = int(v) if v.lstrip("-").isdigit() else v
                else:
                    pos.append(tok)
            if verb == "silence" and pos:
                cmd["name"] = pos[0]
                if len(pos) > 1:
                    cmd["ttl"] = float(pos[1])
            elif verb == "rules" and pos:
                cmd["knob"] = pos[0]
                if len(pos) > 1:
                    cmd["value"] = pos[1]
            elif verb == "enable" and pos:
                cmd["seed"] = int(pos[0])
            rc, outs, outb = mc.mgr_command(cmd)
            if rc == 0 and verb == "status" and outb and not json_out:
                print(_render_alerts(outb))
                return 0
            if outb is not None:
                print(json.dumps(outb, indent=2, default=str))
            if outs:
                print(outs, file=sys.stderr)
            return 0 if rc == 0 else 1
        elif rest[0] == "osd" and rest[1:2] == ["perf"]:
            # commit latency + device-launch breakdown per OSD
            rc, outs, outb = mc.mgr_command({"prefix": "osd perf"})
            if rc == 0 and outb is not None and "json" not in rest[2:]:
                print(_render_osd_perf(outb))
                return 0
            if outb is not None:
                print(json.dumps(outb, indent=2, default=str))
            if outs:
                print(outs, file=sys.stderr)
            return 0 if rc == 0 else 1
        elif rest[0] == "log" and rest[1:2] == ["last"]:
            # `ceph log last [n] [cluster|audit]` — ring tails
            cmd = {"prefix": "log last"}
            for tok in rest[2:]:
                if tok.isdigit():
                    cmd["num"] = int(tok)
                else:
                    cmd["channel"] = tok
        elif rest[0] == "log" and len(rest) > 1:
            # `ceph log <msg...>` — operator entry into the clog
            cmd = {"prefix": "log", "logtext": " ".join(rest[1:])}
        else:
            words = ["status" if w == "-s" else w for w in rest]
            fmt = None
            cleaned = []
            i = 0
            while i < len(words):
                w = words[i]
                if w.startswith("--format="):
                    fmt = w.split("=", 1)[1]
                elif w in ("--format", "-f") and i + 1 < len(words):
                    fmt = words[i + 1]
                    i += 1
                else:
                    cleaned.append(w)
                i += 1
            cmd = {"prefix": " ".join(cleaned),
                   "_render": fmt in (None, "plain")}
        want_render = cmd.pop("_render", False)
        rc, outs, outb = mc.command(cmd)
        if rc == 0 and want_render and outb is not None:
            text = _render(cmd["prefix"], outb)
            if text is not None:
                print(text)
                return 0
        if outb is not None:
            print(json.dumps(outb, indent=2, default=str))
        if outs:
            print(outs, file=sys.stderr)
        return 0 if rc == 0 else 1
    finally:
        mc.shutdown()


def _fmt_event(kind: str, data: dict, stamp: float) -> str | None:
    """One `ceph -w` line per event; None ⇒ suppressed (snapshots)."""
    import datetime
    ts = datetime.datetime.fromtimestamp(
        data.get("stamp", stamp) or stamp).strftime("%H:%M:%S")
    if kind == "clog":
        return (f"{ts} {data.get('channel', 'cluster')} "
                f"[{data.get('prio', 'info').upper()[:3]}] "
                f"{data.get('name', '?')}: {data.get('text', '')}")
    if kind == "health":
        state = data.get("state")
        if state == "snapshot":
            return None     # catch-up frame, not a transition
        if state == "rollup":
            return f"{ts} health: cluster is {data.get('status')}"
        return (f"{ts} health: {data.get('code')} {state} "
                f"({data.get('summary', '')}) → {data.get('status')}")
    if kind == "progress":
        pct = round(float(data.get("progress", 0.0)) * 100)
        return (f"{ts} progress: {data.get('message', '?')} — "
                f"{pct}% ({data.get('state', 'update')})")
    return f"{ts} {kind}: {json.dumps(data, default=str)}"


def _event_matches(kind: str, data: dict, codes: list[str]) -> bool:
    """--filter CODE predicate: health events match on their code,
    clog lines on a mention of the code in their text (the mon logs
    'Health check failed: CODE (...)' transitions), everything else is
    suppressed when a filter is active."""
    if not codes:
        return True
    if kind == "health":
        return data.get("code") in codes
    if kind == "clog":
        text = data.get("text", "")
        return any(c in text for c in codes)
    return False


def _watch(mc: MonClient, count: int = 0, timeout: float = 0.0,
           codes: list[str] | None = None) -> int:
    import queue
    import time as _time
    q: queue.Queue = queue.Queue()
    mc.on_event = lambda kind, data, stamp: q.put((kind, data, stamp))
    mc.sub_want("events", 0)
    printed = 0
    deadline = _time.monotonic() + timeout if timeout > 0 else None
    try:
        while True:
            wait = 1.0 if deadline is None else \
                min(1.0, deadline - _time.monotonic())
            if wait <= 0:
                return 0
            try:
                kind, data, stamp = q.get(timeout=wait)
            except queue.Empty:
                continue
            data = data if isinstance(data, dict) else {}
            if not _event_matches(kind, data, codes or []):
                continue
            line = _fmt_event(kind, data, stamp)
            if line is None:
                continue
            print(line, flush=True)
            printed += 1
            if count and printed >= count:
                return 0
    except KeyboardInterrupt:
        return 0


def _render_iostat(out: dict) -> str:
    """`ceph iostat` panel: one cluster line + one row per OSD."""
    c = out.get("cluster") or {}
    lines = [
        f"cluster: {c.get('ops_per_sec', 0.0):.1f} op/s "
        f"(rd {c.get('read_ops_per_sec', 0.0):.1f}, "
        f"wr {c.get('write_ops_per_sec', 0.0):.1f}), "
        f"{c.get('bytes_per_sec', 0.0):.0f} B/s, "
        f"{c.get('launches_per_sec', 0.0):.1f} launches/s, "
        f"comp {c.get('compress_bytes_per_sec', 0.0):.0f}→"
        f"{c.get('compressed_bytes_per_sec', 0.0):.0f} B/s "
        f"(rd {c.get('decompress_bytes_per_sec', 0.0):.0f}, "
        f"fp {c.get('fingerprint_bytes_per_sec', 0.0):.0f})",
        f"{'OSD':<8}{'OP/S':>10}{'RD/S':>10}{'WR/S':>10}"
        f"{'B/S':>12}{'LAUNCH/S':>10}",
    ]
    for d, r in sorted((out.get("osds") or {}).items()):
        lines.append(
            f"{d:<8}{r.get('ops_per_sec', 0.0):>10.1f}"
            f"{r.get('read_ops_per_sec', 0.0):>10.1f}"
            f"{r.get('write_ops_per_sec', 0.0):>10.1f}"
            f"{r.get('bytes_per_sec', 0.0):>12.0f}"
            f"{r.get('launches_per_sec', 0.0):>10.1f}")
    return "\n".join(lines)


def _render_autotune(out: dict) -> str:
    """`ceph autotune status` panel: controller header + one row per
    actuated knob."""
    state = "enabled" if out.get("enabled") else "disabled"
    lines = [
        f"autotune: {state} seed={out.get('seed')} "
        f"tick={out.get('tick', 0)} "
        f"decisions={out.get('decisions_total', 0)} "
        f"rollbacks={out.get('rollbacks_total', 0)} "
        f"digest={str(out.get('journal_digest', ''))[:12]}",
        f"{'KNOB':<36}{'VALUE':>12}{'PIN':>5}{'COOL':>6}"
        f"{'LAST':>10}",
    ]
    for name, k in sorted((out.get("knobs") or {}).items()):
        v = k.get("value")
        vs = f"{v:g}" if isinstance(v, float) else str(v)
        lines.append(
            f"{name:<36}{vs:>12}"
            f"{'*' if k.get('pinned') else '':>5}"
            f"{k.get('cooldown_ticks', 0):>6}"
            f"{str(k.get('last_action') or '-'):>10}")
    return "\n".join(lines)


def _render_osd_top(out: dict) -> str:
    """`ceph osd top` panel: cluster-merged heavy hitters for one
    attribution dimension, with the sketch's error bound."""
    lines = [
        f"top {out.get('dim')} by {out.get('by')} "
        f"(merged from {len(out.get('osds') or [])} osds, "
        f"err floor {out.get('err_floor', 0)})",
        f"{'KEY':<28}{'OPS':>10}{'±ERR':>8}{'BYTES':>14}"
        f"{'AVG(MS)':>10}{'P99(MS)':>10}",
    ]
    for r in out.get("rows") or []:
        lines.append(
            f"{str(r.get('key', '')):<28}{r.get('ops', 0):>10}"
            f"{r.get('err', 0):>8}{r.get('bytes', 0):>14}"
            f"{r.get('lat_avg_ms', 0.0):>10.2f}"
            f"{r.get('p99_ms', 0.0):>10.2f}")
    return "\n".join(lines)


def _render_alerts(out: dict) -> str:
    """`ceph alerts status` panel: engine header + one row per
    firing alert / active silence."""
    state = "enabled" if out.get("enabled") else "disabled"
    lines = [
        f"alerts: {state} seed={out.get('seed')} "
        f"tick={out.get('tick', 0)} "
        f"fired={out.get('fired_total', 0)} "
        f"cleared={out.get('cleared_total', 0)} "
        f"digest={str(out.get('journal_digest', ''))[:12]}",
    ]
    firing = out.get("firing") or {}
    if not firing:
        lines.append("no alerts firing")
    else:
        lines.append(f"{'ALERT':<36}{'CHECK':<20}{'SEV':>5}"
                     f"{'VALUE':>10}")
        for name in sorted(firing):
            r = firing[name] or {}
            lines.append(
                f"{name:<36}{str(r.get('check', '')):<20}"
                f"{str(r.get('severity', '')):>5}"
                f"{float(r.get('value', 0.0)):>10.2f}")
    for name in sorted(out.get("silences") or {}):
        lines.append(f"silenced: {name}")
    return "\n".join(lines)


def _render_osd_perf(out: dict) -> str:
    """`ceph osd perf` panel: commit latency plus the device-launch
    breakdown the telemetry spine derives from profiler aggregates."""
    lines = [f"{'OSD':<8}{'COMMIT(MS)':>12}{'LAUNCHES':>10}"
             f"{'DISP(MS)':>10}{'COMP(MS)':>10}{'DISP%':>8}"
             f"{'OCC%':>8}{'P99(US)':>10}"]
    for d, r in sorted((out.get("osd_perf") or {}).items()):
        dev = r.get("device") or {}
        lines.append(
            f"{d:<8}{r.get('commit_latency_ms', 0.0):>12.2f}"
            f"{dev.get('launches', 0):>10}"
            f"{dev.get('dispatch_ms_avg', 0.0):>10.2f}"
            f"{dev.get('compute_ms_avg', 0.0):>10.2f}"
            f"{100 * dev.get('dispatch_overhead_ratio', 0.0):>8.1f}"
            f"{100 * dev.get('occupancy_ratio', 1.0):>8.1f}"
            f"{dev.get('p99_us', 0.0):>10.0f}")
    return "\n".join(lines)


def _render(prefix: str, out) -> str | None:
    """Human panels for the classic read commands (reference ceph.in
    plain-format output); None ⇒ caller falls back to JSON."""
    if prefix == "status":
        pgs = " ".join(f"{n} {s}" for s, n in
                       sorted(out.get("pg_states", {}).items()))
        lines = [
            "  cluster:",
            f"    health: {out.get('health')}",
        ]
        for chk in out.get("checks", []):
            lines.append(f"            {chk['code']}: "
                         f"{chk['summary']}")
        lines += [
            "",
            "  services:",
            f"    mon: quorum {out.get('quorum')} "
            f"(leader {out.get('leader')})",
            f"    osd: {out.get('num_up_osds')}/"
            f"{out.get('num_osds')} up (epoch "
            f"{out.get('osdmap_epoch')})",
            "",
            "  data:",
            f"    pools:   {len(out.get('pools', []))} pools, "
            f"{out.get('num_pgs')} pgs",
            f"    objects: {out.get('num_objects')} objects",
            f"    pgs:     {pgs}",
        ]
        return "\n".join(lines)
    if prefix == "df":
        lines = ["--- POOLS ---",
                 f"{'NAME':<16}{'ID':>4}{'PGS':>6}{'OBJECTS':>10}"
                 f"{'USED':>12}{'LOGICAL':>12}{'RATIO':>7}"]
        for p in out.get("pools", []):
            ratio = p.get("compress_ratio", 1.0)
            logical = p.get("bytes_logical", p["bytes_used"])
            dr = p.get("dedup_ratio")
            tail = f" dedup {dr:.2f}x" if dr is not None else ""
            lines.append(f"{p['name']:<16}{p['id']:>4}"
                         f"{p['pg_num']:>6}{p['objects']:>10}"
                         f"{p['bytes_used']:>12}{logical:>12}"
                         f"{ratio:>6.2f}x{tail}")
        lines.append(f"TOTAL objects={out.get('total_objects')} "
                     f"used={out.get('total_bytes_used')}B "
                     f"logical={out.get('total_bytes_logical')}B")
        dd = out.get("dedup") or {}
        if dd.get("chunks"):
            lines.append(
                f"DEDUP chunks={dd['chunks']} refs={dd.get('refs')} "
                f"stored={dd.get('stored_bytes')}B "
                f"referenced={dd.get('referenced_bytes')}B "
                f"ratio={dd.get('ratio', 1.0):.2f}x")
        return "\n".join(lines)
    if prefix == "osd df":
        lines = [f"{'ID':>4}{'UP':>6}{'PGS':>6}{'OPS':>10}"]
        for n in out.get("nodes", []):
            lines.append(f"{n['osd']:>4}{str(n['up']):>6}"
                         f"{n['num_pgs']:>6}{n['ops']:>10}")
        return "\n".join(lines)
    return None


if __name__ == "__main__":
    sys.exit(main())

"""osdmaptool — offline OSDMap operations; ``--test-map-pgs`` is the
north-star CRUSH harness (SURVEY.md §4.5).

Reference: ``src/tools/osdmaptool.cc``.  The reference enumerates every
PG of every pool and maps each through scalar ``crush_do_rule`` one at a
time, single-threaded; here the whole PG batch becomes ONE vectorized
launch through `BatchMapper` (hash → straw2 argmax over [B] PGs), which
is the second TPU win recorded in BASELINE.md.

Usage::

    osdmaptool --createsimple 256 map.json --pg-bits 6
    osdmaptool map.json --test-map-pgs [--pool 0]
    osdmaptool map.json --test-map-object foo --pool 0
    osdmaptool map.json --mark-out 3 -o map2.json
    osdmaptool map.json --export-crush crush.json
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time

import numpy as np

from ..crush.compiler import crushmap_from_dict, crushmap_to_dict
from ..crush.map import CRUSH_ITEM_NONE
from ..crush.mapper import do_rule
from ..osd.osdmap import (EXISTS, UP, Incremental, OSDMap, PGPool, PGid,
                          TYPE_ERASURE, TYPE_REPLICATED)


def osdmap_to_dict(m: OSDMap) -> dict:
    return {
        "version": 1,
        "epoch": m.epoch,
        "max_osd": m.max_osd,
        "osd_state": m.osd_state,
        "osd_weight": m.osd_weight,
        "osd_up_thru": m.osd_up_thru,
        "flags": m.flags,
        "crush": crushmap_to_dict(m.crush),
        "pools": [{
            "id": p.id, "name": p.name, "type": p.type, "size": p.size,
            "min_size": p.min_size, "pg_num": p.pg_num,
            "pgp_num": p.pgp_num, "crush_rule": p.crush_rule,
            "flags": p.flags, "last_change": p.last_change,
            "erasure_code_profile": p.erasure_code_profile,
            "snap_seq": p.snap_seq,
            "snaps": {str(i): n for i, n in p.snaps.items()},
            "quota_max_objects": p.quota_max_objects,
            "quota_max_bytes": p.quota_max_bytes,
            "full": p.full,
            "tier_of": p.tier_of, "read_tier": p.read_tier,
            "write_tier": p.write_tier, "cache_mode": p.cache_mode,
            "tiers": list(p.tiers),
            "is_stretch": p.is_stretch,
            "stretch_min_size": p.stretch_min_size,
            "compression_mode": p.compression_mode,
            "compression_algorithm": p.compression_algorithm,
            "dedup_enable": p.dedup_enable,
        } for p in m.pools.values()],
        "stretch": {
            "enabled": m.stretch_mode_enabled,
            "bucket_type": m.stretch_bucket_type,
            "sites": {s: list(o) for s, o in m.stretch_sites.items()},
            "tiebreaker": m.stretch_tiebreaker,
            "degraded": m.degraded_stretch_mode,
            "recovering": m.recovering_stretch_mode,
            "degraded_site": m.stretch_degraded_site,
        },
        "pg_temp": {str(pg): osds for pg, osds in m.pg_temp.items()},
        "primary_temp": {str(pg): o for pg, o in m.primary_temp.items()},
        "pg_upmap": {str(pg): osds for pg, osds in m.pg_upmap.items()},
        "pg_upmap_items": {str(pg): [list(pair) for pair in pairs]
                           for pg, pairs in m.pg_upmap_items.items()},
        "erasure_code_profiles": m.erasure_code_profiles,
        "osd_addrs": {str(o): a for o, a in m.osd_addrs.items()},
    }


def osdmap_from_dict(d: dict) -> OSDMap:
    m = OSDMap(crush=crushmap_from_dict(d["crush"]), max_osd=d["max_osd"])
    m.epoch = d["epoch"]
    m.osd_state = list(d["osd_state"])
    m.osd_weight = list(d["osd_weight"])
    m.osd_up_thru = list(d.get("osd_up_thru", [])) or [0] * d["max_osd"]
    m.flags = d.get("flags", 0)
    for p in d["pools"]:
        p = dict(p)
        p["snaps"] = {int(i): n
                      for i, n in (p.get("snaps") or {}).items()}
        pool = PGPool(**p)
        m.pools[pool.id] = pool
        m.pool_name[pool.name] = pool.id
    m.pg_temp = {PGid.parse(s): list(v)
                 for s, v in d.get("pg_temp", {}).items()}
    m.primary_temp = {PGid.parse(s): v
                      for s, v in d.get("primary_temp", {}).items()}
    m.pg_upmap = {PGid.parse(s): list(v)
                  for s, v in d.get("pg_upmap", {}).items()}
    m.pg_upmap_items = {
        PGid.parse(s): [tuple(pair) for pair in v]
        for s, v in d.get("pg_upmap_items", {}).items()}
    m.erasure_code_profiles = d.get("erasure_code_profiles", {})
    m.osd_addrs = {int(o): a for o, a in d.get("osd_addrs", {}).items()}
    st = d.get("stretch")
    if st:
        m.stretch_mode_enabled = bool(st.get("enabled", False))
        m.stretch_bucket_type = int(st.get("bucket_type", 0))
        m.stretch_sites = {s: [int(o) for o in osds]
                           for s, osds in (st.get("sites") or {}).items()}
        m.stretch_tiebreaker = st.get("tiebreaker", "")
        m.degraded_stretch_mode = bool(st.get("degraded", False))
        m.recovering_stretch_mode = bool(st.get("recovering", False))
        m.stretch_degraded_site = st.get("degraded_site", "")
    return m


def load_osdmap(path: str) -> OSDMap:
    with open(path) as f:
        return osdmap_from_dict(json.load(f))


def save_osdmap(m: OSDMap, path: str):
    with open(path, "w") as f:
        json.dump(osdmap_to_dict(m), f)
        f.write("\n")


def map_pool_pgs(m: OSDMap, pool: PGPool,
                 use_jax: bool = True,
                 require_batched: bool = False,
                 engines: list | None = None) -> np.ndarray:
    """Map every PG of a pool → [pg_num, size] int32 device matrix
    (CRUSH only — upmap/pg_temp overrides applied by the caller if
    needed).  The batched path computes the pps seeds vectorized, then
    one BatchMapper launch.

    A batched-mapper refusal warns loudly (or raises under
    require_batched) instead of silently timing the Python oracle;
    `engines`, when given, collects which engine ran."""
    from ._engine import fallback
    seeds = np.arange(pool.pg_num, dtype=np.uint32)
    pps = pool.raw_pg_to_pps_batch(seeds)
    rule = m.crush.rule_by_id(pool.crush_rule)
    if use_jax:
        try:
            # the OSDMap-level cache: repeated sweeps (balancer
            # rounds, --test-map-pgs after a reweight) reuse the
            # compiled executable via BatchMapper.set_weights
            bm = m.batch_mapper(rule.id, pool.size)
            out = bm(pps, np.asarray(m.osd_weight, dtype=np.uint32))
            if engines is not None:
                engines.append("tpu-batched")
            return out
        except (NotImplementedError, ValueError, RuntimeError) as e:
            fallback("osdmaptool", f"pool {pool.id} rule {rule.id}",
                     e, require_batched)
    elif require_batched:
        from ._engine import BatchedRequired
        raise BatchedRequired(
            "osdmaptool: --require-batched with --no-jax")
    if engines is not None:
        engines.append("scalar-oracle")
    rows = [do_rule(m.crush, rule, int(x), pool.size, m.osd_weight)
            for x in pps]
    out = np.full((len(rows), pool.size), CRUSH_ITEM_NONE, dtype=np.int32)
    for i, row in enumerate(rows):
        out[i, :len(row)] = row
    return out


def run_test_map_pgs(m: OSDMap, pool_id: int | None, *, use_jax: bool = True,
                 require_batched: bool = False, out=sys.stdout) -> dict:
    """The reference's --test-map-pgs report: per-OSD PG counts,
    first/primary counts, min/max/avg/stddev, size histogram."""
    pools = ([m.pools[pool_id]] if pool_id is not None
             else list(m.pools.values()))
    engines: list[str] = []
    count = np.zeros(m.max_osd, dtype=np.int64)
    first = np.zeros(m.max_osd, dtype=np.int64)
    primary = np.zeros(m.max_osd, dtype=np.int64)
    size_hist: dict[int, int] = {}
    total_pgs = 0
    t0 = time.perf_counter()
    for pool in pools:
        print(f"pool {pool.id} pg_num {pool.pg_num}", file=out)
        total_pgs += pool.pg_num
        res = map_pool_pgs(m, pool, use_jax=use_jax,
                           require_batched=require_batched,
                           engines=engines)
        # apply upmap/pg_temp overrides (host-side; they are sparse)
        overrides = (set(m.pg_upmap) | set(m.pg_upmap_items)
                     | set(m.pg_temp) | set(m.primary_temp))
        for pg in overrides:
            if pg.pool == pool.id and pg.seed < pool.pg_num:
                up, up_p, acting, acting_p = m.pg_to_up_acting_osds(pg)
                row = np.full(pool.size, CRUSH_ITEM_NONE, dtype=np.int32)
                n = min(len(acting), pool.size)
                row[:n] = acting[:n]
                res[pg.seed] = row
        # count only up OSDs — matches pg_to_up_acting_osds's up filtering
        up_mask = np.array([m.is_up(o) for o in range(m.max_osd)],
                           dtype=bool)
        valid = res != CRUSH_ITEM_NONE
        valid &= up_mask[np.clip(res, 0, m.max_osd - 1)]
        np.add.at(count, res[valid], 1)
        fcol = res[np.arange(len(res)), valid.argmax(axis=1)]
        fvalid = (fcol != CRUSH_ITEM_NONE) & valid.any(axis=1)
        np.add.at(first, fcol[fvalid], 1)
        np.add.at(primary, fcol[fvalid], 1)   # no primary-affinity yet
        sizes, freqs = np.unique(valid.sum(axis=1), return_counts=True)
        for s, f in zip(sizes, freqs):
            size_hist[int(s)] = size_hist.get(int(s), 0) + int(f)
    dt = time.perf_counter() - t0

    print("#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    for o in range(m.max_osd):
        print(f"osd.{o}\t{count[o]}\t{first[o]}\t{primary[o]}"
              f"\t{_osd_crush_weight(m, o):.5g}"
              f"\t{m.osd_weight[o] / 0x10000:.5g}", file=out)
    in_osds = max(m.num_in_osds(), 1)
    avg = count.sum() / in_osds
    stddev = float(np.sqrt(((count - avg) ** 2).sum() / in_osds))
    print(f" in {m.num_in_osds()}", file=out)
    print(f" avg {avg:.4g} stddev {stddev:.4g} "
          f"({stddev / avg if avg else 0:.4g}x)", file=out)
    print(f" min osd.{int(count.argmin())} {int(count.min())}", file=out)
    print(f" max osd.{int(count.argmax())} {int(count.max())}", file=out)
    print("size histogram: " + "; ".join(
        f"size {s} {n}" for s, n in sorted(size_hist.items())), file=out)
    rate = total_pgs / dt if dt > 0 else float("inf")
    print(f"mapped {total_pgs} pgs in {dt:.3f}s = {rate:,.0f} pg/s",
          file=out)
    engine = ("+".join(sorted(set(engines)))
              if engines else "scalar-oracle")
    return {"pgs": total_pgs, "seconds": dt, "pgs_per_sec": rate,
            "count": count, "size_hist": size_hist, "engine": engine}


def _osd_crush_weight(m: OSDMap, osd: int) -> float:
    for b in m.crush.buckets:
        if b is None:
            continue
        ws = b.weights if b.weights else [b.item_weight] * b.size
        for item, w in zip(b.items, ws):
            if item == osd:
                return w / 0x10000
    return 0.0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="osdmaptool", description=__doc__)
    p.add_argument("mapfile", nargs="?", help="OSDMap file (JSON)")
    p.add_argument("--createsimple", type=int, metavar="N",
                   help="create a simple map with N osds into MAPFILE")
    p.add_argument("--pg-bits", type=int, default=6)
    p.add_argument("--pool-type", choices=["replicated", "erasure"],
                   default="replicated")
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--test-map-object", metavar="NAME")
    p.add_argument("--pool", type=int, default=None)
    p.add_argument("--mark-out", type=int, action="append", default=[],
                   metavar="OSD")
    p.add_argument("--mark-up-in", action="store_true")
    p.add_argument("--export-crush", metavar="FILE")
    p.add_argument("--import-crush", metavar="FILE")
    p.add_argument("--upmap", metavar="FILE",
                   help="run the upmap balancer, write the proposed "
                        "`osd pg-upmap-items` commands to FILE")
    p.add_argument("--upmap-pool", type=int, default=None)
    p.add_argument("--upmap-max", type=int, default=100)
    p.add_argument("--no-jax", action="store_true",
                   help="force the scalar oracle path")
    p.add_argument("--require-batched", action="store_true",
                   help="error instead of falling back to the scalar "
                        "oracle when the batched mapper declines a rule")
    p.add_argument("-o", "--out-file", metavar="FILE")
    p.add_argument("--print", dest="print_map", action="store_true")
    return p


def main(argv=None) -> int:
    from ..utils import honor_jax_platforms_env
    honor_jax_platforms_env()
    args = build_parser().parse_args(argv)
    maps_pgs = (args.test_map_pgs or args.test_map_object
                or args.upmap)
    if maps_pgs and not args.no_jax:
        # only mapping subcommands touch the batched mapper; pure
        # map-file operations must never initialize a JAX backend
        # (which can hang on TPU-tunnel hiccups — see utils.platform)
        from ..utils.platform import enable_compile_cache, ensure_x64
        ensure_x64()       # BatchMapper needs 64-bit straw2 draws
        enable_compile_cache()
    if not args.mapfile:
        build_parser().print_usage()
        return 1

    if args.createsimple:
        ptype = (TYPE_ERASURE if args.pool_type == "erasure"
                 else TYPE_REPLICATED)
        m = OSDMap.build_simple(args.createsimple, pg_bits=args.pg_bits,
                                pool_type=ptype)
        save_osdmap(m, args.mapfile)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfile}")
        return 0

    m = load_osdmap(args.mapfile)
    dirty = False
    if args.mark_up_in:
        for o in range(m.max_osd):
            m.osd_state[o] |= EXISTS | UP
            m.osd_weight[o] = 0x10000
        dirty = True
    for o in args.mark_out:
        m.mark_out(o)
        dirty = True
    if args.import_crush:
        with open(args.import_crush) as f:
            m.crush = crushmap_from_dict(json.load(f))
        dirty = True
    if args.export_crush:
        with open(args.export_crush, "w") as f:
            json.dump(crushmap_to_dict(m.crush), f)
            f.write("\n")
    if args.print_map:
        print(f"epoch {m.epoch}")
        print(f"max_osd {m.max_osd}")
        for p in m.pools.values():
            kind = "erasure" if p.type == TYPE_ERASURE else "replicated"
            print(f"pool {p.id} '{p.name}' {kind} size {p.size} "
                  f"min_size {p.min_size} pg_num {p.pg_num} "
                  f"crush_rule {p.crush_rule}")
        for o in range(m.max_osd):
            print(f"osd.{o} {'up' if m.is_up(o) else 'down'} "
                  f"{'out' if m.is_out(o) else 'in'} "
                  f"weight {m.osd_weight[o] / 0x10000:g}")
    if args.test_map_object:
        pool = args.pool if args.pool is not None else min(m.pools)
        pg = m.object_locator_to_pg(args.test_map_object, pool)
        pg = m.raw_pg_to_pg(pg)
        up, up_p, acting, acting_p = m.pg_to_up_acting_osds(pg)
        print(f" object '{args.test_map_object}' -> {pg} -> up {up} "
              f"acting {acting}")
    if args.test_map_pgs:
        from ._engine import BatchedRequired, announce
        try:
            rep = run_test_map_pgs(m, args.pool,
                                   use_jax=not args.no_jax,
                                   require_batched=args.require_batched)
            announce("osdmaptool", rep["engine"])
        except BatchedRequired as e:
            print(e, file=sys.stderr)
            return 2
    if args.upmap:
        # reference `osdmaptool --upmap out.txt`: emit the balancer's
        # proposed commands (and keep them applied in -o output)
        from ..mgr.balancer import UpmapBalancer
        pools = ([args.upmap_pool] if args.upmap_pool is not None
                 else list(m.pools))
        from ._engine import BatchedRequired
        lines = []
        for pid in pools:
            try:
                bal = UpmapBalancer(
                    m, pid, use_jax=not args.no_jax,
                    require_batched=args.require_batched)
                before = bal.stddev()
                props = bal.optimize(max_changes=args.upmap_max)
            except BatchedRequired as e:
                print(e, file=sys.stderr)
                return 2
            for pgid, items in sorted(props.items(),
                                      key=lambda kv: str(kv[0])):
                pairs = " ".join(f"{a} {b}" for a, b in items)
                lines.append(f"ceph osd pg-upmap-items {pgid} {pairs}")
            print(f"pool {pid}: stddev {before:.2f} -> "
                  f"{bal.stddev():.2f}, {len(props)} changes")
        with open(args.upmap, "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        dirty = True
    if dirty and args.out_file:
        save_osdmap(m, args.out_file)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.out_file}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""radosgw-admin — offline/administrative ops on the RGW store.

Reference behavior re-created (``src/rgw/rgw_admin.cc``; SURVEY.md
§3.9/§3.10), reduced to the authless gateway's surface: bucket
inventory and surgery straight against the ``.rgw.*`` pools, no
gateway process required (exactly how the reference tool talks to
RADOS directly).

    radosgw-admin -m HOST:PORT[,...] bucket list
    radosgw-admin ... bucket stats --bucket NAME
    radosgw-admin ... bucket rm --bucket NAME [--purge-objects]
    radosgw-admin ... object rm --bucket NAME --object KEY
"""

from __future__ import annotations

import argparse
import json
import sys

from ..osdc.librados import Rados
from ..rgw.gateway import RGWStore
from .rados import _monmap_from_addrs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="radosgw-admin",
                                description=__doc__)
    p.add_argument("-m", "--mon", required=True)
    p.add_argument("target", choices=["bucket", "object"])
    p.add_argument("op", choices=["list", "stats", "rm"])
    p.add_argument("--bucket")
    p.add_argument("--object")
    p.add_argument("--purge-objects", action="store_true")
    a = p.parse_args(argv)

    r = Rados(_monmap_from_addrs(a.mon)).connect()
    try:
        store = RGWStore(r)
        if a.target == "bucket" and a.op == "list":
            print(json.dumps(store.list_buckets(), indent=2))
            return 0
        if a.target == "bucket" and a.op == "stats":
            if not a.bucket:
                raise SystemExit("--bucket required")
            if not store.bucket_exists(a.bucket):
                print(f"no such bucket {a.bucket!r}",
                      file=sys.stderr)
                return 2
            objs = store.list_objects(a.bucket)
            print(json.dumps({
                "bucket": a.bucket,
                "usage": {
                    "num_objects": len(objs),
                    "size": sum(m.get("size", 0)
                                for m in objs.values()),
                },
                "versioning": store.versioning_enabled(a.bucket),
            }, indent=2))
            return 0
        if a.target == "bucket" and a.op == "rm":
            if not a.bucket:
                raise SystemExit("--bucket required")
            if a.purge_objects:
                for key in list(store.list_objects(a.bucket)):
                    store.delete_object(a.bucket, key)
                # purge surviving old versions + markers too
                for e in store.list_versions(a.bucket):
                    store.delete_object(a.bucket, e["key"],
                                        e["version_id"])
            if not store.delete_bucket(a.bucket):
                print("bucket not empty (use --purge-objects)",
                      file=sys.stderr)
                return 2
            return 0
        if a.target == "object" and a.op == "rm":
            if not (a.bucket and a.object):
                raise SystemExit("--bucket and --object required")
            store.delete_object(a.bucket, a.object)
            return 0
        raise SystemExit(f"unsupported: {a.target} {a.op}")
    finally:
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())

"""radosgw-admin — offline/administrative ops on the RGW store.

Reference behavior re-created (``src/rgw/rgw_admin.cc``; SURVEY.md
§3.9/§3.10), reduced to the authless gateway's surface: bucket
inventory and surgery straight against the ``.rgw.*`` pools, no
gateway process required (exactly how the reference tool talks to
RADOS directly).

    radosgw-admin -m HOST:PORT[,...] bucket list
    radosgw-admin ... bucket stats --bucket NAME
    radosgw-admin ... bucket rm --bucket NAME [--purge-objects]
    radosgw-admin ... object rm --bucket NAME --object KEY
    radosgw-admin ... user create --uid UID [--display-name NAME]
    radosgw-admin ... user list
    radosgw-admin ... user info --uid UID
    radosgw-admin ... user rm --uid UID
"""

from __future__ import annotations

import argparse
import json
import sys

from ..osdc.librados import Rados
from ..rgw.gateway import RGWStore
from .rados import _monmap_from_addrs


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="radosgw-admin",
                                description=__doc__)
    p.add_argument("-m", "--mon", required=True)
    p.add_argument("target", choices=["bucket", "object", "user"])
    p.add_argument("op", choices=["list", "stats", "rm", "create",
                                  "info"])
    p.add_argument("--bucket")
    p.add_argument("--object")
    p.add_argument("--uid")
    p.add_argument("--display-name", default="")
    p.add_argument("--purge-objects", action="store_true")
    a = p.parse_args(argv)

    r = Rados(_monmap_from_addrs(a.mon)).connect()
    try:
        store = RGWStore(r)
        if a.target == "bucket" and a.op == "list":
            print(json.dumps(store.list_buckets(), indent=2))
            return 0
        if a.target == "bucket" and a.op == "stats":
            if not a.bucket:
                raise SystemExit("--bucket required")
            if not store.bucket_exists(a.bucket):
                print(f"no such bucket {a.bucket!r}",
                      file=sys.stderr)
                return 2
            objs = store.list_objects(a.bucket)
            print(json.dumps({
                "bucket": a.bucket,
                "usage": {
                    "num_objects": len(objs),
                    "size": sum(m.get("size", 0)
                                for m in objs.values()),
                },
                "versioning": store.versioning_enabled(a.bucket),
            }, indent=2))
            return 0
        if a.target == "bucket" and a.op == "rm":
            if not a.bucket:
                raise SystemExit("--bucket required")
            if a.purge_objects:
                for key in list(store.list_objects(a.bucket)):
                    store.delete_object(a.bucket, key)
                # purge surviving old versions + markers too
                for e in store.list_versions(a.bucket):
                    store.delete_object(a.bucket, e["key"],
                                        e["version_id"])
            if not store.delete_bucket(a.bucket):
                print("bucket not empty (use --purge-objects)",
                      file=sys.stderr)
                return 2
            return 0
        if a.target == "user":
            # reference RGWUserAdminOp: users + their S3 keypairs
            if a.op == "create":
                if not a.uid:
                    raise SystemExit("--uid required")
                print(json.dumps(store.create_user(
                    a.uid, a.display_name), indent=2))
                return 0
            if a.op == "list":
                print(json.dumps(
                    [u["uid"] for u in store.list_users()], indent=2))
                return 0
            if a.op == "info":
                if not a.uid:
                    raise SystemExit("--uid required")
                user = store.get_user(a.uid)
                if user is None:
                    print(f"no such user {a.uid!r}", file=sys.stderr)
                    return 2
                print(json.dumps(user, indent=2))
                return 0
            if a.op == "rm":
                if not a.uid:
                    raise SystemExit("--uid required")
                return 0 if store.remove_user(a.uid) else 2
        if a.target == "object" and a.op == "rm":
            if not (a.bucket and a.object):
                raise SystemExit("--bucket and --object required")
            store.delete_object(a.bucket, a.object)
            return 0
        raise SystemExit(f"unsupported: {a.target} {a.op}")
    finally:
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())

"""cephadm — spec-driven cluster deployment.

Reference behavior re-created (``src/cephadm/cephadm.py``; SURVEY.md
§3.10): bootstrap a whole cluster from a service spec and inspect
what's deployed.  The reference's deployment unit is a container per
daemon; ours is an in-process daemon object per spec entry (the same
single-host posture as ``vstart.sh``, driven by a spec instead of
flags), with a STATE FILE recording what runs where — monmap,
admin-socket paths, service ports — so other tools (``ceph -m``,
``ceph daemon``, s3 clients) can find everything.

    cephadm bootstrap --spec spec.json [--state /tmp/ceph_tpu.state] \
        [--hold]
    cephadm ls --state /tmp/ceph_tpu.state

Spec format (JSON)::

    {"mons": 3, "osds": 4, "mgrs": ["x"], "mds": ["a", "b"],
     "fs": "cephfs", "rgw": true,
     "pools": [{"name": "data", "pg_num": 16, "size": 3}]}
"""

from __future__ import annotations

import argparse
import json
import sys
import time


class Deployment:
    """A running spec (returned by bootstrap; the CLI holds on it)."""

    def __init__(self, cluster, state_path: str, state: dict,
                 rgw=None):
        self.cluster = cluster
        self.state_path = state_path
        self.state = state
        self.rgw = rgw
        self._rados = None

    def stop(self):
        if self.rgw is not None:
            self.rgw.shutdown()
        if self._rados is not None:
            self._rados.shutdown()
        self.cluster.stop()


def bootstrap(spec: dict, state_path: str) -> Deployment:
    from ..vstart import MiniCluster
    n_mons = int(spec.get("mons", 1))
    n_osds = int(spec.get("osds", 3))
    cluster = MiniCluster(n_mons=n_mons, n_osds=n_osds).start()
    try:
        return _bootstrap_services(cluster, spec, state_path)
    except Exception:
        cluster.stop()      # never leak a half-deployed cluster
        raise


def _bootstrap_services(cluster, spec: dict,
                        state_path: str) -> Deployment:
    n_mons = int(spec.get("mons", 1))
    state = {
        "mon_addrs": [f"{a.host}:{a.port}"
                      for a in cluster.monmap.mons.values()],
        "daemons": {},
        "created": time.time(),
    }
    for r in range(n_mons):
        state["daemons"][f"mon.{r}"] = {
            "type": "mon",
            "asok": cluster.mons[r].admin_socket.path}
    for i, osd in cluster.osds.items():
        state["daemons"][f"osd.{i}"] = {
            "type": "osd", "asok": osd.admin_socket.path}
    for name in spec.get("mgrs", []):
        mgr = cluster.start_mgr(name)
        state["daemons"][f"mgr.{name}"] = {
            "type": "mgr", "asok": mgr.admin_socket.path}
    if spec.get("mgrs"):
        cluster.wait_for_active_mgr()
    dep = Deployment(cluster, state_path, state)
    try:
        _deploy_rest(dep, cluster, spec, state)
        with open(state_path, "w") as f:
            json.dump(state, f, indent=1)
    except Exception:
        # rgw/rados started by _deploy_rest must not outlive a failed
        # bootstrap (incl. a state-file write failure)
        if dep.rgw is not None:
            dep.rgw.shutdown()
        if dep._rados is not None:
            dep._rados.shutdown()
        raise
    return dep


def _deploy_rest(dep: Deployment, cluster, spec: dict, state: dict):
    if spec.get("mds"):
        fs_name = spec.get("fs", "cephfs")
        cluster.fs_new(fs_name)
        for name in spec["mds"]:
            mds = cluster.start_mds(name)
            state["daemons"][f"mds.{name}"] = {
                "type": "mds", "asok": mds.admin_socket.path}
        cluster.wait_for_active_mds(fs_name)
        state["fs"] = fs_name
    if spec.get("pools") or spec.get("rgw"):
        from ..osdc.librados import Rados
        dep._rados = Rados(cluster.monmap).connect()
        for p in spec.get("pools", []):
            dep._rados.create_pool(
                p["name"], pg_num=int(p.get("pg_num", 8)),
                size=int(p.get("size", 3)),
                pool_type=p.get("type", "replicated"),
                erasure_code_profile=p.get("profile", ""))
        if spec.get("rgw"):
            from ..rgw import RGWService
            dep.rgw = RGWService(dep._rados).start()
            state["daemons"]["rgw.0"] = {
                "type": "rgw",
                "endpoint": f"http://127.0.0.1:{dep.rgw.port}"}


def _ls(state_path: str) -> int:
    from ..core.admin_socket import admin_command
    try:
        with open(state_path) as f:
            state = json.load(f)
    except FileNotFoundError:
        print(f"cephadm: no state at {state_path}", file=sys.stderr)
        return 1
    rows = []
    for name, d in sorted(state["daemons"].items()):
        alive = "-"
        if d.get("asok"):
            try:
                admin_command(d["asok"], "status")
                alive = "running"
            except Exception:
                alive = "dead"
        rows.append((name, d["type"], alive,
                     d.get("asok") or d.get("endpoint", "")))
    w = max(len(r[0]) for r in rows) + 2
    print(f"{'NAME':<{w}}{'TYPE':<6}{'STATUS':<9}WHERE")
    for r in rows:
        print(f"{r[0]:<{w}}{r[1]:<6}{r[2]:<9}{r[3]}")
    print(f"mons: {','.join(state['mon_addrs'])}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cephadm", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("bootstrap")
    b.add_argument("--spec", required=True)
    b.add_argument("--state", default="/tmp/ceph_tpu.state")
    b.add_argument("--hold", action="store_true",
                   help="stay in the foreground until interrupted "
                        "(in-process daemons live only as long as "
                        "this process — the reference's containers "
                        "don't need this)")
    ls = sub.add_parser("ls")
    ls.add_argument("--state", default="/tmp/ceph_tpu.state")
    a = p.parse_args(argv)

    if a.cmd == "ls":
        return _ls(a.state)
    with open(a.spec) as f:
        spec = json.load(f)
    dep = bootstrap(spec, a.state)
    n = len(dep.state["daemons"])
    print(f"cephadm: bootstrapped {n} daemons "
          f"(mons {','.join(dep.state['mon_addrs'])}); "
          f"state → {a.state}")
    if a.hold:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            dep.stop()
    else:
        dep.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

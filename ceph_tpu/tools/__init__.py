"""CLI parity tools (reference: ``src/tools/``, ``src/test/erasure-code/``)."""

"""Erasure-code benchmark — `ceph_erasure_code_benchmark` CLI parity.

Reference harness being re-created: ``src/test/erasure-code/
ceph_erasure_code_benchmark.{h,cc}`` (SURVEY.md §4.4) — same flags, same
semantics (seconds elapsed, caller derives GB/s), plus:

- ``--batch``: stripes per device launch (the TPU engine's native unit; the
  reference encodes one buffer at a time, we batch to fill the MXU);
- ``--verify``: cross-check parity bytes against the NumPy oracle.

Examples::

    python -m ceph_tpu.tools.ec_bench --plugin jax_tpu --workload encode \
        --size 1048576 --iterations 100 --parameter k=8 --parameter m=3 \
        --parameter technique=reed_sol_van
    python -m ceph_tpu.tools.ec_bench --workload decode --erasures 2 ...
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time

import numpy as np

from ..ec import create_erasure_code
from ..ec.interface import ECProfile
from ..ops import rs


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ec_bench", description=__doc__)
    p.add_argument("--plugin", "-P", default="jax_tpu")
    p.add_argument("--workload", "-w", choices=["encode", "decode"],
                   default="encode")
    p.add_argument("--iterations", "-i", type=int, default=1)
    p.add_argument("--size", "-s", type=int, default=1 << 20,
                   help="total payload bytes per iteration")
    p.add_argument("--parameter", "-p", action="append", default=[],
                   help="profile parameter k=v (repeatable)")
    p.add_argument("--erasures", "-e", type=int, default=1,
                   help="erasures per decode")
    p.add_argument("--erasures-generation", "-E",
                   choices=["random", "exhaustive"], default="random")
    p.add_argument("--erased", type=int, action="append", default=None,
                   help="explicit chunk id to erase (repeatable)")
    p.add_argument("--batch", "-b", type=int, default=None,
                   help="stripes per launch (default: whole payload as one "
                        "stripe, matching the reference)")
    p.add_argument("--verify", "-v", action="store_true",
                   help="verify bytes against the NumPy oracle")
    p.add_argument("--json", action="store_true", help="emit one JSON line")
    return p


def run(argv=None) -> dict:
    from ..utils import honor_jax_platforms_env
    honor_jax_platforms_env()
    args = build_parser().parse_args(argv)
    for kv in args.parameter:
        if "=" not in kv:
            print(f"ec_bench: bad --parameter {kv!r} (expected key=value)",
                  file=sys.stderr)
            raise SystemExit(2)
    params = dict(kv.split("=", 1) for kv in args.parameter)
    params.setdefault("plugin", args.plugin)
    profile = ECProfile.parse(params)
    code = create_erasure_code(profile)
    k, m = code.k, code.m

    rng = np.random.default_rng(0)
    chunk = code.get_chunk_size(args.size)
    batch = args.batch or 1
    data = rng.integers(0, 256, size=(batch, k, chunk), dtype=np.uint8)
    engine = getattr(code, "engine", None)

    def encode_once():
        if engine is not None:
            return engine.encode_device(data)
        stripes = []
        for b in range(batch):
            out = code.encode(set(range(k, k + m)), data[b].reshape(-1))
            # index by chunk id: set/dict iteration order is not id order
            stripes.append(np.stack([out[i] for i in range(k, k + m)]))
        return np.stack(stripes)

    # erasure patterns for decode
    if args.erased:
        patterns = [tuple(args.erased)]
    elif args.erasures_generation == "exhaustive":
        patterns = list(itertools.combinations(range(k + m), args.erasures))
    else:
        patterns = []
        for _ in range(args.iterations):
            patterns.append(tuple(
                sorted(rng.choice(k + m, size=args.erasures, replace=False))))

    parity_np = None
    if args.workload == "decode" or args.verify:
        parity_dev = encode_once()
        if engine is not None:
            parity_np = np.asarray(jax_block(parity_dev))
        else:
            parity_np = np.asarray(parity_dev)

    if args.verify:
        coding = getattr(code, "coding_matrix", None)
        if coding is not None:
            from ..ec.bitmatrix import BitMatrixECEngine
            from ..ec.bitmatrix import encode_oracle as bm_oracle
            bitmatrix = isinstance(engine, BitMatrixECEngine)
            for b in range(min(batch, 4)):
                expect = (bm_oracle(coding, data[b], code.w) if bitmatrix
                          else rs.encode_oracle(coding, data[b]))
                assert np.array_equal(parity_np[b], expect), \
                    f"parity mismatch vs oracle at stripe {b}"

    total_bytes = 0
    if args.workload == "encode":
        jax_block(encode_once())  # warm: exclude XLA compile from timing
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            # materialize EVERY iteration: through the axon relay,
            # block_until_ready returns early and identical repeat
            # executions can be served from a cache — fetching the
            # parity is the only sync that measures real work (the
            # host transfer is included; bench.py's chained-jit loop
            # is the transfer-free metric of record)
            np.asarray(jax_block(encode_once()))
            total_bytes += batch * k * chunk
        elapsed = time.perf_counter() - t0
    else:
        all_chunks = np.concatenate([data, parity_np], axis=1)

        def decode_once(pattern):
            survivors = [i for i in range(k + m) if i not in pattern][:k]
            if engine is not None and code.is_mds:
                # MDS matrix codes: first-k survivor rule (jerasure's).
                # Non-MDS plugins (SHEC/LRC) must use their own solver —
                # an arbitrary k-subset can be singular for them.
                return engine.decode_batch(all_chunks[:, survivors, :],
                                           pattern)
            # non-MDS / locality codes: ask the plugin what to read
            want = set(range(k))
            avail = set(range(k + m)) - set(pattern)
            reads = code.minimum_to_decode(want, avail)
            for b in range(batch):
                code.decode(want, {i: all_chunks[b, i] for i in reads})
            return None

        for pattern in set(patterns):
            decode_once(pattern)  # warm each distinct erasure pattern
        t0 = time.perf_counter()
        for it in range(args.iterations):
            out = decode_once(patterns[it % len(patterns)])
            if out is not None:
                np.asarray(jax_block(out))   # see encode-loop comment
            total_bytes += batch * k * chunk
        elapsed = time.perf_counter() - t0

    result = {
        "plugin": profile.plugin, "technique": profile.technique,
        "k": k, "m": m, "workload": args.workload,
        "size": args.size, "chunk": chunk, "batch": batch,
        "iterations": args.iterations,
        "seconds": elapsed,
        "GBps": total_bytes / elapsed / 1e9 if elapsed > 0 else float("inf"),
        "verified": bool(args.verify),
    }
    if args.json:
        print(json.dumps(result))
    else:
        print(f"{elapsed:.6f}")  # reference prints elapsed seconds
        print(f"# {result['GBps']:.3f} GB/s "
              f"({profile.plugin}/{profile.technique} k={k} m={m} "
              f"chunk={chunk} batch={batch} x{args.iterations})",
              file=sys.stderr)
    return result


def jax_block(x):
    """block_until_ready if x is a jax array (no-op for numpy)."""
    try:
        return x.block_until_ready()
    except AttributeError:
        return x


if __name__ == "__main__":
    run()

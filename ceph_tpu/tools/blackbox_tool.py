"""blackbox-tool — offline reader for a daemon's flight recorder.

Post-mortem companion to ``core.flight_recorder``: parse a (possibly
dead) daemon's black-box sidecar straight from the raw bytes — no
mount, no daemon, no cluster — and print the reconstructed timeline
or the crash summary.  Tolerates a torn tail the same way WAL replay
does (the damage is reported, never fatal)::

    blackbox_tool --path <wal>.bbox --op timeline [--tail N] [--json]
    blackbox_tool --path <wal>.bbox --op info [--json]

``--op timeline`` flattens boot/mark/event/snap/close records into
wall-clock-stamped lines (rebased from the writer's monotonic clock
via the boot records).  ``--op info`` prints the crash summary a
reviving daemon would post as its crash report: identity, last
events, and the armed crash point if the injector announced one
before death.  After a crash+revive the dead incarnation lives at
``<path>.crash`` — point ``--path`` there to autopsy it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..core import flight_recorder


def _fmt_entry(e: dict) -> str:
    stamp = e.get("stamp", 0.0)
    kind = e.get("type", "?")
    rest = {k: v for k, v in e.items() if k not in ("type", "stamp")}
    if kind == "boot":
        body = (f"daemon={rest.get('daemon')} pid={rest.get('pid')} "
                f"nonce={rest.get('nonce')}"
                + (" (rotated)" if rest.get("rotated") else ""))
    elif kind == "mark":
        extra = {k: v for k, v in rest.items() if k != "name"}
        body = rest.get("name", "?") + (
            " " + json.dumps(extra, sort_keys=True, default=str)
            if extra else "")
    elif kind == "event":
        extra = {k: v for k, v in rest.items() if k != "name"}
        body = rest.get("name", "?") + (
            " " + json.dumps(extra, sort_keys=True, default=str)
            if extra else "")
    elif kind == "snap":
        bits = []
        if "spans" in rest:
            bits.append(f"spans={rest['spans']}")
        if "clog" in rest:
            bits.append(f"clog={len(rest['clog'])}")
        if "perf_delta" in rest:
            bits.append(
                f"perf_delta={len(rest['perf_delta'])} sections")
        if "crash_injector" in rest:
            bits.append("crash_injector")
        if "profiler" in rest:
            bits.append("profiler")
        body = " ".join(bits) or "(empty)"
    elif kind == "torn_tail":
        body = json.dumps(rest.get("tail", {}), sort_keys=True)
    else:
        body = json.dumps(rest, sort_keys=True, default=str) \
            if rest else ""
    return f"{stamp:17.6f}  {kind:<9s} {body}".rstrip()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="blackbox-tool",
                                description=__doc__)
    p.add_argument("--path", required=True,
                   help="the black-box sidecar (<wal>.bbox, or "
                        "<wal>.bbox.crash for a dead incarnation)")
    p.add_argument("--op", choices=["timeline", "info"],
                   default="timeline")
    p.add_argument("--tail", type=int, metavar="N",
                   help="only the last N timeline entries")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.exists(args.path) \
            and not os.path.exists(args.path + ".old"):
        print(f"no black box at {args.path!r}", file=sys.stderr)
        return 1
    if args.op == "info":
        info = flight_recorder.crash_info(args.path)
        if args.json:
            print(json.dumps(info, indent=1, sort_keys=True,
                             default=str))
        else:
            cp = info.get("crash_point")
            print(f"daemon:      {info.get('daemon')}")
            print(f"pid:         {info.get('pid')}")
            print(f"nonce:       {info.get('nonce')}")
            print(f"records:     {info.get('records')}")
            print(f"clean_close: {info.get('clean_close')}")
            print(f"tail:        {info.get('tail', {}).get('status')}")
            print("crash_point: " + (
                f"{cp['point']} (occurrence {cp['n']})" if cp
                else "none recorded"))
        return 0
    entries = flight_recorder.timeline(args.path)
    if args.tail:
        entries = entries[-args.tail:]
    if args.json:
        print(json.dumps(entries, indent=1, default=str))
    else:
        for e in entries:
            print(_fmt_entry(e))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:      # e.g. `... --op timeline | head`
        sys.exit(141)

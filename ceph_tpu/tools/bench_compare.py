"""bench_compare — diff two bench result files, metric by metric.

``bench.py`` appends a ``BENCH_r0N.json`` per run; until now the perf
trajectory between runs was eyeball-only.  This tool walks the
``parsed`` trees of two result files (explicit paths, or the latest
pair found in a directory), pairs every numeric leaf by its dotted
path, and prints the relative change::

    bench_compare OLD.json NEW.json [--threshold-pct 5] [--check]
    bench_compare --dir . [--check]          # latest two BENCH_r0N

Direction matters: most metrics are higher-is-better (GB/s, ops/sec,
occupancy), but latency/overhead families are lower-is-better.  The
classifier is a name heuristic: throughput families
(``HIGHER_IS_BETTER``) are checked first so ``*_ops_per_sec`` never
falls into the time-suffix rule, then lower-is-better words and exact
time-unit suffixes invert the grade.  ``--check`` exits non-zero when
any metric regressed past the threshold — the verify skill's perf
gate.  Counters that merely describe the run (seeds, sizes, counts of
work attempted) are noise, not performance; ``IGNORE`` drops them.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# throughput families whose names END in a time unit
# ("sustained_ops_per_sec", "scrub_digest_mb_per_sec", ...): these
# are higher-is-better and must win over the time-suffix rule below
HIGHER_IS_BETTER = (
    "per_sec", "per_s", "gbps", "tops", "goodput", "occupancy",
)
# lower-is-better words, matched anywhere in the leaf name
LOWER_IS_BETTER = (
    "latency", "p99", "p50", "drift", "overhead", "compile", "err",
    "idle", "violation", "ratio", "tax", "slow_ops",
)
# lower-is-better time units, matched as exact leaf suffixes only —
# substring matching here would swallow every "*_ops_per_sec"
LOWER_IS_BETTER_SUFFIXES = ("_ms", "_us", "_s", "_sec")
# run descriptors, not performance: never graded
IGNORE = (
    "seed", "fingerprint", "osds", "pgs", "numrep", "stripes",
    "bytes", "workers", "duration", "offered", "limit", "port",
    "epoch", "records", "keys_tracked", "launches", "spans",
    "samples", "n_ops", "size", "count", "rounds", "batch",
)


def _is_lower_better(path: str) -> bool:
    leaf = path.rsplit(".", 1)[-1].lower()
    if any(tok in leaf for tok in HIGHER_IS_BETTER):
        return False
    if any(tok in leaf for tok in LOWER_IS_BETTER):
        return True
    return leaf.endswith(LOWER_IS_BETTER_SUFFIXES)


def _is_ignored(path: str) -> bool:
    leaf = path.rsplit(".", 1)[-1]
    return any(tok in leaf for tok in IGNORE)


def flatten(node, prefix="") -> dict[str, float]:
    """Numeric leaves of a nested dict, keyed by dotted path.
    Booleans pass through as 0/1 so flags like ``top1_is_culprit``
    are diffable; strings and lists are descriptive, not metrics."""
    out: dict[str, float] = {}
    if isinstance(node, dict):
        for k, v in node.items():
            p = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten(v, p))
    elif isinstance(node, bool):
        out[prefix] = 1.0 if node else 0.0
    elif isinstance(node, (int, float)):
        out[prefix] = float(node)
    return out


def compare(old: dict, new: dict,
            threshold_pct: float = 5.0) -> dict:
    """Pair numeric leaves of two ``parsed`` trees and grade each
    change.  Returns ``{rows, regressions, added, removed}`` where a
    row is ``(path, old, new, delta_pct, verdict)`` and verdict is
    one of ``ok``/``regressed``/``improved``/``flat``."""
    a, b = flatten(old), flatten(new)
    rows, regressions = [], []
    for path in sorted(set(a) & set(b)):
        if _is_ignored(path):
            continue
        va, vb = a[path], b[path]
        if va == vb:
            rows.append((path, va, vb, 0.0, "flat"))
            continue
        if va == 0.0:
            delta = float("inf") if vb > 0 else float("-inf")
        else:
            delta = 100.0 * (vb - va) / abs(va)
        worse = delta > 0 if _is_lower_better(path) else delta < 0
        if worse and abs(delta) > threshold_pct:
            verdict = "regressed"
            regressions.append(path)
        elif not worse and abs(delta) > threshold_pct:
            verdict = "improved"
        else:
            verdict = "ok"
        rows.append((path, va, vb, delta, verdict))
    return {
        "rows": rows,
        "regressions": regressions,
        "added": sorted(k for k in b if k not in a),
        "removed": sorted(k for k in a if k not in b),
    }


def latest_pair(directory: str) -> tuple[str, str]:
    """The two highest-numbered ``BENCH_r0N.json`` files."""
    pat = re.compile(r"^BENCH_r(\d+)\.json$")
    runs = sorted(
        (int(m.group(1)), os.path.join(directory, f))
        for f in os.listdir(directory)
        if (m := pat.match(f)))
    if len(runs) < 2:
        raise FileNotFoundError(
            f"need two BENCH_rNN.json files in {directory!r}, "
            f"found {len(runs)}")
    return runs[-2][1], runs[-1][1]


def _load_parsed(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    return doc.get("parsed") or doc


def _fmt(v: float) -> str:
    if v in (float("inf"), float("-inf")):
        return "inf"
    return f"{v:.4g}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_compare",
        description="diff two bench result files metric-by-metric")
    ap.add_argument("old", nargs="?", help="older BENCH_rNN.json")
    ap.add_argument("new", nargs="?", help="newer BENCH_rNN.json")
    ap.add_argument("--dir", default=None,
                    help="compare the latest two BENCH_rNN.json here")
    ap.add_argument("--threshold-pct", type=float, default=5.0,
                    help="relative change that counts as movement")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any metric regressed past the "
                         "threshold")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    try:
        if args.dir is not None:
            old_path, new_path = latest_pair(args.dir)
        elif args.old and args.new:
            old_path, new_path = args.old, args.new
        else:
            ap.error("give OLD and NEW paths, or --dir")
        rep = compare(_load_parsed(old_path), _load_parsed(new_path),
                      threshold_pct=args.threshold_pct)
    except (OSError, ValueError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "old": old_path, "new": new_path,
            "threshold_pct": args.threshold_pct,
            "regressions": rep["regressions"],
            "added": rep["added"], "removed": rep["removed"],
            "rows": [
                {"metric": p, "old": a, "new": b,
                 "delta_pct": (None if d in (float("inf"),
                                             float("-inf"))
                               else round(d, 2)),
                 "verdict": v}
                for p, a, b, d, v in rep["rows"]],
        }, indent=1, sort_keys=True))
    else:
        print(f"# {old_path} -> {new_path} "
              f"(threshold {args.threshold_pct:g}%)")
        width = max((len(p) for p, *_ in rep["rows"]), default=6)
        for path, va, vb, delta, verdict in rep["rows"]:
            if verdict == "flat":
                continue
            arrow = {"regressed": "!!", "improved": "++"}.get(
                verdict, "  ")
            print(f"{arrow} {path:<{width}}  "
                  f"{_fmt(va)} -> {_fmt(vb)}  "
                  f"({delta:+.1f}%)")
        for path in rep["removed"]:
            print(f"-- {path} (metric gone)")
        for path in rep["added"]:
            print(f"** {path} (new metric)")
        n = len(rep["regressions"])
        print(f"# {n} regression(s) past threshold")
        for path in rep["regressions"]:
            print(f"#   {path}")

    if args.check and rep["regressions"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""ceph-kvstore-tool — offline surgery on a monitor's KV store.

Reference behavior re-created (``src/tools/ceph_kvstore_tool.cc``;
SURVEY.md §3.10): open a **stopped** mon's ``MonitorDBStore`` WAL
directly and list / read / write / delete rows, or copy the whole
store to a fresh compacted file (the reference's ``store-copy``, used
to rescue a mon whose store grew torn or bloated)::

    kvstore-tool <wal> list [prefix]
    kvstore-tool <wal> get <prefix> <key> [out <file>]
    kvstore-tool <wal> set <prefix> <key> in <file>
    kvstore-tool <wal> set <prefix> <key> val <string>
    kvstore-tool <wal> rm <prefix> <key>
    kvstore-tool <wal> store-copy <dest-wal>
"""

from __future__ import annotations

import argparse
import sys

from ..mon.store import MonitorDBStore, StoreTransaction


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-kvstore-tool",
                                description=__doc__)
    p.add_argument("store")
    p.add_argument("command",
                   choices=["list", "get", "set", "rm", "store-copy"])
    p.add_argument("args", nargs="*")
    args = p.parse_args(argv)

    db = MonitorDBStore(args.store, sync=False)
    try:
        if args.command == "list":
            want = args.args[0] if args.args else None
            for prefix in sorted(db._data):
                if want is not None and prefix != want:
                    continue
                for key in db.keys(prefix):
                    print(f"{prefix}\t{key}")
            return 0
        if args.command == "get":
            if len(args.args) < 2:
                raise SystemExit("get <prefix> <key> [out <file>]")
            prefix, key = args.args[0], args.args[1]
            v = db.get(prefix, key)
            if v is None:
                print(f"({prefix}, {key}) does not exist",
                      file=sys.stderr)
                return 1
            if len(args.args) >= 4 and args.args[2] == "out":
                with open(args.args[3], "wb") as f:
                    f.write(v)
                print(f"wrote {len(v)} bytes to {args.args[3]}")
            else:
                print(v.hex())
            return 0
        if args.command == "set":
            if len(args.args) != 4 or args.args[2] not in ("in", "val"):
                raise SystemExit(
                    "set <prefix> <key> in <file> | val <string>")
            prefix, key, mode, src = args.args
            value = (open(src, "rb").read() if mode == "in"
                     else src.encode())
            t = StoreTransaction()
            t.put(prefix, key, value)
            db.apply_transaction(t)
            print(f"set ({prefix}, {key}) = {len(value)} bytes")
            return 0
        if args.command == "rm":
            if len(args.args) != 2:
                raise SystemExit("rm <prefix> <key>")
            prefix, key = args.args
            if db.get(prefix, key) is None:
                print(f"({prefix}, {key}) does not exist",
                      file=sys.stderr)
                return 1
            t = StoreTransaction()
            t.erase(prefix, key)
            db.apply_transaction(t)
            print(f"removed ({prefix}, {key})")
            return 0
        if args.command == "store-copy":
            if len(args.args) != 1:
                raise SystemExit("store-copy <dest-wal>")
            import os
            dest = args.args[0]
            if os.path.exists(dest):
                raise SystemExit(f"{dest} already exists")
            out = MonitorDBStore(dest, sync=False)
            try:
                n = 0
                for prefix in sorted(db._data):
                    t = StoreTransaction()
                    for key in db.keys(prefix):
                        t.put(prefix, key, db.get(prefix, key))
                        n += 1
                    if not t.empty():
                        out.apply_transaction(t)
                print(f"copied {n} keys to {dest}")
            finally:
                out.close()
            return 0
        raise SystemExit("nothing to do")
    finally:
        db.close()


if __name__ == "__main__":
    sys.exit(main())

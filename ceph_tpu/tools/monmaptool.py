"""monmaptool — create/inspect/edit monmap files.

Reference behavior re-created (``src/tools/monmaptool.cc``; SURVEY.md
§3.10): a monmap file names the monitor quorum (rank → address) that
every daemon and client bootstraps from.  Supported operations mirror
the reference CLI::

    monmaptool --create [--add <rank> <host:port>]... <file>
    monmaptool --add <rank> <host:port> <file>
    monmaptool --rm <rank> <file>
    monmaptool --print <file>

Edits bump the epoch, as the reference does.  The on-disk format is
the JSON of ``MonMap.to_dict()`` — the same dict the wire protocol
carries in MMonMap.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..mon.monitor import MonMap
from ..msg import EntityAddr


def load_monmap(path: str) -> MonMap:
    with open(path) as f:
        return MonMap.from_dict(json.load(f))


def save_monmap(path: str, m: MonMap):
    with open(path, "w") as f:
        json.dump(m.to_dict(), f, indent=1, sort_keys=True)
        f.write("\n")


def _parse_addr(s: str) -> EntityAddr:
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise SystemExit(f"invalid address {s!r} (want host:port)")
    return EntityAddr(host, int(port))


def format_monmap(m: MonMap) -> str:
    lines = [f"epoch {m.epoch}", f"num_mons {len(m.mons)}"]
    for r in m.ranks():
        a = m.mons[r]
        lines.append(f"mon.{r} {a.host}:{a.port}")
    return "\n".join(lines)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="monmaptool", description=__doc__)
    p.add_argument("--create", action="store_true",
                   help="create a new (empty) monmap")
    p.add_argument("--add", nargs=2, action="append", default=[],
                   metavar=("RANK", "ADDR"),
                   help="add mon RANK at host:port")
    p.add_argument("--rm", action="append", default=[], metavar="RANK",
                   help="remove mon RANK")
    p.add_argument("--print", action="store_true", dest="show",
                   help="print the monmap")
    p.add_argument("--clobber", action="store_true",
                   help="with --create, overwrite an existing file")
    p.add_argument("mapfile")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import os
    if args.create:
        if os.path.exists(args.mapfile) and not args.clobber:
            print(f"monmaptool: {args.mapfile} exists, "
                  "--clobber to overwrite", file=sys.stderr)
            return 1
        m = MonMap(epoch=0, mons={})
    else:
        try:
            m = load_monmap(args.mapfile)
        except FileNotFoundError:
            print(f"monmaptool: couldn't open {args.mapfile}",
                  file=sys.stderr)
            return 1
    changed = args.create
    for rank_s, addr_s in args.add:
        rank = int(rank_s)
        if rank in m.mons:
            print(f"monmaptool: mon.{rank} already exists",
                  file=sys.stderr)
            return 1
        m.mons[rank] = _parse_addr(addr_s)
        changed = True
    for rank_s in args.rm:
        rank = int(rank_s)
        if rank not in m.mons:
            print(f"monmaptool: mon.{rank} does not exist",
                  file=sys.stderr)
            return 1
        del m.mons[rank]
        changed = True
    if changed:
        m.epoch += 1
        save_monmap(args.mapfile, m)
        print(f"monmaptool: writing epoch {m.epoch} to "
              f"{args.mapfile} ({len(m.mons)} monitors)")
    if args.show:
        print(format_monmap(m))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Engine-selection reporting for the CRUSH CLI tools.

The batched (TPU) mapper covers the common rule shapes and falls back
to the scalar Python oracle elsewhere.  A silent fallback is a perf
trap — a user "benchmarking the TPU path" on an unsupported rule would
time pure Python (VERDICT r4 weak #5) — so every fallback announces
itself on stderr, and ``--require-batched`` turns it into a hard
error instead.
"""

from __future__ import annotations

import sys

_warned: set[str] = set()


class BatchedRequired(RuntimeError):
    """--require-batched was given and the batched mapper declined."""


def fallback(tool: str, what: str, err: Exception,
             require_batched: bool):
    """Handle a batched-mapper refusal: raise under --require-batched,
    else warn once per distinct reason (NOT once per pool/rule — a
    map with hundreds of pools sharing one unsupported shape gets one
    line, not a stderr flood)."""
    msg = (f"{tool}: {what}: batched (TPU) mapper unavailable "
           f"({err}); falling back to the scalar Python oracle")
    if require_batched:
        raise BatchedRequired(msg) from err
    key = f"{tool}\x00{type(err).__name__}\x00{err}"
    if key not in _warned:
        _warned.add(key)
        print(msg, file=sys.stderr)


def announce(tool: str, engine: str):
    """One line saying which engine actually ran."""
    print(f"{tool}: engine: {engine}", file=sys.stderr)

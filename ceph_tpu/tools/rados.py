"""rados CLI — pool/object operations and the classic RADOS benchmark.

Reference behavior re-created (``src/tools/rados/rados.cc`` + the
bench engine ``src/common/obj_bencher.cc``; SURVEY.md §3.10):

    rados -m HOST:PORT[,HOST:PORT...] lspools
    rados -m ... mkpool POOL [--size N] [--pg-num N]
    rados -m ... -p POOL put OBJ FILE | get OBJ FILE | rm OBJ
    rados -m ... -p POOL ls | stat OBJ
    rados -m ... list-inconsistent-obj PGID
    rados -m ... -p POOL bench SECONDS write|seq|rand \\
          [-b BLOCKSIZE] [-t CONCURRENCY] [--no-cleanup] [--json]

``bench write`` drives -t concurrent object writes of -b bytes for
SECONDS and prints the reference-style report (bandwidth MB/s, IOPS,
latency); ``seq``/``rand`` read the benchmark objects back.  The
summary is also emitted as one JSON line with --json so harnesses can
consume it (BASELINE.md row "RADOS MB/s & IOPS").
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ..msg import EntityAddr
from ..mon.monitor import MonMap
from ..osdc.librados import Rados

BENCH_PREFIX = "benchmark_data"


def _monmap_from_addrs(spec: str) -> MonMap:
    mons = {}
    for i, hp in enumerate(spec.split(",")):
        host, _, port = hp.strip().rpartition(":")
        mons[i] = EntityAddr(host or "127.0.0.1", int(port))
    return MonMap(mons=mons)


def _connect(args) -> Rados:
    if not args.mon:
        raise SystemExit("rados: -m HOST:PORT required")
    return Rados(_monmap_from_addrs(args.mon)).connect()


class ObjBencher:
    """The obj_bencher engine: windowed async I/O + periodic report."""

    def __init__(self, io, *, block_size: int, concurrency: int,
                 out=sys.stdout):
        self.io = io
        self.block = block_size
        self.window = concurrency
        self.out = out

    def _report_header(self, mode: str, secs: int):
        print(f"  sec Cur ops   started  finished  avg MB/s  "
              f"cur MB/s last lat(s)  avg lat(s)", file=self.out)

    def _drain(self, pending, limit):
        lat = []
        while len(pending) > limit:
            comp, t0 = pending.pop(0)
            comp.wait_for_complete(30)
            if comp.rc not in (0, None):
                raise RuntimeError(f"bench I/O failed rc={comp.rc}")
            lat.append(time.perf_counter() - t0)
        return lat

    def run(self, mode: str, seconds: int, run_id: str) -> dict:
        payload = bytes(
            (i * 131 + 17) & 0xFF for i in range(self.block))
        start = time.perf_counter()
        deadline = start + seconds
        pending: list = []
        lats: list[float] = []
        done = started = 0
        last_tick = start
        self._report_header(mode, seconds)
        objs: list[str] = []
        if mode in ("seq", "rand"):
            objs = [o for o in self.io.list_objects()
                    if o.startswith(f"{BENCH_PREFIX}_{run_id}_")]
            if not objs:
                raise SystemExit(
                    "no benchmark objects — run `bench write "
                    "--no-cleanup` first")
        i = 0
        import random
        while time.perf_counter() < deadline:
            if mode == "write":
                oid = f"{BENCH_PREFIX}_{run_id}_{i}"
                comp = self.io.aio_write_full(oid, payload)
            else:
                oid = (objs[i % len(objs)] if mode == "seq"
                       else random.choice(objs))
                comp = self.io.aio_read(oid)
            pending.append((comp, time.perf_counter()))
            started += 1
            i += 1
            got = self._drain(pending, self.window - 1)
            lats.extend(got)
            done += len(got)
            now = time.perf_counter()
            if now - last_tick >= 1.0:
                el = now - start
                mbps = done * self.block / el / 1e6
                print(f"{int(el):5d} {len(pending):7d} {started:9d} "
                      f"{done:9d} {mbps:9.2f} {mbps:9.2f} "
                      f"{lats[-1] if lats else 0:11.4f} "
                      f"{(sum(lats)/len(lats)) if lats else 0:11.4f}",
                      file=self.out)
                last_tick = now
        lats.extend(self._drain(pending, 0))
        done = started
        elapsed = time.perf_counter() - start
        total_mb = done * self.block / 1e6
        summary = {
            "mode": mode, "seconds": round(elapsed, 3),
            "ops": done, "block_bytes": self.block,
            "total_MB": round(total_mb, 3),
            "bandwidth_MBps": round(total_mb / elapsed, 3),
            "iops": round(done / elapsed, 1),
            "avg_latency_s": round(sum(lats) / len(lats), 5)
            if lats else 0.0,
            "max_latency_s": round(max(lats), 5) if lats else 0.0,
        }
        print(f"Total time run:       {summary['seconds']}\n"
              f"Total {mode}s made:    {done}\n"
              f"{mode.capitalize()} size:           {self.block}\n"
              f"Bandwidth (MB/sec):   {summary['bandwidth_MBps']}\n"
              f"Average IOPS:         {summary['iops']}\n"
              f"Average Latency(s):   {summary['avg_latency_s']}\n"
              f"Max latency(s):       {summary['max_latency_s']}",
              file=self.out)
        return summary

    def cleanup(self, run_id: str):
        for o in self.io.list_objects():
            if o.startswith(f"{BENCH_PREFIX}_{run_id}_"):
                self.io.remove(o)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rados", description=__doc__)
    p.add_argument("-m", "--mon", help="mon addrs host:port[,...]")
    p.add_argument("-p", "--pool", help="pool name")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("lspools")
    mk = sub.add_parser("mkpool")
    mk.add_argument("name")
    mk.add_argument("--size", type=int, default=3)
    mk.add_argument("--pg-num", type=int, default=8)
    rm = sub.add_parser("rmpool")
    rm.add_argument("name")
    put = sub.add_parser("put")
    put.add_argument("obj")
    put.add_argument("file")
    get = sub.add_parser("get")
    get.add_argument("obj")
    get.add_argument("file")
    rmo = sub.add_parser("rm")
    rmo.add_argument("obj")
    sub.add_parser("ls")
    st = sub.add_parser("stat")
    st.add_argument("obj")
    for name in ("listomapkeys", "listxattr"):
        x = sub.add_parser(name)
        x.add_argument("obj")
    for name in ("getomapval", "getxattr"):
        x = sub.add_parser(name)
        x.add_argument("obj")
        x.add_argument("key")
    for name in ("setomapval", "setxattr"):
        x = sub.add_parser(name)
        x.add_argument("obj")
        x.add_argument("key")
        x.add_argument("value")
    cf = sub.add_parser("cache-flush-evict-all")
    cf.add_argument("base_pool")
    li = sub.add_parser("list-inconsistent-obj")
    li.add_argument("pgid")
    be = sub.add_parser("bench")
    be.add_argument("seconds", type=int)
    be.add_argument("mode", choices=["write", "seq", "rand"])
    be.add_argument("-b", "--block-size", type=int, default=1 << 16)
    be.add_argument("-t", "--concurrency", type=int, default=16)
    be.add_argument("--run-id", default="cli")
    be.add_argument("--no-cleanup", action="store_true")
    be.add_argument("--json", action="store_true")
    return p


def _write_bytes(data: bytes):
    """Binary-safe stdout write that degrades to text when stdout has
    been swapped for a StringIO (test capture)."""
    buf = getattr(sys.stdout, "buffer", None)
    if buf is not None:
        buf.write(data)
    else:
        sys.stdout.write(data.decode(errors="replace"))


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    r = _connect(args)
    try:
        if args.cmd == "lspools":
            for name in r.list_pools():
                print(name)
            return 0
        if args.cmd == "mkpool":
            r.create_pool(args.name, pg_num=args.pg_num,
                          size=args.size)
            print(f"successfully created pool {args.name}")
            return 0
        if args.cmd == "rmpool":
            r.delete_pool(args.name)
            print(f"successfully deleted pool {args.name}")
            return 0
        if args.cmd == "cache-flush-evict-all":
            n = r.cache_flush_evict_all(args.base_pool)
            print(f"flushed and evicted {n} objects")
            return 0
        if args.cmd == "list-inconsistent-obj":
            rc, outs, outb = r.mon_command(
                {"prefix": "pg list-inconsistent-obj",
                 "pgid": args.pgid})
            if outb is not None:
                print(json.dumps(outb, indent=2, default=str))
            if outs:
                print(outs, file=sys.stderr)
            return 0 if rc == 0 else 1
        if not args.pool:
            raise SystemExit("rados: -p POOL required")
        io = r.open_ioctx(args.pool)
        if args.cmd == "put":
            with open(args.file, "rb") as f:
                io.write_full(args.obj, f.read())
        elif args.cmd == "get":
            data = io.read(args.obj)
            with open(args.file, "wb") as f:
                f.write(data)
        elif args.cmd == "rm":
            io.remove(args.obj)
        elif args.cmd == "ls":
            for o in sorted(io.list_objects()):
                print(o)
        elif args.cmd == "stat":
            st = io.stat(args.obj)
            print(f"{args.pool}/{args.obj} size {st['size']}")
        elif args.cmd == "listomapkeys":
            for k in io.omap_get_keys(args.obj):
                print(k)
        elif args.cmd == "getomapval":
            kv = io.omap_get(args.obj, keys=[args.key])
            if args.key not in kv:
                raise SystemExit(f"no omap key {args.key!r}")
            _write_bytes(bytes(kv[args.key]))
            print()
        elif args.cmd == "setomapval":
            io.omap_set(args.obj, {args.key: args.value.encode()})
        elif args.cmd == "listxattr":
            for k in sorted(io.getxattrs(args.obj)):
                print(k)
        elif args.cmd == "getxattr":
            _write_bytes(bytes(io.getxattr(args.obj, args.key)))
            print()
        elif args.cmd == "setxattr":
            io.setxattr(args.obj, args.key, args.value.encode())
        elif args.cmd == "bench":
            bench = ObjBencher(io, block_size=args.block_size,
                               concurrency=args.concurrency)
            summary = bench.run(args.mode, args.seconds, args.run_id)
            if args.mode == "write" and not args.no_cleanup:
                bench.cleanup(args.run_id)
            if args.json:
                print(json.dumps(summary))
        return 0
    finally:
        r.shutdown()


if __name__ == "__main__":
    sys.exit(main())

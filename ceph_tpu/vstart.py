"""MiniCluster — the in-process dev cluster (vstart.sh analog).

Reference behavior re-created (``src/vstart.sh`` + the
``qa/standalone/ceph-helpers.sh`` throwaway-cluster pattern; SURVEY.md
§5.3): N mons + M osds on localhost sockets, started from nothing,
with helpers to kill/revive daemons — the single-host integration
fixture every end-to-end test runs on, and the substrate for the
``rados bench`` harness.
"""

from __future__ import annotations

import queue
import socket
import time

from .mds.daemon import MDSDaemon
from .mon.monitor import MonMap, Monitor
from .msg import EntityAddr
from .osd.daemon import OSDaemon
from .osdc.librados import Rados


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class ClusterWatcher:
    """Live cluster event feed (the `ceph -w` transport): health
    transitions, clog entries and progress updates arrive in order on
    an internal queue via a mon "events" subscription."""

    def __init__(self, monmap, auth=None):
        from .mon.client import MonClient
        self._q: queue.Queue = queue.Queue()
        self.monc = MonClient(monmap, entity="client.watch", auth=auth)
        self.monc.on_event = self._on_event
        self.monc.sub_want("events", 0)
        self.seen: list[dict] = []

    def _on_event(self, kind, data, stamp):
        self._q.put({"kind": kind, "data": data or {}, "stamp": stamp})

    def next(self, timeout: float = 10.0) -> dict:
        """Block for the next event → {"kind", "data", "stamp"}."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError("no cluster event within timeout")
        self.seen.append(ev)
        return ev

    def close(self):
        self.monc.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MiniCluster:
    def __init__(self, n_mons: int = 3, n_osds: int = 3, *,
                 osd_stores=None, mon_stores=None,
                 osd_config: dict | None = None,
                 secure: bool = False):
        # option overrides applied to every OSD BEFORE construction
        # (some, e.g. osd_op_queue, are consumed in the ctor)
        self._osd_config = dict(osd_config or {})
        # secure=True: one ClusterAuth (the deployed-keyring analog)
        # shared by every daemon and client; all messengers run
        # ms_mode=secure (AES-GCM frames) — reference ProtocolV2
        # secure mode cluster-wide
        self.auth = None
        if secure:
            from .core.auth import ClusterAuth
            self.auth = ClusterAuth()
        ports = _free_ports(n_mons)
        self.monmap = MonMap(mons={r: EntityAddr("127.0.0.1", ports[r])
                                   for r in range(n_mons)})
        self.mons = [Monitor(r, self.monmap,
                             store=mon_stores[r] if mon_stores else None,
                             auth=self.auth)
                     for r in range(n_mons)]
        self._osd_stores = osd_stores
        self.osds: dict[int, OSDaemon] = {}
        self.n_osds = n_osds
        self._clients: list[Rados] = []
        self.mdss: dict[str, MDSDaemon] = {}
        self.mgrs: dict[str, object] = {}
        self._fs_clients: list = []

    # -- lifecycle ---------------------------------------------------------
    def start(self, timeout: float = 30.0) -> "MiniCluster":
        for m in self.mons:
            m.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(m.is_leader for m in self.mons):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError("no mon leader")
        for i in range(self.n_osds):
            self.start_osd(i)
        return self

    def start_osd(self, i: int, timeout: float = 30.0) -> OSDaemon:
        store = self._osd_stores[i] if self._osd_stores else None
        cfg = None
        if self._osd_config:
            from .core.config import ConfigProxy
            from .core.options import build_options
            cfg = ConfigProxy(build_options())
            for k, v in self._osd_config.items():
                cfg.set(k, v)
        osd = OSDaemon(i, self.monmap, store=store, config=cfg,
                       auth=self.auth)
        osd.start(wait_for_up=True, timeout=timeout)
        self.osds[i] = osd
        return osd

    def kill_osd(self, i: int):
        """Hard-stop an OSD (keeps its store object for a revive)."""
        osd = self.osds.pop(i)
        osd.running = False
        osd.op_queue.close()
        osd.timer.shutdown()
        osd.admin_socket.shutdown()
        osd.monc.shutdown()
        osd.msgr.shutdown()
        # deliberately NOT umounting: a revive remounts the same store
        if self._osd_stores is None:
            self._osd_stores = {}
        if not isinstance(self._osd_stores, dict):
            self._osd_stores = {j: s for j, s in
                                enumerate(self._osd_stores)}
        self._osd_stores[i] = osd.store

    def revive_osd(self, i: int, timeout: float = 30.0) -> OSDaemon:
        return self.start_osd(i, timeout=timeout)

    # -- mgr ---------------------------------------------------------------
    def start_mgr(self, name: str, **kw):
        from .mgr.daemon import MgrDaemon
        from .mgr.orchestrator import MiniClusterBackend
        kw.setdefault("auth", self.auth)
        # per-daemon admin sockets, for modules that scrape daemons
        # directly (exporter, devicehealth)
        kw.setdefault("asok_paths", {
            f"osd.{i}": osd.admin_socket.path
            for i, osd in self.osds.items()})
        mgr = MgrDaemon(name, self.monmap, **kw)
        # ONE deployment backend per cluster, shared by every mgr
        # (the cephadm-deployer analog — `ceph orch apply` lands
        # here): a per-mgr backend would leak its RGW on failover and
        # make the promoted standby double-deploy the same spec
        if getattr(self, "_orch_backend", None) is None:
            self._orch_backend = MiniClusterBackend(self)
        mgr.orch_backend = self._orch_backend
        mgr.start()
        self.mgrs[name] = mgr
        return mgr

    def kill_mgr(self, name: str):
        self.mgrs.pop(name).kill()

    def wait_for_active_mgr(self, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for name, mgr in self.mgrs.items():
                if mgr.state == "active":
                    return name
            time.sleep(0.05)
        raise TimeoutError("no active mgr")

    # -- mds / cephfs ------------------------------------------------------
    def start_mds(self, name: str, **kw) -> MDSDaemon:
        kw.setdefault("auth", self.auth)
        mds = MDSDaemon(name, self.monmap, **kw).start()
        self.mdss[name] = mds
        return mds

    def kill_mds(self, name: str):
        """Crash an MDS (no journal flush) — the failover fixture."""
        self.mdss.pop(name).kill()

    def fs_new(self, fs_name: str = "cephfs", *, pg_num: int = 8,
               size: int = 2) -> None:
        """Create the metadata/data pools and the filesystem."""
        r = self.rados()
        for pool in (f"{fs_name}_metadata", f"{fs_name}_data"):
            r.create_pool(pool, pg_num=pg_num, size=size)
        rc, outs, _ = r.mon_command({
            "prefix": "fs new", "fs_name": fs_name,
            "metadata": f"{fs_name}_metadata",
            "data": f"{fs_name}_data"})
        if rc != 0:
            raise RuntimeError(f"fs new failed: {outs}")

    def cephfs(self, fs_name: str = "cephfs", **kw):
        from .cephfs.client import CephFS
        kw.setdefault("auth", self.auth)
        fs = CephFS(self.monmap, fs_name=fs_name, **kw).mount()
        self._fs_clients.append(fs)
        return fs

    def wait_for_active_mds(self, fs_name: str = "cephfs",
                            timeout: float = 20.0) -> str:
        """→ name of the active MDS once one is promoted and serving."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for name, mds in self.mdss.items():
                if mds.state == "active":
                    return name
            time.sleep(0.05)
        raise TimeoutError("no active MDS")

    def stop(self):
        for c in self._fs_clients:
            try:
                c.unmount()
            except Exception:
                pass
        for mds in list(self.mdss.values()):
            try:
                mds.shutdown()
            except Exception:
                pass
        backend = getattr(self, "_orch_backend", None)
        if backend is not None:
            try:
                backend.shutdown()
            except Exception:
                pass
        for mgr in list(self.mgrs.values()):
            try:
                mgr.shutdown()
            except Exception:
                pass
        for c in self._clients:
            try:
                c.shutdown()
            except Exception:
                pass
        for osd in list(self.osds.values()):
            try:
                osd.shutdown()
            except Exception:
                pass
        for m in self.mons:
            try:
                m.shutdown()
            except Exception:
                pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- clients -----------------------------------------------------------
    def rados(self, name: str = "client.admin",
              config=None) -> Rados:
        """config: optional ConfigProxy carrying client knobs
        (objecter_resend_*, objecter_backoff_expire)."""
        r = Rados(self.monmap, name=name, auth=self.auth,
                  config=config).connect()
        self._clients.append(r)
        return r

    # -- fault fabric ------------------------------------------------------
    def partition_osds(self, a: int, b: int, *,
                       bidirectional: bool = True):
        """Netsplit osd.a ⇸ osd.b via their messengers' fault
        injectors.  Directed by default semantics of the injector: a's
        sends to b are blackholed; bidirectional=True (the usual
        split) also installs b ⇸ a.  Heartbeats, sub-ops and peering
        traffic all die on the partitioned edges while both daemons
        keep talking to the mons — the classic netsplit."""
        self.osds[a].msgr.faults.partition(f"osd.{b}")
        if bidirectional:
            self.osds[b].msgr.faults.partition(f"osd.{a}")

    def isolate_osd(self, i: int):
        """Partition osd.i from every OTHER osd (mon links stay up)."""
        for j, osd in self.osds.items():
            if j == i:
                continue
            self.osds[i].msgr.faults.partition(f"osd.{j}")
            osd.msgr.faults.partition(f"osd.{i}")

    def heal_netsplit(self):
        """Remove every osd→osd partition rule installed above
        (blanket probabilistic rules from ms_inject_* are kept)."""
        for i, osd in self.osds.items():
            for j in self.osds:
                if j != i:
                    osd.msgr.faults.heal(dst=f"osd.{j}")

    # -- cluster helpers ---------------------------------------------------
    def watch(self) -> ClusterWatcher:
        """Subscribe to the mon event stream (health / clog /
        progress) — the `ceph -w` feed.  Caller closes."""
        return ClusterWatcher(self.monmap, auth=self.auth)

    def wait_for_health_ok(self, timeout: float = 30.0):
        """Block until the cluster reports HEALTH_OK, driven entirely
        by the event stream — no status polling.  The subscription
        snapshot answers immediately when already healthy."""
        with self.watch() as w:
            deadline = time.monotonic() + timeout
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("cluster never reached "
                                       "HEALTH_OK")
                ev = w.next(timeout=left)
                if ev["kind"] == "health" and \
                        ev["data"].get("status") == "HEALTH_OK":
                    return

    def wait_for_clean(self, timeout: float = 30.0):
        """Wait until every PG on every live OSD is active (+clean when
        it owns recovery state)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            states = []
            for osd in self.osds.values():
                with osd.lock:
                    states.extend(pg.state for pg in osd.pgs.values()
                                  if osd.whoami == pg.primary)
            if states and all(s in ("active", "active+clean")
                              for s in states):
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster never went clean: {states}")

    def scrub_pg(self, pgid, timeout: float = 20.0, *,
                 deep: bool = True) -> int:
        """Scrub one PG on its primary; wait for completion and
        subsequent repair to settle.  Returns the error count the
        scrub found (0 = clean).  deep=False runs a shallow scrub
        (metadata only — no payload digests, no parity recheck)."""
        primary = None
        for osd in self.osds.values():
            with osd.lock:
                pg = osd.pgs.get(pgid)
                if pg is not None and pg.is_primary:
                    primary = osd
                    break
        if primary is None:
            raise KeyError(f"no primary for {pgid}")
        deadline = time.monotonic() + timeout
        while not primary.scrub_pg(pgid, deep=deep):
            # refused while writes are in flight — retry
            if time.monotonic() > deadline:
                raise TimeoutError(f"scrub of {pgid} never started")
            time.sleep(0.05)
        while time.monotonic() < deadline:
            with primary.lock:
                pg = primary.pgs[pgid]
                if not pg.scrubbing:
                    return pg.scrub_errors
            time.sleep(0.05)
        raise TimeoutError(f"scrub of {pgid} never finished")

    # -- tracing -----------------------------------------------------------
    def collect_trace(self, trace_id: str) -> list[dict]:
        """Merge one trace's spans from every daemon and client ring,
        ordered by start time (all daemons share this process, so the
        monotonic starts are directly comparable).  Feed the result to
        ``core.tracer.chrome_trace`` for a chrome://tracing export."""
        spans: list[dict] = []
        for osd in self.osds.values():
            spans.extend(osd.tracer.spans_for(trace_id))
        for r in self._clients:
            if r.objecter is not None:
                spans.extend(r.objecter.tracer.spans_for(trace_id))
        spans.sort(key=lambda s: s["start"])
        return spans

    def export_chrome_trace(self, trace_id: str) -> dict:
        """chrome://tracing JSON for one trace."""
        from .core.tracer import chrome_trace
        return chrome_trace(self.collect_trace(trace_id))

    def wait_for_osd_down(self, i: int, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for osd in self.osds.values():
                with osd.lock:
                    if osd.osdmap.max_osd > i and \
                            not osd.osdmap.is_up(i):
                        return
            time.sleep(0.05)
        raise TimeoutError(f"osd.{i} never marked down")

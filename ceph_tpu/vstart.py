"""MiniCluster — the in-process dev cluster (vstart.sh analog).

Reference behavior re-created (``src/vstart.sh`` + the
``qa/standalone/ceph-helpers.sh`` throwaway-cluster pattern; SURVEY.md
§5.3): N mons + M osds on localhost sockets, started from nothing,
with helpers to kill/revive daemons — the single-host integration
fixture every end-to-end test runs on, and the substrate for the
``rados bench`` harness.
"""

from __future__ import annotations

import os
import queue
import random
import shutil
import socket
import tempfile
import time

from .mds.daemon import MDSDaemon
from .mon.monitor import MonMap, Monitor
from .msg import EntityAddr
from .msg.fault import site_pairs
from .os_store import CrashInjector, WALStore
from .osd.daemon import OSDaemon
from .osdc.librados import Rados
from .procs import DaemonSpec, ProcSpawnError, spawn_daemon


def health_event(code: str, state: str):
    """Predicate factory for ``game_day`` phases / watcher loops:
    matches the health event where `code` transitions to `state`
    ("failed" / "cleared"), or — for state "rollup:HEALTH_OK" style —
    a rollup event reaching that status.

    Catch-up snapshots also satisfy the predicate when they already
    show the target state: a watcher whose session mon died mid-drill
    re-hunts and re-subscribes, and the transition it was blocking on
    may only be visible as the fresh snapshot's contents."""
    if state.startswith("rollup:"):
        want = state.split(":", 1)[1]

        def _rollup(ev):
            d = ev["data"]
            return (ev["kind"] == "health"
                    and d.get("state") in ("rollup", "snapshot")
                    and d.get("status") == want)
        return _rollup

    def _pred(ev):
        if ev["kind"] != "health":
            return False
        d = ev["data"]
        if d.get("state") == "snapshot":
            present = code in (d.get("checks") or [])
            return present if state == "failed" else \
                (not present if state == "cleared" else False)
        return d.get("code") == code and d.get("state") == state
    return _pred


def _free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class ClusterWatcher:
    """Live cluster event feed (the `ceph -w` transport): health
    transitions, clog entries and progress updates arrive in order on
    an internal queue via a mon "events" subscription."""

    def __init__(self, monmap, auth=None):
        from .mon.client import MonClient
        self._q: queue.Queue = queue.Queue()
        self.monc = MonClient(monmap, entity="client.watch", auth=auth)
        self.monc.on_event = self._on_event
        self.monc.sub_want("events", 0)
        self.seen: list[dict] = []

    def _on_event(self, kind, data, stamp):
        self._q.put({"kind": kind, "data": data or {}, "stamp": stamp})

    def next(self, timeout: float = 10.0) -> dict:
        """Block for the next event → {"kind", "data", "stamp"}."""
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError("no cluster event within timeout")
        self.seen.append(ev)
        return ev

    def close(self):
        self.monc.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MiniCluster:
    def __init__(self, n_mons: int = 3, n_osds: int = 3, *,
                 osd_stores=None, mon_stores=None,
                 osd_config: dict | None = None,
                 secure: bool = False,
                 stretch_sites: dict[str, list[int]] | None = None,
                 mon_sites: dict[int, str] | None = None,
                 tiebreaker_mon: int = -1,
                 fault_seed: int | None = None,
                 procs: bool = False,
                 crash_probs: dict[str, float] | None = None):
        # option overrides applied to every OSD BEFORE construction
        # (some, e.g. osd_op_queue, are consumed in the ctor)
        self._osd_config = dict(osd_config or {})
        # procs=True: every daemon is its own OS process, spawned from
        # a serializable boot spec and joined over the (already-TCP)
        # messenger.  Threaded mode stays the fast tier-1 default.
        self.procs = bool(procs)
        # per-point crash probabilities applied to every OSD's
        # CrashInjector (threaded AND procs — the seed makes the
        # schedule identical either way)
        self.crash_probs = {k: float(v)
                            for k, v in (crash_probs or {}).items()}
        if self.procs:
            if secure:
                raise ValueError("procs=True does not support secure "
                                 "mode (no keyring distribution yet)")
            if stretch_sites:
                raise ValueError("procs=True does not support stretch"
                                 " sites (fault fabric is in-process)")
            if osd_stores is not None or mon_stores is not None:
                raise ValueError("procs=True boots daemons from "
                                 "serializable specs; live store "
                                 "objects cannot cross a process "
                                 "boundary")
        # secure=True: one ClusterAuth (the deployed-keyring analog)
        # shared by every daemon and client; all messengers run
        # ms_mode=secure (AES-GCM frames) — reference ProtocolV2
        # secure mode cluster-wide
        self.auth = None
        if secure:
            from .core.auth import ClusterAuth
            self.auth = ClusterAuth()
        # stretch topology: OSD site membership drives the CRUSH
        # hierarchy (enable_stretch_mode) and the site fault fabric;
        # mons are spread round-robin across the sites with the last
        # rank as tiebreaker unless the caller places them explicitly
        self.stretch_sites = {s: sorted(o) for s, o
                              in (stretch_sites or {}).items()}
        if self.stretch_sites and mon_sites is None:
            names = sorted(self.stretch_sites)
            if tiebreaker_mon < 0:
                tiebreaker_mon = n_mons - 1
            mon_sites = {}
            k = 0
            for r in range(n_mons):
                if r == tiebreaker_mon:
                    mon_sites[r] = "tiebreaker"
                else:
                    mon_sites[r] = names[k % len(names)]
                    k += 1
        self.fault_seed = fault_seed
        ports = _free_ports(n_mons)
        self.monmap = MonMap(mons={r: EntityAddr("127.0.0.1", ports[r])
                                   for r in range(n_mons)},
                             sites=dict(mon_sites or {}),
                             tiebreaker=tiebreaker_mon)
        self.mons = [] if self.procs else \
            [Monitor(r, self.monmap,
                     store=mon_stores[r] if mon_stores else None,
                     auth=self.auth)
             for r in range(n_mons)]
        self._osd_stores = osd_stores
        # durable backing (osd_objectstore=walstore, the default):
        # per-OSD WAL files in a throwaway dir, paths remembered so a
        # power-lossed OSD cold-remounts the SAME log on revive
        self._wal_dir: str | None = None
        self._wal_paths: dict[int, str] = {}
        self.osds: dict[int, OSDaemon] = {}
        self.n_osds = n_osds
        self._clients: list[Rados] = []
        self.mdss: dict[str, MDSDaemon] = {}
        self.mgrs: dict[str, object] = {}
        self._fs_clients: list = []
        self._rgws: list = []
        # (injector, src, dst) triples the site primitives installed,
        # so heal_sites removes exactly what it added
        self._site_rules: list[tuple] = []
        # procs-mode state: process handles, pre-assigned admin
        # sockets (Unix sockets cross the process boundary), sticky
        # spawn failures (the OSD_STORE_ERROR degradation pattern: an
        # entity that exhausted its spawn retries stays failed instead
        # of flapping), and a cached admin rados client
        self._run_dir: str | None = None
        self._mon_handles: dict[int, object] = {}
        self._osd_handles: dict[int, object] = {}
        self._mgr_handles: dict[str, object] = {}
        self._mon_asoks: dict[int, str] = {}
        self._osd_asoks: dict[int, str] = {}
        self._mgr_asoks: dict[str, str] = {}
        self.spawn_failures: dict[str, str] = {}
        self._admin: Rados | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self, timeout: float = 30.0) -> "MiniCluster":
        if self.procs:
            return self._start_procs(timeout=timeout)
        if self.fault_seed is not None:
            # one logged seed reseeds every daemon injector: verdicts
            # are pure functions of (seed, src, dst, n), so a whole
            # site event replays from this number alone
            for m in self.mons:
                m.msgr.faults.seed = int(self.fault_seed)
                m.msgr.faults.rng = random.Random(int(self.fault_seed))
        for m in self.mons:
            m.start()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(m.is_leader for m in self.mons):
                break
            time.sleep(0.02)
        else:
            raise TimeoutError("no mon leader")
        for i in range(self.n_osds):
            self.start_osd(i)
        return self

    # -- procs runtime -----------------------------------------------------
    def _procs_run_dir(self) -> str:
        if self._run_dir is None:
            self._run_dir = tempfile.mkdtemp(prefix="ceph-tpu-procs-")
        return self._run_dir

    def _start_procs(self, timeout: float) -> "MiniCluster":
        """Boot every daemon as its own OS process from a boot spec;
        quorum is observed from outside via the mons' admin sockets."""
        from .core.admin_socket import admin_command
        for r in self.monmap.ranks():
            asok = os.path.join(self._procs_run_dir(),
                                f"mon.{r}.asok")
            self._mon_asoks[r] = asok
            spec = DaemonSpec(kind="mon", ident=str(r),
                              monmap=self.monmap.to_dict(),
                              fault_seed=self.fault_seed,
                              asok_path=asok)
            self._mon_handles[r] = spawn_daemon(
                spec, timeout=timeout,
                run_dir=self._procs_run_dir())
        deadline = time.monotonic() + timeout
        while True:
            leader = None
            for asok in self._mon_asoks.values():
                try:
                    st = admin_command(asok, "quorum_status",
                                       timeout=2.0)
                except OSError:
                    continue
                if st.get("state") == "leader":
                    leader = st
                    break
            if leader is not None:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("no mon leader (procs)")
            time.sleep(0.05)
        for i in range(self.n_osds):
            self.start_osd(i)
        return self

    def _start_osd_proc(self, i: int, timeout: float):
        ent = f"osd.{i}"
        if ent in self.spawn_failures:
            # sticky failure: exhausting the retry budget degrades the
            # entity (as OSD_STORE_ERROR degrades a store) — it stays
            # down until the operator clears spawn_failures
            raise ProcSpawnError(
                f"{ent}: sticky spawn failure: "
                f"{self.spawn_failures[ent]}")
        stale = self._osd_handles.pop(i, None)
        if stale is not None:
            stale.kill9()   # reap a dead prior incarnation's zombie
        asok = self._osd_asoks.setdefault(
            i, os.path.join(self._procs_run_dir(), f"osd.{i}.asok"))
        extra = {"boot_timeout": timeout}
        if self.crash_probs:
            extra["crash_probs"] = dict(self.crash_probs)
        spec = DaemonSpec(kind="osd", ident=str(i),
                          monmap=self.monmap.to_dict(),
                          wal_path=self._wal_path(i),
                          osd_config=dict(self._osd_config),
                          fault_seed=self.fault_seed,
                          asok_path=asok, extra=extra)
        try:
            h = spawn_daemon(spec, timeout=timeout + 10.0,
                             run_dir=self._procs_run_dir())
        except ProcSpawnError as e:
            self.spawn_failures[ent] = str(e)
            raise
        self._osd_handles[i] = h
        return h

    def _admin_rados(self) -> Rados:
        """Cached mon-command client (procs-mode introspection runs
        entirely over the wire, like a real operator's `ceph` CLI)."""
        if self._admin is None:
            self._admin = self.rados(name="client.vstart-admin")
        return self._admin

    def _mon_cmd(self, cmd: dict):
        rc, outs, out = self._admin_rados().mon_command(cmd)
        if rc != 0:
            raise RuntimeError(
                f"mon command {cmd.get('prefix')!r} failed "
                f"rc={rc}: {outs}")
        return out

    def _osdmap_from_mon(self):
        from .tools.osdmaptool import osdmap_from_dict
        return osdmap_from_dict(self._mon_cmd({"prefix": "osd dump"}))

    def _pg_dump(self) -> dict:
        return self._mon_cmd({"prefix": "pg dump"}) or {}

    def osd_replay_stats(self, i: int) -> dict:
        """The WAL cold-remount damage report of a (revived) OSD —
        threaded reads the store, procs asks the daemon's asok."""
        if self.procs:
            from .core.admin_socket import admin_command
            out = admin_command(self._osd_asoks[i],
                                "dump_replay_stats")
            return dict(out.get("replay_stats") or {})
        return dict(getattr(self.osds[i].store, "replay_stats",
                            None) or {})

    def _mgr_cmd(self, cmd: dict):
        """Active-mgr command over the wire (both modes — threaded
        mgrs serve the same messenger a procs-mode parent talks to)."""
        rc, outs, out = self._admin_rados().mgr_command(cmd)
        if rc != 0:
            raise RuntimeError(
                f"mgr command {cmd.get('prefix')!r} failed "
                f"rc={rc}: {outs}")
        return out

    def profiler_dump(self, i: int) -> dict:
        """One OSD's device-profiler dump — same asok command in both
        modes; threaded just short-circuits the socket."""
        if self.procs:
            from .core.admin_socket import admin_command
            return admin_command(self._osd_asoks[i], "profiler dump")
        d = self.osds[i].profiler.dump()
        d["clock"] = {"wall": time.time(), "mono": time.monotonic()}
        return d

    def telemetry_series(self, daemon: str | None = None) -> dict:
        """TelemetrySpine ring dump via the active mgr's command
        server (`ceph telemetry series`) — identical over threaded
        and procs clusters."""
        cmd: dict = {"prefix": "telemetry series"}
        if daemon is not None:
            cmd["daemon"] = daemon
        return self._mgr_cmd(cmd) or {}

    def prometheus_port(self) -> int | None:
        """TCP port of the active mgr's /metrics exporter (procs
        parents discover it through the mgr asok)."""
        if self.procs:
            from .core.admin_socket import admin_command
            for name, asok in self._mgr_asoks.items():
                if name not in self._mgr_handles:
                    continue
                try:
                    st = admin_command(asok, "status", timeout=2.0)
                except OSError:
                    continue
                if st.get("state") == "active" \
                        and st.get("prometheus_port"):
                    return int(st["prometheus_port"])
            return None
        for mgr in self.mgrs.values():
            if mgr.state == "active":
                mod = mgr.modules.get("prometheus")
                if mod is not None:
                    return mod.port
        return None

    def blackbox_path(self, i: int) -> str:
        """Flight-recorder sidecar path for one OSD — readable
        offline (tools/blackbox_tool.py) even while the daemon is a
        corpse, since WAL paths persist across crash/revive."""
        return self._wal_path(i) + ".bbox"

    def pg_primary(self, pgid) -> int:
        """Acting-primary OSD id for one PG (procs: authoritative map
        via `osd dump`; threaded: the live daemons)."""
        from .osd.osdmap import PGid
        if isinstance(pgid, str):
            pgid = PGid.parse(pgid)
        if self.procs:
            return self._osdmap_from_mon(
                ).pg_to_up_acting_osds(pgid)[3]
        for osd in self.osds.values():
            with osd.lock:
                pg = osd.pgs.get(pgid)
                if pg is not None and pg.is_primary:
                    return osd.whoami
        raise KeyError(f"no live primary for {pgid}")

    def _wal_path(self, i: int) -> str:
        p = self._wal_paths.get(i)
        if p is None:
            if self._wal_dir is None:
                # Prefer tmpfs for the throwaway default WAL dir:
                # power loss here is simulated by truncation, so the
                # semantics are identical, but group-commit fsyncs
                # don't pay the ext4 journal (~2ms each).
                base = "/dev/shm" if os.path.isdir("/dev/shm") else None
                self._wal_dir = tempfile.mkdtemp(
                    prefix="ceph-tpu-wal-", dir=base)
            p = os.path.join(self._wal_dir, f"osd.{i}.wal")
            self._wal_paths[i] = p
        return p

    def _default_store(self, i: int):
        """Fresh store for an OSD with no saved object: a WALStore on
        the OSD's WAL path (so a cold restart replays whatever an
        earlier incarnation committed) unless osd_objectstore asks for
        RAM only.  Every WALStore carries a CrashInjector seeded from
        the cluster fault seed — same seed, same crash schedule."""
        if self._osd_config.get("osd_objectstore",
                                "walstore") != "walstore":
            return None     # OSDaemon defaults to MemStore
        inj = CrashInjector(seed=int(self.fault_seed or 0),
                            osd=f"osd.{i}")
        for point, prob in (self.crash_probs or {}).items():
            inj.set_prob(point, prob)
        return WALStore(
            self._wal_path(i),
            sync_mode=self._osd_config.get("osd_wal_sync_mode",
                                           "batch"),
            name=f"osd.{i}",
            crash=inj,
            compact_min_records=int(self._osd_config.get(
                "osd_wal_compact_min_records", 0)))

    def start_osd(self, i: int, timeout: float = 30.0):
        if self.procs:
            return self._start_osd_proc(i, timeout=timeout)
        store = None
        if self._osd_stores:
            store = (self._osd_stores.get(i)
                     if isinstance(self._osd_stores, dict)
                     else self._osd_stores[i])
        if store is None:
            store = self._default_store(i)
        cfg = None
        if self._osd_config:
            from .core.config import ConfigProxy
            from .core.options import build_options
            cfg = ConfigProxy(build_options())
            for k, v in self._osd_config.items():
                cfg.set(k, v)
        osd = OSDaemon(i, self.monmap, store=store, config=cfg,
                       auth=self.auth)
        if self.fault_seed is not None:
            osd.msgr.faults.seed = int(self.fault_seed)
            osd.msgr.faults.rng = random.Random(int(self.fault_seed))
        osd.start(wait_for_up=True, timeout=timeout)
        self.osds[i] = osd
        return osd

    def kill_osd(self, i: int):
        """Hard-stop an OSD (keeps its store object for a revive)."""
        if self.procs:
            self._osd_handles.pop(i).stop()
            return
        osd = self.osds.pop(i)
        osd.running = False
        osd.op_queue.close()
        osd.timer.shutdown()
        osd.admin_socket.shutdown()
        osd.monc.shutdown()
        osd.msgr.shutdown()
        # a kill is the harness's controlled hard-stop, not a crash
        # drill (that's crash_osd) — close the black box cleanly so
        # the revive doesn't synthesize a crash report and trip
        # RECENT_CRASH
        if osd.flight_recorder is not None:
            try:
                osd.flight_recorder.close()
            except Exception:   # noqa: BLE001 — recorder never
                pass            # blocks a kill
        # deliberately NOT umounting: a revive remounts the same store
        if self._osd_stores is None:
            self._osd_stores = {}
        if not isinstance(self._osd_stores, dict):
            self._osd_stores = {j: s for j, s in
                                enumerate(self._osd_stores)}
        self._osd_stores[i] = osd.store

    def revive_osd(self, i: int, timeout: float = 30.0) -> OSDaemon:
        return self.start_osd(i, timeout=timeout)

    def crash_osd(self, i: int, hard: bool = False):
        """Crash one OSD so ``revive_osd`` must cold-remount from the
        WAL path alone (``kill_osd`` deliberately keeps the store).

        ``hard=False`` is a power cut: stable storage keeps only the
        fsynced WAL prefix (plus any torn fragment an injected crash
        left).  ``hard=True`` is process death (``kill -9``): the OS
        survives, so the page cache — every appended record, fsynced
        or not — is still there on remount; only in-memory daemon
        state is lost.  In procs mode every crash IS process death
        (SIGKILL to a real pid), so ``hard`` is implied: the parent
        cannot reach into the child to truncate an unsynced suffix,
        which is why fsynced-prefix power-cut drills stay
        threaded-only."""
        if self.procs:
            self._osd_handles.pop(i).kill9()
            return
        osd = self.osds.pop(i)
        osd.running = False
        osd.op_queue.close()
        osd.timer.shutdown()
        osd.admin_socket.shutdown()
        osd.monc.shutdown()
        osd.msgr.shutdown()
        store = osd.store
        path = getattr(store, "_path", None)
        if path is not None:
            self._wal_paths[i] = path
        pl = getattr(store,
                     "process_death" if hard else "power_loss", None)
        if pl is not None:
            pl()
        else:
            try:
                store.umount()      # RAM store: everything is lost
            except Exception:
                pass
        if isinstance(self._osd_stores, dict):
            self._osd_stores.pop(i, None)
        elif self._osd_stores is not None:
            self._osd_stores = {j: s for j, s in
                                enumerate(self._osd_stores) if j != i}

    def power_loss(self, revive: bool = True,
                   timeout: float = 60.0) -> dict:
        """Whole-cluster power-loss drill: cut power to every running
        OSD at once, then (by default) cold-restart each from its WAL
        path.  → {osd: replay_stats} for the revived OSDs.  Routed
        through crash_osd/revive_osd, so in procs mode each OSD's
        process is SIGKILLed and the revive cold-remounts the same
        WAL in a fresh process."""
        crashed = sorted(self._osd_handles if self.procs
                         else self.osds)
        for i in crashed:
            self.crash_osd(i)
        report: dict[int, dict] = {}
        if revive:
            for i in crashed:
                self.revive_osd(i, timeout=timeout)
                report[i] = self.osd_replay_stats(i)
        return report

    # -- mgr ---------------------------------------------------------------
    def start_mgr(self, name: str, **kw):
        if self.procs:
            return self._start_mgr_proc(name, **kw)
        from .mgr.daemon import MgrDaemon
        from .mgr.orchestrator import MiniClusterBackend
        kw.setdefault("auth", self.auth)
        # per-daemon admin sockets, for modules that scrape daemons
        # directly (exporter, devicehealth)
        kw.setdefault("asok_paths", {
            f"osd.{i}": osd.admin_socket.path
            for i, osd in self.osds.items()})
        mgr = MgrDaemon(name, self.monmap, **kw)
        # ONE deployment backend per cluster, shared by every mgr
        # (the cephadm-deployer analog — `ceph orch apply` lands
        # here): a per-mgr backend would leak its RGW on failover and
        # make the promoted standby double-deploy the same spec
        if getattr(self, "_orch_backend", None) is None:
            self._orch_backend = MiniClusterBackend(self)
        mgr.orch_backend = self._orch_backend
        mgr.start()
        self.mgrs[name] = mgr
        return mgr

    def _start_mgr_proc(self, name: str, **kw):
        modules = kw.pop("modules", None)
        if kw:
            raise ValueError(
                f"procs=True start_mgr supports only modules=, "
                f"got {sorted(kw)}")
        asok = self._mgr_asoks.setdefault(
            name, os.path.join(self._procs_run_dir(),
                               f"mgr.{name}.asok"))
        extra: dict = {"asok_paths": {f"osd.{i}": p for i, p
                                      in self._osd_asoks.items()}}
        if modules is not None:
            extra["modules"] = [f"{m.__module__}:{m.__name__}"
                                for m in modules]
        spec = DaemonSpec(kind="mgr", ident=name,
                          monmap=self.monmap.to_dict(),
                          fault_seed=self.fault_seed,
                          asok_path=asok, extra=extra)
        h = spawn_daemon(spec, run_dir=self._procs_run_dir())
        self._mgr_handles[name] = h
        return h

    def kill_mgr(self, name: str):
        if self.procs:
            self._mgr_handles.pop(name).kill9()
            return
        self.mgrs.pop(name).kill()

    def wait_for_active_mgr(self, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        if self.procs:
            from .core.admin_socket import admin_command
            while time.monotonic() < deadline:
                for name, asok in self._mgr_asoks.items():
                    if name not in self._mgr_handles:
                        continue
                    try:
                        st = admin_command(asok, "status",
                                           timeout=2.0)
                    except OSError:
                        continue
                    if st.get("state") == "active":
                        return name
                time.sleep(0.05)
            raise TimeoutError("no active mgr (procs)")
        while time.monotonic() < deadline:
            for name, mgr in self.mgrs.items():
                if mgr.state == "active":
                    return name
            time.sleep(0.05)
        raise TimeoutError("no active mgr")

    # -- mds / cephfs ------------------------------------------------------
    def start_mds(self, name: str, **kw) -> MDSDaemon:
        kw.setdefault("auth", self.auth)
        mds = MDSDaemon(name, self.monmap, **kw).start()
        self.mdss[name] = mds
        return mds

    def kill_mds(self, name: str):
        """Crash an MDS (no journal flush) — the failover fixture."""
        self.mdss.pop(name).kill()

    def fs_new(self, fs_name: str = "cephfs", *, pg_num: int = 8,
               size: int = 2) -> None:
        """Create the metadata/data pools and the filesystem."""
        r = self.rados()
        for pool in (f"{fs_name}_metadata", f"{fs_name}_data"):
            r.create_pool(pool, pg_num=pg_num, size=size)
        rc, outs, _ = r.mon_command({
            "prefix": "fs new", "fs_name": fs_name,
            "metadata": f"{fs_name}_metadata",
            "data": f"{fs_name}_data"})
        if rc != 0:
            raise RuntimeError(f"fs new failed: {outs}")

    def cephfs(self, fs_name: str = "cephfs", **kw):
        from .cephfs.client import CephFS
        kw.setdefault("auth", self.auth)
        fs = CephFS(self.monmap, fs_name=fs_name, **kw).mount()
        self._fs_clients.append(fs)
        return fs

    def wait_for_active_mds(self, fs_name: str = "cephfs",
                            timeout: float = 20.0) -> str:
        """→ name of the active MDS once one is promoted and serving."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for name, mds in self.mdss.items():
                if mds.state == "active":
                    return name
            time.sleep(0.05)
        raise TimeoutError("no active MDS")

    def dedup_leak_check(self) -> list[str]:
        """Refcount balance audit over every live OSD store: each
        fingerprint's refcount must equal its live manifest references
        and zero-ref chunks must be gone (deletes balance to zero).
        Engages only on stores that ever ingested a chunk."""
        if self.procs:
            return []   # stores live in child processes
        from .compress import dedup as dd
        problems = []
        for i, osd in sorted(self.osds.items()):
            store = osd.store
            try:
                if dd.DEDUP_COLL not in store.list_collections():
                    continue
            except Exception:
                continue
            problems += [f"osd.{i}: {p}"
                         for p in dd.verify_refcounts(store)]
        return problems

    def stop(self):
        if self.procs:
            self._stop_procs()
            return
        try:
            dedup_problems = self.dedup_leak_check()
        except Exception:
            dedup_problems = []
        for gw in self._rgws:
            try:
                gw.shutdown()
            except Exception:
                pass
        for c in self._fs_clients:
            try:
                c.unmount()
            except Exception:
                pass
        for mds in list(self.mdss.values()):
            try:
                mds.shutdown()
            except Exception:
                pass
        backend = getattr(self, "_orch_backend", None)
        if backend is not None:
            try:
                backend.shutdown()
            except Exception:
                pass
        for mgr in list(self.mgrs.values()):
            try:
                mgr.shutdown()
            except Exception:
                pass
        for c in self._clients:
            try:
                c.shutdown()
            except Exception:
                pass
        for osd in list(self.osds.values()):
            try:
                osd.shutdown()
            except Exception:
                pass
        for m in self.mons:
            try:
                m.shutdown()
            except Exception:
                pass
        if self._wal_dir is not None:
            shutil.rmtree(self._wal_dir, ignore_errors=True)
            self._wal_dir = None
        if dedup_problems:
            raise AssertionError("dedup refcount leak at teardown: "
                                 + "; ".join(dedup_problems))

    def _stop_procs(self):
        for c in self._clients:
            try:
                c.shutdown()
            except Exception:
                pass
        self._admin = None
        # mgrs before osds before mons — daemons deregister downward
        for handles in (self._mgr_handles, self._osd_handles,
                        self._mon_handles):
            for h in list(handles.values()):
                try:
                    h.stop()
                except Exception:
                    pass
            handles.clear()
        if self._run_dir is not None:
            shutil.rmtree(self._run_dir, ignore_errors=True)
            self._run_dir = None
        if self._wal_dir is not None:
            shutil.rmtree(self._wal_dir, ignore_errors=True)
            self._wal_dir = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- clients -----------------------------------------------------------
    def rados(self, name: str = "client.admin",
              config=None) -> Rados:
        """config: optional ConfigProxy carrying client knobs
        (objecter_resend_*, objecter_backoff_expire)."""
        r = Rados(self.monmap, name=name, auth=self.auth,
                  config=config).connect()
        self._clients.append(r)
        return r

    def start_rgw(self, rados=None, **kw):
        """Start an RGW gateway against this cluster (tracked: stop()
        shuts it down).  kwargs pass through to `RGWService`
        (pool_size, max_concurrent, stripe_size, data_pool_opts,
        require_auth, ...)."""
        from .rgw import RGWService
        gw = RGWService(rados if rados is not None else self.rados(),
                        **kw).start()
        self._rgws.append(gw)
        return gw

    # -- fault fabric ------------------------------------------------------
    def partition_osds(self, a: int, b: int, *,
                       bidirectional: bool = True):
        """Netsplit osd.a ⇸ osd.b via their messengers' fault
        injectors.  Directed by default semantics of the injector: a's
        sends to b are blackholed; bidirectional=True (the usual
        split) also installs b ⇸ a.  Heartbeats, sub-ops and peering
        traffic all die on the partitioned edges while both daemons
        keep talking to the mons — the classic netsplit."""
        self.osds[a].msgr.faults.partition(f"osd.{b}")
        if bidirectional:
            self.osds[b].msgr.faults.partition(f"osd.{a}")

    def isolate_osd(self, i: int):
        """Partition osd.i from every OTHER osd (mon links stay up)."""
        for j, osd in self.osds.items():
            if j == i:
                continue
            self.osds[i].msgr.faults.partition(f"osd.{j}")
            osd.msgr.faults.partition(f"osd.{i}")

    def heal_netsplit(self):
        """Remove every osd→osd partition rule installed above
        (blanket probabilistic rules from ms_inject_* are kept)."""
        for i, osd in self.osds.items():
            for j in self.osds:
                if j != i:
                    osd.msgr.faults.heal(dst=f"osd.{j}")

    # -- stretch / site fault fabric ---------------------------------------
    def site_daemons(self, site: str) -> list[str]:
        """Entity names of every daemon placed in `site`: its mons
        (monmap placement) and its OSDs (stretch_sites)."""
        ents = [f"mon.{r}" for r, s in sorted(self.monmap.sites.items())
                if s == site]
        ents += [f"osd.{o}"
                 for o in self.stretch_sites.get(site, [])]
        return ents

    def _entity_injectors(self) -> dict:
        """entity name → that live daemon's FaultInjector."""
        inj = {m.name: m.msgr.faults for m in self.mons}
        inj.update({f"osd.{i}": osd.msgr.faults
                    for i, osd in self.osds.items()})
        return inj

    def enable_stretch_mode(self, rados=None) -> dict:
        """Switch the cluster to stretch mode: two-datacenter CRUSH
        map, stretch rule, every replicated pool size=4/min_size=2.
        Requires the cluster to have been built with
        ``stretch_sites`` (and, for tiebreaker quorum semantics, an
        odd mon count with the tiebreaker rank)."""
        if len(self.stretch_sites) != 2:
            raise ValueError("stretch mode needs exactly 2 sites")
        r = rados or self.rados()
        tb = (f"mon.{self.monmap.tiebreaker}"
              if self.monmap.tiebreaker >= 0 else "")
        rc, outs, out = r.mon_command({
            "prefix": "osd enable-stretch-mode",
            "sites": {s: list(o)
                      for s, o in self.stretch_sites.items()},
            "tiebreaker": tb})
        if rc != 0:
            raise RuntimeError(f"enable-stretch-mode failed: {outs}")
        return out or {}

    def _install(self, inj_map, src: str, dst: str, **kw):
        inj = inj_map.get(src)
        if inj is None:
            return      # daemon currently dead: nothing to install on
        if kw:
            inj.set_rule(src, dst, **kw)
        else:
            inj.partition(dst, src=src)
        self._site_rules.append((inj, src, dst))

    def partition_sites(self, a: str, b: str):
        """Cut every inter-site daemon link between sites `a` and `b`
        (both directions) — the WAN-cut drill.  Intra-site traffic and
        links to daemons outside either site (e.g. the tiebreaker mon)
        keep flowing, which is exactly what lets the surviving side
        keep quorum."""
        inj = self._entity_injectors()
        for s, d in site_pairs(self.site_daemons(a),
                               self.site_daemons(b)):
            self._install(inj, s, d)

    def blackout_site(self, site: str):
        """Whole-site power loss without killing the processes: the
        site's daemons stop talking to ANYONE (clients included) and
        everyone stops reaching them.  Survivors' failure reports mark
        the site's OSDs down; its mons drop out of quorum."""
        inj = self._entity_injectors()
        dead = self.site_daemons(site)
        for d_ent in dead:
            # outbound blanket cut — replies to clients die too
            self._install(inj, d_ent, "*")
        for s_ent in inj:
            if s_ent in dead:
                continue
            for d_ent in dead:
                self._install(inj, s_ent, d_ent)

    def slow_wan(self, a: str, b: str, *, delay: float = 0.5,
                 delay_ms: float = 80.0, reorder: float = 0.0,
                 reorder_ms: float = 120.0, drop: float = 0.0):
        """Degrade (not cut) the inter-site link: delay/reorder/drop
        probabilities applied ONLY to inter-site pairs, in both
        directions.  Intra-site latency is untouched."""
        inj = self._entity_injectors()
        for s, d in site_pairs(self.site_daemons(a),
                               self.site_daemons(b)):
            self._install(inj, s, d, delay=delay, delay_ms=delay_ms,
                          reorder=reorder, reorder_ms=reorder_ms,
                          drop=drop)

    def heal_sites(self):
        """Remove exactly the rules the site primitives installed."""
        for inj, src, dst in self._site_rules:
            inj.heal(src=src, dst=dst)
        self._site_rules.clear()

    def preview_site_schedule(self, a: str, b: str,
                              count: int = 32) -> dict[str, list]:
        """The deterministic fault schedule every inter-site pair
        would see for its next `count` messages — pure (no counter
        advance).  Equal seeds + equal rules ⇒ equal schedules: the
        acceptance hook for site-event replay."""
        inj = self._entity_injectors()
        out = {}
        for s, d in site_pairs(self.site_daemons(a),
                               self.site_daemons(b)):
            if s in inj:
                out[f"{s}>{d}"] = inj[s].preview(s, d, count)
        return out

    def game_day(self, phases, *, timeout: float = 60.0) -> list[dict]:
        """Run a scripted site-disaster drill.

        Each phase is ``{"name", "action": fn(cluster)|None,
        "until": fn(event)->bool|None, "timeout": s}``: fire the
        action, then (if `until` is given) consume the live `ceph -w`
        event stream until the predicate matches.  Returns per-phase
        wall-clock timings — the bench stretch leg reads
        ``site_failover_detect_s`` and ``site_heal_convergence_s``
        straight out of this report."""
        report = []
        with self.watch() as w:
            for ph in phases:
                name = ph.get("name", "?")
                t0 = time.monotonic()
                action = ph.get("action")
                if action is not None:
                    action(self)
                until = ph.get("until")
                if until is not None:
                    deadline = time.monotonic() + \
                        float(ph.get("timeout", timeout))
                    while True:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise TimeoutError(
                                f"game day phase {name!r} never "
                                "reached its target event")
                        ev = w.next(timeout=left)
                        if until(ev):
                            break
                report.append({"phase": name,
                               "elapsed_s": time.monotonic() - t0})
        return report

    # -- cluster helpers ---------------------------------------------------
    def watch(self) -> ClusterWatcher:
        """Subscribe to the mon event stream (health / clog /
        progress) — the `ceph -w` feed.  Caller closes."""
        return ClusterWatcher(self.monmap, auth=self.auth)

    def wait_for_health_ok(self, timeout: float = 30.0):
        """Block until the cluster reports HEALTH_OK, driven entirely
        by the event stream — no status polling.  The subscription
        snapshot answers immediately when already healthy."""
        with self.watch() as w:
            deadline = time.monotonic() + timeout
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("cluster never reached "
                                       "HEALTH_OK")
                ev = w.next(timeout=left)
                if ev["kind"] == "health" and \
                        ev["data"].get("status") == "HEALTH_OK":
                    return

    def wait_for_clean(self, timeout: float = 30.0):
        """Wait until every PG on every live OSD is active (+clean when
        it owns recovery state)."""
        deadline = time.monotonic() + timeout
        if self.procs:
            states: list[str] = []
            while time.monotonic() < deadline:
                try:
                    stats = self._pg_dump().get("pg_stats") or {}
                except Exception:
                    stats = {}
                states = [st.get("state", "")
                          for st in stats.values()]
                if states and all(s in ("active", "active+clean")
                                  for s in states):
                    return
                time.sleep(0.1)
            raise TimeoutError(
                f"cluster never went clean (procs): {states}")
        while time.monotonic() < deadline:
            states = []
            for osd in self.osds.values():
                with osd.lock:
                    states.extend(pg.state for pg in osd.pgs.values()
                                  if osd.whoami == pg.primary)
            if states and all(s in ("active", "active+clean")
                              for s in states):
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster never went clean: {states}")

    def scrub_pg(self, pgid, timeout: float = 20.0, *,
                 deep: bool = True) -> int:
        """Scrub one PG on its primary; wait for completion and
        subsequent repair to settle.  Returns the error count the
        scrub found (0 = clean).  deep=False runs a shallow scrub
        (metadata only — no payload digests, no parity recheck)."""
        if self.procs:
            return self._scrub_pg_procs(pgid, timeout, deep=deep)
        primary = None
        for osd in self.osds.values():
            with osd.lock:
                pg = osd.pgs.get(pgid)
                if pg is not None and pg.is_primary:
                    primary = osd
                    break
        if primary is None:
            raise KeyError(f"no primary for {pgid}")
        deadline = time.monotonic() + timeout
        while not primary.scrub_pg(pgid, deep=deep):
            # refused while writes are in flight — retry
            if time.monotonic() > deadline:
                raise TimeoutError(f"scrub of {pgid} never started")
            time.sleep(0.05)
        while time.monotonic() < deadline:
            with primary.lock:
                pg = primary.pgs[pgid]
                if not pg.scrubbing:
                    return pg.scrub_errors
            time.sleep(0.05)
        raise TimeoutError(f"scrub of {pgid} never finished")

    def _scrub_pg_procs(self, pgid, timeout: float, *,
                        deep: bool) -> int:
        """Drive a scrub over the wire: re-issue the mon command
        (the primary refuses while writes are in flight; the command
        is idempotent) and poll `pg dump` until the scrub stamp moves
        past its pre-command value and the PG left `+scrubbing`."""
        pgid = str(pgid)
        stamp_key = "last_deep_scrub" if deep else "last_scrub"
        prefix = "pg deep-scrub" if deep else "pg scrub"
        st0 = (self._pg_dump().get("pg_stats") or {}).get(pgid) or {}
        before = st0.get(stamp_key, 0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                self._mon_cmd({"prefix": prefix, "pgid": pgid})
            except RuntimeError:
                pass        # no live primary yet / refused — retry
            st = (self._pg_dump().get("pg_stats") or {}
                  ).get(pgid) or {}
            if st.get(stamp_key, 0) > before and \
                    "scrubbing" not in st.get("state", ""):
                return int(st.get("scrub_errors", 0))
            time.sleep(0.1)
        raise TimeoutError(f"scrub of {pgid} never finished (procs)")

    # -- tracing -----------------------------------------------------------
    def collect_trace(self, trace_id: str,
                      format: str = "spans"):
        """Merge one trace's spans from every daemon and client ring,
        ordered by start time.

        Threaded mode reads the in-process rings directly (one shared
        monotonic clock).  Procs mode fetches ``dump_tracing`` over
        each OSD's Unix asok and rebases every child's monotonic span
        starts onto THIS process's monotonic clock using the wall/mono
        pair in the dump header — so spans from N real processes merge
        into one chronologically consistent trace and the downstream
        formatters apply the same single wall-clock offset either way.

        ``format="spans"`` (default) returns the raw span dicts —
        feed them to ``core.tracer.chrome_trace`` for chrome://tracing;
        ``format="otlp"`` returns the OTLP/JSON resource/scope/span
        shape; ``format="chrome"`` the Chrome trace_event JSON."""
        spans: list[dict] = []
        if self.procs:
            from .core.admin_socket import admin_command
            local_off = time.time() - time.monotonic()
            for i, asok in sorted(self._osd_asoks.items()):
                if i not in self._osd_handles:
                    continue
                try:
                    out = admin_command(asok, "dump_tracing",
                                        timeout=5.0)
                except OSError:
                    continue    # mid-crash daemon: skip, don't fail
                clk = out.get("clock") or {}
                child_off = (float(clk.get("wall", 0.0))
                             - float(clk.get("mono", 0.0)))
                for s in out.get("spans") or []:
                    if s.get("trace_id") != trace_id:
                        continue
                    s = dict(s)
                    s["start"] = (s["start"] + child_off
                                  - local_off)
                    spans.append(s)
        else:
            for osd in self.osds.values():
                spans.extend(osd.tracer.spans_for(trace_id))
        for r in self._clients:
            if r.objecter is not None:
                spans.extend(r.objecter.tracer.spans_for(trace_id))
        spans.sort(key=lambda s: s["start"])
        if format == "otlp":
            from .core.tracer import otlp_trace
            return otlp_trace(spans)
        if format == "chrome":
            from .core.tracer import chrome_trace
            return chrome_trace(spans)
        return spans

    def export_chrome_trace(self, trace_id: str) -> dict:
        """chrome://tracing JSON for one trace."""
        from .core.tracer import chrome_trace
        return chrome_trace(self.collect_trace(trace_id))

    def wait_for_osd_down(self, i: int, timeout: float = 20.0):
        deadline = time.monotonic() + timeout
        if self.procs:
            while time.monotonic() < deadline:
                try:
                    m = self._osdmap_from_mon()
                except Exception:
                    m = None
                if m is not None and m.max_osd > i \
                        and not m.is_up(i):
                    return
                time.sleep(0.1)
            raise TimeoutError(
                f"osd.{i} never marked down (procs)")
        while time.monotonic() < deadline:
            for osd in self.osds.values():
                with osd.lock:
                    if osd.osdmap.max_osd > i and \
                            not osd.osdmap.is_up(i):
                        return
            time.sleep(0.05)
        raise TimeoutError(f"osd.{i} never marked down")


class ScaleHarness:
    """Synthetic million-PG control plane — no daemons, no sockets.

    Stands up the mon/mgr aggregation state (OSDMap + array PGMap +
    per-OSD stats) for ``n_osds``/``pg_num`` directly, the way a
    vstart cluster would look after every OSD reported once, so the
    jitted health/summary/balancer passes can be exercised and timed
    at scales no in-process cluster could reach (ISSUE: 4096 OSDs,
    2^20 PGs).  Placement is either one batched CRUSH evaluation of
    the whole pool (``placement="crush"``, reusing the BatchMapper
    spine) or collision-free uniform sampling (``"synthetic"``, the
    default — mapping cost stays out of control-plane timings).

    Everything is deterministic in ``seed``: two harnesses built with
    the same arguments hold bit-identical state, which is what lets
    the tier-1 equality test run the array and legacy paths on twins.
    """

    STATE_MIX = (
        ("active+clean", 0.97),
        ("active+undersized+degraded", 0.015),
        ("active+remapped+backfilling", 0.008),
        ("active+clean+scrubbing", 0.004),
        ("down", 0.002),
        ("incomplete", 0.001),
    )

    def __init__(self, n_osds: int = 4096, pg_num: int = 1 << 20, *,
                 size: int = 3, seed: int = 0,
                 placement: str = "synthetic",
                 down_osds: int = 0,
                 damaged_frac: float = 1e-4,
                 scrub_late_frac: float = 1e-3,
                 stale_frac: float = 0.0,
                 scrub_interval: float | None = None,
                 now: float | None = None):
        import numpy as np
        from .crush.map import build_flat_map
        from .mon import health
        from .mon.pgmap import PGMap
        from .osd.osdmap import EXISTS, UP, OSDMap

        self.now = time.time() if now is None else now
        self.n_osds, self.pg_num, self.size = n_osds, pg_num, size
        rng = np.random.default_rng(seed)

        m = OSDMap(crush=build_flat_map(n_osds), max_osd=n_osds)
        m.epoch = 1
        for o in range(n_osds):
            m.osd_state[o] = EXISTS | UP
        for o in range(down_osds):
            m.mark_down(o)
        self.pool = m.create_pool("scale", pg_num=pg_num, size=size,
                                  crush_rule=0)
        self.osdmap = m

        if placement == "crush":
            from .tools.osdmaptool import map_pool_pgs
            self.placements = np.asarray(
                map_pool_pgs(m, self.pool), dtype=np.int64)
        elif placement == "synthetic":
            self.placements = self._sample_placements(rng)
        else:
            raise ValueError(f"placement={placement!r}")

        # -- pg_stats: one vectorized ingest --------------------------
        names = [s for s, _w in self.STATE_MIX]
        probs = np.array([w for _s, w in self.STATE_MIX])
        codes = rng.choice(len(names), size=pg_num,
                           p=probs / probs.sum())
        interval = health.SCRUB_WARN_INTERVAL \
            if scrub_interval is None else scrub_interval
        lss = self.now - rng.uniform(0.0, 0.5 * interval, pg_num)
        late = rng.random(pg_num) < scrub_late_frac
        lss[late] = self.now - interval * (2.0 + rng.random(late.sum()))
        errs = np.zeros(pg_num, dtype=np.int64)
        dmg = rng.random(pg_num) < damaged_frac
        errs[dmg] = rng.integers(1, 5, dmg.sum())
        degraded = np.isin(codes,
                           [names.index("active+undersized+degraded"),
                            names.index("active+remapped+backfilling")])
        stamp = np.full(pg_num, self.now)
        if stale_frac:
            stale = rng.random(pg_num) < stale_frac
            stamp[stale] = self.now - 10 * health.PG_STALE_GRACE

        pgm = PGMap()
        pgm.ingest_columns(
            self.pool.id, np.arange(pg_num, dtype=np.int64),
            state_names=names, state_codes=codes, stamp=stamp,
            num_objects=rng.integers(0, 2000, pg_num),
            num_bytes=rng.integers(0, 1 << 24, pg_num),
            log_size=rng.integers(0, 100, pg_num),
            missing=np.where(degraded,
                             rng.integers(1, 50, pg_num), 0),
            backfill_remaining=np.where(
                degraded, rng.integers(0, 200, pg_num), 0),
            scrub_errors=errs,
            last_scrub_stamp=lss,
            osd=self.placements[:, 0],
        )
        for o in range(n_osds):
            pgm.osd_stats[o] = {
                "kb": 1 << 20, "kb_used": 1 << 19,
                "bytes_total": 1 << 30, "bytes_used": 1 << 29,
                "op": 100 * o, "op_w": 60 * o, "op_r": 40 * o,
                "stamp": self.now,
            }
        self.pgmap = pgm

    def _sample_placements(self, rng):
        """[pg_num, size] uniform OSD ids, no repeats within a row
        (resample colliding rows until clean — a handful of passes at
        size=3 vs thousands of OSDs)."""
        import numpy as np
        place = rng.integers(0, self.n_osds,
                             size=(self.pg_num, self.size),
                             dtype=np.int64)
        while True:
            srt = np.sort(place, axis=1)
            dup = (srt[:, 1:] == srt[:, :-1]).any(axis=1)
            if not dup.any():
                return place
            place[dup] = rng.integers(0, self.n_osds,
                                      size=(int(dup.sum()), self.size),
                                      dtype=np.int64)

    # -- control-plane entry points -----------------------------------
    def health_context(self):
        from .mon.health import HealthContext
        return HealthContext(osdmap=self.osdmap, pgmap=self.pgmap,
                             monmap_ranks=[0], quorum=[0],
                             now=self.now)

    def evaluate(self) -> list[dict]:
        """One full health pass: states histogram + every registered
        evaluator over the array PGMap."""
        from .mon.health import evaluate_checks
        return evaluate_checks(self.health_context())

    def summary(self) -> dict:
        return self.pgmap.summary(live_pools={self.pool.id},
                                  now=self.now,
                                  total_expected=self.pg_num)

    def legacy_pgmap(self):
        """Dict-backed twin of the array map (materializes every row
        — meant for the fast equality tier, not the 1M smoke)."""
        from .mon.pgmap import LegacyPGMap
        lm = LegacyPGMap()
        lm.pg_stats = self.pgmap.dump()
        lm.osd_stats = {o: dict(st)
                        for o, st in self.pgmap.osd_stats.items()}
        return lm

    def balancer(self):
        """UpmapBalancer over the injected placements (no CRUSH
        recompute); pick the round implementation via
        ``optimize(use_arrays=...)``."""
        from .mgr.balancer import UpmapBalancer
        return UpmapBalancer(self.osdmap, self.pool.id, use_jax=False,
                             placements=self.placements)

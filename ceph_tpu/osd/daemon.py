"""OSD daemon — boot, map consumption, PG ownership, heartbeats.

Reference behavior re-created (``src/osd/OSD.{h,cc}``; SURVEY.md §3.5,
§4.6):

- **boot**: authenticate to the mons, announce ``MOSDBoot`` (address
  included) and wait to appear up in the committed OSDMap;
- **map consumption**: subscribe to osdmap pushes; every epoch advance
  recomputes this OSD's PG set via ``pg_to_up_acting_osds`` and drives
  each PG's peering state machine (``OSD::handle_osd_map`` →
  ``advance_pg``);
- **dispatch**: client ops and peer sub-ops are routed to the owning
  PG under the daemon lock (the sharded op queue collapses to one
  lock at this scale — the TPU compute plane, not this control loop,
  is the throughput path);
- **heartbeats**: ping PG peers on a timer; silence beyond the grace
  window produces ``MOSDFailure`` reports to the mon cluster
  (``OSD::handle_osd_ping`` / ``send_failures``), which marks OSDs
  down and re-triggers peering everywhere.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..core.admin_socket import AdminSocket, default_path
from ..core.config import ConfigProxy
from ..core.options import build_options
from ..core.perf_counters import PerfCountersBuilder
from ..core.threading_utils import SafeTimer
from ..core.tracked_op import OpTracker
from ..mon import messages as MM
from ..mon.client import MonClient
from ..msg import Dispatcher, EntityAddr, Messenger
from ..os_store import MemStore, WALStore
from ..os_store.objectstore import Transaction
from ..tools.osdmaptool import osdmap_from_dict
from . import messages as M
from .osdmap import OSDMap, PGid
from .pg import PG, ECBackend, ReplicatedBackend, _WRITE_OPS
from .scheduler import (CLIENT, PEERING, RECOVERY, SCRUB, SUBOP,
                        make_op_queue)


# message type → scheduler class (reference op_scheduler_class
# assignment in OSD::enqueue_op).  NB: MOSDPGBackfillPrune rides the
# SUBOP class so it stays FIFO with the live rep-ops whose objects it
# must never prune.
_SCHED_CLASS = {
    M.MOSDOp: CLIENT,
    M.MWatchNotifyAck: CLIENT,
    M.MOSDRepOp: SUBOP,
    M.MOSDRepOpReply: SUBOP,
    M.MOSDECSubOpWrite: SUBOP,
    M.MOSDECSubOpWriteReply: SUBOP,
    M.MOSDECSubOpRead: SUBOP,
    M.MOSDECSubOpReadReply: SUBOP,
    M.MOSDPGBackfillPrune: SUBOP,
    M.MOSDPGQuery: PEERING,
    M.MOSDPGNotify: PEERING,
    M.MOSDPGLog: PEERING,
    M.MOSDPGPush: RECOVERY,
    M.MOSDPGPushReply: RECOVERY,
    M.MOSDPGPull: RECOVERY,
    M.MOSDRepScrub: SCRUB,
    M.MOSDRepScrubMap: SCRUB,
    M.MOSDScrubCommand: SCRUB,
}


def _build_osd_perf(name: str):
    """The OSD's counter set (reference ``OSD::create_logger`` —
    l_osd_op & friends, trimmed to the paths this OSD has)."""
    b = PerfCountersBuilder(name)
    b.add_u64_counter("op", "client operations")
    b.add_u64_counter("op_r", "client read operations")
    b.add_u64_counter("op_w", "client write operations")
    b.add_time_avg("op_latency", "client op latency")
    b.add_u64_counter("subop", "replica/shard sub-operations")
    b.add_u64_counter("recovery_ops", "objects recovered/pushed")
    b.add_u64_counter("scrub_errors_found", "scrub inconsistencies")
    b.add_u64_counter("scrub_errors_repaired",
                      "scrub inconsistencies confirmed repaired")
    b.add_u64_counter("scrub_objects_scanned",
                      "objects digested by deep scrub")
    b.add_u64_counter("scrub_digest_bytes",
                      "payload bytes CRC-32C'd by deep scrub")
    b.add_u64_counter("scrub_parity_recheck_bytes",
                      "EC data bytes re-encoded by parity recheck")
    b.add_u64_counter("scrubs_scheduled",
                      "periodic scrubs started by the tick")
    b.add_u64("numpg", "placement groups hosted")
    # per-layer span durations (tracer perf sink; ceph_*_span_duration
    # in the exporter) — zero until jaeger_tracing_enable is on
    b.add_time_avg("osd_span_duration", "OSD op span duration")
    b.add_time_avg("wire_span_duration", "messenger wire span duration")
    b.add_time_avg("device_span_duration",
                   "TPU device kernel span duration")
    # log2 op-latency distribution in microseconds (reference
    # osd_op_latency histograms; `perf histogram dump`)
    b.add_histogram("op_latency_histogram",
                    "client op latency distribution (us, log2 buckets)")
    # device-plane launch accounting (device_profiler sink) — zero
    # until device_profiling_enable is on
    b.add_u64_counter("op_in_bytes", "client write payload bytes")
    b.add_u64_counter("device_launches", "device kernel launches")
    b.add_time_avg("device_dispatch",
                   "host-side dispatch time per launch")
    b.add_time_avg("device_compute",
                   "device compute time per launch")
    b.add_u64_counter("device_bytes_in", "bytes shipped to device")
    b.add_u64_counter("device_bytes_out", "bytes fetched from device")
    b.add_histogram("device_launch_hist",
                    "launch wall time distribution (us, log2 buckets)")
    return b.create_perf_counters()


class OSDaemon(Dispatcher):
    def __init__(self, whoami: int, monmap, store=None, *,
                 heartbeat_interval: float = 0.5,
                 heartbeat_grace: float = 3.0,
                 config: ConfigProxy | None = None,
                 admin_socket_path: str | None = None,
                 auth=None):
        self.whoami = whoami
        self.monmap = monmap
        # every knob below reads through the typed option table
        # (reference md_config_t; ctor kwargs land as overrides so
        # `config set` / injectargs can retune a live daemon)
        self.config = config or ConfigProxy(build_options())
        # ctor kwargs are the TEST-friendly fast defaults, but an
        # explicit override already present in a caller-supplied
        # config (MiniCluster osd_config=...) wins — do not clobber it
        for key, val in (("osd_heartbeat_interval", heartbeat_interval),
                         ("osd_heartbeat_grace", heartbeat_grace)):
            if self.config.source_of(key) == "default":
                self.config.set(key, val)
        self.perf = _build_osd_perf(f"osd.{whoami}")
        self.op_tracker = OpTracker(
            history_size=int(self.config.get("op_history_size") or 20),
            complaint_time=float(
                self.config.get("op_complaint_time") or 30.0),
            history_duration=float(
                self.config.get("osd_op_history_duration") or 600.0))
        self.config.add_observer(
            "op_complaint_time",
            lambda _n, v: setattr(self.op_tracker, "complaint_time",
                                  float(v)))
        self.config.add_observer(
            "osd_op_history_duration",
            lambda _n, v: setattr(self.op_tracker, "history_duration",
                                  float(v)))
        # op tracing: spans adopted from the client ctx riding MOSDOp;
        # the perf sink feeds the *_span_duration counters above
        from ..core.tracer import Tracer
        self.tracer = Tracer(
            daemon=f"osd.{whoami}",
            ring_size=int(self.config.get("tracer_ring_size") or 4096),
            enabled=bool(self.config.get("jaeger_tracing_enable")),
            perf=self.perf,
            sampling_rate=float(
                self.config.get("tracer_sampling_rate") or 1.0),
            span_budget=int(
                self.config.get("tracer_span_budget") or 0))
        self.config.add_observer(
            "jaeger_tracing_enable",
            lambda _n, v: setattr(self.tracer, "enabled", bool(v)))
        self.config.add_observer(
            "tracer_sampling_rate",
            lambda _n, v: setattr(self.tracer, "sampling_rate",
                                  float(v)))
        self.config.add_observer(
            "tracer_span_budget",
            lambda _n, v: setattr(self.tracer, "span_budget", int(v)))
        self.config.add_observer(
            "tracer_tail_slow_ms",
            lambda _n, v: setattr(self.tracer, "tail_slow_s",
                                  float(v) / 1000.0))
        self.tracer.tail_slow_s = float(
            self.config.get("tracer_tail_slow_ms") or 0.0) / 1000.0
        # workload attribution: client/pool/pg space-saving top-K
        # sketches fed from the op-reply path; dumps ride the
        # osd_stats beacon and merge cluster-wide in the mgr
        # (`ceph osd top`)
        from ..core.topk import TopKSet
        self.topk = TopKSet(
            k=int(self.config.get("osd_topk_k") or 16),
            enabled=bool(self.config.get("osd_topk_enable")))
        self.config.add_observer(
            "osd_topk_enable",
            lambda _n, v: setattr(self.topk, "enabled", bool(v)))
        self.config.add_observer(
            "osd_topk_k", lambda _n, v: self.topk.set_k(int(v)))
        # metric→trace exemplar window on the op-latency histogram
        _lat_hist = self.perf._counters["op_latency_histogram"].hist
        _lat_hist.exemplar_window = float(
            self.config.get("osd_exemplar_window_s") or 60.0)
        self.config.add_observer(
            "osd_exemplar_window_s",
            lambda _n, v: setattr(_lat_hist, "exemplar_window",
                                  float(v)))
        # device-plane launch profiler: PG device call sites bind() it
        # so launches attribute to this daemon; aggregates ride the
        # osd_stats beacon into the mgr telemetry spine
        from ..core.device_profiler import DeviceProfiler
        self.profiler = DeviceProfiler(
            name=f"osd.{whoami}",
            ring_size=int(
                self.config.get("device_profiler_ring_size") or 1024),
            enabled=bool(self.config.get("device_profiling_enable")),
            perf=self.perf)
        self.config.add_observer(
            "device_profiling_enable",
            lambda _n, v: self.profiler.set_enabled(bool(v)))
        self.config.add_observer(
            "device_profiler_ring_size",
            lambda _n, v: self.profiler.set_ring_size(int(v)))
        # coalescing device data plane: PG write paths submit encode/
        # digest work here instead of launching per-op; the deadline
        # timer rides SafeTimer (resolved lazily — the timer is
        # constructed below), megabatch launches attribute to the
        # device profiler, flush spans link member op spans
        from .batch_engine import BatchEngine
        self.batch_engine = BatchEngine(
            name=f"osd.{whoami}",
            enabled=bool(self.config.get("osd_batch_enable")),
            max_bytes=int(
                self.config.get("osd_batch_max_bytes") or (8 << 20)),
            max_ops=int(self.config.get("osd_batch_max_ops") or 64),
            flush_ms=float(
                self.config.get("osd_batch_flush_ms") or 0.0),
            recon_enabled=bool(
                self.config.get("osd_recovery_batch_enable")),
            recon_max_bytes=int(
                self.config.get("osd_recovery_batch_max_bytes")
                or (8 << 20)),
            recon_max_ops=int(
                self.config.get("osd_recovery_batch_max_ops") or 64),
            recon_flush_ms=float(
                self.config.get("osd_recovery_batch_flush_ms") or 0.0),
            comp_enabled=bool(
                self.config.get("osd_compress_batch_enable")),
            comp_max_bytes=int(
                self.config.get("osd_compress_batch_max_bytes")
                or (8 << 20)),
            comp_max_ops=int(
                self.config.get("osd_compress_batch_max_ops") or 64),
            comp_flush_ms=float(
                self.config.get("osd_compress_batch_flush_ms")
                or 0.0),
            comp_segment_bytes=int(
                self.config.get("osd_compress_segment_bytes")
                or (1 << 20)),
            bucket_floor=int(
                self.config.get("osd_batch_bucket_floor") or 32),
            use_mesh=bool(
                self.config.get("osd_recovery_batch_mesh")),
            on_lane_flush=self._on_lane_flush,
            schedule=lambda d, fn: self.timer.add_event_after(d, fn),
            profiler=self.profiler, tracer=self.tracer)
        for _opt, _attr, _cast in (
                ("osd_batch_enable", "enabled", bool),
                ("osd_batch_max_bytes", "max_bytes", int),
                ("osd_batch_max_ops", "max_ops", int),
                ("osd_batch_flush_ms", "flush_ms", float),
                ("osd_recovery_batch_enable", "recon_enabled", bool),
                ("osd_recovery_batch_max_bytes", "recon_max_bytes",
                 int),
                ("osd_recovery_batch_max_ops", "recon_max_ops", int),
                ("osd_recovery_batch_flush_ms", "recon_flush_ms",
                 float),
                ("osd_compress_batch_enable", "comp_enabled", bool),
                ("osd_compress_batch_max_bytes", "comp_max_bytes",
                 int),
                ("osd_compress_batch_max_ops", "comp_max_ops", int),
                ("osd_compress_batch_flush_ms", "comp_flush_ms",
                 float),
                ("osd_compress_segment_bytes", "comp_segment_bytes",
                 int),
                ("osd_batch_bucket_floor", "bucket_floor", int),
                ("osd_recovery_batch_mesh", "use_mesh", bool)):
            self.config.add_observer(
                _opt, lambda _n, v, _a=_attr, _c=_cast: setattr(
                    self.batch_engine, _a, _c(v)))
        # recovery pacing: PGs read this live per backfill kick — an
        # autotuner `config set` retunes the next batch, no restart
        self.recovery_max_active = int(
            self.config.get("osd_recovery_max_active") or 8)
        self.config.add_observer(
            "osd_recovery_max_active",
            lambda _n, v: setattr(self, "recovery_max_active",
                                  max(1, int(v))))
        self.admin_socket = AdminSocket(
            admin_socket_path or default_path(f"osd.{whoami}"))
        self._register_admin_commands()
        self.store = store if store is not None else MemStore(
            name=f"osd.{whoami}")
        # durability wiring: the batch engine nudges the WAL group-
        # commit thread at each megabatch flush boundary (one fsync
        # covers the whole flush), and a failed append/fsync degrades
        # the daemon instead of crashing its op thread
        self._store_error: str | None = None
        self.batch_engine.store_kick = getattr(self.store, "kick", None)
        if isinstance(self.store, WALStore):
            self.store.on_error = self._on_store_error
            self.config.add_observer(
                "osd_wal_sync_mode",
                lambda _n, v: self.store.set_sync_mode(v))
            self.config.add_observer(
                "osd_wal_compact_min_records",
                lambda _n, v: setattr(self.store,
                                      "compact_min_records", int(v)))
        # black-box flight recorder: a crash-surviving sidecar next to
        # the WAL journaling the observability tails (spans/clog/perf/
        # profiler/injector), readable offline from a dead process
        self.flight_recorder = None
        self._crash_report_id: str | None = None
        store_path = getattr(self.store, "_path", None)
        if store_path and bool(self.config.get("osd_blackbox_enable")):
            from ..core.flight_recorder import FlightRecorder
            self.flight_recorder = FlightRecorder(
                store_path + ".bbox", daemon=f"osd.{whoami}",
                max_bytes=int(
                    self.config.get("osd_blackbox_max_bytes")),
                tail_events=int(
                    self.config.get("osd_blackbox_tail_events")))
            self.store.flight_recorder = self.flight_recorder
            self.config.add_observer(
                "osd_blackbox_enable",
                lambda _n, v: setattr(self.flight_recorder,
                                      "enabled", bool(v)))
        self.auth = auth
        # fault fabric: the messenger's injector is built from the
        # ms_inject_* options and stays retunable while the daemon
        # runs — `config set`/injectargs feed the observers below,
        # `fault *` admin commands poke the policy table directly
        from ..msg.fault import injector_from_config
        self.msgr = Messenger(
            f"osd.{whoami}",
            inject_socket_failures=int(
                self.config.get("ms_inject_socket_failures") or 0),
            fault_injector=injector_from_config(self.config),
            **(auth.msgr_kwargs(f"osd.{whoami}") if auth else {}))
        self.config.add_observer(
            "ms_inject_socket_failures",
            lambda _n, v: setattr(self.msgr, "inject_socket_failures",
                                  int(v)))
        for _opt, _knob in (("ms_inject_drop_prob", "drop"),
                            ("ms_inject_delay_prob", "delay"),
                            ("ms_inject_delay_ms", "delay_ms"),
                            ("ms_inject_dup_prob", "dup"),
                            ("ms_inject_reorder_prob", "reorder"),
                            ("ms_inject_reorder_ms", "reorder_ms")):
            self.config.add_observer(
                _opt, lambda _n, v, _k=_knob: self.msgr.faults.set_rule(
                    "*", "*", **{_k: float(v)}))
        self.msgr.add_dispatcher(self)
        self.msgr.tracer = self.tracer
        self.monc = MonClient(monmap, entity=f"osd.{whoami}",
                              auth=auth)
        # cluster log: ring + batched MLog uplink, flushed on the tick
        from ..core.log_client import LogClient
        self.clog = LogClient(f"osd.{whoami}", send_fn=self.monc.send)
        self._slow_ops_logged = 0      # clog on 0→N transitions
        self._scrub_errors_logged = 0
        self.osdmap = OSDMap()
        self.pgs: dict[PGid, PG] = {}
        # interval history per PG, built by walking EVERY map epoch in
        # order (the mon feeds the full range on a start>0
        # subscription).  closed intervals: {"first","last","acting",
        # "primary","maybe_went_rw"} — reference PastIntervals built
        # by check_new_interval over the fetched map range.
        self.pg_intervals: dict[PGid, list[dict]] = {}
        self._open_intervals: dict[PGid, dict] = {}
        self.lock = threading.RLock()
        self.running = False
        self.addr: EntityAddr | None = None
        self._peer_cons: dict[int, object] = {}
        self._hb_interval = self.config.get("osd_heartbeat_interval")
        self._hb_grace = self.config.get("osd_heartbeat_grace")
        self.config.add_observer(
            "osd_heartbeat_interval",
            lambda _n, v: setattr(self, "_hb_interval", v))
        self.config.add_observer(
            "osd_heartbeat_grace",
            lambda _n, v: setattr(self, "_hb_grace", v))
        self._hb_last: dict[int, float] = {}
        self._hb_reported: dict[int, float] = {}  # osd → last report time
        self._stats_interval = max(1.0, heartbeat_interval * 2)
        self._stats_last = 0.0
        self.timer = SafeTimer(f"osd.{whoami}-tick")
        self._tick_token = None
        # the op scheduler (reference ShardedOpWQ + OpScheduler):
        # dispatch classifies work, one worker drains by weighted
        # priority (wpq) or dmclock QoS tags (mclock) per
        # `osd_op_queue`, so recovery/scrub storms can't bury client
        # I/O (heartbeats bypass the queue entirely — their latency
        # IS the failure detector)
        self.op_queue = make_op_queue(self.config)
        self._op_worker = threading.Thread(
            target=self._op_worker_loop, name=f"osd.{whoami}-opwq",
            daemon=True)

    def _register_admin_commands(self):
        """Live-introspection surface (reference AdminSocket hooks:
        `ceph daemon osd.N <cmd>`)."""
        a = self.admin_socket
        a.register("perf dump", lambda c: self.perf.dump(),
                   "dump perf counters")
        a.register("perf schema", lambda c: self.perf.schema(),
                   "perf counter schema")
        a.register("dump_ops_in_flight",
                   lambda c: self.op_tracker.dump_ops_in_flight(),
                   "in-flight client ops")
        a.register("dump_historic_ops",
                   lambda c: self.op_tracker.dump_historic_ops(),
                   "recently completed ops")
        a.register(
            "dump_historic_ops_by_duration",
            lambda c: self.op_tracker.dump_historic_ops_by_duration(),
            "recently completed ops, slowest first")
        a.register("perf histogram dump",
                   lambda c: self.perf.dump_histograms(),
                   "2-D log-bucket histogram counters")
        # op tracing surface (reference `dump_tracing` / blkin):
        # `trace start|stop` rides one registration — the dispatcher
        # hands the full prefix through, so parse the verb here
        # clock header for cross-process merging: span starts and
        # black-box stamps are this process's monotonic clock; readers
        # rebase them onto the wall clock with this pair (the same
        # alignment procs.write_ready stamps into readiness files)
        def _clock():
            return {"wall": time.time(), "mono": time.monotonic()}

        def _dump_tracing(c):
            spans = self.tracer.dump()
            if c.get("format") == "otlp":
                from ..core.tracer import otlp_trace
                return otlp_trace(spans)
            return {"enabled": self.tracer.enabled,
                    "num_spans": len(self.tracer),
                    "clock": _clock(),
                    "spans": spans}
        a.register("dump_tracing", _dump_tracing,
                   "collected spans (format=otlp for OTLP JSON)")

        def _trace_ctl(c):
            verb = c.get("prefix", "").split()[-1]
            if verb == "start":
                self.tracer.enabled = True
            elif verb == "stop":
                self.tracer.enabled = False
            elif verb == "clear":
                self.tracer.clear()
            else:
                return {"error": "usage: trace start|stop|clear"}
            return {"enabled": self.tracer.enabled}
        a.register("trace", _trace_ctl,
                   "trace start|stop|clear — toggle span collection")

        def _profiler_ctl(c):
            verb = c.get("prefix", "").split()[-1]
            if verb == "dump":
                d = self.profiler.dump()
                d["clock"] = _clock()
                return d
            if verb == "reset":
                self.profiler.reset()
                return {"success": "profiler reset"}
            return {"error": "usage: profiler dump|reset"}
        a.register("profiler", _profiler_ctl,
                   "profiler dump|reset — per-launch device profiles")

        def _blackbox(c):
            verb = c.get("prefix", "").split()[-1]
            fr = self.flight_recorder
            if fr is None:
                return {"enabled": False,
                        "error": "no black box (RAM store)"}
            if verb == "snap":
                self._blackbox_snap()
            return {"clock": _clock(), **fr.stats()}
        a.register("blackbox", _blackbox,
                   "blackbox dump|snap — flight-recorder state "
                   "(snap forces a snapshot now)")
        a.register("dump_batch_engine",
                   lambda c: self.batch_engine.dump(),
                   "coalescing data-plane counters + flush config")

        # workload attribution: per-OSD heavy-hitter sketches + the
        # metric→trace exemplars the mgr exporter attaches to
        # `_bucket` lines — both carry the clock pair so procs-mode
        # readers can rebase
        def _topk_dump(c):
            return {"enabled": self.topk.enabled,
                    "clock": _clock(), **self.topk.dump()}
        a.register("topk", _topk_dump,
                   "heavy-hitter sketches (clients/pools/pgs)")

        def _exemplar_dump(c):
            return {"clock": _clock(),
                    "exemplars": self._histogram_exemplars()}
        a.register("dump_exemplars", _exemplar_dump,
                   "slowest-op trace exemplars per histogram bucket")
        a.register("config show", lambda c: {
            k: self.config.get(k) for k in self.config.keys()},
            "effective configuration")
        a.register("config set", lambda c: (
            self.config.set(c["key"], c["value"]),
            {"success": f"{c['key']} = {self.config.get(c['key'])}"}
        )[1], "set a config override")
        a.register("config help", lambda c: self.config.help(c["key"]),
                   "option metadata")
        a.register("injectargs", lambda c: (
            self.config.injectargs(c.get("args", "")),
            {"success": self.config.diff()})[1],
            "apply '--key value ...' runtime overrides")
        from ..core.mempool import dump_mempools
        a.register("dump_mempools", lambda c: dump_mempools(),
                   "per-pool live allocation accounting")
        a.register("status", lambda c: {
            "whoami": self.whoami, "epoch": self.osdmap.epoch,
            "num_pgs": len(self.pgs),
            "state": "active" if self.running else "stopped"},
            "daemon status")
        a.register("dump_replay_stats", lambda c: {
            "replay_stats": getattr(self.store, "replay_stats", None),
            "wal_stats": dict(getattr(self.store, "wal_stats", {}))},
            "WAL mount-replay summary + append/sync counters")
        # fault fabric controls (handlers bind self.msgr lazily — the
        # messenger is constructed after this registration)
        _FAULT_KNOBS = ("drop", "delay", "delay_ms", "dup", "reorder",
                        "reorder_ms")
        a.register("fault show",
                   lambda c: self.msgr.faults.describe(),
                   "dump fault-injection policy table + seed")
        a.register("fault set", lambda c: self.msgr.faults.set_rule(
            c.get("src", "*"), c.get("dst", "*"),
            **{k: float(v) for k, v in c.items()
               if k in _FAULT_KNOBS}).to_dict(),
            "set per-peer-pair fault probabilities")
        a.register("fault partition", lambda c: (
            self.msgr.faults.partition(c["dst"], c.get("src", "*")),
            {"partitioned": f"{c.get('src', '*')}>{c['dst']}"})[1],
            "directed partition: blackhole sends to dst")
        a.register("fault heal", lambda c: (
            self.msgr.faults.heal(c.get("src"), c.get("dst")),
            {"healed": True})[1],
            "remove fault rules (all, or filtered by src/dst)")
        # SMART-style device health (reference: the OSD shells out to
        # smartctl; here synthetic counters steered by a DEV option so
        # devicehealth's scrape→predict→warn pipeline is testable).
        # Raw counters only: the verdict thresholds live in ONE place
        # (mgr devicehealth), never here
        a.register("smart", lambda c: {
            "devid": f"SYNTH-osd{self.whoami}",
            "media_errors": self.config.get(
                "osd_debug_smart_media_errors"),
            "temperature_c": 35,
        }, "device health metrics")

    # -- lifecycle ---------------------------------------------------------
    def start(self, wait_for_up: bool = True, timeout: float = 15.0):
        self.store.mount()
        rs = getattr(self.store, "replay_stats", None)
        if rs and not rs.get("clean_shutdown", True):
            tail = rs.get("tail") or {}
            note = (f"; dropped {tail.get('error')}"
                    if tail.get("status") != "clean" else "")
            self.clog.info(
                f"osd.{self.whoami} unclean shutdown detected: "
                f"replayed {rs.get('records', 0)} WAL records{note}")
        prior_crash = None
        if self.flight_recorder is not None:
            try:
                prior_crash = self.flight_recorder.open()
            except OSError:
                # an unwritable sidecar must not stop the daemon
                self.store.flight_recorder = None
                self.flight_recorder = None
        self.admin_socket.start()
        self.addr = self.msgr.bind()
        self.running = True
        if not self._op_worker.is_alive():
            self._op_worker.start()
        self.monc.on_osdmap = self._on_osdmap
        # subscribe from epoch 1: the full history replay rebuilds
        # pg_intervals (a revived OSD starts a fresh daemon object)
        self.monc.sub_want("osdmap", 1)
        self._send_boot()
        if wait_for_up:
            # re-send boot while waiting: the first MOSDBoot can race a
            # mon election (or land on a peon mid-forward) and be
            # dropped — the reference OSD re-queues boot on every map
            # update while still marked down (OSD::_send_boot / start_boot)
            deadline = time.monotonic() + timeout
            next_boot = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                with self.lock:
                    if self.osdmap.is_up(self.whoami):
                        break
                if time.monotonic() >= next_boot:
                    self._send_boot()
                    next_boot = time.monotonic() + 2.0
                time.sleep(0.02)
            else:
                raise TimeoutError(f"osd.{self.whoami} never came up")
        if prior_crash is not None:
            # the previous incarnation died with its black box dirty:
            # post the synthesized report now that the mon is
            # reachable (reference: the ceph-crash agent posts on the
            # next boot, not at the moment of death)
            self._post_crash_report(prior_crash)
        self._tick_token = self.timer.add_event_after(
            self._hb_interval, self._tick)

    # -- black box / crash post-mortem -------------------------------------
    def _blackbox_snap(self):
        """One flight-recorder snapshot: the observability tails this
        daemon would want read back from its corpse."""
        fr = self.flight_recorder
        if fr is None or not fr.enabled:
            return
        try:
            inj = getattr(self.store, "crash", None)
            fr.snap(
                spans=self.tracer.dump()[-fr.tail_spans:],
                clog=self.clog.last(fr.tail_clog),
                perf=self.perf.dump(),
                profiler=self.profiler.aggregate(),
                crash=inj.describe() if inj is not None else None)
        except Exception:   # noqa: BLE001 — the black box must never
            pass            # take the daemon down

    def _post_crash_report(self, info: dict):
        """Synthesize a crash report from the dead incarnation's black
        box and post it into the mgr crash module's config-key
        namespace (reference ceph-crash agent → `ceph crash post`)."""
        from ..core.flight_recorder import (CRASH_KEY_PREFIX,
                                            crash_id_for)
        entity = f"osd.{self.whoami}"
        stamp = time.time()
        tail_n = int(self.config.get("osd_blackbox_tail_events"))
        report = {
            "entity": entity,
            "timestamp": stamp,
            "boot_nonce": info.get("nonce"),
            "crash_pid": info.get("pid"),
            "crash_point": info.get("crash_point"),
            "timeline": (info.get("events") or [])[-tail_n:],
            "replay_stats": getattr(self.store, "replay_stats", None),
            "blackbox_tail": info.get("tail"),
        }
        crash_id = crash_id_for(entity, stamp)
        try:
            rc, _outs, _ = self.monc.command(
                {"prefix": "config-key put",
                 "key": CRASH_KEY_PREFIX + crash_id,
                 "val": json.dumps(report, default=str)},
                timeout=5.0)
        except Exception:   # noqa: BLE001 — the post-mortem is
            return          # advisory; boot continues without it
        if rc == 0:
            self._crash_report_id = crash_id
            self.clog.warn(
                f"{entity} previous instance crashed uncleanly; "
                f"posted crash report {crash_id}")

    # -- cache-tier agent --------------------------------------------------
    def _tier_rados(self):
        """Lazy internal client for tiering (reference: the OSD's own
        Objecter drives promotes).  The entity name's `client.tier-`
        prefix is the recursion guard the cache PGs check."""
        # guarded: two concurrent promotes (or a promote racing
        # shutdown) must not each connect a client and orphan one
        with self.lock:
            if not self.running:
                raise ConnectionError("osd shutting down")
            if getattr(self, "_tier_client", None) is None:
                import uuid
                from ..osdc.librados import Rados
                self._tier_client = Rados(
                    self.monmap,
                    name=f"client.tier-osd{self.whoami}-"
                         f"{uuid.uuid4().hex[:8]}",
                    auth=self.auth).connect()
            return self._tier_client

    def tier_agent(self, pg, oid: str, base_pool_id: int,
                   delete: bool = False):
        """Background promote (copy base→cache) or base-delete for a
        parked op; runs OFF the op worker so the agent's own client
        ops (which come back through this OSD's queue) can't
        deadlock.  Completion requeues the parked ops under the
        daemon lock."""
        import threading as _threading
        from ..osdc.librados import ObjectNotFound

        def run():
            try:
                r = self._tier_rados()
                base_name = r.objecter.osdmap.pools[base_pool_id].name
                base_io = r.open_ioctx_direct(base_name)
                if delete:
                    try:
                        base_io.remove(oid)
                    except ObjectNotFound:
                        pass
                else:
                    cache_name = \
                        r.objecter.osdmap.pools[pg.pool.id].name
                    cache_io = r.open_ioctx_direct(cache_name)
                    try:
                        data = bytes(base_io.read(oid))
                    except ObjectNotFound:
                        data = None     # miss in base too: plain ENOENT
                    if data is not None:
                        cache_io.write_full(oid, data)
                        try:
                            for k, v in base_io.getxattrs(oid).items():
                                cache_io.setxattr(oid, k, v)
                        except Exception:   # noqa: BLE001 — optional
                            pass
                        try:
                            rows = base_io.omap_get(oid)
                            if rows:
                                cache_io.omap_set(oid, rows)
                        except Exception:   # noqa: BLE001 — optional
                            pass
            except Exception:   # noqa: BLE001 — a failed promote
                pass            # releases the op; it runs as a miss
            finally:
                with self.lock:
                    pg._promote_done(oid)

        _threading.Thread(target=run, daemon=True,
                          name=f"osd.{self.whoami}-tier").start()

    def _op_worker_loop(self):
        while True:
            got = self.op_queue.dequeue(timeout=1.0)
            if got is None:
                if not self.running:
                    return
                continue
            _klass, msg = got
            try:
                self._route(msg)
            except Exception:       # noqa: BLE001 — a poisoned op
                # must not kill the op thread; fail the op visibly
                # instead of leaving the client to time out
                tracked = getattr(msg, "tracked", None)
                if tracked is not None:
                    tracked.finish()
                con = getattr(msg, "connection", None)
                if isinstance(msg, M.MOSDOp) and con is not None:
                    try:
                        con.send_message(M.MOSDOpReply(
                            tid=msg.tid, rc=-5, outs="op faulted",
                            results=None, version=[0, 0],
                            epoch=self.osdmap.epoch,
                            trace=getattr(msg, "trace", None)))
                    except ConnectionError:
                        pass

    def shutdown(self):
        self.running = False
        self.op_queue.close()
        # drain the data plane while the messenger is still up: the
        # flights' completions fan out their sub-writes
        try:
            self.batch_engine.stop()
        except Exception:   # noqa: BLE001 — shutdown is best-effort
            pass
        self.timer.shutdown()
        self.admin_socket.shutdown()
        tier = getattr(self, "_tier_client", None)
        if tier is not None:
            try:
                tier.shutdown()
            except Exception:   # noqa: BLE001
                pass
            self._tier_client = None
        self.monc.shutdown()
        self.msgr.shutdown()
        if self.flight_recorder is not None:
            try:
                self._blackbox_snap()
                self.flight_recorder.close()
            except OSError:
                pass
        self.store.umount()

    def _on_store_error(self, exc):
        """The backing store can no longer durably commit (ENOSPC,
        fsync failure, injected power loss).  Reference behavior
        (BlueStore::_txc_state_proc on EIO → ceph_abort, softened to
        our daemon model): clog the failure, self-report so the mon
        marks us down, stop answering heartbeats so peers confirm it,
        and fail client ops with EIO instead of crashing the op
        thread.  May fire from the op worker (mid-queue_transaction)
        or the WAL commit thread."""
        if self._store_error is not None:
            return
        self._store_error = str(exc)
        try:
            self.clog.error(
                f"osd.{self.whoami} objectstore write failure, "
                f"marking self down: {exc}")
        except Exception:   # noqa: BLE001 — degradation is best-effort
            pass
        try:
            self.monc.send(MM.MOSDFailure(
                target=self.whoami, reporter=self.whoami))
        except Exception:   # noqa: BLE001
            pass

    def _on_lane_flush(self, lane: str, ops: int, nbytes: int):
        """Batch-engine flush hook: debit the op queue for the device
        bandwidth the reconstruct lane just consumed, so queued
        recovery-class work defers in proportion and client ops keep
        their p99 through a recovery sweep (the mClock recovery
        reservation governs the lane even though its megabatches
        bypass the queue itself)."""
        if lane != "recon" or not ops:
            return
        q = getattr(self, "op_queue", None)
        account = getattr(q, "account", None)
        if account is not None:
            account(RECOVERY, float(ops))

    def _send_boot(self):
        self.monc.send(MM.MOSDBoot(
            osd=self.whoami, addr=f"{self.addr.host}:{self.addr.port}"))

    def request_up_thru(self, want: int):
        """Ask the mon to bump our up_thru (idempotent; the committed
        map's arrival re-drives the waiting PGs' peering)."""
        self.monc.send(MM.MOSDAlive(osd=self.whoami, want=want))

    def _start_scrub_or_retry(self, pg, msg, *, max_tries: int = 20):
        """An operator scrub refused (writes in flight, already
        scrubbing, mid-peering) requeues itself instead of silently
        dropping — the mon already acked the command.  ``repair``
        implies deep (a shallow pass can't see what to repair)."""
        deep = bool(getattr(msg, "repair", False)) or \
            getattr(msg, "deep", True) is not False
        if pg.start_scrub(deep=deep,
                          trigger=getattr(msg, "trace", None)):
            return
        tries = getattr(msg, "_scrub_tries", 0)
        if tries >= max_tries:
            return
        msg._scrub_tries = tries + 1
        self.timer.add_event_after(
            0.5, lambda: self.op_queue.enqueue("scrub", msg))

    def scrub_pg(self, pgid: PGid, deep: bool = True) -> bool:
        """Kick a scrub on a PG this OSD is primary for."""
        with self.lock:
            pg = self.pgs.get(pgid)
            return bool(pg is not None and pg.start_scrub(deep=deep))

    # -- map handling ------------------------------------------------------
    def _on_osdmap(self, epoch: int, map_dict: dict, newest: int = 0):
        with self.lock:
            if epoch <= self.osdmap.epoch:
                return
            prev = self.osdmap
            self.osdmap = osdmap_from_dict(map_dict)
            # a peer that came back up starts a fresh grace window —
            # its stale _hb_last would otherwise trip an immediate
            # failure report (one flap per revive)
            for o in range(self.osdmap.max_osd):
                if self.osdmap.is_up(o) and \
                        (o >= prev.max_osd or not prev.is_up(o)):
                    self._hb_last.pop(o, None)
                    self._hb_reported.pop(o, None)
            self._split_pgs(prev)
            placements = self._update_pg_intervals()
            catching_up = epoch < max(newest, self.monc.osdmap_epoch)
            if catching_up:
                # history replay: record intervals only — peering,
                # PG creation and rejoin-boot wait for the live map
                return
            if self.running and not self.osdmap.is_up(self.whoami):
                # marked down but alive: rejoin (reference
                # OSD::_committed_osd_maps → start_boot)
                self._send_boot()
            self._scan_pgs(placements)
            # pool snapshot deletions drive clone trimming (reference
            # snap trim queue fed by OSDMap snap removals)
            for pid, pool in self.osdmap.pools.items():
                prevpool = prev.pools.get(pid)
                if prevpool is None:
                    continue
                removed = set(prevpool.snaps) - set(pool.snaps)
                if not removed:
                    continue
                for pgid, pg in self.pgs.items():
                    if pgid.pool == pid and \
                            self.whoami in pg.acting:
                        fn = getattr(pg.backend, "snap_trim", None)
                        if fn is not None:
                            fn(removed)

    def _split_pgs(self, prev: OSDMap):
        """PG splitting on pg_num growth (reference ``OSD::split_pgs``
        + ``PG::split_into`` + ``pg_t::is_split``): every OSD holding
        a parent collection carves out the child PGs locally — objects
        (with their snap clones), log entries, snap-mapper index, and
        info move by ``ceph_stable_mod`` re-hash; children then peer
        under the new map with their data already in place, and CRUSH
        relocation proceeds as ordinary recovery/backfill."""
        import json as _json

        from ..crush.hash import ceph_str_hash_rjenkins
        from .osdmap import ceph_stable_mod
        from .pg import META_OID, SNAPMAP_OID, _SNAP_SEP

        for pid, pool in self.osdmap.pools.items():
            old = prev.pools.get(pid)
            if old is None or pool.pg_num <= old.pg_num:
                continue
            old_n, old_mask = old.pg_num, old.pg_num_mask

            def head_of(oid: str) -> str:
                return oid.split(_SNAP_SEP, 1)[0]

            def child_ps(oid: str) -> int:
                seed = int(ceph_str_hash_rjenkins(head_of(oid).encode()))
                return pool.raw_pg_to_pg(seed)

            shards = range(pool.size) if pool.is_erasure() else (-1,)
            for p_ps in range(old_n):
                children = [c for c in range(old_n, pool.pg_num)
                            if ceph_stable_mod(c, old_n, old_mask) == p_ps]
                if not children:
                    continue
                parent = PGid(pid, p_ps)
                child_set = set(children)
                for s in shards:
                    pcid = str(parent) if s < 0 else f"{parent}s{s}"
                    if not self.store.collection_exists(pcid):
                        continue
                    try:
                        meta = self.store.omap_get(pcid, META_OID)
                    except KeyError:
                        meta = {}
                    pinfo = (_json.loads(meta["info"])
                             if "info" in meta else None)
                    plog = (_json.loads(meta["log"])
                            if "log" in meta else None)
                    try:
                        snapmap = self.store.omap_get(pcid, SNAPMAP_OID)
                    except KeyError:
                        snapmap = {}
                    # one bucketing pass: hash every object / snap-row /
                    # log entry ONCE and group by destination child
                    # (not once per child — splits can fan 1→64)
                    oids_by_child: dict[int, list] = {}
                    for oid in self.store.list_objects(pcid):
                        if oid in (META_OID, SNAPMAP_OID):
                            continue
                        c = child_ps(oid)
                        if c in child_set:
                            oids_by_child.setdefault(c, []).append(oid)
                    rows_by_child: dict[int, dict] = {}
                    for key, val in snapmap.items():
                        c = child_ps(key.split("|", 1)[1]
                                     .rsplit("|", 1)[0])
                        if c in child_set:
                            rows_by_child.setdefault(c, {})[key] = val
                    entries_by_child: dict[int, list] = {}
                    kept_entries = []
                    for e in (plog or {}).get("entries", []):
                        c = child_ps(e["oid"])
                        if c in child_set:
                            entries_by_child.setdefault(c, []).append(e)
                        else:
                            kept_entries.append(e)
                    for c in children:
                        child = PGid(pid, c)
                        ccid = str(child) if s < 0 else f"{child}s{s}"
                        if self.store.collection_exists(ccid):
                            continue    # idempotent (restart replay)
                        t = Transaction().create_collection(ccid)
                        t.touch(ccid, META_OID)
                        for oid in oids_by_child.get(c, ()):
                            t.coll_move(pcid, oid, ccid)
                        # snap-mapper index rows follow their objects
                        moved_rows = rows_by_child.get(c, {})
                        if moved_rows:
                            t.omap_setkeys(ccid, SNAPMAP_OID,
                                           moved_rows)
                            t.omap_rmkeys(pcid, SNAPMAP_OID,
                                          list(moved_rows))
                        # meta: child inherits the parent's history,
                        # log filtered to its objects (reference
                        # PGLog::split_out_child)
                        if pinfo is not None:
                            cinfo = dict(pinfo, pgid=str(child))
                            clog = dict(plog or {})
                            clog["entries"] = entries_by_child.get(c, [])
                            t.omap_setkeys(ccid, META_OID, {
                                "info": _json.dumps(cinfo).encode(),
                                "log": _json.dumps(clog).encode()})
                        self.store.queue_transaction(t)
                        # child peering must account for the parent's
                        # maybe-went-rw history
                        self.pg_intervals.setdefault(child, [])
                        self.pg_intervals[child][:] = [
                            dict(iv) for iv in
                            self.pg_intervals.get(parent, [])]
                    if pinfo is not None and plog is not None and \
                            len(kept_entries) != \
                            len(plog.get("entries", [])):
                        plog = dict(plog, entries=kept_entries)
                        self.store.queue_transaction(
                            Transaction().omap_setkeys(pcid, META_OID, {
                                "log": _json.dumps(plog).encode()}))
                # in-memory parent drops the moved objects' log rows
                # and missing entries (a re-homed oid must not pin the
                # parent in 'recovering' — its peers also dropped it);
                # everything else reloads naturally on advance_map
                ppg = self.pgs.get(parent)
                if ppg is not None:
                    ppg._held_cache = None
                    ppg.log.entries = [
                        e for e in ppg.log.entries
                        if child_ps(e.oid) == p_ps]
                    for moid in [o for o in ppg.missing
                                 if child_ps(o) != p_ps]:
                        ppg.missing.pop(moid, None)

    def _update_pg_intervals(self):
        """Track acting-set intervals for every PG of every pool at
        every epoch (reference PastIntervals::check_new_interval).
        ``maybe_went_rw``: the interval had a primary and at least
        min_size live members, so it COULD have accepted writes —
        peering must see a member of every such interval since
        last_epoch_started before activating, or acknowledged writes
        could be silently lost (ADVICE r2 high).

        Returns the {pgid: mapping} snapshot so _scan_pgs reuses it
        instead of recomputing every PG's CRUSH placement."""
        m = self.osdmap
        from ..crush.map import CRUSH_ITEM_NONE
        placements: dict[PGid, tuple] = {}
        for pool in m.pools.values():
            for ps in range(pool.pg_num):
                pgid = PGid(pool.id, ps)
                mapping = m.pg_to_up_acting_osds(pgid)
                placements[pgid] = mapping
                _up, _upp, acting, actingp = mapping
                open_iv = self._open_intervals.get(pgid)
                if open_iv is not None and \
                        open_iv["acting"] == acting and \
                        open_iv["primary"] == actingp:
                    continue
                if open_iv is not None and open_iv["primary"] != -1:
                    open_iv["last"] = m.epoch - 1
                    # rw additionally requires the primary to have
                    # bumped up_thru into the interval (reference
                    # check_new_interval's could_have_gone_active):
                    # a primary that was already dead never does, so
                    # its phantom intervals can't block peering
                    open_iv["maybe_went_rw"] = (
                        open_iv["maybe_went_rw"]
                        and m.up_thru(open_iv["primary"])
                        >= open_iv["first"])
                    self.pg_intervals.setdefault(pgid, []).append(open_iv)
                live = sum(1 for o in acting if o != CRUSH_ITEM_NONE)
                self._open_intervals[pgid] = {
                    "first": m.epoch, "acting": list(acting),
                    "primary": actingp,
                    "maybe_went_rw": actingp != -1
                    and live >= max(1, pool.min_size)}
        return placements

    def _scan_pgs(self, placements: dict | None = None):
        """Recompute which PGs this OSD hosts and advance each
        (reference OSD::consume_map / split into advance_pg)."""
        m = self.osdmap
        seen: set[PGid] = set()
        for pool in m.pools.values():
            for ps in range(pool.pg_num):
                pgid = PGid(pool.id, ps)
                mapping = (placements.get(pgid) if placements
                           else None) or m.pg_to_up_acting_osds(pgid)
                up, upp, acting, actingp = mapping
                if self.whoami not in acting and pgid not in self.pgs:
                    continue
                seen.add(pgid)
                pg = self.pgs.get(pgid)
                if pg is None:
                    pg = PG(self, pgid, pool)
                    pg.acting = []   # force interval change on first map
                    # share the daemon-maintained interval history (the
                    # daemon appends under the same lock the PG reads)
                    pg.past_intervals = self.pg_intervals.setdefault(
                        pgid, [])
                    self.pgs[pgid] = pg
                    # adopt whatever an earlier incarnation persisted
                    pg.primary = actingp
                    if self.whoami in acting:
                        pg.shard = acting.index(self.whoami)
                    pg.load_from_store()
                    pg.create_onstore()
                    fn = getattr(pg.backend, "snap_trim", None)
                    if fn is not None:
                        fn(None)    # reconcile missed snap removals
                pg.pool = m.pools[pool.id]
                pg.advance_map(up, upp, acting, actingp, m.epoch)
        self.perf.set("numpg", len(self.pgs))

    # -- peer plumbing -----------------------------------------------------
    def send_to_osd(self, osd: int, msg):
        if osd == self.whoami:
            # loop back through local dispatch (the reference short-
            # circuits local sub-ops the same way)
            self._route(msg)
            return
        addr_s = self.osdmap.osd_addrs.get(osd)
        if not addr_s:
            return
        cached = self._peer_cons.get(osd)
        con = None
        if cached is not None:
            cached_addr, cached_con = cached
            if cached_addr == addr_s and not cached_con._closed:
                con = cached_con
            else:
                # the peer rebooted on a new address (or the link
                # died): drop the stale connection or every message
                # queues forever against the dead incarnation
                cached_con.mark_down()
        if con is None:
            host, _, port = addr_s.rpartition(":")
            con = self.msgr.connect_to_lazy(EntityAddr(host, int(port)))
            self._peer_cons[osd] = (addr_s, con)
        try:
            con.send_message(msg)
        except ConnectionError:
            self._peer_cons.pop(osd, None)

    # -- heartbeats --------------------------------------------------------
    def _hb_peers(self) -> set[int]:
        """PG peers plus every other up OSD: the reference tops up
        heartbeat peers beyond PG membership (OSD::maybe_update_
        heartbeat_peers, osd_heartbeat_min_peers) so failures are
        detected even when the failed OSD shares no PG with a
        survivor; at mini-cluster scale that means everyone."""
        m = self.osdmap
        return {o for o in range(m.max_osd)
                if o != self.whoami and m.is_up(o)}

    def _tick(self):
        if not self.running:
            return
        # deadline backstop for the data plane: a flush whose timer
        # event was lost (or an engine configured without a schedule)
        # still drains within one tick
        self.batch_engine.maybe_flush()
        with self.lock:
            now = time.monotonic()
            # peering retransmit: queries/notifies are fire-and-forget
            # and can race a peer's map update (its reply goes to a
            # stale address); a stuck primary simply re-asks
            for pg in self.pgs.values():
                pg.check_scrub_timeout()
                self._maybe_schedule_scrub(pg)
                if pg.is_primary and pg.state in ("peering",
                                                  "incomplete"):
                    pg._start_peering()
                elif pg.is_primary and pg.state == "down" and \
                        len(pg.acting_live()) >= max(1, pg.pool.min_size):
                    pg._start_peering()
                elif pg.is_primary and pg.state == "active" and \
                        (pg.missing or pg.backfill_targets or
                         any(pg.peer_missing.values())):
                    # recovery retry: a push/pull whose reconstruct
                    # read failed transiently has no event to re-kick
                    # it — the tick is the retry engine (reference:
                    # the recovery work queue re-schedules).  Also
                    # re-deliver activation: a peer whose map advance
                    # raced it sits in 'stray' answering nothing.
                    pg._resend_activation()
                    pg._kick_recovery()
            for o in self._hb_peers():
                self._hb_last.setdefault(o, now)
                self.send_to_osd(o, M.MOSDPing(
                    from_osd=self.whoami, epoch=self.osdmap.epoch,
                    kind="ping", stamp=now))
                if (now - self._hb_last[o] > self._hb_grace
                        and self.osdmap.is_up(o)
                        and now - self._hb_reported.get(o, 0.0)
                        > self._hb_grace):
                    # RE-send while the map still shows the peer up:
                    # a report can be dropped by a mon mid-election
                    # (reference OSD::send_failures retries too)
                    self._hb_reported[o] = now
                    self.monc.send(MM.MOSDFailure(
                        target=o, reporter=self.whoami))
            if now - self._stats_last >= self._stats_interval:
                self._stats_last = now
                self._report_pg_stats()
                self._maybe_clog_health()
                self._blackbox_snap()
                self.clog.flush()
        if self.running:
            self._tick_token = self.timer.add_event_after(
                self._hb_interval, self._tick)

    def _maybe_schedule_scrub(self, pg):
        """Periodic scrub scheduling (reference OSD::sched_scrub):
        when a primary active PG's last (deep-)scrub is older than
        ``osd_scrub_interval`` / ``osd_deep_scrub_interval``, kick one
        from the tick.  0 disables an interval; a refusal (writes in
        flight etc.) just waits for the next tick.  Never-scrubbed PGs
        age from their creation stamp, so a restart doesn't stampede
        every PG at once."""
        # active+clean is the steady state a periodic scrub targets
        if not pg.is_primary or not pg.state.startswith("active") \
                or pg.scrubbing:
            return
        # operator flags gate PERIODIC scrubs only (reference
        # OSD::sched_scrub): noscrub stops shallow, nodeep-scrub stops
        # deep; an explicit `ceph pg scrub` still rides
        # MOSDScrubCommand → _start_scrub_or_retry and overrides both
        from .osdmap import CLUSTER_FLAGS
        flags = self.osdmap.flags
        noscrub = bool(flags & CLUSTER_FLAGS["noscrub"])
        nodeep = bool(flags & CLUSTER_FLAGS["nodeep-scrub"])
        now = time.time()
        floor = pg._scrub_stamp_floor
        deep_iv = float(self.config.get("osd_deep_scrub_interval"))
        if deep_iv > 0 and not nodeep and \
                now - max(pg.last_deep_scrub, floor) >= deep_iv:
            if pg.start_scrub(deep=True):
                self.perf.inc("scrubs_scheduled")
            return
        iv = float(self.config.get("osd_scrub_interval"))
        if iv > 0 and not noscrub and \
                now - max(pg.last_scrub, floor) >= iv:
            if pg.start_scrub(deep=False):
                self.perf.inc("scrubs_scheduled")

    def _maybe_clog_health(self):
        """Cluster-log the SLOW_OPS / scrub-error transitions
        (reference: OSD clog warnings feeding `ceph -w`); only edges
        are logged so a stuck op does not spam an entry per tick."""
        slow = self.op_tracker.slow_summary()
        if slow["count"] > self._slow_ops_logged:
            self.clog.warn(
                f"{slow['count']} slow requests, oldest "
                f"{slow['oldest_age']:.1f}s: {slow['oldest_desc']}")
        self._slow_ops_logged = slow["count"]
        errors = sum(pg.scrub_errors for pg in self.pgs.values()
                     if pg.is_primary)
        if errors > self._scrub_errors_logged:
            self.clog.error(
                f"scrub found {errors} inconsistencies")
        self._scrub_errors_logged = errors

    def _report_pg_stats(self):
        """Primary PGs report state/object counts to the mon (reference
        MPGStats → PGMap; caller holds the lock)."""
        stats = {}
        for pgid, pg in self.pgs.items():
            if not pg.is_primary:
                continue
            # per-PG usage is only rescanned when the PG changed since
            # the last report — the tick must not stat() every object
            # of an idle cluster over and over
            objs = pg._list_objects()
            cache = getattr(pg, "_usage_cache", None)
            # keyed on (last_update, object count): splits, recovery
            # pulls, and backfill move objects WITHOUT bumping
            # last_update, so the listing length must participate or
            # the byte count goes stale (review r3)
            key = (pg.info.last_update, len(objs))
            if cache is not None and cache[0] == key:
                nbytes, lbytes = cache[1], cache[2]
            else:
                # physical (stored) vs logical bytes: sealed objects
                # (pool compression / dedup) store fewer bytes than
                # they logically hold — `num_bytes` stays PHYSICAL so
                # capacity accounting reflects post-compression
                # reality; `num_bytes_logical` feeds the df/ratio view
                nbytes = 0
                lbytes = 0
                for o in objs:
                    phys = 0
                    try:
                        phys = self.store.stat(pg.cid, o)["size"]
                    except KeyError:
                        pass
                    nbytes += phys
                    try:
                        meta = json.loads(bytes(self.store.getattr(
                            pg.cid, o, "_")))
                        lbytes += int(meta.get("size", phys))
                    except (KeyError, ValueError):
                        lbytes += phys
                pg._usage_cache = (key, nbytes, lbytes)
            stats[str(pgid)] = {
                "state": pg.state + ("+scrubbing" if pg.scrubbing
                                     else ""),
                "num_objects": len(objs),
                "num_bytes": nbytes,
                "num_bytes_logical": lbytes,
                "log_size": len(pg.log.entries),
                "missing": len(pg.missing) + sum(
                    len(pm) for pm in pg.peer_missing.values()),
                # misplaced-work analogue: what backfill still owes —
                # the mgr progress module derives its fraction from
                # missing + backfill_remaining deltas
                "backfill_remaining": pg.backfill_remaining(),
                "last_scrub": pg.last_scrub,
                "last_deep_scrub": pg.last_deep_scrub,
                # effective stamp for PG_NOT_SCRUBBED: a never-scrubbed
                # PG counts from creation, not from the epoch
                "last_scrub_stamp": max(pg.last_scrub,
                                        pg._scrub_stamp_floor),
                "scrub_errors": pg.scrub_errors,
                "inconsistent_objects": pg.inconsistent_objects,
            }
            if pg.scrubbing:
                # chunk position of an in-flight scrub (maps gathered
                # vs. acting-set size) — the mgr progress module turns
                # this into a per-PG `pg_scrub/<pgid>` event
                stats[str(pgid)]["scrub_chunks_done"] = \
                    pg.scrub_chunks_done()
                stats[str(pgid)]["scrub_chunks_total"] = \
                    pg.scrub_chunks_total()
        if stats or self.pgs:
            # dedup chunk bytes live in the store-global "dedup"
            # collection, outside any PG — capacity accounting must
            # include them or dedup pools look free
            from ..compress import dedup as dd
            dstats = dd.dedup_stats(self.store)
            bytes_used = sum(st["num_bytes"]
                             for st in stats.values()) \
                + dstats["stored_bytes"]
            eng = self.batch_engine.stats
            self.monc.send(MM.MPGStats(
                osd=self.whoami, epoch=self.osdmap.epoch,
                pg_stats=stats,
                osd_stats={"num_pgs": len(self.pgs),
                           # non-None once the backing store failed
                           # (ENOSPC/fsync error): feeds the
                           # OSD_STORE_ERROR health check
                           "store_error": self._store_error,
                           # storage-efficiency lane aggregates: the
                           # telemetry spine differentiates these into
                           # compress/decompress/fingerprint byte
                           # rates; dedup index totals ride whole
                           "dedup": dstats,
                           "comp": {
                               "bytes_in": eng.get("comp_bytes_in", 0),
                               "bytes_out": eng.get("comp_bytes_out",
                                                    0),
                               "decompress_bytes": eng.get(
                                   "comp_decompress_bytes", 0),
                               "fingerprint_bytes": eng.get(
                                   "comp_fingerprint_bytes", 0),
                               "passthrough": eng.get(
                                   "comp_passthrough", 0)},
                           # stub capacity accounting for the
                           # OSD_NEARFULL check: primary-PG bytes vs a
                           # configured synthetic device size
                           "bytes_used": bytes_used,
                           "bytes_total": int(self.config.get(
                               "osd_stub_capacity_bytes")),
                           # cumulative client-op counters: the mgr
                           # iostat module differentiates these into
                           # IOPS (reference osd_stat_t op counters)
                           "op": self.perf.get("op"),
                           "op_w": self.perf.get("op_w"),
                           "op_r": self.perf.get("op_r"),
                           "op_in_bytes": self.perf.get("op_in_bytes"),
                           # (sum, count) so the spine can derive a
                           # windowed commit latency, not lifetime avg
                           "op_latency": {
                               "sum": self.perf._counters[
                                   "op_latency"].sum,
                               "count": self.perf._counters[
                                   "op_latency"].count},
                           # device-plane launch aggregates for the
                           # telemetry spine (dispatch/compute split,
                           # occupancy, idle gap, launch histogram)
                           "profiler": self.profiler.aggregate(),
                           # slow-op attribution: the mon's SLOW_OPS
                           # health check and the exporter gauges are
                           # fed from here (reference osd_stat_t
                           # num_slow_ops via the mgr report)
                           "slow_ops": self.op_tracker.slow_summary(),
                           # heavy-hitter sketches + slowest-op trace
                           # exemplars: the telemetry spine merges the
                           # sketches cluster-wide (`ceph osd top`)
                           # and serves `tracing exemplar` from these
                           "topk": self.topk.dump(),
                           "exemplars":
                               self._histogram_exemplars()}))

    def _histogram_exemplars(self) -> dict:
        """{counter: {bucket: {trace_id, value, ts}}} for every
        histogram counter carrying live exemplars."""
        out = {}
        for c in self.perf._counters.values():
            if c.hist is not None and c.hist.exemplars:
                out[c.name] = {str(b): dict(ex)
                               for b, ex in c.hist.exemplars.items()}
        return out

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, msg) -> bool:
        # heartbeats answer inline on the messenger thread; everything
        # else is classified into the weighted op queue
        if isinstance(msg, M.MOSDPing):
            return self._route(msg)
        klass = _SCHED_CLASS.get(type(msg))
        if klass is None:
            return False
        dmc = getattr(msg, "dmc", None)
        if klass == CLIENT and isinstance(dmc, dict):
            # distributed dmclock: per-client tags advanced by the
            # client's cross-OSD completion feedback.  Wire values
            # are untrusted JSON — anything non-numeric degrades to
            # the 1-op default instead of killing the dispatch
            try:
                delta = int(dmc.get("delta", 1))
                rho = int(dmc.get("rho", 1))
            except (TypeError, ValueError):
                delta = rho = 1
            # the tenant QoS tag (RGW auth uid) outranks the wire
            # entity as the mClock client key: isolation is
            # per-tenant, not per-connection — every connection a
            # tenant opens shares ONE set of QoS streams
            self.op_queue.enqueue(
                klass, msg,
                client=(getattr(msg, "qos_client", None)
                        or getattr(msg, "client", None)),
                delta=delta, rho=rho)
        else:
            self.op_queue.enqueue(klass, msg)
        return True

    def _route(self, msg) -> bool:
        with self.lock:
            if self._store_error is not None:
                # dead backing store: a silent heartbeat lets peers
                # report us down, and client ops fail fast with EIO
                # rather than acking writes that can never commit
                if isinstance(msg, M.MOSDPing):
                    return True
                if isinstance(msg, M.MOSDOp):
                    tracked = getattr(msg, "tracked", None)
                    if tracked is not None:
                        tracked.finish()
                    if msg.connection is not None:
                        try:
                            msg.connection.send_message(M.MOSDOpReply(
                                tid=msg.tid, rc=-5,
                                outs="objectstore error: "
                                     + self._store_error,
                                results=None, version=[0, 0],
                                epoch=self.osdmap.epoch,
                                trace=getattr(msg, "trace", None)))
                        except ConnectionError:
                            pass
                    return True
            if isinstance(msg, M.MOSDPing):
                if msg.kind == "ping":
                    if msg.connection is not None:
                        try:
                            msg.connection.send_message(M.MOSDPing(
                                from_osd=self.whoami,
                                epoch=self.osdmap.epoch,
                                kind="ping_reply", stamp=msg.stamp))
                        except ConnectionError:
                            pass
                else:
                    self._hb_last[msg.from_osd] = time.monotonic()
                    self._hb_reported.pop(msg.from_osd, None)
                return True
            if isinstance(msg, M.MOSDOp):
                self._handle_client_op(msg)
                return True
            handlers = {
                M.MOSDPGQuery: lambda pg: pg.handle_query(msg),
                M.MOSDPGNotify: lambda pg: pg.handle_notify(msg),
                M.MOSDPGLog: lambda pg: pg.handle_log(msg),
                M.MOSDPGPush: lambda pg: pg.handle_push(msg),
                M.MOSDPGPushReply: lambda pg: pg.handle_push_reply(msg),
                M.MOSDPGPull: lambda pg: pg.handle_pull(msg),
                M.MOSDRepOp: lambda pg: pg.backend.apply_rep_op(msg),
                M.MOSDRepOpReply:
                    lambda pg: pg.backend.handle_rep_reply(msg),
                M.MOSDECSubOpWrite:
                    lambda pg: pg.backend.apply_sub_write(msg),
                M.MOSDECSubOpWriteReply:
                    lambda pg: pg.backend.handle_sub_write_reply(msg),
                M.MOSDECSubOpRead:
                    lambda pg: pg.backend.handle_sub_read(msg),
                M.MOSDECSubOpReadReply:
                    lambda pg: pg.backend.handle_sub_read_reply(msg),
                M.MOSDRepScrub: lambda pg: pg.handle_rep_scrub(msg),
                M.MOSDRepScrubMap:
                    lambda pg: pg.handle_scrub_map(msg),
                M.MWatchNotifyAck:
                    lambda pg: pg.handle_notify_ack(msg),
                M.MOSDPGBackfillPrune:
                    lambda pg: pg.handle_backfill_prune(msg),
                M.MOSDScrubCommand:
                    lambda pg: self._start_scrub_or_retry(pg, msg),
            }
            fn = handlers.get(type(msg))
            if fn is None:
                return False
            pg = self._pg_for(msg)
            if pg is None and isinstance(msg, (M.MOSDPGQuery,
                                               M.MOSDPGPull,
                                               M.MOSDRepScrub)):
                # a peering primary is probing a prior-interval holder
                # that hasn't instantiated this PG (e.g. just revived,
                # no longer acting): materialize it from the store so
                # its info/objects can flow back (the reference
                # likewise answers queries for PGs it only has on disk)
                pg = self._create_stray_pg(msg.pgid)
            if pg is None:
                return True
            backend_kind = (ECBackend if isinstance(msg, (
                M.MOSDECSubOpWrite, M.MOSDECSubOpWriteReply,
                M.MOSDECSubOpRead, M.MOSDECSubOpReadReply))
                else None)
            if backend_kind and not isinstance(pg.backend, backend_kind):
                return True
            rep_kind = (ReplicatedBackend if isinstance(msg, (
                M.MOSDRepOp, M.MOSDRepOpReply)) else None)
            if rep_kind and not isinstance(pg.backend, rep_kind):
                return True
            fn(pg)
            return True

    def _create_stray_pg(self, pgid_s: str) -> PG | None:
        try:
            pgid = PGid.parse(pgid_s)
        except (ValueError, AttributeError):
            return None
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None:
            return None
        pg = PG(self, pgid, pool)
        pg.past_intervals = self.pg_intervals.setdefault(pgid, [])
        _up, _upp, acting, actingp = \
            self.osdmap.pg_to_up_acting_osds(pgid)
        pg.acting = list(acting)
        pg.primary = actingp
        pg.state = "stray"
        pg.interval_epoch = self.osdmap.epoch
        if self.whoami in acting:
            pg.shard = acting.index(self.whoami)
        elif pool.is_erasure():
            # find which shard collection an earlier incarnation left
            for s in range(pool.size):
                if self.store.collection_exists(f"{pgid}s{s}"):
                    pg.shard = s
                    break
        pg.load_from_store()
        self.pgs[pgid] = pg
        return pg

    def _pg_for(self, msg) -> PG | None:
        try:
            pgid = PGid.parse(msg.pgid)
        except (AttributeError, ValueError):
            return None
        pg = self.pgs.get(pgid)
        if pg is None:
            return None
        # discard cross-interval stragglers (the reference drops
        # messages from older intervals after comparing epochs)
        if getattr(msg, "epoch", None) is not None and \
                msg.epoch < pg.interval_epoch:
            return None
        return pg

    def _handle_client_op(self, msg: M.MOSDOp):
        # TrackedOp + counters on the op path (reference
        # OSD::ms_fast_dispatch → op_tracker.create_request)
        kinds = {op.get("op") for op in (msg.ops or [])}
        is_write = bool(kinds & _WRITE_OPS)
        self.perf.inc("op")
        self.perf.inc("op_w" if is_write else "op_r")
        if is_write:
            # payload rides as hex text: 2 chars per byte
            in_bytes = sum(
                len(op.get("data", "")) // 2 for op in (msg.ops or [])
                if op.get("op") in _WRITE_OPS)
            self.perf.inc("op_in_bytes", in_bytes)
            # stash for the reply-path attribution sketch (reads
            # account ops + latency only; write bytes are what the
            # heavy-hitter byte ranking attributes)
            msg._acct_bytes = in_bytes
        msg.tracked = self.op_tracker.create_request(
            f"osd_op({msg.client}.{msg.tid} {msg.pgid} {msg.oid} "
            f"{'+'.join(sorted(k for k in kinds if k))})")
        # adopt the client's trace ctx: every mark_event on the
        # tracked op becomes a span event, finish() closes the span
        msg.tracked.span = self.tracer.start_span(
            f"osd_op:{msg.oid}", parent=getattr(msg, "trace", None),
            tags={"layer": "osd", "pgid": msg.pgid,
                  "write": is_write})
        pg = self.pgs.get(PGid.parse(msg.pgid))
        if pg is None:
            msg.tracked.finish()
            msg.tracked = None
            try:
                msg.connection.send_message(M.MOSDOpReply(
                    tid=msg.tid, rc=-11, outs="pg not here",
                    results=None, version=[0, 0],
                    epoch=self.osdmap.epoch,
                    trace=getattr(msg, "trace", None)))
            except (ConnectionError, AttributeError):
                pass
            return
        pg.do_op(msg)

    def ms_handle_reset(self, con):
        with self.lock:
            for o, (_a, c) in list(self._peer_cons.items()):
                if c is con:
                    del self._peer_cons[o]
            for pg in self.pgs.values():
                pg.con_reset(con)


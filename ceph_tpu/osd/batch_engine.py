"""Per-OSD coalescing device data plane — kill the per-op dispatch floor.

BENCH_r05's ``dispatch_floor_ms`` is the tax every OSD op pays to
cross Python→device once: EC encode, CRC digest, parity recheck each
launch alone, so an op-mix workload runs at launch rate, not at MXU
rate.  This engine is the Python mirror of the native coalescing ring
(``native/pjrt_executor.cc``): the write stream for a tick — across
PGs and across op types — accumulates into one **megabatch** that a
single fused launch (`ops.gf_jax.GFEncodeDigest`) encodes *and*
digests, so per-shard hinfo CRCs ride the same program.

Shape discipline keeps the jit cache bounded: members are grouped by
EC code identity and bucketed by chunk length, rows and lengths both
pad to powers of two.  Zero padding is free for the GF encode
(linearity: zero columns encode to zero parity) and reversible for
the digest (`scrub.crc32c_jax.crc32c_zero_unpad` strips the pad with
two 32-bit GF(2) matrix applications) — so batched results are
**bit-identical** to the unbatched path, asserted in
tests/test_batch_engine.py and before any bench timing.

Two lanes share the machinery but accumulate separately.  The
**write lane** (PR 8) carries encode+digest for the client write
stream.  The **reconstruct lane** carries the degraded path —
degraded reads, recovery pushes, backfill pulls, and scrub parity
rechecks — grouped per (code identity, erasure pattern, size
bucket) so one fused launch reconstructs a whole sweep's worth of
objects: a single ``GFLinear`` over the plan's stacked
``[k + p, k]`` recovery matrix on CPU/1-chip, the resident
bit-plane path (``ops.gf_pallas2.ResidentPlanes``,
expand-once/multiply-many with per-matrix operands held across the
sweep) when planes are selected, or the ``parallel.reconstruct``
shard_map program over a (dp, shard) mesh.  Erased *parity* rows
ride the same launch via the plan's composed ``coding ∘ dm``
matrix (GF associativity makes the composition byte-exact).  The
lane has its own knobs (``recon_*``, defaulting to the write
lane's) and its own stats (``recon_`` prefix); each lane flush
reports to ``on_lane_flush`` so the OSD can debit the mClock
recovery reservation for the bandwidth the lane just consumed.

Flush policy (reference: the OSD op queue's batching heuristics):

- ``max_bytes`` / ``max_ops`` — size triggers, checked at submit;
- ``flush_ms`` — the accumulation deadline.  ``0`` (the default)
  means *immediate*: every submit flushes synchronously and
  completions fire before ``submit_*`` returns — CPU-only CI runs
  exactly the old one-op-at-a-time semantics, just through one code
  path.  ``> 0`` arms a timer (``schedule``) and enables the
  double-buffered flight pipeline: a flush dispatches its launches
  asynchronously and hands the flights to a completion worker that
  fences them in FIFO order while the next tick keeps staging — the
  device never idles between launches, and FIFO completion preserves
  per-PG version ordering.

Lock order (lockdep-clean by construction): submitters may hold the
daemon lock when calling ``submit_*`` (engine locks are leaves);
completion callbacks re-acquire the daemon lock but run either on
the submitter's own thread (immediate mode — RLock re-entry) or on
the completion worker with **no** engine lock held, so there is no
path that holds an engine lock while waiting on the daemon lock.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

import numpy as np


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


class Completion:
    """One submitted op's pending result.

    ``value`` for an encode op is ``(shard_chunks, hinfos)`` —
    ``{shard: bytes}`` for all k+m shards and ``{shard: crc32c}`` to
    match; for a digest op it is the ``int`` crc.  ``info`` carries
    flush attribution (rows, members, reason) for the member's span.
    """

    __slots__ = ("_ev", "value", "error", "info", "_cb")

    def __init__(self, callback=None):
        self._ev = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        self.info: dict = {}
        self._cb = callback

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._ev.wait(timeout)

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("batch op still pending")
        if self.error is not None:
            raise self.error
        return self.value

    def _fire(self, value=None, error: BaseException | None = None):
        if self._ev.is_set():
            return              # first outcome wins
        self.value = value
        self.error = error
        self._ev.set()
        if self._cb is not None:
            self._cb(self)


class _Op:
    __slots__ = ("kind", "key", "chunks", "payload", "length",
                 "nbytes", "comp", "span", "want", "passthrough")

    def __init__(self, kind, key, comp, span, length, nbytes,
                 chunks=None, payload=None, want=None,
                 passthrough=None):
        self.kind = kind            # "encode"|"digest"|"recon"|"recheck"
        self.key = key              # executable-identity group key
        self.comp = comp
        self.span = span
        self.length = length        # true (unpadded) per-row length
        self.nbytes = nbytes
        self.chunks = chunks        # encode/recheck: [k, length];
        #                             recon: survivor stack [k, length]
        self.payload = payload      # digest: bytes
        self.want = want            # recon: frozenset of wanted ids
        self.passthrough = passthrough  # recon: {id: chunk} present+wanted


class _Flight:
    """One dispatched launch awaiting its fence."""

    __slots__ = ("kind", "ops", "out", "length", "bucket", "ln",
                 "span", "reason", "plan")

    def __init__(self, kind, ops, out, length, bucket, ln, span,
                 reason, plan=None):
        self.kind = kind
        self.ops = ops
        self.out = out              # device value(s), un-fenced
        self.length = length        # bucket row length
        self.bucket = bucket        # padded row count
        self.ln = ln                # profiler launch (overlap) or None
        self.span = span
        self.reason = reason
        self.plan = plan            # recon: DecodePlan (row_of mapping)


class BatchEngine:
    """Tick-accumulating megabatch launcher for one OSD's device ops."""

    def __init__(self, name: str = "", *, enabled: bool = True,
                 max_bytes: int = 8 << 20, max_ops: int = 64,
                 flush_ms: float = 0.0, schedule=None,
                 profiler=None, tracer=None,
                 recon_enabled: bool | None = None,
                 recon_max_bytes: int | None = None,
                 recon_max_ops: int | None = None,
                 recon_flush_ms: float | None = None,
                 use_mesh: bool = False, on_lane_flush=None):
        self.name = name
        self.enabled = bool(enabled)
        self.max_bytes = int(max_bytes)
        self.max_ops = int(max_ops)
        self.flush_ms = float(flush_ms)
        # reconstruct-lane knobs default to the write lane's values
        self.recon_enabled = (self.enabled if recon_enabled is None
                              else bool(recon_enabled))
        self.recon_max_bytes = (self.max_bytes if recon_max_bytes
                                is None else int(recon_max_bytes))
        self.recon_max_ops = (self.max_ops if recon_max_ops is None
                              else int(recon_max_ops))
        self.recon_flush_ms = (self.flush_ms if recon_flush_ms is None
                               else float(recon_flush_ms))
        self.use_mesh = bool(use_mesh)
        self.use_planes: bool | None = None  # None = auto (TPU only)
        self.on_lane_flush = on_lane_flush   # (lane, ops, bytes) hook
        self._schedule = schedule   # schedule(delay_s, fn) -> token
        self.profiler = profiler
        self.tracer = tracer
        self._lock = threading.Lock()        # pending accumulator
        self._flush_lock = threading.Lock()  # serializes dispatch
        self._pending: list[_Op] = []
        self._pending_bytes = 0
        self._pending_since: float | None = None
        self._deadline_armed = False
        self._pending_recon: list[_Op] = []
        self._pending_recon_bytes = 0
        self._recon_since: float | None = None
        self._recon_armed = False
        self._fused: dict = {}               # code key → GFEncodeDigest
        self._rexec: dict = {}               # recon/recheck key → GFLinear
        self._plan_cache: dict = {}          # DecodePlan per erasure set
        self._plane_mats: dict = {}          # bit-plane matrix operands
        self._sharded: dict = {}             # code key → ShardedEC
        self._mesh = None
        self._flights: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        self._stopped = False
        self.stats = collections.Counter()

    # -- submission --------------------------------------------------------

    @staticmethod
    def _matrix_engine(ec):
        """The batchable core of an EC plugin, or None (LRC/SHEC/
        bitmatrix layers fall back to the unbatched path)."""
        from ..ec.jax_backend import MatrixECEngine
        eng = getattr(ec, "engine", None)
        return eng if isinstance(eng, MatrixECEngine) else None

    def submit_encode(self, ec, data, *, span=None,
                      callback=None) -> Completion:
        """Queue a full-stripe encode+digest; the completion's value is
        ``({shard: bytes}, {shard: crc32c})`` over all k+m shards —
        byte- and digest-identical to ``ec.encode`` + host
        ``crc32c`` per shard."""
        comp = Completion(callback)
        self.stats["ops_submitted"] += 1
        value = None
        try:
            eng = self._matrix_engine(ec)
            if eng is None or not self.enabled or self._stopped:
                value = self._encode_unbatched(ec, data)
            else:
                chunks = np.ascontiguousarray(
                    ec.encode_prepare(data), dtype=np.uint8)
                key = ("encode", eng.k, eng.m, eng.coding.tobytes())
                op = _Op("encode", key, comp, span,
                         length=int(chunks.shape[1]),
                         nbytes=int(chunks.nbytes), chunks=chunks)
                self._enqueue(op)
                return comp
        except Exception as e:      # noqa: BLE001 — poisoned payloads
            self.stats["ops_failed"] += 1   # fail their own op only
            comp._fire(error=e)
            return comp
        # fire outside the try: a callback raising must surface to the
        # submitter, not masquerade as an encode failure
        comp._fire(value=value)
        return comp

    def submit_digest(self, payload, *, span=None,
                      callback=None) -> Completion:
        """Queue a CRC-32C digest; completion value is the int crc."""
        comp = Completion(callback)
        self.stats["ops_submitted"] += 1
        try:
            buf = bytes(payload)
            if self.enabled and not self._stopped and buf:
                op = _Op("digest", ("digest",), comp, span,
                         length=len(buf), nbytes=len(buf),
                         payload=buf)
                self._enqueue(op)
                return comp
            from ..scrub.crc32c_jax import crc32c
            value = crc32c(buf)
        except Exception as e:      # noqa: BLE001
            self.stats["ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    @staticmethod
    def _encode_unbatched(ec, data):
        """The exact pre-engine semantics: whole-stripe encode, then
        host CRC per shard — the bit-identity reference."""
        from ..scrub.crc32c_jax import crc32c
        n = ec.k + ec.m
        out = ec.encode(set(range(n)), data)
        shard_chunks = {i: bytes(np.asarray(out[i]).tobytes())
                        for i in range(n)}
        hinfos = {i: crc32c(shard_chunks[i]) for i in range(n)}
        return shard_chunks, hinfos

    # -- reconstruct lane --------------------------------------------------

    def submit_reconstruct(self, ec, chunks, *, want=None, span=None,
                           callback=None) -> Completion:
        """Queue a degraded decode; the completion's value is
        ``{chunk_id: uint8 array}`` for every wanted id — byte-identical
        to ``ec.decode(want, chunks)``.

        ``want`` defaults to the k data ids (the client-read case).
        When every wanted id is already present the op completes
        synchronously with no device work (the systematic fast path,
        mirroring ``ErasureCode.decode``'s early-out); otherwise ops
        group per (code identity, erasure pattern, size bucket) and
        one fused launch recovers the whole group."""
        comp = Completion(callback)
        self.stats["recon_ops_submitted"] += 1
        value = None
        try:
            from ..ec.interface import ECError
            present = {int(i): np.asarray(c, dtype=np.uint8)
                       for i, c in chunks.items()}
            if not present:
                raise ECError("no chunks to decode from")
            want_ids = frozenset(
                int(i) for i in (want if want is not None
                                 else range(ec.k)))
            if want_ids <= present.keys():
                # systematic fast path: nothing to reconstruct
                self.stats["recon_fast_path"] += 1
                value = {i: present[i] for i in want_ids}
            else:
                eng = self._matrix_engine(ec)
                if (eng is None or not self.enabled
                        or not self.recon_enabled or self._stopped):
                    value = self._reconstruct_unbatched(
                        ec, want_ids, chunks)
                else:
                    sizes = {c.size for c in present.values()}
                    if len(sizes) != 1:
                        raise ECError("chunk sizes differ")
                    if len(present) < eng.k:
                        raise ECError(
                            f"{len(present)} chunks < k={eng.k}")
                    erasures = tuple(i for i in range(eng.k + eng.m)
                                     if i not in present)
                    avail = sorted(present)
                    surv = np.ascontiguousarray(np.stack(
                        [present[i] for i in avail[:eng.k]]))
                    op = _Op("recon",
                             ("recon", eng.k, eng.m,
                              eng.coding.tobytes(), erasures),
                             comp, span, length=int(surv.shape[1]),
                             nbytes=int(surv.nbytes), chunks=surv,
                             want=want_ids,
                             passthrough={i: present[i]
                                          for i in want_ids
                                          if i in present})
                    self._enqueue(op, lane="recon")
                    return comp
        except Exception as e:      # noqa: BLE001 — poisoned payloads
            self.stats["recon_ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    def submit_recheck(self, ec, data, *, span=None,
                       callback=None) -> Completion:
        """Queue a scrub parity re-encode; completion value is the
        ``[m, length]`` parity array, byte-identical to
        ``np.asarray(ec._encode_chunks(data))`` — so deep-scrub parity
        rechecks coalesce with recovery reconstructs instead of
        launching standalone."""
        comp = Completion(callback)
        self.stats["recon_ops_submitted"] += 1
        try:
            eng = self._matrix_engine(ec)
            arr = np.ascontiguousarray(data, dtype=np.uint8)
            if (eng is None or not self.enabled
                    or not self.recon_enabled or self._stopped):
                value = np.asarray(ec._encode_chunks(arr))
            else:
                op = _Op("recheck",
                         ("recheck", eng.k, eng.m,
                          eng.coding.tobytes()),
                         comp, span, length=int(arr.shape[1]),
                         nbytes=int(arr.nbytes), chunks=arr)
                self._enqueue(op, lane="recon")
                return comp
        except Exception as e:      # noqa: BLE001
            self.stats["recon_ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    @staticmethod
    def _reconstruct_unbatched(ec, want, chunks):
        """The exact pre-lane semantics — the bit-identity reference."""
        return ec.decode(set(want), chunks)

    def _enqueue(self, op: _Op, lane: str = "write"):
        arm = False
        fire = None
        recon = lane == "recon"
        max_ops = self.recon_max_ops if recon else self.max_ops
        max_bytes = self.recon_max_bytes if recon else self.max_bytes
        flush_ms = self.recon_flush_ms if recon else self.flush_ms
        with self._lock:
            if recon:
                self._pending_recon.append(op)
                self._pending_recon_bytes += op.nbytes
                if self._recon_since is None:
                    self._recon_since = time.monotonic()
                n, nbytes = (len(self._pending_recon),
                             self._pending_recon_bytes)
                armed = self._recon_armed
            else:
                self._pending.append(op)
                self._pending_bytes += op.nbytes
                if self._pending_since is None:
                    self._pending_since = time.monotonic()
                n, nbytes = len(self._pending), self._pending_bytes
                armed = self._deadline_armed
            if n >= max_ops:
                fire = "max_ops"
            elif nbytes >= max_bytes:
                fire = "max_bytes"
            elif flush_ms <= 0:
                fire = "immediate"
            elif not armed and self._schedule is not None:
                if recon:
                    self._recon_armed = True
                else:
                    self._deadline_armed = True
                arm = True
        if fire is not None:
            self.flush(reason=fire, lane=lane)
        elif arm:
            self._schedule(flush_ms / 1000.0,
                           self._on_recon_deadline if recon
                           else self._on_deadline)

    def _on_deadline(self):
        self.flush(reason="deadline", lane="write")

    def _on_recon_deadline(self):
        self.flush(reason="deadline", lane="recon")

    def maybe_flush(self) -> bool:
        """Tick backstop: flush any lane whose oldest pending op has
        waited past its deadline window (covers a lost/absent timer)."""
        now = time.monotonic()
        with self._lock:
            w = (bool(self._pending)
                 and self._pending_since is not None
                 and (now - self._pending_since) * 1000.0
                 >= self.flush_ms)
            r = (bool(self._pending_recon)
                 and self._recon_since is not None
                 and (now - self._recon_since) * 1000.0
                 >= self.recon_flush_ms)
        if w:
            self.flush(reason="deadline", lane="write")
        if r:
            self.flush(reason="deadline", lane="recon")
        return w or r

    # -- flush / dispatch --------------------------------------------------

    def flush(self, reason: str = "manual", lane: str | None = None
              ) -> int:
        """Dispatch everything pending as megabatch launches.  In
        immediate mode the flights complete inline (after all engine
        locks drop); in batched mode they go to the FIFO completion
        worker so the next tick stages while these fence.  ``lane``
        limits the flush to one lane; default flushes both."""
        lanes = ("write", "recon") if lane is None else (lane,)
        return sum(self._flush_lane(ln, reason) for ln in lanes)

    def flush_sync(self, lane: str, reason: str = "manual") -> int:
        """Dispatch and complete a lane's pending inline on the
        calling thread, bypassing the completion worker.  For
        submitters that must consume results synchronously while
        possibly holding the daemon lock (deep-scrub parity recheck):
        inline completion re-enters that lock on the caller's own
        thread (RLock), so the caller never waits behind worker-queue
        flights whose callbacks need the lock it holds."""
        return self._flush_lane(lane, reason, force_inline=True)

    def _flush_lane(self, lane: str, reason: str,
                    force_inline: bool = False) -> int:
        inline: list[_Flight] = []
        recon = lane == "recon"
        n = 0
        with self._flush_lock:
            with self._lock:
                if recon:
                    pending = self._pending_recon
                    self._pending_recon = []
                    staged = self._pending_recon_bytes
                    self._pending_recon_bytes = 0
                    self._recon_since = None
                    self._recon_armed = False
                    ms = self.recon_flush_ms
                else:
                    pending, self._pending = self._pending, []
                    staged = self._pending_bytes
                    self._pending_bytes = 0
                    self._pending_since = None
                    self._deadline_armed = False
                    ms = self.flush_ms
                use_worker = (ms > 0 and not self._stopped
                              and not force_inline)
            if not pending:
                return 0
            prefix = "recon_" if recon else ""
            self.stats[f"{prefix}flush_{reason}"] += 1
            flights = self._dispatch(pending, reason, lane)
            n = len(flights)
            for fl in flights:
                if use_worker:
                    self._ensure_worker()
                    self._flights.put(fl)
                else:
                    inline.append(fl)
        for fl in inline:
            self._complete(fl)
        if self.on_lane_flush is not None:
            try:
                self.on_lane_flush(lane, len(pending), staged)
            except Exception:       # noqa: BLE001 — accounting hook
                self.stats["callback_errors"] += 1
        return n

    def drain(self):
        """Flush and wait until every in-flight completion has fired
        (shutdown / test barrier)."""
        self.flush(reason="drain")
        self._flights.join()

    def stop(self):
        """Drain, then retire the completion worker.  Later submits
        degrade to the synchronous unbatched path."""
        self._stopped = True
        self.drain()
        w = self._worker
        if w is not None:
            self._flights.put(None)
            w.join(timeout=5.0)
            self._worker = None

    def _ensure_worker(self):
        w = self._worker
        if w is not None and w.is_alive():
            return
        w = threading.Thread(target=self._worker_loop,
                             name=f"batch-{self.name}", daemon=True)
        self._worker = w
        w.start()

    def _worker_loop(self):
        while True:
            fl = self._flights.get()
            try:
                if fl is None:
                    return
                self._complete(fl)
            finally:
                self._flights.task_done()

    def _groups(self, pending):
        groups: dict = {}
        for op in pending:
            bucket_len = _next_pow2(max(op.length, 32))
            groups.setdefault((op.key, bucket_len), []).append(op)
        return groups

    def _dispatch(self, pending, reason, lane="write") -> list[_Flight]:
        flights = []
        launches_key = "recon_launches" if lane == "recon" else "launches"
        for (key, bucket_len), ops in self._groups(pending).items():
            rows = _next_pow2(len(ops))
            span = None
            if self.tracer is not None:
                span = self.tracer.start_span(
                    "megabatch_flush", tags={
                        "layer": "device", "kernel": "megabatch",
                        "op": key[0], "lane": lane,
                        "members": len(ops),
                        "rows": rows, "row_len": bucket_len,
                        "reason": reason})
                if span is not None:
                    for op in ops:
                        if op.span is not None:
                            span.add_link(op.span)
            try:
                if key[0] == "encode":
                    fl = self._launch_encode(key, ops, rows,
                                             bucket_len, span, reason)
                elif key[0] == "digest":
                    fl = self._launch_digest(ops, rows, bucket_len,
                                             span, reason)
                elif key[0] == "recon":
                    fl = self._launch_reconstruct(
                        key, ops, rows, bucket_len, span, reason)
                else:
                    fl = self._launch_recheck(key, ops, rows,
                                              bucket_len, span, reason)
            except Exception as e:  # noqa: BLE001 — one group's
                # launch failure must not kill sibling groups
                self._fail_group(ops, e, span)
                continue
            flights.append(fl)
            self.stats[launches_key] += 1
        return flights

    def _prof_start(self, ops, rows, staged_bytes, reason, op_kind,
                    cache_hit, lane="write"):
        if self.profiler is None:
            return None
        return self.profiler.start(
            "megabatch", bytes_in=staged_bytes,
            bytes_used=sum(o.nbytes for o in ops),
            rows=rows, rows_used=len(ops), overlap=True,
            members=len(ops), reason=reason, op=op_kind,
            cache_hit=cache_hit, lane=lane)

    def _launch_encode(self, key, ops, rows, bucket_len, span,
                       reason) -> _Flight:
        from ..ops.gf_jax import GFEncodeDigest
        _kind, k, m, mat = key
        fused = self._fused.get(key)
        if fused is None:
            fused = self._fused[key] = GFEncodeDigest(
                np.frombuffer(mat, dtype=np.uint8).reshape(m, k))
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        shape = (rows, k, bucket_len)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "encode", fused.export_hits.get(shape,
                                                              False))
        try:
            out = fused(batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("encode", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_digest(self, ops, rows, bucket_len, span,
                       reason) -> _Flight:
        import jax.numpy as jnp
        from ..scrub.crc32c_jax import _batch_kernel
        batch = np.zeros((rows, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :op.length] = np.frombuffer(op.payload, np.uint8)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "digest", True)
        try:
            out = _batch_kernel(bucket_len)(
                jnp.asarray(batch), jnp.zeros(rows, jnp.uint32))
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("digest", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_reconstruct(self, key, ops, rows, bucket_len, span,
                            reason) -> _Flight:
        from ..parallel.reconstruct import decode_plan
        _kind, k, m, mat, erasures = key
        coding = np.frombuffer(mat, dtype=np.uint8).reshape(m, k)
        plan = decode_plan(coding, k, m, erasures,
                           cache=self._plan_cache)
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "recon", key in self._rexec,
                              lane="recon")
        try:
            out = self._run_reconstruct(key, plan, batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("recon", ops, out, bucket_len, rows, ln, span,
                       reason, plan=plan)

    def _run_reconstruct(self, key, plan, batch):
        """Pick the reconstruct strategy for one fused group:

        - mesh (``use_mesh`` and >1 device): the shard_map program of
          ``parallel.reconstruct.ShardedEC`` — survivor rows scattered
          to their chunk-id positions, batch padded to a dp multiple.
          Only for pure-data erasure patterns (the common recovery
          case); composed parity rows stay on the fused path.
        - resident planes (``use_planes``, auto on TPU): expand the
          survivor batch to bit planes once, multiply by the plan's
          stacked matrix — per-matrix operands persist in
          ``_plane_mats`` across the whole sweep.
        - default: one cached ``GFLinear`` over the plan's fused
          ``[k + p, k]`` matrix — a single launch per group.
        """
        import jax
        if (self.use_mesh and plan.parity_matrix is None
                and len(jax.devices()) > 1):
            return self._run_mesh(key, plan, batch)
        planes = (self.use_planes if self.use_planes is not None
                  else jax.default_backend() == "tpu")
        if planes:
            from ..ops.gf_pallas2 import ResidentPlanes
            rp = ResidentPlanes(
                batch, interpret=jax.default_backend() != "tpu",
                mats=self._plane_mats)
            return rp.multiply(plan.matrix)
        prog = self._rexec.get(key)
        if prog is None:
            from ..ops.gf_jax import GFLinear
            prog = self._rexec[key] = GFLinear(plan.matrix)
        return prog(batch)

    def _run_mesh(self, key, plan, batch):
        from ..parallel.mesh import make_mesh
        from ..parallel.reconstruct import ShardedEC
        code_key = key[:4]
        sh = self._sharded.get(code_key)
        if sh is None:
            if self._mesh is None:
                self._mesh = make_mesh()
            _kind, k, m, mat = code_key
            coding = np.frombuffer(mat, dtype=np.uint8).reshape(m, k)
            # byte payloads in, byte payloads out: word_native stays
            # off so host staging needs no dtype views
            sh = self._sharded[code_key] = ShardedEC(
                coding, k, m, self._mesh, word_native=False)
        rows, _k, length = batch.shape
        dp = sh.mesh.shape["dp"]
        b_pad = -(-rows // dp) * dp
        full = np.zeros((b_pad, sh.n_pad, length), dtype=np.uint8)
        for r, sid in enumerate(plan.survivors):
            full[:rows, sid] = batch[:, r]
        out = sh.reconstruct(full, plan.erasures)
        return out[:rows]

    def _launch_recheck(self, key, ops, rows, bucket_len, span,
                        reason) -> _Flight:
        _kind, k, m, mat = key
        cache_hit = key in self._rexec
        prog = self._rexec.get(key)
        if prog is None:
            from ..ops.gf_jax import GFLinear
            prog = self._rexec[key] = GFLinear(
                np.frombuffer(mat, dtype=np.uint8).reshape(m, k))
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "recheck", cache_hit, lane="recon")
        try:
            out = prog(batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("recheck", ops, out, bucket_len, rows, ln, span,
                       reason)

    # -- completion --------------------------------------------------------

    def _complete(self, fl: _Flight):
        from ..scrub.crc32c_jax import crc32c_zero_unpad
        parity = crcs = rec = None
        try:
            if fl.kind == "encode":
                parity = np.asarray(fl.out[0])
                crcs = np.asarray(fl.out[1])
                bytes_out = int(parity.nbytes) + int(crcs.nbytes)
            elif fl.kind == "digest":
                crcs = np.asarray(fl.out)
                bytes_out = int(crcs.nbytes)
            else:               # recon | recheck
                rec = np.asarray(fl.out)
                bytes_out = int(rec.nbytes)
        except Exception as e:      # noqa: BLE001 — launch died at the
            if fl.ln is not None:   # fence: fail every member
                fl.ln.abort()
            self._fail_group(fl.ops, e, fl.span)
            return
        if fl.ln is not None:
            fl.ln.finish(bytes_out=bytes_out)
        if fl.span is not None:
            fl.span.finish()
        info = {"rows": fl.bucket, "members": len(fl.ops),
                "row_len": fl.length, "reason": fl.reason}
        if rec is not None:
            info["lane"] = "recon"
            plan = fl.plan
            for i, op in enumerate(fl.ops):
                try:
                    if fl.kind == "recheck":
                        value = np.ascontiguousarray(
                            rec[i, :, :op.length])
                    else:
                        value = {
                            cid: (op.passthrough[cid]
                                  if cid in op.passthrough else
                                  np.ascontiguousarray(
                                      rec[i, plan.row_of[cid],
                                          :op.length]))
                            for cid in op.want}
                    op.comp.info = info
                    op.comp._fire(value=value)
                    self.stats["recon_ops_completed"] += 1
                except Exception:   # noqa: BLE001 — a member's
                    # callback blowing up must not starve its siblings
                    self.stats["callback_errors"] += 1
            return
        for i, op in enumerate(fl.ops):
            pad = fl.length - op.length
            try:
                if fl.kind == "encode":
                    k = op.chunks.shape[0]
                    m = parity.shape[1]
                    shard_chunks = {j: op.chunks[j].tobytes()
                                    for j in range(k)}
                    for j in range(m):
                        shard_chunks[k + j] = \
                            parity[i, j, :op.length].tobytes()
                    hinfos = {s: crc32c_zero_unpad(int(crcs[i, s]),
                                                   pad)
                              for s in range(k + m)}
                    value = (shard_chunks, hinfos)
                else:
                    value = crc32c_zero_unpad(int(crcs[i]), pad)
                op.comp.info = info
                op.comp._fire(value=value)
                self.stats["ops_completed"] += 1
            except Exception:       # noqa: BLE001 — a member's
                # callback blowing up must not starve its siblings
                self.stats["callback_errors"] += 1

    def _fail_group(self, ops, err, span):
        if span is not None:
            span.set_tag("error", repr(err))
            span.finish()
        for op in ops:
            self.stats["recon_ops_failed"
                       if op.kind in ("recon", "recheck")
                       else "ops_failed"] += 1
            try:
                op.comp._fire(error=err)
            except Exception:       # noqa: BLE001
                self.stats["callback_errors"] += 1

    # -- introspection -----------------------------------------------------

    def dump(self) -> dict:
        with self._lock:
            pending = len(self._pending)
            pending_bytes = self._pending_bytes
            rpending = len(self._pending_recon)
            rpending_bytes = self._pending_recon_bytes
        d = dict(self.stats)
        d.update(enabled=self.enabled, flush_ms=self.flush_ms,
                 max_bytes=self.max_bytes, max_ops=self.max_ops,
                 pending_ops=pending, pending_bytes=pending_bytes,
                 recon_enabled=self.recon_enabled,
                 recon_flush_ms=self.recon_flush_ms,
                 recon_max_bytes=self.recon_max_bytes,
                 recon_max_ops=self.recon_max_ops,
                 recon_pending_ops=rpending,
                 recon_pending_bytes=rpending_bytes,
                 recon_use_mesh=self.use_mesh,
                 recon_plans=len(self._plan_cache),
                 inflight=self._flights.unfinished_tasks)
        return d

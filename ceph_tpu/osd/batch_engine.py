"""Per-OSD coalescing device data plane — kill the per-op dispatch floor.

BENCH_r05's ``dispatch_floor_ms`` is the tax every OSD op pays to
cross Python→device once: EC encode, CRC digest, parity recheck each
launch alone, so an op-mix workload runs at launch rate, not at MXU
rate.  This engine is the Python mirror of the native coalescing ring
(``native/pjrt_executor.cc``): the write stream for a tick — across
PGs and across op types — accumulates into one **megabatch** that a
single fused launch (`ops.gf_jax.GFEncodeDigest`) encodes *and*
digests, so per-shard hinfo CRCs ride the same program.

Shape discipline keeps the jit cache bounded: members are grouped by
EC code identity and bucketed by chunk length, rows and lengths both
pad to powers of two.  Zero padding is free for the GF encode
(linearity: zero columns encode to zero parity) and reversible for
the digest (`scrub.crc32c_jax.crc32c_zero_unpad` strips the pad with
two 32-bit GF(2) matrix applications) — so batched results are
**bit-identical** to the unbatched path, asserted in
tests/test_batch_engine.py and before any bench timing.

Three lanes share the machinery but accumulate separately.  The
**write lane** (PR 8) carries encode+digest for the client write
stream.  The **compression lane** carries the storage-efficiency
pre-pass of the write path: per-pool inline compression (the codec's
device scan runs once over the whole size-bucketed megabatch, see
``compress/codec.py``) and dedup fingerprinting (gear-hash CDC
boundaries as one jitted launch + one batched CRC-32C launch per
flush, ``compress/chunker.py``), with its own knobs
(``osd_compress_batch_*`` → ``comp_*``, defaulting to the write
lane's) and stats (``comp_`` prefix).  Oversized payloads split into
fixed segments that batch *across* objects — the streaming segment
path.  The **reconstruct lane** carries the degraded path —
degraded reads, recovery pushes, backfill pulls, and scrub parity
rechecks — grouped per (code identity, erasure pattern, size
bucket) so one fused launch reconstructs a whole sweep's worth of
objects: a single ``GFLinear`` over the plan's stacked
``[k + p, k]`` recovery matrix on CPU/1-chip, the resident
bit-plane path (``ops.gf_pallas2.ResidentPlanes``,
expand-once/multiply-many with per-matrix operands held across the
sweep) when planes are selected, or the ``parallel.reconstruct``
shard_map program over a (dp, shard) mesh.  Erased *parity* rows
ride the same launch via the plan's composed ``coding ∘ dm``
matrix (GF associativity makes the composition byte-exact).  The
lane has its own knobs (``recon_*``, defaulting to the write
lane's) and its own stats (``recon_`` prefix); each lane flush
reports to ``on_lane_flush`` so the OSD can debit the mClock
recovery reservation for the bandwidth the lane just consumed.

Flush policy (reference: the OSD op queue's batching heuristics):

- ``max_bytes`` / ``max_ops`` — size triggers, checked at submit;
- ``flush_ms`` — the accumulation deadline.  ``0`` (the default)
  means *immediate*: every submit flushes synchronously and
  completions fire before ``submit_*`` returns — CPU-only CI runs
  exactly the old one-op-at-a-time semantics, just through one code
  path.  ``> 0`` arms a timer (``schedule``) and enables the
  double-buffered flight pipeline: a flush dispatches its launches
  asynchronously and hands the flights to a completion worker that
  fences them in FIFO order while the next tick keeps staging — the
  device never idles between launches, and FIFO completion preserves
  per-PG version ordering.

Lock order (lockdep-clean by construction): submitters may hold the
daemon lock when calling ``submit_*`` (engine locks are leaves);
completion callbacks re-acquire the daemon lock but run either on
the submitter's own thread (immediate mode — RLock re-entry) or on
the completion worker with **no** engine lock held, so there is no
path that holds an engine lock while waiting on the daemon lock.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

import numpy as np


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


class Completion:
    """One submitted op's pending result.

    ``value`` for an encode op is ``(shard_chunks, hinfos)`` —
    ``{shard: bytes}`` for all k+m shards and ``{shard: crc32c}`` to
    match; for a digest op it is the ``int`` crc.  ``info`` carries
    flush attribution (rows, members, reason) for the member's span.
    """

    __slots__ = ("_ev", "value", "error", "info", "_cb")

    def __init__(self, callback=None):
        self._ev = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        self.info: dict = {}
        self._cb = callback

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._ev.wait(timeout)

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("batch op still pending")
        if self.error is not None:
            raise self.error
        return self.value

    def _fire(self, value=None, error: BaseException | None = None):
        if self._ev.is_set():
            return              # first outcome wins
        self.value = value
        self.error = error
        self._ev.set()
        if self._cb is not None:
            self._cb(self)


class _Op:
    __slots__ = ("kind", "key", "chunks", "payload", "length",
                 "nbytes", "comp", "span", "want", "passthrough",
                 "codec", "mode", "chunker")

    def __init__(self, kind, key, comp, span, length, nbytes,
                 chunks=None, payload=None, want=None,
                 passthrough=None, codec=None, mode=None,
                 chunker=None):
        self.kind = kind            # "encode"|"digest"|"recon"|
        #                             "recheck"|"compress"|"fingerprint"
        self.key = key              # executable-identity group key
        self.comp = comp
        self.span = span
        self.length = length        # true (unpadded) per-row length
        self.nbytes = nbytes
        self.chunks = chunks        # encode/recheck: [k, length];
        #                             recon: survivor stack [k, length]
        self.payload = payload      # digest/compress/fingerprint: bytes
        self.want = want            # recon: frozenset of wanted ids
        self.passthrough = passthrough  # recon: {id: chunk} present+wanted
        self.codec = codec          # compress: Codec instance
        self.mode = mode            # compress: pool compression_mode
        self.chunker = chunker      # fingerprint: Chunker instance


class _Flight:
    """One dispatched launch awaiting its fence."""

    __slots__ = ("kind", "ops", "out", "length", "bucket", "ln",
                 "span", "reason", "plan")

    def __init__(self, kind, ops, out, length, bucket, ln, span,
                 reason, plan=None):
        self.kind = kind
        self.ops = ops
        self.out = out              # device value(s), un-fenced
        self.length = length        # bucket row length
        self.bucket = bucket        # padded row count
        self.ln = ln                # profiler launch (overlap) or None
        self.span = span
        self.reason = reason
        self.plan = plan            # recon: DecodePlan (row_of mapping)


class BatchEngine:
    """Tick-accumulating megabatch launcher for one OSD's device ops."""

    def __init__(self, name: str = "", *, enabled: bool = True,
                 max_bytes: int = 8 << 20, max_ops: int = 64,
                 flush_ms: float = 0.0, schedule=None,
                 profiler=None, tracer=None,
                 recon_enabled: bool | None = None,
                 recon_max_bytes: int | None = None,
                 recon_max_ops: int | None = None,
                 recon_flush_ms: float | None = None,
                 comp_enabled: bool | None = None,
                 comp_max_bytes: int | None = None,
                 comp_max_ops: int | None = None,
                 comp_flush_ms: float | None = None,
                 comp_segment_bytes: int = 1 << 20,
                 bucket_floor: int = 32,
                 use_mesh: bool = False, on_lane_flush=None,
                 store_kick=None):
        self.name = name
        self.enabled = bool(enabled)
        self.max_bytes = int(max_bytes)
        self.max_ops = int(max_ops)
        self.flush_ms = float(flush_ms)
        # reconstruct-lane knobs default to the write lane's values
        self.recon_enabled = (self.enabled if recon_enabled is None
                              else bool(recon_enabled))
        self.recon_max_bytes = (self.max_bytes if recon_max_bytes
                                is None else int(recon_max_bytes))
        self.recon_max_ops = (self.max_ops if recon_max_ops is None
                              else int(recon_max_ops))
        self.recon_flush_ms = (self.flush_ms if recon_flush_ms is None
                               else float(recon_flush_ms))
        # compression-lane knobs default to the write lane's values
        self.comp_enabled = (self.enabled if comp_enabled is None
                             else bool(comp_enabled))
        self.comp_max_bytes = (self.max_bytes if comp_max_bytes
                               is None else int(comp_max_bytes))
        self.comp_max_ops = (self.max_ops if comp_max_ops is None
                             else int(comp_max_ops))
        self.comp_flush_ms = (self.flush_ms if comp_flush_ms is None
                              else float(comp_flush_ms))
        self.comp_segment_bytes = int(comp_segment_bytes)
        self.bucket_floor = int(bucket_floor)
        self.use_mesh = bool(use_mesh)
        self.use_planes: bool | None = None  # None = auto (TPU only)
        self.on_lane_flush = on_lane_flush   # (lane, ops, bytes) hook
        # zero-arg durability nudge (WALStore.kick): one group-commit
        # fsync per megabatch flush instead of one per op
        self.store_kick = store_kick
        self._schedule = schedule   # schedule(delay_s, fn) -> token
        self.profiler = profiler
        self.tracer = tracer
        self._lock = threading.Lock()        # pending accumulator
        self._flush_lock = threading.Lock()  # serializes dispatch
        self._pending: list[_Op] = []
        self._pending_bytes = 0
        self._pending_since: float | None = None
        self._deadline_armed = False
        self._pending_recon: list[_Op] = []
        self._pending_recon_bytes = 0
        self._recon_since: float | None = None
        self._recon_armed = False
        self._pending_comp: list[_Op] = []
        self._pending_comp_bytes = 0
        self._comp_since: float | None = None
        self._comp_armed = False
        self._fused: dict = {}               # code key → GFEncodeDigest
        self._rexec: dict = {}               # recon/recheck key → GFLinear
        self._plan_cache: dict = {}          # DecodePlan per erasure set
        self._plane_mats: dict = {}          # bit-plane matrix operands
        self._sharded: dict = {}             # code key → ShardedEC
        self._mesh = None
        self._mesh_devs: tuple[str, ...] | None = None
        self._flights: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        self._stopped = False
        self.stats = collections.Counter()

    # -- submission --------------------------------------------------------

    @staticmethod
    def _matrix_engine(ec):
        """The batchable core of an EC plugin, or None (LRC/SHEC/
        bitmatrix layers fall back to the unbatched path)."""
        from ..ec.jax_backend import MatrixECEngine
        eng = getattr(ec, "engine", None)
        return eng if isinstance(eng, MatrixECEngine) else None

    def submit_encode(self, ec, data, *, span=None,
                      callback=None) -> Completion:
        """Queue a full-stripe encode+digest; the completion's value is
        ``({shard: bytes}, {shard: crc32c})`` over all k+m shards —
        byte- and digest-identical to ``ec.encode`` + host
        ``crc32c`` per shard."""
        comp = Completion(callback)
        self.stats["ops_submitted"] += 1
        value = None
        try:
            eng = self._matrix_engine(ec)
            if eng is None or not self.enabled or self._stopped:
                value = self._encode_unbatched(ec, data)
            else:
                chunks = np.ascontiguousarray(
                    ec.encode_prepare(data), dtype=np.uint8)
                key = ("encode", eng.k, eng.m, eng.coding.tobytes())
                op = _Op("encode", key, comp, span,
                         length=int(chunks.shape[1]),
                         nbytes=int(chunks.nbytes), chunks=chunks)
                self._enqueue(op)
                return comp
        except Exception as e:      # noqa: BLE001 — poisoned payloads
            self.stats["ops_failed"] += 1   # fail their own op only
            comp._fire(error=e)
            return comp
        # fire outside the try: a callback raising must surface to the
        # submitter, not masquerade as an encode failure
        comp._fire(value=value)
        return comp

    def submit_digest(self, payload, *, span=None,
                      callback=None) -> Completion:
        """Queue a CRC-32C digest; completion value is the int crc."""
        comp = Completion(callback)
        self.stats["ops_submitted"] += 1
        try:
            buf = bytes(payload)
            if self.enabled and not self._stopped and buf:
                op = _Op("digest", ("digest",), comp, span,
                         length=len(buf), nbytes=len(buf),
                         payload=buf)
                self._enqueue(op)
                return comp
            from ..scrub.crc32c_jax import crc32c
            value = crc32c(buf)
        except Exception as e:      # noqa: BLE001
            self.stats["ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    @staticmethod
    def _encode_unbatched(ec, data):
        """The exact pre-engine semantics: whole-stripe encode, then
        host CRC per shard — the bit-identity reference."""
        from ..scrub.crc32c_jax import crc32c
        n = ec.k + ec.m
        out = ec.encode(set(range(n)), data)
        shard_chunks = {i: bytes(np.asarray(out[i]).tobytes())
                        for i in range(n)}
        hinfos = {i: crc32c(shard_chunks[i]) for i in range(n)}
        return shard_chunks, hinfos

    # -- reconstruct lane --------------------------------------------------

    def submit_reconstruct(self, ec, chunks, *, want=None, span=None,
                           callback=None) -> Completion:
        """Queue a degraded decode; the completion's value is
        ``{chunk_id: uint8 array}`` for every wanted id — byte-identical
        to ``ec.decode(want, chunks)``.

        ``want`` defaults to the k data ids (the client-read case).
        When every wanted id is already present the op completes
        synchronously with no device work (the systematic fast path,
        mirroring ``ErasureCode.decode``'s early-out); otherwise ops
        group per (code identity, erasure pattern, size bucket) and
        one fused launch recovers the whole group."""
        comp = Completion(callback)
        self.stats["recon_ops_submitted"] += 1
        value = None
        try:
            from ..ec.interface import ECError
            present = {int(i): np.asarray(c, dtype=np.uint8)
                       for i, c in chunks.items()}
            if not present:
                raise ECError("no chunks to decode from")
            want_ids = frozenset(
                int(i) for i in (want if want is not None
                                 else range(ec.k)))
            if want_ids <= present.keys():
                # systematic fast path: nothing to reconstruct
                self.stats["recon_fast_path"] += 1
                value = {i: present[i] for i in want_ids}
            else:
                eng = self._matrix_engine(ec)
                if (eng is None or not self.enabled
                        or not self.recon_enabled or self._stopped):
                    value = self._reconstruct_unbatched(
                        ec, want_ids, chunks)
                else:
                    sizes = {c.size for c in present.values()}
                    if len(sizes) != 1:
                        raise ECError("chunk sizes differ")
                    if len(present) < eng.k:
                        raise ECError(
                            f"{len(present)} chunks < k={eng.k}")
                    erasures = tuple(i for i in range(eng.k + eng.m)
                                     if i not in present)
                    avail = sorted(present)
                    surv = np.ascontiguousarray(np.stack(
                        [present[i] for i in avail[:eng.k]]))
                    op = _Op("recon",
                             ("recon", eng.k, eng.m,
                              eng.coding.tobytes(), erasures),
                             comp, span, length=int(surv.shape[1]),
                             nbytes=int(surv.nbytes), chunks=surv,
                             want=want_ids,
                             passthrough={i: present[i]
                                          for i in want_ids
                                          if i in present})
                    self._enqueue(op, lane="recon")
                    return comp
        except Exception as e:      # noqa: BLE001 — poisoned payloads
            self.stats["recon_ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    def submit_recheck(self, ec, data, *, span=None,
                       callback=None) -> Completion:
        """Queue a scrub parity re-encode; completion value is the
        ``[m, length]`` parity array, byte-identical to
        ``np.asarray(ec._encode_chunks(data))`` — so deep-scrub parity
        rechecks coalesce with recovery reconstructs instead of
        launching standalone."""
        comp = Completion(callback)
        self.stats["recon_ops_submitted"] += 1
        try:
            eng = self._matrix_engine(ec)
            arr = np.ascontiguousarray(data, dtype=np.uint8)
            if (eng is None or not self.enabled
                    or not self.recon_enabled or self._stopped):
                value = np.asarray(ec._encode_chunks(arr))
            else:
                op = _Op("recheck",
                         ("recheck", eng.k, eng.m,
                          eng.coding.tobytes()),
                         comp, span, length=int(arr.shape[1]),
                         nbytes=int(arr.nbytes), chunks=arr)
                self._enqueue(op, lane="recon")
                return comp
        except Exception as e:      # noqa: BLE001
            self.stats["recon_ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    @staticmethod
    def _reconstruct_unbatched(ec, want, chunks):
        """The exact pre-lane semantics — the bit-identity reference."""
        return ec.decode(set(want), chunks)

    # -- compression lane --------------------------------------------------

    def submit_compress(self, codec, payload, *, mode: str = "aggressive",
                        span=None, callback=None) -> Completion:
        """Queue an inline-compression pass; the completion's value is
        ``(stored_bytes, header | None)``.  ``header is None`` means
        pass-through — the payload did not shrink under an
        ``aggressive`` mode and is stored verbatim (``force`` always
        stores compressed).  The header (``{"algo", "len"}``, plus
        ``{"seg", "segs"}`` on the streaming segment path) is what the
        caller persists in the object meta so reads can expand.

        Payloads above ``comp_segment_bytes`` split into fixed
        segments that batch across objects — the oversized path keeps
        one row per segment instead of blowing up the bucket ladder.
        Batched and unbatched paths are bit-identical: the device scan
        feeds the same host finalize the single-op path uses."""
        comp = Completion(callback)
        self.stats["comp_ops_submitted"] += 1
        try:
            buf = bytes(payload)
            if len(buf) > self.comp_segment_bytes > 0:
                return self._submit_compress_segmented(
                    codec, buf, mode, span, comp)
            if (not self.enabled or not self.comp_enabled
                    or self._stopped or not buf):
                value = self._compress_unbatched(codec, buf, mode)
            else:
                op = _Op("compress", ("compress", codec.name), comp,
                         span, length=len(buf), nbytes=len(buf),
                         payload=buf, codec=codec, mode=mode)
                self._enqueue(op, lane="comp")
                return comp
        except Exception as e:      # noqa: BLE001 — poisoned payloads
            self.stats["comp_ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    def _compress_unbatched(self, codec, buf: bytes, mode: str):
        """Single-op host semantics — the bit-identity reference for
        the lane (same codec, same fallback rule)."""
        blob = codec.compress(buf)
        self.stats["comp_bytes_in"] += len(buf)
        if mode != "force" and len(blob) >= len(buf):
            self.stats["comp_passthrough"] += 1
            self.stats["comp_bytes_out"] += len(buf)
            return buf, None
        self.stats["comp_bytes_out"] += len(blob)
        return blob, {"algo": codec.name, "len": len(buf)}

    def _submit_compress_segmented(self, codec, buf: bytes, mode: str,
                                   span, comp: Completion) -> Completion:
        """Streaming segment path: fixed-size segments submitted as
        ordinary lane members (they coalesce with other objects'
        segments), joined back into one blob whose header carries the
        per-segment compressed lengths."""
        seg = self.comp_segment_bytes
        segs = [buf[i:i + seg] for i in range(0, len(buf), seg)]
        results: list = [None] * len(segs)
        state = {"left": len(segs), "err": None}
        lock = threading.Lock()

        def _child(i):
            def cb(child):
                with lock:
                    if child.error is not None:
                        state["err"] = state["err"] or child.error
                    else:
                        results[i] = child.value
                    state["left"] -= 1
                    if state["left"]:
                        return
                if state["err"] is not None:
                    comp._fire(error=state["err"])
                    return
                clens = [[len(b), 1 if h is None else 0]
                         for b, h in results]
                total = sum(c for c, _raw in clens)
                if mode != "force" and total >= len(buf):
                    self.stats["comp_passthrough"] += 1
                    comp._fire(value=(buf, None))
                    return
                blob = b"".join(b for b, _h in results)
                comp._fire(value=(blob, {
                    "algo": codec.name, "len": len(buf),
                    "seg": seg, "segs": clens}))
            return cb

        for i, s in enumerate(segs):
            self.submit_compress(codec, s, mode=mode, span=span,
                                 callback=_child(i))
        return comp

    def decompress(self, blob, header: dict) -> bytes:
        """Expand a sealed blob back to its logical bytes (the
        read/recovery half).  Host work by design: RLE expansion is a
        single ``np.repeat`` gather with nothing for the MXU to win,
        so it stays synchronous where the read path needs it — the
        lane's device budget goes to the write-side scans.  Counted
        under ``comp_decompress_bytes`` for the telemetry spine."""
        from ..compress.codec import CodecError
        from ..compress.registry import create_codec
        blob = bytes(blob)
        codec = create_codec(header["algo"])
        total = int(header["len"])
        segs = header.get("segs")
        if segs is None:
            out = codec.decompress(blob, total)
        else:
            seg = int(header["seg"])
            parts = []
            off = 0
            for i, (clen, raw) in enumerate(segs):
                llen = min(seg, total - i * seg)
                piece = blob[off:off + clen]
                off += clen
                parts.append(bytes(piece) if raw
                             else codec.decompress(piece, llen))
            out = b"".join(parts)
        if len(out) != total:
            raise CodecError(
                f"decompress produced {len(out)} of {total} bytes")
        self.stats["comp_decompress_bytes"] += len(out)
        return out

    def submit_fingerprint(self, chunker, payload, *, span=None,
                           callback=None) -> Completion:
        """Queue a dedup fingerprint pass; the completion's value is
        ``[(off, length, fp), ...]`` — content-defined chunk spans
        with their fingerprints.  The gear-hash boundary scan runs as
        one fused launch over the size-bucketed megabatch and every
        chunk of the flush digests through one batched CRC-32C
        launch; the host path (lane off / empty payload) computes the
        identical spans and fingerprints."""
        comp = Completion(callback)
        self.stats["comp_ops_submitted"] += 1
        try:
            buf = bytes(payload)
            if (not self.enabled or not self.comp_enabled
                    or self._stopped or not buf):
                value = self._fingerprint_unbatched(chunker, buf)
            else:
                op = _Op("fingerprint",
                         ("fingerprint",) + chunker.key(), comp, span,
                         length=len(buf), nbytes=len(buf),
                         payload=buf, chunker=chunker)
                self._enqueue(op, lane="comp")
                return comp
        except Exception as e:      # noqa: BLE001
            self.stats["comp_ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    def _fingerprint_unbatched(self, chunker, buf: bytes):
        from ..compress.chunker import fingerprint
        self.stats["comp_fingerprint_bytes"] += len(buf)
        return [(off, ln, fingerprint(buf[off:off + ln]))
                for off, ln in chunker.chunks(buf)]

    def _lane_knobs(self, lane: str) -> tuple[int, int, float]:
        """(max_ops, max_bytes, flush_ms) for one lane."""
        if lane == "recon":
            return (self.recon_max_ops, self.recon_max_bytes,
                    self.recon_flush_ms)
        if lane == "comp":
            return (self.comp_max_ops, self.comp_max_bytes,
                    self.comp_flush_ms)
        return self.max_ops, self.max_bytes, self.flush_ms

    def _enqueue(self, op: _Op, lane: str = "write"):
        arm = False
        fire = None
        max_ops, max_bytes, flush_ms = self._lane_knobs(lane)
        with self._lock:
            if lane == "recon":
                self._pending_recon.append(op)
                self._pending_recon_bytes += op.nbytes
                if self._recon_since is None:
                    self._recon_since = time.monotonic()
                n, nbytes = (len(self._pending_recon),
                             self._pending_recon_bytes)
                armed = self._recon_armed
            elif lane == "comp":
                self._pending_comp.append(op)
                self._pending_comp_bytes += op.nbytes
                if self._comp_since is None:
                    self._comp_since = time.monotonic()
                n, nbytes = (len(self._pending_comp),
                             self._pending_comp_bytes)
                armed = self._comp_armed
            else:
                self._pending.append(op)
                self._pending_bytes += op.nbytes
                if self._pending_since is None:
                    self._pending_since = time.monotonic()
                n, nbytes = len(self._pending), self._pending_bytes
                armed = self._deadline_armed
            if n >= max_ops:
                fire = "max_ops"
            elif nbytes >= max_bytes:
                fire = "max_bytes"
            elif flush_ms <= 0:
                fire = "immediate"
            elif not armed and self._schedule is not None:
                if lane == "recon":
                    self._recon_armed = True
                elif lane == "comp":
                    self._comp_armed = True
                else:
                    self._deadline_armed = True
                arm = True
        if fire is not None:
            self.flush(reason=fire, lane=lane)
        elif arm:
            self._schedule(
                flush_ms / 1000.0,
                lambda: self.flush(reason="deadline", lane=lane))

    def maybe_flush(self) -> bool:
        """Tick backstop: flush any lane whose oldest pending op has
        waited past its deadline window (covers a lost/absent timer)."""
        now = time.monotonic()
        due = []
        with self._lock:
            for lane, pending, since in (
                    ("write", self._pending, self._pending_since),
                    ("recon", self._pending_recon, self._recon_since),
                    ("comp", self._pending_comp, self._comp_since)):
                ms = self._lane_knobs(lane)[2]
                if pending and since is not None \
                        and (now - since) * 1000.0 >= ms:
                    due.append(lane)
        for lane in due:
            self.flush(reason="deadline", lane=lane)
        return bool(due)

    # -- flush / dispatch --------------------------------------------------

    def flush(self, reason: str = "manual", lane: str | None = None
              ) -> int:
        """Dispatch everything pending as megabatch launches.  In
        immediate mode the flights complete inline (after all engine
        locks drop); in batched mode they go to the FIFO completion
        worker so the next tick stages while these fence.  ``lane``
        limits the flush to one lane; default flushes all."""
        lanes = ("write", "recon", "comp") if lane is None else (lane,)
        return sum(self._flush_lane(ln, reason) for ln in lanes)

    def flush_sync(self, lane: str, reason: str = "manual") -> int:
        """Dispatch and complete a lane's pending inline on the
        calling thread, bypassing the completion worker.  For
        submitters that must consume results synchronously while
        possibly holding the daemon lock (deep-scrub parity recheck):
        inline completion re-enters that lock on the caller's own
        thread (RLock), so the caller never waits behind worker-queue
        flights whose callbacks need the lock it holds."""
        return self._flush_lane(lane, reason, force_inline=True)

    def _flush_lane(self, lane: str, reason: str,
                    force_inline: bool = False) -> int:
        inline: list[_Flight] = []
        n = 0
        ms = self._lane_knobs(lane)[2]
        with self._flush_lock:
            with self._lock:
                if lane == "recon":
                    pending = self._pending_recon
                    self._pending_recon = []
                    staged = self._pending_recon_bytes
                    self._pending_recon_bytes = 0
                    self._recon_since = None
                    self._recon_armed = False
                elif lane == "comp":
                    pending = self._pending_comp
                    self._pending_comp = []
                    staged = self._pending_comp_bytes
                    self._pending_comp_bytes = 0
                    self._comp_since = None
                    self._comp_armed = False
                else:
                    pending, self._pending = self._pending, []
                    staged = self._pending_bytes
                    self._pending_bytes = 0
                    self._pending_since = None
                    self._deadline_armed = False
                use_worker = (ms > 0 and not self._stopped
                              and not force_inline)
            if not pending:
                return 0
            prefix = {"recon": "recon_", "comp": "comp_"}.get(lane, "")
            self.stats[f"{prefix}flush_{reason}"] += 1
            flights = self._dispatch(pending, reason, lane)
            n = len(flights)
            for fl in flights:
                if use_worker:
                    self._ensure_worker()
                    self._flights.put(fl)
                else:
                    inline.append(fl)
        for fl in inline:
            self._complete(fl)
        if self.on_lane_flush is not None:
            try:
                self.on_lane_flush(lane, len(pending), staged)
            except Exception:       # noqa: BLE001 — accounting hook
                self.stats["callback_errors"] += 1
        if self.store_kick is not None:
            # durability boundary: the completions just dispatched
            # queued their transactions — nudge the WAL group-commit
            # thread so the whole megabatch shares ONE fsync and its
            # acks (gated on commit) release together
            try:
                self.store_kick()
                self.stats[f"{prefix}store_kicks"] += 1
            except Exception:       # noqa: BLE001
                self.stats["callback_errors"] += 1
        return n

    def drain(self):
        """Flush and wait until every in-flight completion has fired
        (shutdown / test barrier)."""
        self.flush(reason="drain")
        self._flights.join()

    def stop(self):
        """Drain, then retire the completion worker.  Later submits
        degrade to the synchronous unbatched path."""
        self._stopped = True
        self.drain()
        w = self._worker
        if w is not None:
            self._flights.put(None)
            w.join(timeout=5.0)
            self._worker = None

    def _ensure_worker(self):
        w = self._worker
        if w is not None and w.is_alive():
            return
        w = threading.Thread(target=self._worker_loop,
                             name=f"batch-{self.name}", daemon=True)
        self._worker = w
        w.start()

    def _worker_loop(self):
        while True:
            fl = self._flights.get()
            try:
                if fl is None:
                    return
                self._complete(fl)
            finally:
                self._flights.task_done()

    def _groups(self, pending):
        groups: dict = {}
        floor = max(1, int(self.bucket_floor))
        for op in pending:
            bucket_len = _next_pow2(max(op.length, floor))
            groups.setdefault((op.key, bucket_len), []).append(op)
        return groups

    def _dispatch(self, pending, reason, lane="write") -> list[_Flight]:
        flights = []
        launches_key = {"recon": "recon_launches",
                        "comp": "comp_launches"}.get(lane, "launches")
        for (key, bucket_len), ops in self._groups(pending).items():
            rows = _next_pow2(len(ops))
            span = None
            if self.tracer is not None:
                span = self.tracer.start_span(
                    "megabatch_flush", tags={
                        "layer": "device", "kernel": "megabatch",
                        "op": key[0], "lane": lane,
                        "members": len(ops),
                        "rows": rows, "row_len": bucket_len,
                        "reason": reason})
                if span is not None:
                    for op in ops:
                        if op.span is not None:
                            span.add_link(op.span)
            try:
                if key[0] == "encode":
                    fl = self._launch_encode(key, ops, rows,
                                             bucket_len, span, reason)
                elif key[0] == "digest":
                    fl = self._launch_digest(ops, rows, bucket_len,
                                             span, reason)
                elif key[0] == "recon":
                    fl = self._launch_reconstruct(
                        key, ops, rows, bucket_len, span, reason)
                elif key[0] == "compress":
                    fl = self._launch_compress(ops, rows, bucket_len,
                                               span, reason)
                elif key[0] == "fingerprint":
                    fl = self._launch_fingerprint(ops, rows,
                                                  bucket_len, span,
                                                  reason)
                else:
                    fl = self._launch_recheck(key, ops, rows,
                                              bucket_len, span, reason)
            except Exception as e:  # noqa: BLE001 — one group's
                # launch failure must not kill sibling groups
                self._fail_group(ops, e, span)
                continue
            flights.append(fl)
            self.stats[launches_key] += 1
        return flights

    def _prof_start(self, ops, rows, staged_bytes, reason, op_kind,
                    cache_hit, lane="write", devices=None):
        if self.profiler is None:
            return None
        return self.profiler.start(
            "megabatch", bytes_in=staged_bytes,
            bytes_used=sum(o.nbytes for o in ops),
            rows=rows, rows_used=len(ops), overlap=True,
            devices=devices,
            members=len(ops), reason=reason, op=op_kind,
            cache_hit=cache_hit, lane=lane)

    def _engine_mesh(self):
        """The process-wide cluster mesh when ``use_mesh`` is on and
        more than one device is visible, else None (single-chip paths
        unchanged).  One mesh serves every lane, so all sharded
        executable caches key off the same device grid."""
        if not self.use_mesh:
            return None
        if self._mesh is None:
            import jax
            if len(jax.devices()) <= 1:
                return None
            from ..parallel.mesh import cluster_mesh
            self._mesh = cluster_mesh()
        return self._mesh

    def _mesh_labels(self):
        mesh = self._engine_mesh()
        if mesh is None:
            return None
        if self._mesh_devs is None:
            from ..parallel.mesh import mesh_device_labels
            self._mesh_devs = mesh_device_labels(mesh)
        return self._mesh_devs

    def _launch_encode(self, key, ops, rows, bucket_len, span,
                       reason) -> _Flight:
        from ..ops.gf_jax import GFEncodeDigest
        _kind, k, m, mat = key
        fused = self._fused.get(key)
        if fused is None:
            fused = self._fused[key] = GFEncodeDigest(
                np.frombuffer(mat, dtype=np.uint8).reshape(m, k),
                mesh=self._engine_mesh())
        if fused.mesh is not None:
            # pad the row bucket up so the batch axis divides the mesh
            # (pow2 rows and pow2 device counts nest; odd device
            # counts fall back silently inside GFEncodeDigest)
            rows = max(rows, _next_pow2(fused.mesh.size))
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        shape = (rows, k, bucket_len)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "encode", fused.export_hits.get(shape,
                                                              False),
                              devices=(self._mesh_labels()
                                       if fused.mesh is not None
                                       else None))
        try:
            out = fused(batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("encode", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_digest(self, ops, rows, bucket_len, span,
                       reason) -> _Flight:
        import jax.numpy as jnp
        from ..scrub.crc32c_jax import _batch_kernel
        batch = np.zeros((rows, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :op.length] = np.frombuffer(op.payload, np.uint8)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "digest", True)
        try:
            out = _batch_kernel(bucket_len)(
                jnp.asarray(batch), jnp.zeros(rows, jnp.uint32))
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("digest", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_reconstruct(self, key, ops, rows, bucket_len, span,
                            reason) -> _Flight:
        from ..parallel.reconstruct import decode_plan
        _kind, k, m, mat, erasures = key
        coding = np.frombuffer(mat, dtype=np.uint8).reshape(m, k)
        plan = decode_plan(coding, k, m, erasures,
                           cache=self._plan_cache)
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "recon", key in self._rexec,
                              lane="recon",
                              devices=self._mesh_labels())
        try:
            out = self._run_reconstruct(key, plan, batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("recon", ops, out, bucket_len, rows, ln, span,
                       reason, plan=plan)

    def _run_reconstruct(self, key, plan, batch):
        """Pick the reconstruct strategy for one fused group:

        - resident planes (``use_planes``, auto on TPU): expand the
          survivor batch to bit planes once, multiply by the plan's
          stacked matrix — per-matrix operands persist in
          ``_plane_mats`` across the whole sweep.  With the mesh on,
          the planes expand *sharded* over the batch axis and each
          multiply is a shard_map of the local kernel.
        - mesh (``use_mesh`` and >1 device): the shard_map program of
          ``parallel.reconstruct.ShardedEC`` — survivor rows scattered
          to their chunk-id positions, batch padded to a dp multiple.
          Parity-hole erasure patterns ride this launch too: the
          decode fn is built from the plan's stacked ``[k + p, k]``
          matrix, so the all-gather reduce emits the composed
          ``coding ∘ dm`` rows alongside the data rows.
        - default: one cached ``GFLinear`` over the plan's fused
          ``[k + p, k]`` matrix — a single launch per group.
        """
        import jax
        mesh = self._engine_mesh()
        planes = (self.use_planes if self.use_planes is not None
                  else jax.default_backend() == "tpu")
        if planes:
            from ..ops.gf_pallas2 import ResidentPlanes
            rp = ResidentPlanes(
                batch, interpret=jax.default_backend() != "tpu",
                mats=self._plane_mats, mesh=mesh)
            return rp.multiply(plan.matrix)
        if mesh is not None:
            return self._run_mesh(key, plan, batch)
        prog = self._rexec.get(key)
        if prog is None:
            from ..ops.gf_jax import GFLinear
            prog = self._rexec[key] = GFLinear(plan.matrix)
        return prog(batch)

    def _sharded_ec(self, k, m, mat):
        """Cached per-code ShardedEC over the cluster mesh — shared by
        the recovery reconstruct and the scrub recheck paths (one
        compiled program family per code, not per caller)."""
        code_key = (k, m, mat)
        sh = self._sharded.get(code_key)
        if sh is None:
            from ..parallel.reconstruct import ShardedEC
            coding = np.frombuffer(mat, dtype=np.uint8).reshape(m, k)
            # byte payloads in, byte payloads out: word_native stays
            # off so host staging needs no dtype views
            sh = self._sharded[code_key] = ShardedEC(
                coding, k, m, self._engine_mesh(), word_native=False)
        return sh

    def _run_mesh(self, key, plan, batch):
        _kind, k, m, mat = key[:4]
        sh = self._sharded_ec(k, m, mat)
        rows, _k, length = batch.shape
        dp = sh.mesh.shape["dp"]
        b_pad = -(-rows // dp) * dp
        full = np.zeros((b_pad, sh.n_pad, length), dtype=np.uint8)
        for r, sid in enumerate(plan.survivors):
            full[:rows, sid] = batch[:, r]
        # emit="plan": the mesh launch returns the k data rows AND the
        # composed parity rows in plan.out_ids order, so parity-hole
        # patterns complete through the same plan.row_of indexing the
        # fused single-chip matrix uses.
        out = sh.reconstruct(full, plan.erasures, emit="plan")
        return out[:rows]

    def _launch_recheck(self, key, ops, rows, bucket_len, span,
                        reason) -> _Flight:
        if self._engine_mesh() is not None:
            return self._launch_recheck_mesh(key, ops, rows,
                                             bucket_len, span, reason)
        _kind, k, m, mat = key
        cache_hit = key in self._rexec
        prog = self._rexec.get(key)
        if prog is None:
            from ..ops.gf_jax import GFLinear
            prog = self._rexec[key] = GFLinear(
                np.frombuffer(mat, dtype=np.uint8).reshape(m, k))
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "recheck", cache_hit, lane="recon")
        try:
            out = prog(batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("recheck", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_recheck_mesh(self, key, ops, rows, bucket_len, span,
                             reason) -> _Flight:
        """Scrub parity recheck on the mesh: a recheck IS an encode,
        so it rides the same chunk-sharded ShardedEC program the
        recovery lane caches (per-device GF partials XOR-combined over
        ICI) — bit-identical to the single-chip GFLinear, both being
        oracle-exact."""
        _kind, k, m, mat = key
        cache_hit = (k, m, mat) in self._sharded
        sh = self._sharded_ec(k, m, mat)
        dp = sh.mesh.shape["dp"]
        rows = -(-rows // dp) * dp
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "recheck", cache_hit, lane="recon",
                              devices=self._mesh_labels())
        try:
            out = sh.encode(sh.pad_data(batch))
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("recheck", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_compress(self, ops, rows, bucket_len, span,
                         reason) -> _Flight:
        """Stage one codec's ops into a pow2 megabatch and run the
        codec's device boundary scan (``scan_batch``); host-only
        codecs (zlib) fly with ``out=None`` and finalize entirely in
        ``_complete_comp`` — they still gain the shared flush cadence
        and stats spine."""
        codec = ops[0].codec
        scan = getattr(codec, "scan_batch", None)
        out = None
        if scan is not None:
            batch = np.zeros((rows, bucket_len), dtype=np.uint8)
            for i, op in enumerate(ops):
                batch[i, :op.length] = np.frombuffer(op.payload,
                                                     np.uint8)
            staged = batch.nbytes
        else:
            staged = sum(op.length for op in ops)
        ln = self._prof_start(ops, rows, staged, reason, "compress",
                              True, lane="comp")
        try:
            if scan is not None:
                out = scan(batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("compress", ops, out, bucket_len, rows, ln,
                       span, reason)

    def _launch_fingerprint(self, ops, rows, bucket_len, span,
                            reason) -> _Flight:
        """Two fused launches per flush: the gear-hash boundary scan
        over the pow2 megabatch, then — after the host pass walks the
        sparse candidate lists into bounded chunk spans — one CRC-32C
        batch launch digesting *every* chunk of the flush at once.
        The flight carries the finished per-op values; the fence in
        ``_complete_comp`` only fires completions."""
        import zlib as _zlib
        import jax.numpy as jnp
        from ..scrub.crc32c_jax import (_batch_kernel,
                                        crc32c_zero_unpad)
        chunker = ops[0].chunker
        mesh = self._engine_mesh()
        if mesh is not None:
            # pad rows so the gear scan's row axis divides the mesh —
            # zero rows hash to a constant the cut walk never reads
            rows = max(rows, _next_pow2(mesh.size))
        batch = np.zeros((rows, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :op.length] = np.frombuffer(op.payload, np.uint8)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "fingerprint", True, lane="comp",
                              devices=self._mesh_labels())
        try:
            hashes = np.asarray(chunker.hash_batch(batch, mesh=mesh))
            spans_per_op = []
            all_chunks = []
            for i, op in enumerate(ops):
                spans = []
                last = 0
                for c in chunker.cuts_from_hashes(hashes[i],
                                                  op.length):
                    spans.append((last, c - last))
                    all_chunks.append(op.payload[last:c])
                    last = c
                spans_per_op.append(spans)
            if all_chunks:
                cbucket = _next_pow2(
                    max(max(len(c) for c in all_chunks), 32))
                cbatch = np.zeros((len(all_chunks), cbucket),
                                  dtype=np.uint8)
                for i, c in enumerate(all_chunks):
                    cbatch[i, :len(c)] = np.frombuffer(c, np.uint8)
                crcs = np.asarray(_batch_kernel(cbucket)(
                    jnp.asarray(cbatch),
                    jnp.zeros(len(all_chunks), jnp.uint32)))
            values = []
            j = 0
            for spans in spans_per_op:
                vals = []
                for off, clen in spans:
                    c = all_chunks[j]
                    crc = crc32c_zero_unpad(int(crcs[j]),
                                            cbucket - len(c))
                    vals.append((off, clen,
                                 f"{crc:08x}"
                                 f"{_zlib.crc32(c) & 0xFFFFFFFF:08x}"
                                 f"{len(c):08x}"))
                    j += 1
                values.append(vals)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("fingerprint", ops, values, bucket_len, rows,
                       ln, span, reason)

    # -- completion --------------------------------------------------------

    def _complete(self, fl: _Flight):
        from ..scrub.crc32c_jax import crc32c_zero_unpad
        if fl.kind in ("compress", "fingerprint"):
            self._complete_comp(fl)
            return
        parity = crcs = rec = None
        try:
            if fl.kind == "encode":
                parity = np.asarray(fl.out[0])
                crcs = np.asarray(fl.out[1])
                bytes_out = int(parity.nbytes) + int(crcs.nbytes)
            elif fl.kind == "digest":
                crcs = np.asarray(fl.out)
                bytes_out = int(crcs.nbytes)
            else:               # recon | recheck
                rec = np.asarray(fl.out)
                bytes_out = int(rec.nbytes)
        except Exception as e:      # noqa: BLE001 — launch died at the
            if fl.ln is not None:   # fence: fail every member
                fl.ln.abort()
            self._fail_group(fl.ops, e, fl.span)
            return
        if fl.ln is not None:
            fl.ln.finish(bytes_out=bytes_out)
        if fl.span is not None:
            fl.span.finish()
        info = {"rows": fl.bucket, "members": len(fl.ops),
                "row_len": fl.length, "reason": fl.reason}
        if rec is not None:
            info["lane"] = "recon"
            plan = fl.plan
            for i, op in enumerate(fl.ops):
                try:
                    if fl.kind == "recheck":
                        value = np.ascontiguousarray(
                            rec[i, :, :op.length])
                    else:
                        value = {
                            cid: (op.passthrough[cid]
                                  if cid in op.passthrough else
                                  np.ascontiguousarray(
                                      rec[i, plan.row_of[cid],
                                          :op.length]))
                            for cid in op.want}
                    op.comp.info = info
                    op.comp._fire(value=value)
                    self.stats["recon_ops_completed"] += 1
                except Exception:   # noqa: BLE001 — a member's
                    # callback blowing up must not starve its siblings
                    self.stats["callback_errors"] += 1
            return
        for i, op in enumerate(fl.ops):
            pad = fl.length - op.length
            try:
                if fl.kind == "encode":
                    k = op.chunks.shape[0]
                    m = parity.shape[1]
                    shard_chunks = {j: op.chunks[j].tobytes()
                                    for j in range(k)}
                    for j in range(m):
                        shard_chunks[k + j] = \
                            parity[i, j, :op.length].tobytes()
                    hinfos = {s: crc32c_zero_unpad(int(crcs[i, s]),
                                                   pad)
                              for s in range(k + m)}
                    value = (shard_chunks, hinfos)
                else:
                    value = crc32c_zero_unpad(int(crcs[i]), pad)
                op.comp.info = info
                op.comp._fire(value=value)
                self.stats["ops_completed"] += 1
            except Exception:       # noqa: BLE001 — a member's
                # callback blowing up must not starve its siblings
                self.stats["callback_errors"] += 1

    def _complete_comp(self, fl: _Flight):
        """Fence + per-member finalize for the compression lane.  A
        member whose codec finalize blows up fails alone — group
        isolation inside the flight, same contract as the write
        lane's per-group isolation outside it."""
        try:
            if fl.kind == "compress":
                mask = (np.asarray(fl.out) if fl.out is not None
                        else None)
            else:
                values = fl.out     # precomputed in the launch half
        except Exception as e:      # noqa: BLE001 — died at the fence
            if fl.ln is not None:
                fl.ln.abort()
            self._fail_group(fl.ops, e, fl.span)
            return
        info = {"rows": fl.bucket, "members": len(fl.ops),
                "row_len": fl.length, "reason": fl.reason,
                "lane": "comp"}
        bytes_out = 0
        for i, op in enumerate(fl.ops):
            try:
                if fl.kind == "compress":
                    codec = op.codec
                    if mask is not None:
                        row = np.frombuffer(op.payload, np.uint8)
                        blob = codec.compress_from_scan(
                            row, op.length, mask[i])
                    else:
                        blob = codec.compress(op.payload)
                    self.stats["comp_bytes_in"] += op.length
                    if op.mode != "force" and len(blob) >= op.length:
                        self.stats["comp_passthrough"] += 1
                        self.stats["comp_bytes_out"] += op.length
                        bytes_out += op.length
                        value = (op.payload, None)
                    else:
                        self.stats["comp_bytes_out"] += len(blob)
                        bytes_out += len(blob)
                        value = (blob, {"algo": codec.name,
                                        "len": op.length})
                else:
                    self.stats["comp_fingerprint_bytes"] += op.length
                    value = values[i]
            except Exception as e:  # noqa: BLE001 — poisoned member
                self.stats["comp_ops_failed"] += 1
                try:
                    op.comp._fire(error=e)
                except Exception:   # noqa: BLE001
                    self.stats["callback_errors"] += 1
                continue
            op.comp.info = info
            try:
                op.comp._fire(value=value)
                self.stats["comp_ops_completed"] += 1
            except Exception:       # noqa: BLE001 — a member's
                # callback blowing up must not starve its siblings
                self.stats["callback_errors"] += 1
        if fl.ln is not None:
            fl.ln.finish(bytes_out=bytes_out)
        if fl.span is not None:
            fl.span.finish()

    def _fail_group(self, ops, err, span):
        if span is not None:
            span.set_tag("error", repr(err))
            span.finish()
        for op in ops:
            self.stats["recon_ops_failed"
                       if op.kind in ("recon", "recheck")
                       else "comp_ops_failed"
                       if op.kind in ("compress", "fingerprint")
                       else "ops_failed"] += 1
            try:
                op.comp._fire(error=err)
            except Exception:       # noqa: BLE001
                self.stats["callback_errors"] += 1

    # -- introspection -----------------------------------------------------

    def dump(self) -> dict:
        with self._lock:
            pending = len(self._pending)
            pending_bytes = self._pending_bytes
            rpending = len(self._pending_recon)
            rpending_bytes = self._pending_recon_bytes
            cpending = len(self._pending_comp)
            cpending_bytes = self._pending_comp_bytes
        d = dict(self.stats)
        d.update(enabled=self.enabled, flush_ms=self.flush_ms,
                 max_bytes=self.max_bytes, max_ops=self.max_ops,
                 bucket_floor=self.bucket_floor,
                 pending_ops=pending, pending_bytes=pending_bytes,
                 recon_enabled=self.recon_enabled,
                 recon_flush_ms=self.recon_flush_ms,
                 recon_max_bytes=self.recon_max_bytes,
                 recon_max_ops=self.recon_max_ops,
                 recon_pending_ops=rpending,
                 recon_pending_bytes=rpending_bytes,
                 recon_use_mesh=self.use_mesh,
                 recon_plans=len(self._plan_cache),
                 comp_enabled=self.comp_enabled,
                 comp_flush_ms=self.comp_flush_ms,
                 comp_max_bytes=self.comp_max_bytes,
                 comp_max_ops=self.comp_max_ops,
                 comp_segment_bytes=self.comp_segment_bytes,
                 comp_pending_ops=cpending,
                 comp_pending_bytes=cpending_bytes,
                 inflight=self._flights.unfinished_tasks)
        return d

"""Per-OSD coalescing device data plane — kill the per-op dispatch floor.

BENCH_r05's ``dispatch_floor_ms`` is the tax every OSD op pays to
cross Python→device once: EC encode, CRC digest, parity recheck each
launch alone, so an op-mix workload runs at launch rate, not at MXU
rate.  This engine is the Python mirror of the native coalescing ring
(``native/pjrt_executor.cc``): the write stream for a tick — across
PGs and across op types — accumulates into one **megabatch** that a
single fused launch (`ops.gf_jax.GFEncodeDigest`) encodes *and*
digests, so per-shard hinfo CRCs ride the same program.

Shape discipline keeps the jit cache bounded: members are grouped by
EC code identity and bucketed by chunk length, rows and lengths both
pad to powers of two.  Zero padding is free for the GF encode
(linearity: zero columns encode to zero parity) and reversible for
the digest (`scrub.crc32c_jax.crc32c_zero_unpad` strips the pad with
two 32-bit GF(2) matrix applications) — so batched results are
**bit-identical** to the unbatched path, asserted in
tests/test_batch_engine.py and before any bench timing.

Flush policy (reference: the OSD op queue's batching heuristics):

- ``max_bytes`` / ``max_ops`` — size triggers, checked at submit;
- ``flush_ms`` — the accumulation deadline.  ``0`` (the default)
  means *immediate*: every submit flushes synchronously and
  completions fire before ``submit_*`` returns — CPU-only CI runs
  exactly the old one-op-at-a-time semantics, just through one code
  path.  ``> 0`` arms a timer (``schedule``) and enables the
  double-buffered flight pipeline: a flush dispatches its launches
  asynchronously and hands the flights to a completion worker that
  fences them in FIFO order while the next tick keeps staging — the
  device never idles between launches, and FIFO completion preserves
  per-PG version ordering.

Lock order (lockdep-clean by construction): submitters may hold the
daemon lock when calling ``submit_*`` (engine locks are leaves);
completion callbacks re-acquire the daemon lock but run either on
the submitter's own thread (immediate mode — RLock re-entry) or on
the completion worker with **no** engine lock held, so there is no
path that holds an engine lock while waiting on the daemon lock.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

import numpy as np


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


class Completion:
    """One submitted op's pending result.

    ``value`` for an encode op is ``(shard_chunks, hinfos)`` —
    ``{shard: bytes}`` for all k+m shards and ``{shard: crc32c}`` to
    match; for a digest op it is the ``int`` crc.  ``info`` carries
    flush attribution (rows, members, reason) for the member's span.
    """

    __slots__ = ("_ev", "value", "error", "info", "_cb")

    def __init__(self, callback=None):
        self._ev = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        self.info: dict = {}
        self._cb = callback

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._ev.wait(timeout)

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("batch op still pending")
        if self.error is not None:
            raise self.error
        return self.value

    def _fire(self, value=None, error: BaseException | None = None):
        if self._ev.is_set():
            return              # first outcome wins
        self.value = value
        self.error = error
        self._ev.set()
        if self._cb is not None:
            self._cb(self)


class _Op:
    __slots__ = ("kind", "key", "chunks", "payload", "length",
                 "nbytes", "comp", "span")

    def __init__(self, kind, key, comp, span, length, nbytes,
                 chunks=None, payload=None):
        self.kind = kind            # "encode" | "digest"
        self.key = key              # executable-identity group key
        self.comp = comp
        self.span = span
        self.length = length        # true (unpadded) per-row length
        self.nbytes = nbytes
        self.chunks = chunks        # encode: [k, length] uint8
        self.payload = payload      # digest: bytes


class _Flight:
    """One dispatched launch awaiting its fence."""

    __slots__ = ("kind", "ops", "out", "length", "bucket", "ln",
                 "span", "reason")

    def __init__(self, kind, ops, out, length, bucket, ln, span,
                 reason):
        self.kind = kind
        self.ops = ops
        self.out = out              # device value(s), un-fenced
        self.length = length        # bucket row length
        self.bucket = bucket        # padded row count
        self.ln = ln                # profiler launch (overlap) or None
        self.span = span
        self.reason = reason


class BatchEngine:
    """Tick-accumulating megabatch launcher for one OSD's device ops."""

    def __init__(self, name: str = "", *, enabled: bool = True,
                 max_bytes: int = 8 << 20, max_ops: int = 64,
                 flush_ms: float = 0.0, schedule=None,
                 profiler=None, tracer=None):
        self.name = name
        self.enabled = bool(enabled)
        self.max_bytes = int(max_bytes)
        self.max_ops = int(max_ops)
        self.flush_ms = float(flush_ms)
        self._schedule = schedule   # schedule(delay_s, fn) -> token
        self.profiler = profiler
        self.tracer = tracer
        self._lock = threading.Lock()        # pending accumulator
        self._flush_lock = threading.Lock()  # serializes dispatch
        self._pending: list[_Op] = []
        self._pending_bytes = 0
        self._pending_since: float | None = None
        self._deadline_armed = False
        self._fused: dict = {}               # code key → GFEncodeDigest
        self._flights: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        self._stopped = False
        self.stats = collections.Counter()

    # -- submission --------------------------------------------------------

    @staticmethod
    def _matrix_engine(ec):
        """The batchable core of an EC plugin, or None (LRC/SHEC/
        bitmatrix layers fall back to the unbatched path)."""
        from ..ec.jax_backend import MatrixECEngine
        eng = getattr(ec, "engine", None)
        return eng if isinstance(eng, MatrixECEngine) else None

    def submit_encode(self, ec, data, *, span=None,
                      callback=None) -> Completion:
        """Queue a full-stripe encode+digest; the completion's value is
        ``({shard: bytes}, {shard: crc32c})`` over all k+m shards —
        byte- and digest-identical to ``ec.encode`` + host
        ``crc32c`` per shard."""
        comp = Completion(callback)
        self.stats["ops_submitted"] += 1
        value = None
        try:
            eng = self._matrix_engine(ec)
            if eng is None or not self.enabled or self._stopped:
                value = self._encode_unbatched(ec, data)
            else:
                chunks = np.ascontiguousarray(
                    ec.encode_prepare(data), dtype=np.uint8)
                key = ("encode", eng.k, eng.m, eng.coding.tobytes())
                op = _Op("encode", key, comp, span,
                         length=int(chunks.shape[1]),
                         nbytes=int(chunks.nbytes), chunks=chunks)
                self._enqueue(op)
                return comp
        except Exception as e:      # noqa: BLE001 — poisoned payloads
            self.stats["ops_failed"] += 1   # fail their own op only
            comp._fire(error=e)
            return comp
        # fire outside the try: a callback raising must surface to the
        # submitter, not masquerade as an encode failure
        comp._fire(value=value)
        return comp

    def submit_digest(self, payload, *, span=None,
                      callback=None) -> Completion:
        """Queue a CRC-32C digest; completion value is the int crc."""
        comp = Completion(callback)
        self.stats["ops_submitted"] += 1
        try:
            buf = bytes(payload)
            if self.enabled and not self._stopped and buf:
                op = _Op("digest", ("digest",), comp, span,
                         length=len(buf), nbytes=len(buf),
                         payload=buf)
                self._enqueue(op)
                return comp
            from ..scrub.crc32c_jax import crc32c
            value = crc32c(buf)
        except Exception as e:      # noqa: BLE001
            self.stats["ops_failed"] += 1
            comp._fire(error=e)
            return comp
        comp._fire(value=value)
        return comp

    @staticmethod
    def _encode_unbatched(ec, data):
        """The exact pre-engine semantics: whole-stripe encode, then
        host CRC per shard — the bit-identity reference."""
        from ..scrub.crc32c_jax import crc32c
        n = ec.k + ec.m
        out = ec.encode(set(range(n)), data)
        shard_chunks = {i: bytes(np.asarray(out[i]).tobytes())
                        for i in range(n)}
        hinfos = {i: crc32c(shard_chunks[i]) for i in range(n)}
        return shard_chunks, hinfos

    def _enqueue(self, op: _Op):
        arm = False
        fire = None
        with self._lock:
            self._pending.append(op)
            self._pending_bytes += op.nbytes
            if self._pending_since is None:
                self._pending_since = time.monotonic()
            if len(self._pending) >= self.max_ops:
                fire = "max_ops"
            elif self._pending_bytes >= self.max_bytes:
                fire = "max_bytes"
            elif self.flush_ms <= 0:
                fire = "immediate"
            elif not self._deadline_armed and self._schedule is not None:
                self._deadline_armed = True
                arm = True
        if fire is not None:
            self.flush(reason=fire)
        elif arm:
            self._schedule(self.flush_ms / 1000.0, self._on_deadline)

    def _on_deadline(self):
        self.flush(reason="deadline")

    def maybe_flush(self) -> bool:
        """Tick backstop: flush if the oldest pending op has waited
        past the deadline window (covers a lost/absent timer)."""
        with self._lock:
            since = self._pending_since
            if not self._pending or since is None:
                return False
            if (time.monotonic() - since) * 1000.0 < self.flush_ms:
                return False
        self.flush(reason="deadline")
        return True

    # -- flush / dispatch --------------------------------------------------

    def flush(self, reason: str = "manual") -> int:
        """Dispatch everything pending as megabatch launches.  In
        immediate mode the flights complete inline (after all engine
        locks drop); in batched mode they go to the FIFO completion
        worker so the next tick stages while these fence."""
        inline: list[_Flight] = []
        n = 0
        with self._flush_lock:
            with self._lock:
                pending, self._pending = self._pending, []
                self._pending_bytes = 0
                self._pending_since = None
                self._deadline_armed = False
                use_worker = self.flush_ms > 0 and not self._stopped
            if not pending:
                return 0
            self.stats[f"flush_{reason}"] += 1
            flights = self._dispatch(pending, reason)
            n = len(flights)
            for fl in flights:
                if use_worker:
                    self._ensure_worker()
                    self._flights.put(fl)
                else:
                    inline.append(fl)
        for fl in inline:
            self._complete(fl)
        return n

    def drain(self):
        """Flush and wait until every in-flight completion has fired
        (shutdown / test barrier)."""
        self.flush(reason="drain")
        self._flights.join()

    def stop(self):
        """Drain, then retire the completion worker.  Later submits
        degrade to the synchronous unbatched path."""
        self._stopped = True
        self.drain()
        w = self._worker
        if w is not None:
            self._flights.put(None)
            w.join(timeout=5.0)
            self._worker = None

    def _ensure_worker(self):
        w = self._worker
        if w is not None and w.is_alive():
            return
        w = threading.Thread(target=self._worker_loop,
                             name=f"batch-{self.name}", daemon=True)
        self._worker = w
        w.start()

    def _worker_loop(self):
        while True:
            fl = self._flights.get()
            try:
                if fl is None:
                    return
                self._complete(fl)
            finally:
                self._flights.task_done()

    def _groups(self, pending):
        groups: dict = {}
        for op in pending:
            bucket_len = _next_pow2(max(op.length, 32))
            groups.setdefault((op.key, bucket_len), []).append(op)
        return groups

    def _dispatch(self, pending, reason) -> list[_Flight]:
        flights = []
        for (key, bucket_len), ops in self._groups(pending).items():
            rows = _next_pow2(len(ops))
            span = None
            if self.tracer is not None:
                span = self.tracer.start_span(
                    "megabatch_flush", tags={
                        "layer": "device", "kernel": "megabatch",
                        "op": key[0], "members": len(ops),
                        "rows": rows, "row_len": bucket_len,
                        "reason": reason})
                if span is not None:
                    for op in ops:
                        if op.span is not None:
                            span.add_link(op.span)
            try:
                if key[0] == "encode":
                    fl = self._launch_encode(key, ops, rows,
                                             bucket_len, span, reason)
                else:
                    fl = self._launch_digest(ops, rows, bucket_len,
                                             span, reason)
            except Exception as e:  # noqa: BLE001 — one group's
                # launch failure must not kill sibling groups
                self._fail_group(ops, e, span)
                continue
            flights.append(fl)
            self.stats["launches"] += 1
        return flights

    def _prof_start(self, ops, rows, staged_bytes, reason, op_kind,
                    cache_hit):
        if self.profiler is None:
            return None
        return self.profiler.start(
            "megabatch", bytes_in=staged_bytes,
            bytes_used=sum(o.nbytes for o in ops),
            rows=rows, rows_used=len(ops), overlap=True,
            members=len(ops), reason=reason, op=op_kind,
            cache_hit=cache_hit)

    def _launch_encode(self, key, ops, rows, bucket_len, span,
                       reason) -> _Flight:
        from ..ops.gf_jax import GFEncodeDigest
        _kind, k, m, mat = key
        fused = self._fused.get(key)
        if fused is None:
            fused = self._fused[key] = GFEncodeDigest(
                np.frombuffer(mat, dtype=np.uint8).reshape(m, k))
        batch = np.zeros((rows, k, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :, :op.length] = op.chunks
        shape = (rows, k, bucket_len)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "encode", fused.export_hits.get(shape,
                                                              False))
        try:
            out = fused(batch)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("encode", ops, out, bucket_len, rows, ln, span,
                       reason)

    def _launch_digest(self, ops, rows, bucket_len, span,
                       reason) -> _Flight:
        import jax.numpy as jnp
        from ..scrub.crc32c_jax import _batch_kernel
        batch = np.zeros((rows, bucket_len), dtype=np.uint8)
        for i, op in enumerate(ops):
            batch[i, :op.length] = np.frombuffer(op.payload, np.uint8)
        ln = self._prof_start(ops, rows, batch.nbytes, reason,
                              "digest", True)
        try:
            out = _batch_kernel(bucket_len)(
                jnp.asarray(batch), jnp.zeros(rows, jnp.uint32))
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.dispatched()
        return _Flight("digest", ops, out, bucket_len, rows, ln, span,
                       reason)

    # -- completion --------------------------------------------------------

    def _complete(self, fl: _Flight):
        from ..scrub.crc32c_jax import crc32c_zero_unpad
        try:
            if fl.kind == "encode":
                parity = np.asarray(fl.out[0])
                crcs = np.asarray(fl.out[1])
            else:
                crcs = np.asarray(fl.out)
                parity = None
        except Exception as e:      # noqa: BLE001 — launch died at the
            if fl.ln is not None:   # fence: fail every member
                fl.ln.abort()
            self._fail_group(fl.ops, e, fl.span)
            return
        if fl.ln is not None:
            fl.ln.finish(bytes_out=int(crcs.nbytes) +
                         (int(parity.nbytes) if parity is not None
                          else 0))
        if fl.span is not None:
            fl.span.finish()
        info = {"rows": fl.bucket, "members": len(fl.ops),
                "row_len": fl.length, "reason": fl.reason}
        for i, op in enumerate(fl.ops):
            pad = fl.length - op.length
            try:
                if fl.kind == "encode":
                    k = op.chunks.shape[0]
                    m = parity.shape[1]
                    shard_chunks = {j: op.chunks[j].tobytes()
                                    for j in range(k)}
                    for j in range(m):
                        shard_chunks[k + j] = \
                            parity[i, j, :op.length].tobytes()
                    hinfos = {s: crc32c_zero_unpad(int(crcs[i, s]),
                                                   pad)
                              for s in range(k + m)}
                    value = (shard_chunks, hinfos)
                else:
                    value = crc32c_zero_unpad(int(crcs[i]), pad)
                op.comp.info = info
                op.comp._fire(value=value)
                self.stats["ops_completed"] += 1
            except Exception:       # noqa: BLE001 — a member's
                # callback blowing up must not starve its siblings
                self.stats["callback_errors"] += 1

    def _fail_group(self, ops, err, span):
        if span is not None:
            span.set_tag("error", repr(err))
            span.finish()
        for op in ops:
            self.stats["ops_failed"] += 1
            try:
                op.comp._fire(error=err)
            except Exception:       # noqa: BLE001
                self.stats["callback_errors"] += 1

    # -- introspection -----------------------------------------------------

    def dump(self) -> dict:
        with self._lock:
            pending = len(self._pending)
            pending_bytes = self._pending_bytes
        d = dict(self.stats)
        d.update(enabled=self.enabled, flush_ms=self.flush_ms,
                 max_bytes=self.max_bytes, max_ops=self.max_ops,
                 pending_ops=pending, pending_bytes=pending_bytes,
                 inflight=self._flights.unfinished_tasks)
        return d

"""OSD data-plane message types.

Reference: ``src/messages/MOSDOp.h``, ``MOSDOpReply.h``, ``MOSDRepOp.h``,
``MOSDRepOpReply.h``, ``MOSDPGQuery/Notify/Log.h``, ``MOSDECSubOpWrite/
Read.h`` (ECMsgTypes), ``MOSDPing.h``, push/pull recovery messages
(SURVEY.md §3.2/§3.5).  Like the mon plane, payloads are JSON-in-frame:
the framed messenger carries them; bulk chunk bytes ride hex-encoded —
the TPU data plane moves real bulk through JAX arrays/ICI, not through
this control messenger, so wire-byte thrift here buys nothing.

Field conventions:
- ``reqid``: "client_name:tid" — the reference's osd_reqid_t, used for
  dup-op detection via the PG log.
- ``version``: [epoch, v] pairs — eversion_t.
- ``txn``: an ``os_store.Transaction.to_dict()`` opcode stream.
"""

from __future__ import annotations

from ..mon.messages import _JsonMessage
from ..msg.message import register_message


@register_message
class MOSDOp(_JsonMessage):
    """Client → primary: one object op batch (reference MOSDOp).
    ``snapc``: the writer's SnapContext {"seq", "snaps"} from the pool
    (reference SnapContext riding every write); read ops may carry a
    per-op "snapid" for snapshot reads.  ``dmc``: distributed-dmclock
    feedback {"delta", "rho"} — how many of this client's requests
    completed anywhere (delta) / under reservation (rho) since its
    last request to THIS osd (reference src/dmclock ReqParams).
    ``qos_client``: optional tenant/uid QoS tag (reference the rgw
    user riding req_state) — when set, the mClock scheduler keys its
    per-client streams by tenant instead of the wire entity, so
    noisy-neighbor isolation is per-tenant, not per-connection."""
    TYPE = 40
    FIELDS = ("tid", "client", "pgid", "oid", "epoch", "ops", "flags",
              "snapc", "dmc", "trace", "qos_client")


@register_message
class MOSDOpReply(_JsonMessage):
    """``dmc_phase``: which dmclock phase served the op —
    "reservation" or "priority" (reference PhaseType riding the
    reply) — the client's tracker feeds it back as rho.
    ``trace``: the OSD-side span ctx (``{"t","s"}``) echoed back so
    the client's wire_recv span nests under the server's trace."""
    TYPE = 41
    FIELDS = ("tid", "rc", "outs", "results", "version", "epoch",
              "dmc_phase", "trace")


@register_message
class MOSDRepOp(_JsonMessage):
    """Primary → replica: apply this transaction (ReplicatedBackend)."""
    TYPE = 42
    FIELDS = ("reqid", "pgid", "epoch", "txn", "version", "log_entries",
              "pg_info", "trace")


@register_message
class MOSDRepOpReply(_JsonMessage):
    TYPE = 43
    FIELDS = ("reqid", "pgid", "epoch", "rc", "from_osd")


@register_message
class MOSDPGQuery(_JsonMessage):
    """Primary → peer: send me your info/log (reference MOSDPGQuery;
    kind: "info" | "log"; since: eversion for log requests)."""
    TYPE = 44
    FIELDS = ("pgid", "epoch", "kind", "since", "from_osd")


@register_message
class MOSDPGNotify(_JsonMessage):
    """Peer → primary: my pg_info + my missing set (reference
    MOSDPGNotify; pg_missing_t travels with peering info)."""
    TYPE = 45
    FIELDS = ("pgid", "epoch", "info", "from_osd", "missing")


@register_message
class MOSDPGLog(_JsonMessage):
    """Log share / activation (reference MOSDPGLog): when ``activate``
    is set the receiver adopts the authoritative info+log and goes
    active.  ``missing``: the sender's own missing set (peering)."""
    TYPE = 46
    FIELDS = ("pgid", "epoch", "info", "entries", "activate",
              "from_osd", "missing")


@register_message
class MOSDECSubOpWrite(_JsonMessage):
    """Primary → shard k: write your chunk (reference MOSDECSubOpWrite)."""
    TYPE = 47
    FIELDS = ("reqid", "pgid", "shard", "epoch", "txn", "version",
              "log_entries", "pg_info", "trace")


@register_message
class MOSDECSubOpWriteReply(_JsonMessage):
    TYPE = 48
    FIELDS = ("reqid", "pgid", "shard", "epoch", "rc", "from_osd")


@register_message
class MOSDECSubOpRead(_JsonMessage):
    """Primary → shard: read chunk extents (reference MOSDECSubOpRead)."""
    TYPE = 49
    FIELDS = ("tid", "pgid", "shard", "epoch", "oid", "attrs")


@register_message
class MOSDECSubOpReadReply(_JsonMessage):
    TYPE = 50
    FIELDS = ("tid", "pgid", "shard", "epoch", "rc", "data", "attrs",
              "from_osd")


@register_message
class MOSDPing(_JsonMessage):
    """OSD↔OSD heartbeat (reference MOSDPing; kind: "ping" |
    "ping_reply")."""
    TYPE = 51
    FIELDS = ("from_osd", "epoch", "kind", "stamp")


@register_message
class MOSDPGPush(_JsonMessage):
    """Recovery push: full object (or shard chunk) state (reference
    MOSDPGPush carrying PushOp).  `clones`/`snapmap` carry the head's
    snap clones and their SnapMapper index rows — the reference's
    SnapSet-aware push (a recovered head without its clones would
    silently lose snapshot history).  `dedup`: {fp: chunk frame hex}
    for a dedup-manifested head — chunk payloads travel with the
    manifest so the target can ingest them into its own refcount
    index (decodes to None on pushes from older senders)."""
    TYPE = 52
    FIELDS = ("pgid", "epoch", "oid", "data", "attrs", "omap", "version",
              "from_osd", "pull_tid", "clones", "snapmap", "dedup")


@register_message
class MOSDPGPushReply(_JsonMessage):
    TYPE = 53
    FIELDS = ("pgid", "epoch", "oid", "from_osd")


@register_message
class MOSDPGPull(_JsonMessage):
    """Primary-missing recovery: ask a peer holding the object to push
    it back (reference MOSDPGPull carrying PullOp)."""
    TYPE = 54
    FIELDS = ("pgid", "epoch", "oid", "from_osd", "pull_tid")


@register_message
class MOSDScrubCommand(_JsonMessage):
    """Mon → primary OSD: operator-requested scrub/repair of one PG
    (reference MOSDScrub, the `ceph pg scrub|deep-scrub|repair` path;
    our scrub repairs inconsistencies it finds, so repair implies
    deep).  ``deep``: read data and verify digests/parity; a shallow
    scrub (deep falsy) compares metadata only."""
    TYPE = 70
    FIELDS = ("pgid", "epoch", "repair", "deep")


@register_message
class MOSDRepScrub(_JsonMessage):
    """Primary → acting member: build and return your scrub map for
    this PG (reference MOSDRepScrub → replica ScrubMap build).
    ``deep``: read payloads and digest them (deep scrub); shallow
    maps carry sizes/versions only.  ``trace``: the primary's scrub
    span ctx so replica map-build spans link to the sweep."""
    TYPE = 55
    FIELDS = ("pgid", "epoch", "scrub_tid", "from_osd", "deep",
              "trace")


@register_message
class MOSDRepScrubMap(_JsonMessage):
    """Acting member → primary: my scrub map (reference
    MOSDRepScrubMap).  objects: {oid: {"size", "crc", "version",
    "valid"}} — for EC shards "crc" is the chunk CRC-32C and "valid"
    is the self-check against the stored hinfo; deep EC maps also
    carry "data" (hex chunk payload) so the primary can re-run the
    erasure code across shards (parity recheck)."""
    TYPE = 56
    FIELDS = ("pgid", "epoch", "scrub_tid", "shard", "objects",
              "from_osd")


@register_message
class MWatchNotify(_JsonMessage):
    """Primary → watching client: a notify fired on an object you
    watch (reference ``src/messages/MWatchNotify.h``)."""
    TYPE = 57
    FIELDS = ("oid", "pgid", "notify_id", "watch_id", "data")


@register_message
class MWatchNotifyAck(_JsonMessage):
    """Watching client → primary: notify delivered+handled."""
    TYPE = 58
    FIELDS = ("oid", "pgid", "notify_id", "watch_id", "reply")


@register_message
class MOSDBackoff(_JsonMessage):
    """Primary → client: RADOS backoff (reference
    ``src/messages/MOSDBackoff.h``).  ``op`` is "block" or "unblock";
    a blocked client parks every op targeting (this OSD, this PG) and
    neither resends nor submits new ones until the matching unblock
    (or a map advance re-targets the PG).  Sent instead of silently
    queueing when the PG cannot serve (not active / below min_size) —
    the server-directed alternative to a client resend storm."""
    TYPE = 71
    FIELDS = ("pgid", "id", "op", "epoch")


@register_message
class MOSDPGBackfillPrune(_JsonMessage):
    """Primary → backfill target: the authoritative object list; the
    target removes anything extraneous (reference backfill's
    remove-extraneous pass during the scan)."""
    TYPE = 59
    FIELDS = ("pgid", "epoch", "keep", "from_osd")

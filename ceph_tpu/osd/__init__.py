"""OSD layer: cluster maps, placement groups, backends.

Reference: ``src/osd/`` (SURVEY.md §3.4/§3.5).
"""

from .osdmap import OSDMap, PGPool, PGid, ceph_stable_mod  # noqa: F401

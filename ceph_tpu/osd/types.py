"""OSD types: eversion, pg_info, pg_log, missing set.

Reference behavior re-created (``src/osd/osd_types.{h,cc}``,
``src/osd/PGLog.{h,cc}``; SURVEY.md §3.5):

- ``eversion_t`` — (epoch, version) totally ordered pairs stamping
  every PG mutation;
- ``pg_log_entry_t`` — MODIFY/DELETE/ERROR entries keyed by object,
  carrying the request id for duplicate-op detection;
- ``PGLog`` — the bounded per-PG op journal; divergence between a
  peer's ``last_update`` and the authoritative log yields that peer's
  **missing set** (object → newest version needed), which drives
  log-based recovery instead of full backfill;
- ``pg_info_t`` — the summary peers exchange during peering.

All types are dict-round-trippable: they ride in MOSDPGNotify/Log
messages and persist in the PG's meta object, the same dual life the
reference's encode/decode gives them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# log entry ops (reference pg_log_entry_t::{MODIFY,DELETE,ERROR})
MODIFY = "modify"
DELETE = "delete"
ERROR = "error"

ZERO = (0, 0)    # eversion_t() — "nothing"


def ver_str(v: tuple[int, int]) -> str:
    return f"{v[0]}'{v[1]}"


@dataclass
class LogEntry:
    op: str                     # MODIFY | DELETE | ERROR
    oid: str
    version: tuple[int, int]    # eversion: (epoch, v)
    prior_version: tuple[int, int] = ZERO
    reqid: str = ""             # "client:tid" for dup detection
    mtime: float = 0.0

    def to_dict(self) -> dict:
        return {"op": self.op, "oid": self.oid,
                "version": list(self.version),
                "prior_version": list(self.prior_version),
                "reqid": self.reqid, "mtime": self.mtime}

    @classmethod
    def from_dict(cls, d: dict) -> "LogEntry":
        return cls(op=d["op"], oid=d["oid"],
                   version=tuple(d["version"]),
                   prior_version=tuple(d.get("prior_version", ZERO)),
                   reqid=d.get("reqid", ""), mtime=d.get("mtime", 0.0))


@dataclass
class PGInfo:
    pgid: str
    last_update: tuple[int, int] = ZERO
    last_complete: tuple[int, int] = ZERO
    log_tail: tuple[int, int] = ZERO
    same_interval_since: int = 0
    epoch_created: int = 0
    # epoch at which this PG last activated (reference
    # pg_history_t::last_epoch_started) — the cutoff for which past
    # intervals peering must still account for
    last_epoch_started: int = 0
    # EC only: which shard collections this member actually holds DATA
    # for.  After a split or pgp_num re-placement the assigned shard
    # can differ from the held one (chunk identity is positional); the
    # primary reads this to re-home reconstruction sources and to mark
    # mismatched members missing (reference: per-shard pg_info_t —
    # EC PGs are addressed as pgid.shard upstream).
    shards_held: list | None = None

    def to_dict(self) -> dict:
        return {"pgid": self.pgid,
                "last_update": list(self.last_update),
                "last_complete": list(self.last_complete),
                "log_tail": list(self.log_tail),
                "same_interval_since": self.same_interval_since,
                "epoch_created": self.epoch_created,
                "last_epoch_started": self.last_epoch_started,
                "shards_held": self.shards_held}

    @classmethod
    def from_dict(cls, d: dict) -> "PGInfo":
        return cls(pgid=d["pgid"],
                   last_update=tuple(d["last_update"]),
                   last_complete=tuple(d["last_complete"]),
                   log_tail=tuple(d.get("log_tail", ZERO)),
                   same_interval_since=d.get("same_interval_since", 0),
                   epoch_created=d.get("epoch_created", 0),
                   last_epoch_started=d.get("last_epoch_started", 0),
                   shards_held=d.get("shards_held"))


MAX_DUPS = 3000     # reference osd_pg_log_dups_tracked (default 3000)


@dataclass
class PGLog:
    """The per-PG op journal (reference ``PGLog``/``pg_log_t``)."""

    entries: list[LogEntry] = field(default_factory=list)
    tail: tuple[int, int] = ZERO      # versions ≤ tail are trimmed away
    # reqids of trimmed entries (reference pg_log_dup_t): trimming must
    # not forget which client ops already applied, or a late resend
    # re-applies them
    dups: list[tuple[str, tuple[int, int]]] = field(default_factory=list)

    @property
    def head(self) -> tuple[int, int]:
        return self.entries[-1].version if self.entries else self.tail

    def add(self, e: LogEntry):
        self.entries.append(e)

    def trim(self, to: tuple[int, int]):
        """Drop entries ≤ `to`, keeping their reqids in the bounded
        dup list (reference PGLog::trim + pg_log_dup_t).  Entries are
        version-ordered, so the cut point is a bisect, not a scan —
        trim runs on every write once the log is at its cap."""
        import bisect
        idx = bisect.bisect_right(self.entries, to,
                                  key=lambda e: e.version)
        if idx:
            for e in self.entries[:idx]:
                if e.reqid:
                    self.dups.append((e.reqid, e.version))
            if len(self.dups) > MAX_DUPS:
                del self.dups[: len(self.dups) - MAX_DUPS]
            del self.entries[:idx]
        if to > self.tail:
            self.tail = to

    def find_reqid(self, reqid: str) -> LogEntry | None:
        """Duplicate-op check (reference pg_log dup detection), also
        consulting the trimmed-dup history."""
        for e in reversed(self.entries):
            if e.reqid == reqid:
                return e
        for rid, ver in reversed(self.dups):
            if rid == reqid:
                return LogEntry(op=MODIFY, oid="", version=ver,
                                reqid=rid)
        return None

    def entries_after(self, since: tuple[int, int]) -> list[LogEntry]:
        return [e for e in self.entries if e.version > since]

    def missing_for(self, peer_last_update: tuple[int, int],
                    ) -> dict[str, tuple[int, int] | None]:
        """Objects a peer at `peer_last_update` lacks, per this
        (authoritative) log: object → newest needed version, or None
        when the newest entry is a delete (reference
        PGLog::merge_log building pg_missing_t).

        Requires ``peer_last_update >= tail`` — otherwise the journal
        no longer covers the peer's gap and backfill (full resync) is
        needed; the caller checks that."""
        missing: dict[str, tuple[int, int] | None] = {}
        for e in self.entries:
            if e.version <= peer_last_update:
                continue
            if e.op == MODIFY:
                missing[e.oid] = e.version
            elif e.op == DELETE:
                missing[e.oid] = None
        return missing

    def to_dict(self) -> dict:
        return {"tail": list(self.tail),
                "entries": [e.to_dict() for e in self.entries],
                "dups": [[r, list(v)] for r, v in self.dups]}

    @classmethod
    def from_dict(cls, d: dict) -> "PGLog":
        return cls(entries=[LogEntry.from_dict(e)
                            for e in d.get("entries", [])],
                   tail=tuple(d.get("tail", ZERO)),
                   dups=[(r, tuple(v))
                         for r, v in d.get("dups", [])])

"""PG — placement group: peering, op engine, recovery, backends.

Reference behavior re-created (``src/osd/PG.{h,cc}``,
``src/osd/PeeringState.cc``, ``src/osd/PrimaryLogPG.cc``,
``src/osd/PGBackend.h``, ``src/osd/ReplicatedBackend.cc``,
``src/osd/ECBackend.cc``; SURVEY.md §3.5, §4.1–4.3):

- **Peering** (GetInfo → GetLog → Active): on every interval change the
  primary queries acting peers' ``pg_info``, adopts the authoritative
  log (highest ``last_update``), derives per-peer missing sets from log
  divergence, and activates the acting set;
- **Op engine**: client ``MOSDOp`` batches execute on the primary only;
  writes stamp an eversion, append a log entry, and fan out through the
  backend; duplicate requests are answered from the log (reqid dup
  detection); ops touching degraded objects wait for recovery
  (``wait_for_degraded_object``);
- **ReplicatedBackend**: primary-copy — apply locally, ship the same
  transaction in ``MOSDRepOp`` to every acting replica, ack the client
  when all commit;
- **ECBackend**: objects are erasure-coded through the TPU engine
  (``ceph_tpu.ec``); shard *i* of every stripe lives in collection
  ``<pgid>s<i>`` on acting[i]; reads gather ``minimum_to_decode``
  shards and decode (systematic fast path reads data shards straight
  through); degraded objects reconstruct missing chunks from k
  survivors — the §4.3 all-gather path;
- **Recovery**: log-based — push newer objects to stale peers, pull
  what the primary itself lacks; EC recovery reconstructs the missing
  shard's chunk instead of copying it.

Threading: every entry point runs under the owning daemon's lock
(mirroring the reference's per-PG lock discipline); backends never
block on network replies — completions are continuation callbacks
fired by the reply dispatch path.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..crush.map import CRUSH_ITEM_NONE
from ..ec.interface import ECProfile
from ..ec.registry import create_erasure_code
from ..os_store import Transaction
from ..osd.osdmap import PGid
from ..scrub import engine as scrub_engine
from ..scrub.crc32c_jax import crc32c
from . import messages as M
from .types import (DELETE, LogEntry, MODIFY, PGInfo, PGLog, ZERO)

META_OID = "_meta"          # per-PG meta object (info+log in omap)
SNAPMAP_OID = "_snapmapper"  # snap id → clone index (reference SnapMapper)
_SNAP_SEP = "\x00snap\x00"   # head oid + sep + seq = clone object name


def snap_clone_oid(oid: str, seq: int) -> str:
    return f"{oid}{_SNAP_SEP}{seq}"


def is_snap_clone(name: str) -> bool:
    return _SNAP_SEP in name


def _obj_meta(version, size: int, hinfo: int | None = None,
              extra: dict | None = None) -> bytes:
    """Object "_" attribute.  ``size`` is always the LOGICAL length;
    storage-efficiency extras describe the physical form: ``stored``
    (physical payload bytes), ``comp`` (compression header), ``dedup``
    (chunk manifest ``[[fp, len], ...]``)."""
    d = {"version": list(version), "size": size}
    if hinfo is not None:
        d["hinfo"] = hinfo
    if extra:
        d.update(extra)
    return json.dumps(d).encode()


def _meta_extra(meta: dict | None) -> dict | None:
    """The storage-efficiency extras of an existing "_" meta (to carry
    through rewrites that don't change the payload)."""
    if not meta:
        return None
    out = {k: meta[k] for k in ("stored", "comp", "dedup") if k in meta}
    return out or None


class PG:
    """One placement group as seen by one OSD (primary or replica).

    For EC pools each acting member instantiates the PG with its own
    ``shard`` index; collections are per-shard.
    """

    def __init__(self, daemon, pgid: PGid, pool):
        self.daemon = daemon
        self.pgid = pgid
        self.pool = pool
        self.acting: list[int] = []
        self.up: list[int] = []
        self.primary: int = -1
        self.shard: int = -1            # my index in acting (EC); -1 repl
        self.state = "reset"
        self.interval_epoch = 0
        self.info = PGInfo(pgid=str(pgid))
        self.log = PGLog()
        self.missing: dict[str, tuple | None] = {}
        # primary-only peering/recovery state
        self.peer_info: dict[int, PGInfo] = {}
        self.peer_missing: dict[int, dict[str, tuple | None]] = {}
        # what each peer SAYS it misses (exchanged during peering;
        # reference pg_missing_t) — unioned into peer_missing at
        # activation because the log diff can't see gaps behind an
        # already-adopted log
        self.peer_reported_missing: dict[int, dict] = {}
        # acting peers that confirmed THIS interval's activation (they
        # notify back on activate); _resend_activation skips them
        self.peer_activated: set[int] = set()
        self.waiting_for_active: list = []
        # RADOS backoff sessions (reference PG::Backoff): client
        # connections we told to block for this PG — released (unblock
        # sent) on activation; keyed by connection identity so one
        # block per session no matter how many ops raced in
        self.backoffs: dict[int, tuple[object, int]] = {}
        self._backoff_id = 0
        self._promote_waiters: dict[str, list] = {}
        self.waiting_for_object: dict[str, list] = {}
        self._queried: set[int] = set()
        # closed acting intervals, maintained by the daemon from the
        # full map history (reference PastIntervals); peering refuses
        # to activate while a maybe-went-rw interval since
        # last_epoch_started has no gathered representative
        self.past_intervals: list[dict] = []
        self._probe_targets: set[int] = set()
        # scrub state (primary-driven; reference src/osd/scrubber/)
        self.scrubbing = False
        self.last_scrub = 0.0
        self.last_deep_scrub = 0.0
        self.scrub_errors = 0
        self._scrub_tid = 0
        self._scrub_deep = True
        self._scrub_maps: dict[int, dict] = {}
        self._scrub_waiting: set[int] = set()
        # list-inconsistent-obj report from the last scrub that found
        # errors (primary; cleared by a clean scrub)
        self.inconsistent_objects: list[dict] = []
        # periodic scrub scheduling baseline: a never-scrubbed PG
        # waits a full interval from creation (no startup storm)
        self._scrub_stamp_floor = time.time()
        self._pulls: dict[int, str] = {}       # pull_tid → oid
        self._pull_tid = 0
        self._held_cache: list[int] | None = None   # see _held_shards
        # backfill (reference PrimaryLogPG backfill scan): peers whose
        # gap exceeds the log are refilled by walking the collection
        # in batches behind a cursor, not one giant synchronous push
        self.backfill_targets: dict[int, dict] = {}
        # watch/notify (reference src/osd/Watch.h): primary-resident
        # sessions oid → {watch_id: connection}; notifies pend until
        # every watcher acks (or the timeout fires)
        self.watchers: dict[str, dict[str, object]] = {}
        self._notifies: dict[int, dict] = {}
        self._notify_id = 0
        # storage-efficiency caches (codec keyed by pool algorithm,
        # chunker by the daemon's CDC target — both cheap to rebuild)
        self._codec_cache = None
        self._chunker_cache = None
        self.backend = (ECBackend(self) if pool.is_erasure()
                        else ReplicatedBackend(self))

    # -- identity ----------------------------------------------------------
    @property
    def is_primary(self) -> bool:
        return self.primary == self.daemon.whoami

    def cid_for_shard(self, shard: int) -> str:
        if self.pool.is_erasure():
            return f"{self.pgid}s{shard}"
        return str(self.pgid)

    @property
    def cid(self) -> str:
        return self.cid_for_shard(max(self.shard, 0))

    def acting_live(self) -> list[int]:
        """Acting members that are actually up in the current map."""
        m = self.daemon.osdmap
        return [o for o in self.acting
                if o != CRUSH_ITEM_NONE and m.is_up(o)]

    # -- storage efficiency (pool compression / dedup) ---------------------
    # The pool flags live on the OSDMap pool entry (self.pool is
    # refreshed on every map advance), so `osd pool set` takes effect
    # on the next write without touching the PG.  Reference:
    # BlueStore inline compression modes + the tiering-based dedup
    # engine (manifest objects over a refcounted chunk store).
    @property
    def compression_on(self) -> bool:
        mode = getattr(self.pool, "compression_mode", "none")
        return (mode in ("aggressive", "force")
                and bool(getattr(self.pool, "compression_algorithm",
                                 "")))

    @property
    def dedup_on(self) -> bool:
        return (bool(getattr(self.pool, "dedup_enable", False))
                and not self.pool.is_erasure())

    @property
    def efficiency_on(self) -> bool:
        return self.compression_on or self.dedup_on

    def _codec(self):
        from ..compress.registry import create_codec
        name = getattr(self.pool, "compression_algorithm", "") or "rle"
        if self._codec_cache is None or self._codec_cache.name != name:
            self._codec_cache = create_codec(name)
        return self._codec_cache

    def _chunker(self):
        if self._chunker_cache is None:
            from ..compress.chunker import Chunker
            avg = int(self.daemon.config.get("osd_dedup_chunk_avg")
                      or 4096)
            self._chunker_cache = Chunker(avg_size=avg)
        return self._chunker_cache

    def seal_payload(self, data: bytes, span, done):
        """Turn a logical payload into its stored form through the
        batch engine's comp lane.  ``done(err, stored, extra, ingest)``:
        ``stored`` = bytes to write to the object (b"" for dedup —
        the manifest in ``extra`` IS the object), ``extra`` = meta
        extras dict or None (None ⇒ plain object, bit-identical to
        efficiency-off), ``ingest`` = [(fp, frame)] chunk payloads the
        txn must dedup_ingest."""
        engine = self.daemon.batch_engine
        data = bytes(data)
        if self.dedup_on:
            mode = ("force" if getattr(self.pool, "compression_mode",
                                       "none") == "force"
                    else "aggressive")
            compress = self.compression_on

            def _chunked(comp):
                if comp.error is not None:
                    done(comp.error, None, None, None)
                    return
                spans = comp.value
                manifest = [[fp, ln] for _off, ln, fp in spans]
                uniq: dict[str, bytes] = {}
                for off, ln, fp in spans:
                    if fp not in uniq:
                        uniq[fp] = data[off:off + ln]
                self._seal_chunks(engine, manifest, uniq, compress,
                                  mode, span, done)

            engine.submit_fingerprint(self._chunker(), data, span=span,
                                      callback=_chunked)
            return
        if self.compression_on:
            mode = ("force" if getattr(self.pool, "compression_mode",
                                       "none") == "force"
                    else "aggressive")

            def _compressed(comp):
                if comp.error is not None:
                    done(comp.error, None, None, None)
                    return
                blob, hdr = comp.value
                if hdr is None:      # didn't shrink → stored verbatim
                    done(None, blob, None, [])
                else:
                    done(None, blob,
                         {"stored": len(blob), "comp": hdr}, [])

            engine.submit_compress(self._codec(), data, mode=mode,
                                   span=span, callback=_compressed)
            return
        done(None, data, None, [])

    def _seal_chunks(self, engine, manifest, uniq, compress, mode,
                     span, done):
        """Dedup phase 2: frame each unique chunk (compressing when
        the pool also enables compression — chunking happens on RAW
        content so identical chunks dedup across compression modes)."""
        from ..compress import dedup as dd
        if not uniq:
            done(None, b"", {"stored": 0, "dedup": manifest}, [])
            return
        if not compress:
            raws = {fp: dd.frame_raw(c) for fp, c in uniq.items()}
            # one ingest per manifest ENTRY (dup fps repeat): the
            # refcount invariant counts references, not unique chunks
            done(None, b"", {"stored": 0, "dedup": manifest},
                 [(fp, raws[fp]) for fp, _ln in manifest])
            return
        codec = self._codec()
        state = {"left": len(uniq), "err": None}
        frames: dict[str, bytes] = {}
        lock = self.daemon.lock

        def _one(fp, chunk):
            def _cb(comp):
                with lock:
                    if comp.error is not None:
                        if state["err"] is None:
                            state["err"] = comp.error
                    else:
                        blob, hdr = comp.value
                        frames[fp] = (dd.frame_raw(chunk) if hdr is None
                                      else dd.frame_sealed(blob, hdr))
                    state["left"] -= 1
                    if state["left"] == 0:
                        if state["err"] is not None:
                            done(state["err"], None, None, None)
                        else:
                            done(None, b"",
                                 {"stored": 0, "dedup": manifest},
                                 [(fp, frames[fp])
                                  for fp, _ln in manifest
                                  if fp in frames])
            engine.submit_compress(codec, chunk, mode=mode, span=span,
                                   callback=_cb)

        for fp, chunk in list(uniq.items()):
            _one(fp, chunk)

    def unseal_payload(self, raw, meta: dict | None) -> bytes:
        """Stored form → logical bytes (host path: expansion is
        np.repeat/zlib, nothing for the MXU)."""
        engine = self.daemon.batch_engine
        meta = meta or {}
        manifest = list(meta.get("dedup") or [])
        if manifest:
            from ..compress import dedup as dd
            store = self.daemon.store
            parts = []
            for fp, ln in manifest:
                frame = store.read(dd.DEDUP_COLL, dd.chunk_oid(fp))
                payload, hdr = dd.unframe(frame)
                chunk = (bytes(payload) if hdr is None
                         else engine.decompress(payload, hdr))
                if len(chunk) != ln:
                    raise ValueError(
                        f"dedup chunk {fp}: {len(chunk)} != {ln}")
                parts.append(chunk)
            return b"".join(parts)
        if "comp" in meta:
            stored = int(meta.get("stored", len(bytes(raw))))
            return engine.decompress(bytes(raw)[:stored], meta["comp"])
        return bytes(raw)

    # -- EC shard reality (split / re-placement) ---------------------------
    def _held_shards(self) -> list[int]:
        """Which shard collections on THIS OSD hold actual object
        data.  After a split or pgp_num re-placement the assigned
        shard can differ from the held one — peering advertises this
        so the primary can re-home reconstruction (see PGInfo).
        Cached: the store scan is O(objects); invalidated on interval
        change / split, extended in place on local writes."""
        if not self.pool.is_erasure():
            return []
        if self._held_cache is None:
            out = []
            for s in range(self.pool.size):
                cid = self.cid_for_shard(s)
                if not self.daemon.store.collection_exists(cid):
                    continue
                try:
                    objs = self.daemon.store.list_objects(cid)
                except KeyError:
                    continue
                if any(o not in (META_OID, SNAPMAP_OID) for o in objs):
                    out.append(s)
            self._held_cache = out
        return list(self._held_cache)

    def _note_local_object_write(self):
        """First write into the assigned shard collection makes it
        'held' — keep the cache truthful without a rescan."""
        if self._held_cache is not None and self.shard >= 0 \
                and self.shard not in self._held_cache:
            self._held_cache.append(self.shard)

    def _info_dict(self) -> dict:
        d = self.info.to_dict()
        if self.pool.is_erasure():
            d["shards_held"] = self._held_shards()
        return d

    def _ec_inventory(self) -> dict[str, tuple]:
        """oid → version for every object this PG should hold: the
        log's surviving writes, plus anything in locally held shard
        collections the (possibly trimmed) log no longer mentions."""
        inv: dict[str, tuple] = {}
        for e in self.log.entries:
            if e.op == DELETE:
                inv.pop(e.oid, None)
            else:
                inv[e.oid] = e.version
        store = self.daemon.store
        for s in self._held_shards():
            cid = self.cid_for_shard(s)
            for oid in store.list_objects(cid):
                if oid in (META_OID, SNAPMAP_OID) or oid in inv:
                    continue
                try:
                    meta = json.loads(bytes(store.getattr(cid, oid,
                                                          "_")))
                    inv[oid] = tuple(meta.get("version", ZERO))
                except KeyError:
                    inv[oid] = ZERO
        return inv

    # -- persistence -------------------------------------------------------
    def _persist_meta(self, txn: Transaction | None = None) -> Transaction:
        t = txn if txn is not None else Transaction()
        t.omap_setkeys(self.cid, META_OID, {
            "info": json.dumps(self.info.to_dict()).encode(),
            "log": json.dumps(self.log.to_dict()).encode(),
            # the missing set MUST survive a restart (reference:
            # pg_missing_t is persisted in the pg-log omap): a revived
            # OSD that kept its adopted log but forgot what bytes it
            # lacks would claim completeness it doesn't have, and the
            # object would silently never be recovered
            "missing": json.dumps(
                {o: list(v) if v is not None else None
                 for o, v in self.missing.items()}).encode()})
        return t

    def load_from_store(self):
        store = self.daemon.store
        try:
            meta = store.omap_get(self.cid, META_OID)
        except KeyError:
            return
        if "info" in meta:
            self.info = PGInfo.from_dict(json.loads(meta["info"]))
        if "log" in meta:
            self.log = PGLog.from_dict(json.loads(meta["log"]))
        if "missing" in meta:
            self.missing = {
                o: tuple(v) if v is not None else None
                for o, v in json.loads(meta["missing"]).items()}

    def create_onstore(self):
        if not self.daemon.store.collection_exists(self.cid):
            t = Transaction().create_collection(self.cid)
            t.touch(self.cid, META_OID)
            self.daemon.store.queue_transaction(self._persist_meta(t))

    # =======================================================================
    # peering (reference PeeringState: GetInfo → GetLog → Activate)
    # =======================================================================
    def advance_map(self, up, up_primary, acting, acting_primary, epoch):
        new_acting = list(acting)
        if new_acting != self.acting or acting_primary != self.primary:
            self.acting = new_acting
            self.up = list(up)
            self.primary = acting_primary
            if self.daemon.whoami in new_acting:
                self.shard = new_acting.index(self.daemon.whoami)
                # a PG first materialized as a stray (probe answer) has
                # no collection yet; becoming acting means we will hold
                # data, so make sure it exists before any txn lands
                self.create_onstore()
            self.interval_epoch = epoch
            self.info.same_interval_since = epoch
            self.state = "peering" if self.is_primary else "stray"
            # drop cross-interval op state; clients resend on map change
            self.scrubbing = False
            self._scrub_maps.clear()
            self._scrub_waiting.clear()
            self.backend.on_change()
            self._held_cache = None
            self.peer_info.clear()
            self.peer_missing.clear()
            self.peer_reported_missing.clear()
            self.peer_activated.clear()
            self._queried.clear()
            self._pulls.clear()     # re-pull in the new interval
            self.backfill_targets.clear()   # re-scan, pushes are
                                            # version-guarded anyway
            if self.is_primary:
                self._start_peering()
        elif self.daemon.whoami == self.primary and \
                self.state in ("reset", "stray", "down", "incomplete"):
            # same interval, but we never got going (e.g. min_size
            # regained without an acting change, or a prior-interval
            # holder came back up without changing our acting set)
            self._start_peering()
        elif self.state.startswith("active") and \
                self.waiting_for_active:
            from .osdmap import CLUSTER_FLAGS
            if not (self.daemon.osdmap.flags &
                    CLUSTER_FLAGS["pause"]):
                # an unpause epoch (same interval) releases the ops
                # the pause gate queued
                waiters, self.waiting_for_active = \
                    self.waiting_for_active, []
                for fn in waiters:
                    fn()

    def _peer_osds(self) -> list[int]:
        me = self.daemon.whoami
        return [o for o in dict.fromkeys(self.acting_live()) if o != me]

    def _prior_interval_osds(self) -> set[int]:
        """Up members of maybe-went-rw intervals since our
        last_epoch_started (reference PeeringState::build_prior's
        probe set): they may hold acknowledged writes the current
        acting set never saw, so GetInfo must include them."""
        m = self.daemon.osdmap
        me = self.daemon.whoami
        targets: set[int] = set()
        les = self.info.last_epoch_started
        for iv in self.past_intervals:
            if iv["last"] < les or not iv["maybe_went_rw"]:
                continue
            for o in iv["acting"]:
                if o != CRUSH_ITEM_NONE and o != me and m.is_up(o):
                    targets.add(o)
        return targets

    def _start_peering(self):
        self.state = "peering"
        if len(self.acting_live()) < max(1, self.pool.min_size):
            self.state = "down"      # not enough members to go active
            return
        probe = set(self._peer_osds()) | self._prior_interval_osds()
        self._probe_targets = probe
        if not probe:
            if self._check_prior_intervals():
                self._activate()
            else:
                self.state = "incomplete"
            return
        for o in probe:
            self._queried.add(o)
            self.daemon.send_to_osd(o, M.MOSDPGQuery(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                kind="info", since=None,
                from_osd=self.daemon.whoami))

    def _check_prior_intervals(self) -> bool:
        """True when every maybe-went-rw past interval since the
        newest known last_epoch_started has at least one member among
        the gathered infos (self + peers) — i.e. no interval's
        acknowledged writes can be invisible to this peering round
        (reference PeeringState 'incomplete'/'down' gating)."""
        les = max([self.info.last_epoch_started] +
                  [pi.last_epoch_started
                   for pi in self.peer_info.values()])
        known = {self.daemon.whoami} | set(self.peer_info)
        for iv in self.past_intervals:
            if iv["last"] < les or not iv["maybe_went_rw"]:
                continue
            members = [o for o in iv["acting"] if o != CRUSH_ITEM_NONE]
            if members and not any(o in known for o in members):
                return False
        return True

    def _missing_dict(self) -> dict:
        """Wire form of the local missing set (reference pg_missing_t
        travels with peering info): only MODIFY gaps — missing deletes
        self-resolve at activation."""
        return {o: list(v) for o, v in self.missing.items()
                if v is not None}

    def handle_query(self, msg: M.MOSDPGQuery):
        """Replica side: answer info/log queries."""
        if msg.kind == "info":
            self.daemon.send_to_osd(msg.from_osd, M.MOSDPGNotify(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                info=self._info_dict(), from_osd=self.daemon.whoami,
                missing=self._missing_dict()))
        elif msg.kind == "log":
            since = tuple(msg.since) if msg.since else ZERO
            entries = [e.to_dict() for e in self.log.entries_after(since)]
            self.daemon.send_to_osd(msg.from_osd, M.MOSDPGLog(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                info=self._info_dict(), entries=entries,
                activate=False, from_osd=self.daemon.whoami,
                missing=self._missing_dict()))

    def handle_notify(self, msg: M.MOSDPGNotify):
        """Primary side: collect peer infos (GetInfo), and while
        ACTIVE, activation acks — the peer confirms it activated and
        reports what it still misses."""
        if self.is_primary and self.state == "active" and \
                msg.from_osd in self.acting:
            if (msg.epoch or 0) < self.interval_epoch:
                # stale ack from a prior interval delivered after
                # on_change: counting it would mark the peer activated
                # in THIS interval (so _resend_activation never
                # re-delivers) and union a stale missing set — mirror
                # the stale-activation gate in handle_pg_log
                return
            self.peer_activated.add(msg.from_osd)
            self.peer_info[msg.from_osd] = PGInfo.from_dict(msg.info)
            pm = self.peer_missing.setdefault(msg.from_osd, {})
            changed = False
            for oid, ver in (msg.missing or {}).items():
                if oid not in pm:
                    pm[oid] = tuple(ver)
                    changed = True
            if changed:
                self._kick_recovery()
            return
        if not self.is_primary or self.state not in ("peering",
                                                     "incomplete"):
            return
        self.peer_info[msg.from_osd] = PGInfo.from_dict(msg.info)
        # the peer's own missing set: a log diff alone can't see it —
        # log adoption advances last_update BEFORE the bytes arrive,
        # so a peer re-peering mid-recovery looks complete by version
        # while still lacking objects (reference: pg_missing_t is
        # exchanged during peering, not derived)
        self.peer_reported_missing[msg.from_osd] = {
            o: tuple(v) for o, v in (msg.missing or {}).items()}
        # only wait on probe targets that are still up — a target that
        # died mid-gather is re-probed (or re-gated) by the tick retry
        m = self.daemon.osdmap
        pending = {o for o in self._probe_targets if m.is_up(o)}
        if set(self.peer_info) >= pending:
            self._choose_authoritative()

    def _choose_authoritative(self):
        """GetLog: adopt the best log if a peer is ahead of us — but
        first refuse to proceed while a prior rw interval has no
        gathered representative (acknowledged writes could be lost)."""
        if not self._check_prior_intervals():
            self.state = "incomplete"
            return
        self.state = "peering"
        best_osd, best = self.daemon.whoami, self.info
        for o, pi in self.peer_info.items():
            if pi.last_update > best.last_update:
                best_osd, best = o, pi
        if best_osd == self.daemon.whoami:
            self._activate()
        else:
            # best may be a stray from a prior interval — its log (and
            # via recovery, its objects) flow back into the acting set
            self.daemon.send_to_osd(best_osd, M.MOSDPGQuery(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                kind="log", since=list(self.info.last_update),
                from_osd=self.daemon.whoami))

    def _merge_authoritative(self, info: PGInfo, entries: list[LogEntry]):
        """Adopt a better peer's log: newer entries become local missing
        (we have the journal but not yet the bytes) — reference
        PGLog::merge_log."""
        for e in entries:
            if e.version <= self.log.head:
                continue
            self.log.add(e)
            if e.op == MODIFY:
                # pg_missing_t semantics: missing means the STORE
                # lacks the bytes — a push/backfill may already have
                # delivered this version before the log caught up
                if self.backend._object_version(e.oid) >= e.version:
                    self.missing.pop(e.oid, None)
                else:
                    self.missing[e.oid] = e.version
            elif e.op == DELETE:
                self.missing[e.oid] = None
        self.info.last_update = max(self.info.last_update,
                                    info.last_update)
        self.daemon.store.queue_transaction(self._persist_meta())

    def handle_log(self, msg: M.MOSDPGLog):
        entries = [LogEntry.from_dict(e) for e in msg.entries or []]
        info = PGInfo.from_dict(msg.info)
        if msg.activate:
            if (msg.epoch or 0) < self.interval_epoch:
                # stale activation from a deposed primary (it can be
                # re-sent on a tick): must not flip this newer
                # interval's state
                return
            # replica activation: adopt authoritative log
            self._merge_authoritative(info, entries)
            self.info.last_epoch_started = max(
                self.info.last_epoch_started, info.last_epoch_started)
            self.state = "active"
            self._apply_local_deletes()
            self.daemon.store.queue_transaction(self._persist_meta())
            # activation ACK: fresh info + missing back to the primary
            # (lets it stop re-sending and learn post-adoption gaps)
            self.daemon.send_to_osd(msg.from_osd, M.MOSDPGNotify(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                info=self._info_dict(), from_osd=self.daemon.whoami,
                missing=self._missing_dict()))
        else:
            if not self.is_primary or self.state != "peering":
                return
            if msg.missing is not None:
                self.peer_reported_missing[msg.from_osd] = {
                    o: tuple(v) for o, v in msg.missing.items()}
            self._merge_authoritative(info, entries)
            self._activate()

    def _apply_local_deletes(self):
        """Missing deletes need no recovery: apply them now."""
        for oid in [o for o, v in self.missing.items() if v is None]:
            if self.daemon.store.exists(self.cid, oid):
                self.daemon.store.queue_transaction(
                    Transaction().remove(self.cid, oid))
            del self.missing[oid]

    def _activate(self):
        """Primary: compute peer missing, activate acting set, kick
        recovery (reference PeeringState::Active + activate())."""
        # before going rw, our up_thru must reach this interval so a
        # FUTURE peering can tell this interval might have accepted
        # writes (reference PeeringState::need_up_thru / MOSDAlive);
        # stay in peering until the bumped map arrives — the tick
        # retries and the request is idempotent
        daemon = self.daemon
        if daemon.osdmap.up_thru(daemon.whoami) < self.interval_epoch:
            daemon.request_up_thru(self.interval_epoch)
            self.state = "peering"
            return
        # this interval went rw: record it so future peerings know the
        # cutoff below which past intervals no longer matter
        self.info.last_epoch_started = max(
            self.info.last_epoch_started, self.interval_epoch)
        self._apply_local_deletes()
        self.peer_missing = {}
        for o in self._peer_osds():
            pi = self.peer_info.get(o)
            plu = pi.last_update if pi else ZERO
            if plu < self.log.tail:
                # journal no longer covers the peer: backfill — walk
                # the collection behind a cursor in bounded batches
                # (reference backfill scan in PrimaryLogPG); pushes
                # racing live writes are version-guarded on apply
                pm: dict[str, tuple | None] = {}
                # objs=None: the scan initializes lazily in
                # _kick_backfill, AFTER the primary has recovered its
                # own missing objects — a snapshot taken now would
                # omit them and the prune would delete the target's
                # only copies
                self.backfill_targets[o] = {"cursor": "",
                                            "pending": set(),
                                            "objs": None}
            else:
                pm = self.log.missing_for(plu)
            # union what the peer itself reported missing: bytes it
            # never received under a log it already adopted
            for oid, ver in (self.peer_reported_missing.get(o)
                             or {}).items():
                if oid not in pm:
                    pm[oid] = ver
            self.peer_missing[o] = pm
            entries = (self.log.entries_after(plu)
                       if plu >= self.log.tail else
                       [e for e in self.log.entries])
            self.daemon.send_to_osd(o, M.MOSDPGLog(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                info=self._info_dict(),
                entries=[e.to_dict() for e in entries],
                activate=True, from_osd=self.daemon.whoami))
        if self.pool.is_erasure():
            # split / pgp_num re-placement can permute shard
            # assignments: a member whose ASSIGNED shard collection is
            # empty (its data lives under another shard id) needs its
            # whole chunk set reconstructed, invisible to the log diff
            # above because logs match (reference: EC PGs are
            # per-shard entities; this recreates the shard-granular
            # missing set)
            inv = None
            if self.shard not in self._held_shards():
                inv = self._ec_inventory()
                for oid, ver in inv.items():
                    self.missing.setdefault(oid, ver)
            for o in self._peer_osds():
                pi = self.peer_info.get(o)
                if pi is None or pi.shards_held is None:
                    continue
                if self.acting.index(o) not in pi.shards_held:
                    if inv is None:
                        inv = self._ec_inventory()
                    if inv:
                        self.peer_missing[o] = dict(inv)
        self.state = "active"
        self.daemon.store.queue_transaction(self._persist_meta())
        self.release_backoffs()
        waiters, self.waiting_for_active = self.waiting_for_active, []
        for fn in waiters:
            fn()
        self._kick_recovery()

    def _resend_activation(self):
        """Re-send the activation log to acting peers (idempotent).
        An activation can race a peer's own map advance — the peer
        lands back in 'stray' for the same interval and nothing else
        would ever deliver it (reference: peering machine re-drives
        activation; acting peers ack and the primary retries)."""
        for o in self._peer_osds():
            if o in self.peer_activated:
                continue        # confirmed: no traffic needed
            pi = self.peer_info.get(o)
            plu = pi.last_update if pi else ZERO
            entries = (self.log.entries_after(plu)
                       if plu >= self.log.tail else
                       list(self.log.entries))
            self.daemon.send_to_osd(o, M.MOSDPGLog(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                info=self._info_dict(),
                entries=[e.to_dict() for e in entries],
                activate=True, from_osd=self.daemon.whoami))

    def _list_objects(self, include_snaps: bool = False) -> list[str]:
        """Head objects by default; include_snaps adds clone objects
        (backfill/scrub want them — pgls and clients never do)."""
        try:
            objs = self.daemon.store.list_objects(self.cid)
        except KeyError:
            return []
        return [o for o in objs
                if o not in (META_OID, SNAPMAP_OID)
                and (include_snaps or not is_snap_clone(o))]

    # =======================================================================
    # recovery (log-based push/pull; EC reconstructs chunks)
    # =======================================================================
    def is_degraded_object(self, oid: str) -> bool:
        if oid in self.missing:
            return True
        return any(oid in pm for pm in self.peer_missing.values())

    @staticmethod
    def _supersedes_object(msg: M.MOSDOp) -> bool:
        """True when the op REPLACES the object wholesale — it needs
        none of the missing bytes, and applying it heals the degraded
        state (every member gets the fresh full copy and drops its
        missing entry).  Waiting on recovery here is not just slow, it
        can deadlock: an interrupted write can leave a version only
        the primary holds, unrecoverable until exactly such an
        overwrite arrives."""
        ops = [op.get("op") for op in msg.ops]
        return bool(ops) and all(o in ("write_full", "delete")
                                 for o in ops)

    def wait_for_object(self, oid: str, retry):
        self.waiting_for_object.setdefault(oid, []).append(retry)

    def _object_recovered(self, oid: str):
        waiters = self.waiting_for_object.pop(oid, [])
        for fn in waiters:
            fn()

    def _kick_recovery(self, trigger=None):
        if not self.is_primary:
            return
        # marker span for the background work burst; `trigger` (the
        # blocked op's span, or the scrub that queued repairs via
        # _scrub_trace) becomes a span LINK — causal, not parental:
        # recovery outlives and out-fans any single op's trace
        span = self.daemon.tracer.start_span(
            "recovery_kick", tags={
                "layer": "recovery", "pgid": str(self.pgid),
                "missing": len(self.missing),
                "peer_missing": sum(len(pm) for pm in
                                    self.peer_missing.values())})
        if span is not None:
            span.add_link(trigger if trigger is not None
                          else getattr(self, "_scrub_trace", None))
            span.finish()
        # pull what WE miss first (clients read from us)
        for oid, ver in list(self.missing.items()):
            if ver is None:
                continue
            self.backend.recover_primary_object(oid, ver)
        # push what peers miss
        for o, pm in self.peer_missing.items():
            for oid, ver in list(pm.items()):
                if ver is None:
                    # peer applies deletes from the log it adopted
                    pm.pop(oid, None)
                    continue
                if oid in self.missing:
                    continue       # recover locally first
                self.backend.push_object(o, oid, ver)
        self._kick_backfill()
        self._maybe_clean()

    BACKFILL_BATCH = 8      # fallback when the daemon has no config

    def _object_version_onstore(self, oid: str) -> tuple:
        try:
            meta = json.loads(bytes(self.daemon.store.getattr(
                self.cid, oid, "_")))
            return tuple(meta.get("version", ZERO))
        except KeyError:
            return self.info.last_update

    def backfill_gate(self, peer: int, oid: str,
                      is_delete: bool = False) -> bool:
        """True → send the live write to this peer now.  Objects the
        peer hasn't been backfilled yet must NOT receive partial
        mutations (they'd build on a base the peer lacks; the later
        full push would then be rejected as stale) — the backfill scan
        delivers their current state instead (reference: writes gated
        by the target's last_backfill).  Deletes always flow (removing
        a never-backfilled object is harmlessly idempotent and keeps
        pre-downtime copies from resurfacing)."""
        st = self.backfill_targets.get(peer)
        if st is None or is_delete:
            return True
        if st["objs"] is None:
            return False        # scan not started: snapshot will cover
        if oid <= st["cursor"]:
            return True         # already backfilled: live writes apply
        import bisect
        i = bisect.bisect_left(st["objs"], oid)
        if i >= len(st["objs"]) or st["objs"][i] != oid:
            st["objs"].insert(i, oid)   # new object: scan must visit
        return False

    def _kick_backfill(self):
        """Advance each backfill target by one bounded batch once its
        previous batch fully acked (reference backfill with
        osd_max_backfills-style pacing, single-queue here).  The scan
        walks the object-list snapshot taken at registration — objects
        created afterwards flow through live replication, deleted ones
        are skipped (the push would find nothing to read)."""
        import bisect
        for o, st in list(self.backfill_targets.items()):
            if st["pending"]:
                continue
            if st["objs"] is None:
                if self.missing:
                    continue    # wait until the primary is whole
                objs = self._list_objects(include_snaps=True)
                if self.daemon.store.exists(self.cid, SNAPMAP_OID):
                    # the snap index must travel too, or the target
                    # can never trim its backfilled clones
                    objs.append(SNAPMAP_OID)
                st["objs"] = sorted(objs)
                # the target may hold objects deleted on the primary
                # while it was gone and no longer in the log: hand it
                # the authoritative list to prune against (reference
                # backfill removes extraneous objects on the target)
                self.daemon.send_to_osd(o, M.MOSDPGBackfillPrune(
                    pgid=str(self.pgid),
                    epoch=self.daemon.osdmap.epoch,
                    keep=st["objs"], from_osd=self.daemon.whoami))
            objs = st["objs"]
            lo = bisect.bisect_right(objs, st["cursor"])
            batch = []
            # live pacing knob (osd_recovery_max_active observer on
            # the daemon): autotuner-retunable per kick
            cap = max(1, int(getattr(self.daemon, "recovery_max_active",
                                     self.BACKFILL_BATCH)))
            while lo < len(objs) and len(batch) < cap:
                oid = objs[lo]
                st["cursor"] = oid
                lo += 1
                if self.daemon.store.exists(self.cid, oid):
                    batch.append(oid)
            if not batch:
                if lo >= len(objs):
                    del self.backfill_targets[o]
                    self._maybe_clean()
                continue
            for oid in batch:
                st["pending"].add(oid)
                self.backend.push_object(
                    o, oid, self._object_version_onstore(oid))

    def backfill_remaining(self) -> int:
        """Objects still to push across all backfill targets —
        progress telemetry for MPGStats (reference pg_stat_t
        misplaced counts).  A target whose scan hasn't started counts
        its full listing (min 1 so pending work never reads as 0)."""
        import bisect
        rem = 0
        for st in self.backfill_targets.values():
            objs = st["objs"]
            if objs is None:
                rem += max(1, len(self._list_objects()))
            else:
                rem += len(st["pending"]) + max(
                    0, len(objs) - bisect.bisect_right(
                        objs, st["cursor"]))
        return rem

    def _maybe_clean(self):
        if self.state == "active" and not self.missing and \
                self.backfill_targets == {} and \
                not any(self.peer_missing.values()):
            self.info.last_complete = self.info.last_update
            self.state = "active+clean"

    def handle_push(self, msg: M.MOSDPGPush):
        """Receive a recovered/backfilled object (replica or primary)."""
        self.daemon.perf.inc("recovery_ops")
        self.backend.apply_push(msg)
        if msg.pull_tid is not None and self.is_primary:
            # this push answered one of OUR pulls
            oid = self._pulls.pop(msg.pull_tid, None)
            if oid is not None:
                self.missing.pop(oid, None)
                self._object_recovered(oid)
                self._kick_recovery()
        else:
            self.daemon.send_to_osd(msg.from_osd, M.MOSDPGPushReply(
                pgid=str(self.pgid), epoch=msg.epoch, oid=msg.oid,
                from_osd=self.daemon.whoami))

    def handle_push_reply(self, msg: M.MOSDPGPushReply):
        if not self.is_primary:
            return
        pm = self.peer_missing.get(msg.from_osd)
        if pm is not None:
            pm.pop(msg.oid, None)
        bf = self.backfill_targets.get(msg.from_osd)
        if bf is not None:
            bf["pending"].discard(msg.oid)
            if not bf["pending"]:
                self._kick_backfill()
        self._object_recovered(msg.oid)
        self._maybe_clean()

    def handle_pull(self, msg: M.MOSDPGPull):
        """A primary asks us to push an object back to it."""
        self.backend.answer_pull(msg)

    # =======================================================================
    # client op engine (reference PrimaryLogPG::do_op / do_osd_ops)
    # =======================================================================
    def next_version(self) -> tuple[int, int]:
        e = self.daemon.osdmap.epoch
        return (e, self.info.last_update[1] + 1)

    # -- cache tiering (reference PrimaryLogPG promote/agent paths) -------
    def _maybe_promote(self, msg: M.MOSDOp) -> bool:
        """Writeback cache-pool PGs promote on miss: an op on an
        object absent locally but present in the base pool parks
        while a background copy-up runs (the reference blocks the op
        on a promote too).  DELETEs propagate to the base first so an
        evicted cache can't resurrect them.  → True when parked."""
        pool = self.pool
        if pool is None or pool.tier_of < 0 or \
                pool.cache_mode != "writeback":
            return False
        if str(msg.client).startswith("client.tier-"):
            return False        # the agent's own ops must not recurse
        if getattr(msg, "_tier_done", False):
            return False        # agent already ran for this op
        oid = msg.oid
        is_delete = any(op.get("op") == "delete" for op in msg.ops)
        if not is_delete and \
                self.daemon.store.exists(self.cid, oid):
            return False
        waiters = self._promote_waiters.setdefault(oid, [])

        def requeue():
            msg._tier_done = True
            self.do_op(msg)

        waiters.append(requeue)
        if len(waiters) > 1:
            return True         # a promote is already in flight
        self.daemon.tier_agent(self, oid, pool.tier_of,
                               delete=is_delete)
        return True

    def _promote_done(self, oid: str):
        """Agent callback (daemon lock held): release parked ops."""
        for w in self._promote_waiters.pop(oid, []):
            w()

    def do_op(self, msg: M.MOSDOp):
        if not self.is_primary:
            self._reply(msg, -11, "not primary")   # EAGAIN: client remaps
            return
        if self.state in ("peering", "down", "reset", "stray",
                          "incomplete"):
            # RADOS backoff: tell the client to park the op instead of
            # queueing server-side / letting it resend blindly — the
            # unblock on activation releases it (reference
            # PrimaryLogPG::do_request backoff path)
            self._send_backoff(msg)
            return
        reqid = f"{msg.client}:{msg.tid}"
        dup = self.log.find_reqid(reqid)
        if dup is not None and any(
                op.get("op") in _WRITE_OPS or op.get("op") == "call"
                for op in msg.ops):
            # 'call' methods may mutate, so their resends must dedup
            # too (the dup reply can't reproduce a read-only call's
            # output — the reference stores per-dup result codes; a
            # client that truly lost a read-only reply simply retries
            # with a fresh tid)
            self._reply(msg, 0, "", results=[{}] * len(msg.ops),
                        version=dup.version)
            return
        oid = msg.oid
        if self.is_degraded_object(oid) and \
                not self._supersedes_object(msg):
            self.wait_for_object(oid, lambda: self.do_op(msg))
            self._kick_recovery(trigger=getattr(
                getattr(msg, "tracked", None), "span", None))
            return
        if self._maybe_promote(msg):
            return      # parked; requeued when the promote lands
        watchish = [op.get("op") in ("watch", "unwatch", "notify")
                    for op in msg.ops]
        if any(watchish):
            if not all(watchish):
                # a mixed batch would silently drop the data ops
                self._reply(msg, -22,
                            "watch/notify ops cannot batch with "
                            "data ops")
                return
            self._do_watch_ops(msg)
            return
        if any(op.get("op") == "call" for op in msg.ops):
            msg = self._expand_class_calls(msg)
            if msg is None:
                return      # class method failed; error already sent
        from .osdmap import CLUSTER_FLAGS
        if self.daemon.osdmap.flags & CLUSTER_FLAGS["pause"]:
            # operator paused client I/O (reference pauserd|pausewr):
            # queue, don't fail — unpausing releases everything
            self.waiting_for_active.append(lambda: self.do_op(msg))
            return
        is_write = any(op.get("op") in _WRITE_OPS for op in msg.ops)
        if is_write and \
                len(self.acting_live()) < max(1, self.pool.min_size):
            # too few live members to make the write durable: block
            # the client until peering resolves it (the map advance
            # that shrank acting_live will re-peer us into down/
            # incomplete, or recovery restores min_size and unblocks)
            self._send_backoff(msg)
            return
        if is_write and self.pool.full and \
                not all(op.get("op") == "delete" for op in msg.ops):
            # quota exceeded (reference: FULL_QUOTA pools reply
            # -EDQUOT; deletes stay allowed so the operator can free
            # space)
            self._reply(msg, -122, "pool quota exceeded")
            return
        if is_write and self.scrubbing:
            # writes quiesce during scrub (reference blocks the scrub
            # chunk range; PG granularity here) — released by
            # _maybe_finish_scrub / check_scrub_timeout
            self.waiting_for_active.append(lambda: self.do_op(msg))
            return
        try:
            if is_write:
                self.backend.submit_write(msg, reqid)
            else:
                results = self.backend.do_reads(msg)
                if results is not None:     # EC async reads return None
                    self._reply(msg, 0, "", results=results)
        except KeyError:
            self._reply(msg, -2, "no such object")   # ENOENT
        except ValueError as e:
            self._reply(msg, -22, str(e))            # EINVAL

    def _send_backoff(self, msg: M.MOSDOp):
        """Block the client session for this PG instead of queueing
        the op: it parks client-side and comes back on unblock (or a
        map advance).  Re-sends the block for an already-blocked
        session — the injector can drop the first copy, and a silent
        drop here would strand the client's periodic resends forever."""
        con = getattr(msg, "connection", None)
        if con is None:
            # internal re-entry with no session: server-side queueing
            # is the only option left
            self.waiting_for_active.append(lambda: self.do_op(msg))
            return
        # the client holds the op now; keeping it in the tracker
        # would count a parked (not stuck) op as slow forever
        self.finish_tracked(msg, "backoff")
        key = id(con)
        if key in self.backoffs:
            _, bid = self.backoffs[key]
        else:
            self._backoff_id += 1
            bid = self._backoff_id
            self.backoffs[key] = (con, bid)
        try:
            con.send_message(M.MOSDBackoff(
                pgid=str(self.pgid), id=bid, op="block",
                epoch=self.daemon.osdmap.epoch))
        except ConnectionError:
            self.backoffs.pop(key, None)
            self.waiting_for_active.append(lambda: self.do_op(msg))

    def release_backoffs(self):
        """Unblock every backed-off session (on activation)."""
        backoffs, self.backoffs = self.backoffs, {}
        for con, bid in backoffs.values():
            try:
                con.send_message(M.MOSDBackoff(
                    pgid=str(self.pgid), id=bid, op="unblock",
                    epoch=self.daemon.osdmap.epoch))
            except ConnectionError:
                pass    # client re-targets on its next map instead

    @staticmethod
    def finish_tracked(msg, event: str):
        """Finish a message's TrackedOp (idempotent).  Every path
        that stops working on an op — reply, backoff handoff,
        interval-change drop — must come through here, or the tracker
        counts the op as slow forever."""
        tracked = getattr(msg, "tracked", None)
        if tracked is not None:
            msg.tracked = None
            tracked.mark_event(event)
            tracked.finish()
        return tracked

    def _reply(self, msg: M.MOSDOp, rc: int, outs: str = "",
               results=None, version=ZERO):
        call_results = getattr(msg, "_call_results", None)
        if call_results and results is not None:
            results = list(results)
            for idx, res in call_results.items():
                if idx < len(results):
                    results[idx] = res
        # capture the server-side span ctx BEFORE finish_tracked nulls
        # msg.tracked: the reply echoes it so the client's wire_recv
        # span nests under the OSD's op span, not the client root
        span = getattr(getattr(msg, "tracked", None), "span", None)
        trace = span.ctx() if span is not None \
            else getattr(msg, "trace", None)
        tracked = self.finish_tracked(msg, "replied")
        if tracked is not None:
            self.daemon.perf.tinc("op_latency", tracked.age)
            # log2 distribution in µs (perf histogram dump / exporter);
            # the span's trace id rides along as the per-bucket
            # slowest-op exemplar (OpenMetrics `_bucket` # {...})
            try:
                self.daemon.perf.hinc(
                    "op_latency_histogram", tracked.age * 1e6,
                    trace_id=span.trace_id if span is not None
                    else None)
            except KeyError:
                pass
            # heavy-hitter attribution: client/pool/pg space-saving
            # sketches (`ceph osd top`), fed only on the primary's
            # client-op reply path — subops never misattribute here
            topk = getattr(self.daemon, "topk", None)
            if topk is not None and topk.enabled:
                topk.update(
                    client=(getattr(msg, "qos_client", None)
                            or getattr(msg, "client", None) or "?"),
                    pool=str(self.pgid.pool), pg=str(self.pgid),
                    nbytes=int(getattr(msg, "_acct_bytes", 0)),
                    lat_s=tracked.age)
        try:
            msg.connection.send_message(M.MOSDOpReply(
                tid=msg.tid, rc=rc, outs=outs, results=results,
                version=list(version), epoch=self.daemon.osdmap.epoch,
                dmc_phase=getattr(msg, "_dmc_phase", None),
                trace=trace))
        except (ConnectionError, AttributeError):
            pass

    # =======================================================================
    # object classes (reference ClassHandler + src/cls/)
    # =======================================================================
    def _expand_class_calls(self, msg: M.MOSDOp):
        """Run `call` ops on the primary: the method reads the current
        object and stages standard mutations that replace the call in
        the op list — durability then rides the normal replication
        path (reference: cls methods execute inside do_osd_ops and
        their writes join the op's transaction)."""
        from ..cls import ClsContext, ClsError, call as cls_call
        store, cid, oid = self.daemon.store, self.cid, msg.oid

        def read_xattr(name):
            try:
                return store.getattr(cid, oid, name)
            except KeyError:
                return None

        def exists():
            return store.exists(cid, oid)

        def read_omap():
            try:
                return store.omap_get(cid, oid)
            except KeyError:
                return {}

        new_ops = []
        call_results = {}
        for i, op in enumerate(msg.ops):
            if op.get("op") != "call":
                new_ops.append(op)
                continue
            ctx = ClsContext(read_xattr, exists, read_omap)
            try:
                out = cls_call(op["cls"], op["method"], ctx,
                               bytes.fromhex(op.get("data", "")))
            except ClsError as e:
                self._reply(msg, e.rc, str(e))
                return None
            call_results[len(new_ops)] = {"data": out.hex()}
            if ctx.staged_ops:
                new_ops.extend(ctx.staged_ops)
            else:
                # read-only method: keep a no-op placeholder so the
                # result stays aligned with an op slot
                new_ops.append({"op": "cls_noop"})
        expanded = M.MOSDOp(tid=msg.tid, client=msg.client,
                            pgid=msg.pgid, oid=oid, epoch=msg.epoch,
                            ops=new_ops, flags=msg.flags,
                            snapc=getattr(msg, "snapc", None))
        expanded.connection = msg.connection
        expanded.tracked = getattr(msg, "tracked", None)
        expanded._call_results = call_results
        return expanded

    # =======================================================================
    # watch / notify (reference src/osd/Watch.{h,cc} + Notify)
    # =======================================================================
    def _do_watch_ops(self, msg: M.MOSDOp):
        results = []
        for op in msg.ops:
            kind = op.get("op")
            if kind == "watch":
                wid = f"{msg.client}:{op.get('watch_id', 0)}"
                self.watchers.setdefault(msg.oid, {})[wid] = \
                    msg.connection
                results.append({"watch_id": wid})
            elif kind == "unwatch":
                wid = f"{msg.client}:{op.get('watch_id', 0)}"
                ws = self.watchers.get(msg.oid, {})
                ws.pop(wid, None)
                results.append({})
            elif kind == "notify":
                self._start_notify(msg, op)
                return          # replies when acks (or timeout) land
            else:
                results.append({})
        self._reply(msg, 0, "", results=results)

    def _start_notify(self, msg: M.MOSDOp, op: dict):
        self._notify_id += 1
        nid = self._notify_id
        targets = dict(self.watchers.get(msg.oid, {}))
        st = {"msg": msg, "waiting": set(targets), "replies": {},
              "done": False}
        self._notifies[nid] = st
        for wid, con in targets.items():
            try:
                con.send_message(M.MWatchNotify(
                    oid=msg.oid, pgid=str(self.pgid), notify_id=nid,
                    watch_id=wid, data=op.get("data", "")))
            except (ConnectionError, AttributeError):
                st["waiting"].discard(wid)
        timeout = float(op.get("timeout", 10.0))
        self.daemon.timer.add_event_after(
            timeout, lambda: self._finish_notify(nid, timed_out=True))
        self._maybe_finish_notify(nid)

    def handle_notify_ack(self, msg: M.MWatchNotifyAck):
        st = self._notifies.get(msg.notify_id)
        if st is None:
            return
        st["waiting"].discard(msg.watch_id)
        st["replies"][msg.watch_id] = msg.reply
        self._maybe_finish_notify(msg.notify_id)

    def _maybe_finish_notify(self, nid: int):
        st = self._notifies.get(nid)
        if st is not None and not st["waiting"]:
            self._finish_notify(nid)

    def _finish_notify(self, nid: int, timed_out: bool = False):
        st = self._notifies.pop(nid, None)
        if st is None or st["done"]:
            return
        st["done"] = True
        self._reply(st["msg"], 0, "", results=[{
            "notify_id": nid, "replies": st["replies"],
            "timed_out_watchers": sorted(st["waiting"])}])

    def handle_backfill_prune(self, msg):
        """Backfill target: delete objects the primary no longer has
        (they were removed while we were down and have fallen out of
        the log).  Version-epoch guard: an object written at or after
        the prune's epoch is NEVER extraneous — a stale prune from a
        deposed primary (reordered behind a newer primary's writes)
        must not delete fresh data."""
        keep = set(msg.keep or ())
        store, cid = self.daemon.store, self.cid
        for oid in self._list_objects(include_snaps=True):
            if oid in keep:
                continue
            try:
                meta = json.loads(bytes(store.getattr(cid, oid, "_")))
                ver_epoch = int(meta.get("version", ZERO)[0])
            except KeyError:
                ver_epoch = 0
            if ver_epoch >= (msg.epoch or 0):
                continue
            store.queue_transaction(Transaction().remove(cid, oid))

    def con_reset(self, con):
        """A client connection died: its watches evaporate and any
        notify still waiting on it completes without it (reference
        watch timeout/disconnect handling)."""
        dead_wids = set()
        for oid, ws in list(self.watchers.items()):
            for wid, c in list(ws.items()):
                if c is con:
                    del ws[wid]
                    dead_wids.add(wid)
            if not ws:
                self.watchers.pop(oid, None)
        for nid in list(self._notifies):
            st = self._notifies.get(nid)
            if st and st["waiting"] & dead_wids:
                st["waiting"] -= dead_wids
                self._maybe_finish_notify(nid)

    def append_log_entry(self, entry: LogEntry, txn: Transaction):
        """Stamp a mutation into the journal + meta, atomically with
        the data write (the reference writes log and data in one
        ObjectStore transaction)."""
        self.log.add(entry)
        self.info.last_update = entry.version
        self._maybe_trim_log()
        self._persist_meta(txn)

    def _maybe_trim_log(self):
        """Bound the journal (reference PGLog::trim via
        osd_min/max_pg_log_entries): every member sees the identical
        entry sequence, so local trimming converges to the same tail
        cluster-wide; peers that fall behind the tail get backfill."""
        limit = self.daemon.config.get("osd_max_pg_log_entries")
        if len(self.log.entries) > limit:
            self.log.trim(self.log.entries[-limit - 1].version)

    # =======================================================================
    # scrub (reference src/osd/scrubber/: primary gathers a ScrubMap
    # from every acting member, compares, repairs from survivors)
    # =======================================================================
    def start_scrub(self, deep: bool = True, trigger=None) -> bool:
        """Primary: kick a scrub round.  False if the PG can't scrub
        now (not primary / not active / already scrubbing / writes in
        flight — scrub maps must not race uncommitted writes).

        deep=True (the default) reads every payload and verifies
        CRC-32C digests — plus the EC parity recheck on the primary;
        deep=False is the shallow pass: sizes/versions/presence only,
        no data reads.

        noscrub/nodeep-scrub do NOT gate here: the flags suppress the
        periodic scheduler (OSD._maybe_schedule_scrub) only, while an
        operator `ceph pg scrub` overrides them — reference
        OSD::sched_scrub vs the forced-scrub path."""
        busy = (self.backend._inflight
                or getattr(self.backend, "_rmw", None)
                or getattr(self.backend, "_reads", None))
        if not self.is_primary or not self.state.startswith("active") \
                or self.scrubbing or busy:
            return False
        self.scrubbing = True
        self._scrub_deep = bool(deep)
        self._scrub_started = time.monotonic()
        self._scrub_tid += 1
        # the sweep span covers the whole round (local map build →
        # replica maps → compare); `trigger` — the operator command or
        # scheduler event that kicked it — rides as a span link, and
        # the ctx travels in MOSDRepScrub so replica digest spans link
        # back to this sweep
        span = self.daemon.tracer.start_span(
            "pg_scrub", tags={"layer": "scrub",
                              "pgid": str(self.pgid),
                              "deep": bool(deep)})
        if span is not None:
            span.add_link(trigger)
        self._scrub_span = span
        self._scrub_trace = span.ctx() if span is not None else None
        self._scrub_maps = {
            self.daemon.whoami: self.backend.build_scrub_map(deep=deep)}
        self._scrub_waiting = set(self._peer_osds())
        for o in self._scrub_waiting:
            self.daemon.send_to_osd(o, M.MOSDRepScrub(
                pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
                scrub_tid=self._scrub_tid,
                from_osd=self.daemon.whoami, deep=bool(deep),
                trace=self._scrub_trace))
        self._maybe_finish_scrub()
        return True

    def handle_rep_scrub(self, msg: M.MOSDRepScrub):
        """Acting member: walk my collection, return the scrub map."""
        # expose the primary's sweep ctx so the backend's crc_digest
        # span links to it, then drop it (we are not the sweep owner)
        self._scrub_trace = getattr(msg, "trace", None)
        try:
            objects = self.backend.build_scrub_map(
                deep=msg.deep is not False)
        finally:
            self._scrub_trace = None
        self.daemon.send_to_osd(msg.from_osd, M.MOSDRepScrubMap(
            pgid=str(self.pgid), epoch=self.daemon.osdmap.epoch,
            scrub_tid=msg.scrub_tid, shard=self.shard,
            objects=objects, from_osd=self.daemon.whoami))

    def handle_scrub_map(self, msg: M.MOSDRepScrubMap):
        if not self.scrubbing or msg.scrub_tid != self._scrub_tid:
            return
        self._scrub_maps[msg.from_osd] = msg.objects
        self._scrub_waiting.discard(msg.from_osd)
        self._maybe_finish_scrub()

    # chunk position of the in-flight sweep (scrub maps gathered vs.
    # the acting set) — rides pg_stats so the mgr progress module can
    # show per-PG scrub sweeps mid-flight
    def scrub_chunks_done(self) -> int:
        return len(self._scrub_maps)

    def scrub_chunks_total(self) -> int:
        return len(self._scrub_maps) + len(self._scrub_waiting)

    def _maybe_finish_scrub(self):
        if self._scrub_waiting:
            return
        prev_errors = self.scrub_errors
        errors = self.backend.scrub_compare(self._scrub_maps,
                                            deep=self._scrub_deep)
        if errors:
            self.daemon.perf.inc("scrub_errors_found", errors)
        elif prev_errors:
            # a clean scrub after a dirty one: the repairs took
            self.daemon.perf.inc("scrub_errors_repaired", prev_errors)
            self.inconsistent_objects = []
        self.scrub_errors = errors
        self.last_scrub = time.time()
        if self._scrub_deep:
            self.last_deep_scrub = self.last_scrub
        self.scrubbing = False
        self._scrub_maps = {}
        span = getattr(self, "_scrub_span", None)
        if span is not None:
            span.set_tag("errors", errors)
            span.finish()
            self._scrub_span = None
        if errors:
            # repair queued as recovery state by scrub_compare;
            # _scrub_trace still set → recovery_kick links to the sweep
            self.state = "active"
            self._kick_recovery()
        self._scrub_trace = None
        # release writes that queued behind the scrub
        waiters, self.waiting_for_active = self.waiting_for_active, []
        for fn in waiters:
            fn()

    def check_scrub_timeout(self, grace: float = 30.0):
        """Abort a scrub whose peers never answered (a peer without
        the PG materialized, or whose address dropped from the map) so
        the PG doesn't refuse scrubs forever."""
        if self.scrubbing and \
                time.monotonic() - getattr(self, "_scrub_started", 0.0) \
                > grace:
            self.scrubbing = False
            self._scrub_maps = {}
            self._scrub_waiting = set()
            span = getattr(self, "_scrub_span", None)
            if span is not None:
                span.set_tag("timeout", True)
                span.finish()
                self._scrub_span = None
            self._scrub_trace = None
            waiters, self.waiting_for_active = \
                self.waiting_for_active, []
            for fn in waiters:
                fn()


_WRITE_OPS = {"write", "write_full", "append", "delete", "truncate",
              "setxattr", "rmxattr", "omap_set", "omap_rm"}
_NOOP_OPS = {"cls_noop"}

# sentinel member of a repop's waiting set: the primary's own WAL
# commit (peers are int OSD ids, so a string can never collide) —
# ack-after-commit means the client reply waits for every replica's
# committed reply AND this local durability signal
_LOCAL_COMMIT = "local"


def _omap_read_result(kv: dict, op: dict) -> dict:
    """Shared omap_get result shaping: optional server-side key
    filter (reference omap_get_vals_by_keys) and keys-only mode
    (omap_get_keys) — one implementation for both backends."""
    want = op.get("keys")
    if want is not None:
        kv = {k: kv[k] for k in want if k in kv}
    if op.get("keys_only"):
        return {"kv": {k: "" for k in kv}}
    return {"kv": {k: v.hex() for k, v in kv.items()}}


def _push_is_stale(store, cid: str, msg) -> bool:
    """A backfill/recovery push racing live writes must never regress
    an object: skip apply when the local copy is already at or past
    the pushed version (the reply still flows so the primary's
    cursor advances)."""
    try:
        meta = json.loads(bytes(store.getattr(cid, msg.oid, "_")))
        local = tuple(meta.get("version", ZERO))
    except KeyError:
        return False
    # STRICTLY newer only: an equal-version push is either an
    # idempotent re-push or a scrub repair overwriting corrupt bytes
    # whose version never changed — both must apply
    return local > tuple(msg.version or ZERO)


# ===========================================================================
# Backend base — shared pull bookkeeping
# ===========================================================================
class PGBackendBase:
    """The pull-tracking protocol both backends share (reference
    ``PGBackend``): one in-flight pull per object, identified by a
    monotonically increasing per-PG pull tid that ``on_change``
    invalidates wholesale (``_pulls.clear()`` on interval change)."""

    pg: PG

    def _alloc_pull(self, oid: str) -> int | None:
        """Register a pull intent for ``oid``; None when a pull for it
        is already in flight (the single dedup point both recovery
        paths and the scrub donor-pull go through)."""
        pg = self.pg
        if any(oid == o for o in pg._pulls.values()):
            return None
        pg._pull_tid += 1
        pg._pulls[pg._pull_tid] = oid
        return pg._pull_tid

    def _send_pull(self, peer: int, oid: str) -> int | None:
        """Allocate a pull tid and request ``oid`` from ``peer``."""
        pg = self.pg
        tid = self._alloc_pull(oid)
        if tid is None:
            return None
        pg.daemon.send_to_osd(peer, M.MOSDPGPull(
            pgid=str(pg.pgid), epoch=pg.daemon.osdmap.epoch, oid=oid,
            from_osd=pg.daemon.whoami, pull_tid=tid))
        return tid


# ===========================================================================
# Replicated backend
# ===========================================================================
class ReplicatedBackend(PGBackendBase):
    """Primary-copy replication (reference ReplicatedBackend)."""

    def __init__(self, pg: PG):
        self.pg = pg
        self._inflight: dict[str, dict] = {}   # reqid → waiting state
        # per-object gate for sealed (compressed/dedup) writes: the
        # read-modify-seal pipeline is asynchronous through the comp
        # lane, so concurrent writes to one object must serialize
        # (mirrors ECBackend._rmw at object granularity)
        self._seal_gate: dict[str, list] = {}

    def on_change(self):
        # cross-interval repops die here and their clients resend
        # against the new interval — finish the tracked ops, or the
        # dropped originals count as slow ops forever
        for st in self._inflight.values():
            self.pg.finish_tracked(st.get("msg"), "reset")
        self._inflight.clear()
        self._seal_gate.clear()

    # -- writes ------------------------------------------------------------
    def submit_write(self, msg: M.MOSDOp, reqid: str):
        pg, daemon = self.pg, self.pg.daemon
        cid, oid = pg.cid, msg.oid
        if self._needs_seal(msg):
            self._submit_write_sealed(msg, reqid)
            return
        version = pg.next_version()
        prior = self._object_version(oid)
        snap_txn = self._maybe_clone_for_snap(cid, oid, msg)
        txn, results, delete = self._prepare_txn(cid, oid, msg.ops,
                                                 version)
        if snap_txn is not None:
            snap_txn.append(txn)
            txn = snap_txn
        entry = LogEntry(op=DELETE if delete else MODIFY, oid=oid,
                         version=version, prior_version=prior,
                         reqid=reqid, mtime=time.time())
        pg.append_log_entry(entry, txn)
        peers = [o for o in pg._peer_osds()
                 if pg.backfill_gate(o, oid, is_delete=delete)]
        # ack-after-commit: the primary's own WAL commit is one more
        # member of the waiting set, exactly like each replica's reply
        state = {"waiting": set(peers) | {_LOCAL_COMMIT}, "msg": msg,
                 "version": version, "results": results}
        self._inflight[reqid] = state
        wire_txn = txn.to_dict()
        # sub-ops join the trace as children of the OSD op span (fall
        # back to the client ctx when tracking was skipped)
        span = getattr(getattr(msg, "tracked", None), "span", None)
        trace = span.ctx() if span is not None \
            else getattr(msg, "trace", None)
        for o in peers:
            daemon.send_to_osd(o, M.MOSDRepOp(
                reqid=reqid, pgid=str(pg.pgid),
                epoch=daemon.osdmap.epoch, txn=wire_txn,
                version=list(version),
                log_entries=[entry.to_dict()],
                pg_info=pg.info.to_dict(), trace=trace))
        daemon.store.queue_transaction(txn, self._local_commit_cb(reqid))

    def _local_commit_cb(self, reqid: str):
        """Commit callback gating the client ack on the primary's own
        WAL durability.  Runs on the store finisher; a state that
        vanished (interval change) means the client is resending —
        nothing to do."""
        daemon = self.pg.daemon

        def _committed():
            with daemon.lock:
                st = self._inflight.get(reqid)
                if st is None:
                    return
                st["waiting"].discard(_LOCAL_COMMIT)
                self._maybe_ack(reqid)
        return _committed

    def _object_version(self, oid: str) -> tuple:
        meta = self._read_local_meta(oid)
        return tuple(meta["version"]) if meta else ZERO

    def _read_local_meta(self, oid: str) -> dict | None:
        try:
            return json.loads(bytes(self.pg.daemon.store.getattr(
                self.pg.cid, oid, "_")))
        except (KeyError, ValueError):
            return None

    # -- sealed writes (pool compression / dedup) --------------------------
    def _needs_seal(self, msg: M.MOSDOp) -> bool:
        """Data mutations route through the seal pipeline when the
        pool wants efficiency OR the object is already stored sealed
        (so turning a pool's compression off re-plains objects on
        their next write, and deletes release dedup references)."""
        if not any(op.get("op") in ("write", "write_full", "append",
                                    "truncate", "delete")
                   for op in msg.ops):
            return False
        if self.pg.efficiency_on:
            return True
        return _meta_extra(self._read_local_meta(msg.oid)) is not None

    def _submit_write_sealed(self, msg: M.MOSDOp, reqid: str):
        pg = self.pg
        oid = msg.oid
        if oid in self._seal_gate:
            self._seal_gate[oid].append(
                lambda: self._submit_write_sealed(msg, reqid))
            return
        self._seal_gate[oid] = []
        try:
            self._seal_and_submit(msg, reqid)
        except Exception as e:   # noqa: BLE001 — a poisoned op must
            # release the gate, not wedge every later write
            self._release_seal_gate(oid)
            pg._reply(msg, -22, f"write failed: {e!r}")

    def _release_seal_gate(self, oid: str):
        waiters = self._seal_gate.pop(oid, [])
        for fn in waiters:
            fn()

    def _seal_and_submit(self, msg: M.MOSDOp, reqid: str):
        """Read-modify-seal: materialize the old LOGICAL bytes, apply
        the ops logically (the EC switch shape), then run the result
        through the batch engine's comp lane; the continuation builds
        the replicated txn at its own version (assigned at txn-build
        time so the log stays monotone under async sealing)."""
        pg, daemon = self.pg, self.pg.daemon
        cid, oid = pg.cid, msg.oid
        store = daemon.store
        old_meta = self._read_local_meta(oid)
        cur = b""
        if store.exists(cid, oid):
            cur = pg.unseal_payload(store.read(cid, oid), old_meta)
        delete = False
        attr_ops = []
        results = []
        for op in msg.ops:
            kind = op.get("op")
            if kind in _NOOP_OPS:
                results.append({})
            elif kind == "write_full":
                cur = bytes.fromhex(op["data"])
                results.append({})
            elif kind == "write":
                buf = bytes.fromhex(op["data"])
                off = int(op.get("off", 0))
                base = bytearray(cur)
                if len(base) < off:
                    base.extend(b"\x00" * (off - len(base)))
                base[off:off + len(buf)] = buf
                cur = bytes(base)
                results.append({})
            elif kind == "append":
                cur = cur + bytes.fromhex(op["data"])
                results.append({})
            elif kind == "truncate":
                size = int(op["size"])
                cur = (cur[:size] if size <= len(cur)
                       else cur + b"\x00" * (size - len(cur)))
                results.append({})
            elif kind == "delete":
                want = op.get("if_version")
                if want is not None and \
                        list(self._object_version(oid)) != list(want):
                    raise ValueError(
                        "if_version mismatch: object changed")
                delete = True
                results.append({})
            elif kind in ("setxattr", "rmxattr", "omap_set",
                          "omap_rm"):
                attr_ops.append(op)
                results.append({})
            else:
                raise ValueError(f"unknown write op {kind!r}")
        if delete:
            self._finish_sealed(msg, reqid, old_meta, 0, True,
                                attr_ops, results, b"", None, [])
            return
        span = getattr(getattr(msg, "tracked", None), "span", None)

        def _sealed(err, stored, extra, ingest):
            with daemon.lock:
                if err is not None:
                    self._release_seal_gate(oid)
                    pg._reply(msg, -22, f"write failed: {err!r}")
                    return
                try:
                    self._finish_sealed(
                        msg, reqid, old_meta, len(cur), False,
                        attr_ops, results, stored, extra, ingest)
                except Exception as e:   # noqa: BLE001
                    self._release_seal_gate(oid)
                    pg._reply(msg, -22, f"write failed: {e!r}")

        pg.seal_payload(cur, span, _sealed)

    def _finish_sealed(self, msg: M.MOSDOp, reqid: str, old_meta,
                       logical_size: int, delete: bool, attr_ops,
                       results, stored: bytes, extra, ingest):
        """Build + fan out the sealed txn (under the daemon lock —
        inline for immediate flush, from the completion worker for a
        deadline lane).  New chunk references ingest BEFORE the old
        manifest releases so shared chunks never dip to zero."""
        from ..compress import dedup as dd
        pg, daemon = self.pg, self.pg.daemon
        cid, oid = pg.cid, msg.oid
        version = pg.next_version()
        prior = tuple(old_meta["version"]) if old_meta else ZERO
        old_manifest = dd.manifest_entries(old_meta)
        snap_txn = (None if delete or pg.dedup_on
                    else self._maybe_clone_for_snap(cid, oid, msg))
        txn = Transaction()
        if delete:
            txn.remove(cid, oid)
        else:
            txn.truncate(cid, oid, 0)
            if stored:
                txn.write(cid, oid, 0, stored)
            txn.setattrs(cid, oid, {"_": _obj_meta(
                version, logical_size, extra=extra)})
            for op in attr_ops:
                kind = op["op"]
                if kind == "setxattr":
                    txn.setattrs(cid, oid, {
                        op["name"]: bytes.fromhex(op["data"])})
                elif kind == "rmxattr":
                    txn.rmattr(cid, oid, op["name"])
                elif kind == "omap_set":
                    txn.omap_setkeys(cid, oid, {
                        k: bytes.fromhex(v)
                        for k, v in op["kv"].items()})
                elif kind == "omap_rm":
                    txn.omap_rmkeys(cid, oid, list(op["keys"]))
            for fp, frame in ingest:
                txn.dedup_ingest(dd.DEDUP_COLL, fp, frame)
        for fp, _ln in old_manifest:
            txn.dedup_release(dd.DEDUP_COLL, fp)
        if snap_txn is not None:
            snap_txn.append(txn)
            txn = snap_txn
        entry = LogEntry(op=DELETE if delete else MODIFY, oid=oid,
                         version=version, prior_version=prior,
                         reqid=reqid, mtime=time.time())
        pg.append_log_entry(entry, txn)
        peers = [o for o in pg._peer_osds()
                 if pg.backfill_gate(o, oid, is_delete=delete)]
        state = {"waiting": set(peers) | {_LOCAL_COMMIT}, "msg": msg,
                 "version": version, "results": results}
        self._inflight[reqid] = state
        wire_txn = txn.to_dict()
        span = getattr(getattr(msg, "tracked", None), "span", None)
        trace = span.ctx() if span is not None \
            else getattr(msg, "trace", None)
        for o in peers:
            daemon.send_to_osd(o, M.MOSDRepOp(
                reqid=reqid, pgid=str(pg.pgid),
                epoch=daemon.osdmap.epoch, txn=wire_txn,
                version=list(version),
                log_entries=[entry.to_dict()],
                pg_info=pg.info.to_dict(), trace=trace))
        daemon.store.queue_transaction(txn, self._local_commit_cb(reqid))
        # gate drops once the local (primary) apply is queued —
        # replicated primaries apply immediately, so the next queued
        # write reads this write's bytes (the ack still waits for the
        # WAL commit via _LOCAL_COMMIT)
        self._release_seal_gate(oid)

    # -- pool snapshots (reference PrimaryLogPG make_writeable +
    # SnapMapper: clone the head before the first write past each
    # snap; the clone txn replicates with the write so every acting
    # member holds identical clones) --------------------------------------
    def _maybe_clone_for_snap(self, cid, oid, msg) -> Transaction | None:
        snapc = getattr(msg, "snapc", None)
        if not snapc:
            return None
        seq = int(snapc.get("seq", 0))
        store = self.pg.daemon.store
        if not store.exists(cid, oid):
            # creation after the snaps: stamp when the object appeared
            # (snapshot reads older than that report ENOENT) AND set
            # its snap baseline — clones made later must never claim
            # to cover snaps that predate the object
            t = Transaction()
            t.touch(cid, oid)
            t.setattrs(cid, oid,
                       {"created_seq": str(seq).encode(),
                        "snap_seq": str(seq).encode()})
            return t
        try:
            last = int(bytes(store.getattr(cid, oid, "snap_seq")))
        except KeyError:
            last = 0
        if last >= seq:
            return None
        covered = sorted(s for s in (snapc.get("snaps") or ())
                         if s > last)
        t = Transaction()
        if covered:
            clone = snap_clone_oid(oid, seq)
            t.clone(cid, oid, clone)
            t.setattrs(cid, clone, {
                "snaps": json.dumps(covered).encode()})
            t.omap_setkeys(cid, SNAPMAP_OID, {
                f"{s:010d}|{oid}|{seq}": clone.encode()
                for s in covered})
        t.setattrs(cid, oid, {"snap_seq": str(seq).encode()})
        return t

    def _resolve_snap_read(self, oid: str, snapid: int) -> str | None:
        """Which object holds `oid` as of snapshot `snapid`: the
        OLDEST clone whose seq >= snapid, else the head if it has not
        been cloned past snapid (and existed by then), else nothing
        (reference SnapSet clone resolution)."""
        pg = self.pg
        store, cid = pg.daemon.store, pg.cid
        prefix = f"{oid}{_SNAP_SEP}"
        seqs = sorted(int(o[len(prefix):])
                      for o in pg._list_objects(include_snaps=True)
                      if o.startswith(prefix))
        for cseq in seqs:
            clone = snap_clone_oid(oid, cseq)
            try:
                covered = json.loads(bytes(
                    store.getattr(cid, clone, "snaps")))
            except KeyError:
                covered = []
            if snapid in covered:
                return clone
        if not store.exists(cid, oid):
            return None
        try:
            created = int(bytes(store.getattr(cid, oid,
                                              "created_seq")))
            if created >= snapid:
                return None     # didn't exist at snapshot time
        except KeyError:
            pass
        # no clone >= snapid and the object predates the snapshot:
        # the head is unchanged since then (any later write would
        # have left a clone covering snapid)
        return oid

    def _prepare_txn(self, cid, oid, ops, version):
        """The per-opcode switch (reference do_osd_ops) for mutations."""
        store = self.pg.daemon.store
        txn = Transaction()
        results = []
        delete = False
        # logical size + storage-efficiency extras come from the
        # existing meta (a sealed object's physical stat lies about
        # its length; attr-only rewrites must not clobber the extras)
        extra = None
        meta = self._read_local_meta(oid)
        if meta is not None:
            size = int(meta.get("size", 0))
            extra = _meta_extra(meta)
        else:
            size = 0
            try:
                size = store.stat(cid, oid)["size"]
            except KeyError:
                pass
        for op in ops:
            kind = op.get("op")
            if kind in _NOOP_OPS:
                results.append({})
            elif kind == "write":
                data = bytes.fromhex(op["data"])
                off = int(op.get("off", 0))
                txn.write(cid, oid, off, data)
                size = max(size, off + len(data))
                results.append({})
            elif kind == "write_full":
                data = bytes.fromhex(op["data"])
                txn.truncate(cid, oid, 0)
                txn.write(cid, oid, 0, data)
                size = len(data)
                results.append({})
            elif kind == "append":
                data = bytes.fromhex(op["data"])
                txn.write(cid, oid, size, data)
                size += len(data)
                results.append({})
            elif kind == "truncate":
                size = int(op["size"])
                txn.truncate(cid, oid, size)
                results.append({})
            elif kind == "delete":
                want = op.get("if_version")
                if want is not None and \
                        list(self._object_version(oid)) != list(want):
                    # the flush agent's guarded evict: the object
                    # changed since it was read — do NOT discard the
                    # newer write (reference assert_version semantics)
                    raise ValueError(
                        "if_version mismatch: object changed")
                txn.remove(cid, oid)
                delete = True
                results.append({})
            elif kind == "setxattr":
                txn.setattrs(cid, oid,
                             {op["name"]: bytes.fromhex(op["data"])})
                results.append({})
            elif kind == "rmxattr":
                txn.rmattr(cid, oid, op["name"])
                results.append({})
            elif kind == "omap_set":
                txn.omap_setkeys(cid, oid, {
                    k: bytes.fromhex(v) for k, v in op["kv"].items()})
                results.append({})
            elif kind == "omap_rm":
                txn.omap_rmkeys(cid, oid, list(op["keys"]))
                results.append({})
            else:
                raise ValueError(f"unknown write op {kind!r}")
        if not delete:
            txn.setattrs(cid, oid,
                         {"_": _obj_meta(version, size, extra=extra)})
        return txn, results, delete

    def _maybe_ack(self, reqid: str):
        st = self._inflight.get(reqid)
        if st is None or st["waiting"]:
            return
        del self._inflight[reqid]
        self.pg._reply(st["msg"], 0, "", results=st["results"],
                       version=st["version"])

    def handle_rep_reply(self, msg: M.MOSDRepOpReply):
        st = self._inflight.get(msg.reqid)
        if st is None:
            return
        st["waiting"].discard(msg.from_osd)
        self._maybe_ack(msg.reqid)

    # -- replica apply -----------------------------------------------------
    def apply_rep_op(self, msg: M.MOSDRepOp):
        pg, daemon = self.pg, self.pg.daemon
        daemon.perf.inc("subop")
        txn = Transaction.from_dict(msg.txn)
        for ed in msg.log_entries or []:
            e = LogEntry.from_dict(ed)
            # this txn supersedes pending recovery for the object even
            # when the entry is a dup of one merged during activation
            pg.missing.pop(e.oid, None)
            if e.version > pg.log.head:
                pg.log.add(e)
                pg.info.last_update = e.version
        pg._maybe_trim_log()
        pg._persist_meta(txn)
        reply = M.MOSDRepOpReply(
            reqid=msg.reqid, pgid=msg.pgid,
            epoch=daemon.osdmap.epoch, rc=0,
            from_osd=daemon.whoami)

        def _committed():
            # the replica's ack is its commit promise — it must not
            # leave this OSD before the txn is WAL-durable here
            with daemon.lock:
                daemon.send_to_osd(pg.primary, reply)
        daemon.store.queue_transaction(txn, _committed)

    # -- reads -------------------------------------------------------------
    def do_reads(self, msg: M.MOSDOp):
        store, cid, oid = self.pg.daemon.store, self.pg.cid, msg.oid
        results = []
        for op in msg.ops:
            kind = op.get("op")
            src = oid
            if op.get("snapid"):
                # snapshot read: resolve through the clone chain
                src = self._resolve_snap_read(oid, int(op["snapid"]))
                if src is None:
                    raise KeyError(oid)     # ENOENT at that snapshot
            if kind in _NOOP_OPS:
                results.append({})
            elif kind == "read":
                meta = self._read_local_meta(src)
                if _meta_extra(meta) is not None:
                    # sealed object: expand to logical, then slice
                    full = self.pg.unseal_payload(
                        store.read(cid, src), meta)
                    off = int(op.get("off", 0))
                    length = op.get("len")
                    end = (len(full) if length is None
                           else off + int(length))
                    results.append({"data": full[off:end].hex()})
                else:
                    length = op.get("len")
                    data = store.read(
                        cid, src, int(op.get("off", 0)),
                        None if length is None else int(length))
                    results.append({"data": data.hex()})
            elif kind == "stat":
                meta = self._read_local_meta(src)
                size = (int(meta["size"]) if meta and "size" in meta
                        else store.stat(cid, src)["size"])
                results.append({"size": size,
                                "version": self._object_version(oid)})
            elif kind == "getxattr":
                results.append(
                    {"data": store.getattr(cid, oid, op["name"]).hex()})
            elif kind == "getxattrs":
                results.append({"attrs": {
                    k: v.hex() for k, v in store.getattrs(cid, oid).items()
                    if k != "_"}})
            elif kind == "omap_get":
                results.append(_omap_read_result(
                    store.omap_get(cid, oid), op))
            elif kind == "pgls":
                results.append({"objects": self.pg._list_objects()})
            else:
                raise ValueError(f"unknown read op {kind!r}")
        return results

    # -- scrub -------------------------------------------------------------
    def build_scrub_map(self, deep: bool = True) -> dict:
        """oid → {size, crc, version} over my copy of the collection
        (reference ScrubMap build: whole-object crc per replica).
        Deep maps carry a true CRC-32C data digest — payloads are
        bucketed and digested through the batched scrub engine; a
        shallow map reads no data (size from the object meta)."""
        pg = self.pg
        store, cid = pg.daemon.store, pg.cid
        out = {}
        payloads: dict[str, bytes] = {}
        for oid in pg._list_objects(include_snaps=True):
            try:
                meta = json.loads(bytes(store.getattr(cid, oid, "_")))
                if deep:
                    payloads[oid] = bytes(store.read(cid, oid))
                    size = len(payloads[oid])
                else:
                    size = int(meta.get("size", 0))
            except KeyError:
                continue
            out[oid] = {"size": size,
                        "version": meta.get("version", list(ZERO)),
                        "valid": True}
        if deep:
            eng = scrub_engine.default_engine()
            span = pg.daemon.tracer.start_span(
                "crc_digest", tags={
                    "layer": "device", "kernel": "crc32c",
                    "pgid": str(pg.pgid), "objects": len(payloads),
                    "bytes": sum(len(b) for b in payloads.values())})
            if span is not None:
                span.add_link(getattr(pg, "_scrub_trace", None))
            for oid, digest in eng.compute_digests(payloads).items():
                out[oid]["crc"] = digest
            if span is not None:
                span.finish()
            perf = pg.daemon.perf
            perf.inc("scrub_objects_scanned", len(payloads))
            perf.inc("scrub_digest_bytes",
                     sum(len(b) for b in payloads.values()))
        return out

    def scrub_compare(self, maps: dict[int, dict],
                      deep: bool = True) -> int:
        """Majority-vote across replica digests (sizes only, for a
        shallow scrub); divergent or absent copies become recovery
        state (pushed from the authoritative copy).  Ties prefer the
        primary's copy — the reference prefers the copy matching the
        object_info digest and falls back to the primary.  Returns the
        inconsistency count and leaves a ``list-inconsistent-obj``
        report on the PG."""
        pg = self.pg
        me = pg.daemon.whoami
        oids = set()
        for m in maps.values():
            oids.update(m)
        errors = 0
        report = []
        for oid in sorted(oids):
            votes: dict[tuple, list[int]] = {}
            for osd, m in maps.items():
                e = m.get(oid)
                if e is not None:
                    votes.setdefault((e.get("crc"), e["size"]),
                                     []).append(osd)
            best = max(votes, key=lambda k: (len(votes[k]),
                                             me in votes[k]))
            good = votes[best]
            ver = tuple(next(m[oid] for m in maps.values()
                             if oid in m)["version"])
            shard_report: dict[tuple, dict] = {}
            obj_errors: set[str] = set()
            for osd, m in maps.items():
                if osd in good:
                    continue
                errors += 1
                e = m.get(oid)
                if e is None:
                    shard_report[osd, 0] = {"errors": ["missing"]}
                    obj_errors.add("missing")
                else:
                    kind = ("size_mismatch"
                            if e["size"] != best[1]
                            else "data_digest_mismatch")
                    shard_report[osd, 0] = {
                        "size": e["size"], "digest": e.get("crc"),
                        "errors": [kind]}
                    obj_errors.add(kind)
                if osd == me:
                    pg.missing[oid] = ver
                    # pull specifically from an authoritative copy
                    # (recover_primary_object would pick any peer,
                    # including another inconsistent one)
                    donor = next((o for o in good if o != me), None)
                    if donor is not None:
                        self._send_pull(donor, oid)
                else:
                    pg.peer_missing.setdefault(osd, {})[oid] = ver
            if shard_report:
                for osd in good:
                    e = maps[osd][oid]
                    shard_report[osd, 0] = {
                        "size": e["size"], "digest": e.get("crc"),
                        "errors": []}
                report.append(scrub_engine.inconsistent_entry(
                    oid, sorted(obj_errors), shard_report))
        if report:
            pg.inconsistent_objects = report
        return errors

    def snap_trim(self, removed: set[int] | None):
        """Deleted pool snaps release their clones (reference
        SnapMapper-driven snap trim): each clone's covered-snaps set
        shrinks; empty → the clone object is removed.  Runs on every
        acting member (clones are replicated, so is the trim).
        removed=None reconciles against the pool's current snap set —
        the catch-up path for an OSD that missed rmsnap epochs."""
        pg = self.pg
        store, cid = pg.daemon.store, pg.cid
        try:
            index = store.omap_get(cid, SNAPMAP_OID)
        except KeyError:
            return
        if removed is None:
            live = set(pg.pool.snaps)
            removed = {int(k.split("|", 1)[0]) for k in index} - live
            if not removed:
                return
        t = Transaction()
        dead_keys = []
        clones: dict[str, None] = {}
        for key in index:
            sid = int(key.split("|", 1)[0])
            if sid in removed:
                dead_keys.append(key)
                clones[bytes(index[key]).decode()] = None
        for clone in clones:
            try:
                covered = set(json.loads(bytes(
                    store.getattr(cid, clone, "snaps"))))
            except KeyError:
                continue
            covered -= removed
            if covered:
                t.setattrs(cid, clone, {
                    "snaps": json.dumps(sorted(covered)).encode()})
            else:
                t.remove(cid, clone)
        if dead_keys:
            t.omap_rmkeys(cid, SNAPMAP_OID, dead_keys)
        if not t.empty():
            store.queue_transaction(t)

    # -- recovery ----------------------------------------------------------
    @staticmethod
    def _snap_payload(store, cid: str, oid: str):
        """A head's snap clones + SnapMapper rows, for the push
        payload (reference: recovery is SnapSet-aware — clones travel
        with the head)."""
        clones = {}
        prefix = f"{oid}{_SNAP_SEP}"
        try:
            siblings = store.list_objects(cid)
        except KeyError:
            return None, None
        for o in siblings:
            if o.startswith(prefix):
                clones[o] = {
                    "data": store.read(cid, o).hex(),
                    "attrs": {k: v.hex() for k, v in
                              store.getattrs(cid, o).items()}}
        rows = {}
        try:
            snapmap = store.omap_get(cid, SNAPMAP_OID)
        except KeyError:
            snapmap = {}
        for key, val in snapmap.items():
            if key.split("|", 1)[1].rsplit("|", 1)[0] == oid:
                rows[key] = val.hex()
        return clones or None, rows or None

    @staticmethod
    def _dedup_payload(store, attrs) -> dict | None:
        """{fp: chunk frame hex} for a manifested head's push — chunk
        payloads travel with the manifest so the target can ingest
        them into its own refcount index."""
        from ..compress import dedup as dd
        try:
            meta = json.loads(bytes(attrs.get("_", b"{}")) or b"{}")
        except ValueError:
            return None
        frames = {}
        for fp, _ln in dd.manifest_entries(meta):
            if fp in frames:
                continue
            try:
                frames[fp] = bytes(store.read(
                    dd.DEDUP_COLL, dd.chunk_oid(fp))).hex()
            except KeyError:
                continue
        return frames or None

    def push_object(self, peer: int, oid: str, version: tuple):
        pg, daemon = self.pg, self.pg.daemon
        cid = pg.cid
        try:
            data = daemon.store.read(cid, oid)
            attrs = daemon.store.getattrs(cid, oid)
            omap = daemon.store.omap_get(cid, oid)
        except KeyError:
            return
        clones, snaprows = self._snap_payload(daemon.store, cid, oid)
        daemon.send_to_osd(peer, M.MOSDPGPush(
            pgid=str(pg.pgid), epoch=daemon.osdmap.epoch, oid=oid,
            data=data.hex(),
            attrs={k: v.hex() for k, v in attrs.items()},
            omap={k: v.hex() for k, v in omap.items()},
            version=list(version), from_osd=daemon.whoami,
            pull_tid=None, clones=clones, snapmap=snaprows,
            dedup=self._dedup_payload(daemon.store, attrs)))

    def recover_primary_object(self, oid: str, version: tuple):
        """Pull from any peer whose info covers the version."""
        pg = self.pg
        donor = next((o for o, pi in pg.peer_info.items()
                      if pi.last_update >= version), None)
        if donor is not None:
            self._send_pull(donor, oid)

    def answer_pull(self, msg: M.MOSDPGPull):
        pg, daemon = self.pg, self.pg.daemon
        try:
            data = daemon.store.read(pg.cid, msg.oid)
            attrs = daemon.store.getattrs(pg.cid, msg.oid)
            omap = daemon.store.omap_get(pg.cid, msg.oid)
        except KeyError:
            return
        meta = json.loads(bytes(attrs.get("_", b"{}")) or b"{}")
        clones, snaprows = self._snap_payload(daemon.store, pg.cid,
                                              msg.oid)
        daemon.send_to_osd(msg.from_osd, M.MOSDPGPush(
            pgid=str(pg.pgid), epoch=daemon.osdmap.epoch, oid=msg.oid,
            data=data.hex(),
            attrs={k: v.hex() for k, v in attrs.items()},
            omap={k: v.hex() for k, v in omap.items()},
            version=meta.get("version", list(ZERO)),
            from_osd=daemon.whoami, pull_tid=msg.pull_tid,
            clones=clones, snapmap=snaprows,
            dedup=self._dedup_payload(daemon.store, attrs)))

    def apply_push(self, msg: M.MOSDPGPush):
        pg, daemon = self.pg, self.pg.daemon
        cid = pg.cid
        if _push_is_stale(daemon.store, cid, msg):
            # the bytes are already here at (or past) the pushed
            # version — the object is NOT missing; forgetting to clear
            # the entry makes the peer re-report it at every peering
            # and the cluster re-push forever
            pg.missing.pop(msg.oid, None)
            return
        from ..compress import dedup as dd
        old_meta = None
        try:
            old_meta = json.loads(bytes(daemon.store.getattr(
                cid, msg.oid, "_")))
        except (KeyError, ValueError):
            pass
        t = Transaction()
        if not daemon.store.collection_exists(cid):
            t.create_collection(cid)
        t.remove(cid, msg.oid)
        t.touch(cid, msg.oid)
        if msg.data:
            t.write(cid, msg.oid, 0, bytes.fromhex(msg.data))
        if msg.attrs:
            t.setattrs(cid, msg.oid,
                       {k: bytes.fromhex(v) for k, v in msg.attrs.items()})
        # dedup bookkeeping: ingest the pushed manifest's chunks (one
        # ref per entry) BEFORE releasing the replaced local copy's
        # references — shared chunks must never dip to zero
        new_meta = None
        try:
            new_meta = json.loads(bytes.fromhex(
                (msg.attrs or {}).get("_", "")))
        except ValueError:
            pass
        frames = msg.dedup or {}
        for fp, _ln in dd.manifest_entries(new_meta):
            if fp in frames:
                t.dedup_ingest(dd.DEDUP_COLL, fp,
                               bytes.fromhex(frames[fp]))
        for fp, _ln in dd.manifest_entries(old_meta):
            t.dedup_release(dd.DEDUP_COLL, fp)
        if msg.omap:
            t.omap_setkeys(cid, msg.oid, {
                k: bytes.fromhex(v) for k, v in msg.omap.items()})
        for coid, payload in (msg.clones or {}).items():
            t.remove(cid, coid)
            t.write(cid, coid, 0, bytes.fromhex(payload["data"]))
            if payload.get("attrs"):
                t.setattrs(cid, coid, {
                    k: bytes.fromhex(v)
                    for k, v in payload["attrs"].items()})
        if msg.snapmap:
            t.omap_setkeys(cid, SNAPMAP_OID, {
                k: bytes.fromhex(v) for k, v in msg.snapmap.items()})
        pg.missing.pop(msg.oid, None)
        pg._persist_meta(t)
        daemon.store.queue_transaction(t)


# ===========================================================================
# EC backend
# ===========================================================================
class ECBackend(PGBackendBase):
    """Erasure-coded I/O (reference ECBackend): full-object writes are
    encoded into k+m shard chunks on the TPU engine; reads gather
    ``minimum_to_decode`` shards and decode (straight concat when the
    data shards survive — systematic code)."""

    def __init__(self, pg: PG):
        self.pg = pg
        self._engine = None
        self._inflight: dict[str, dict] = {}
        self._reads: dict[int, dict] = {}
        self._read_tid = 0
        # per-object read-modify-write gate: oid → queued retries
        # (reference ECBackend's extent cache serializes RMW per
        # object; PG-object granularity here)
        self._rmw: dict[str, list] = {}
        # reqids anywhere between submit and ack — resends dup-drop
        # against this (the log can't dup-detect pre-ack ops under
        # primary-applies-last)
        self._active_reqids: set = set()

    @property
    def engine(self):
        if self._engine is None:
            # resolve exactly like the mon's `osd pool create` (same
            # "default" alias and k=2/m=2 fallback): a different
            # fallback here desyncs the chunk count from pool.size —
            # CRUSH then maps a shard the encoder never produces
            prof_d = self.pg.daemon.osdmap.erasure_code_profiles.get(
                self.pg.pool.erasure_code_profile or "default",
                {"k": "2", "m": "2"})
            self._engine = create_erasure_code(ECProfile.parse(prof_d))
        return self._engine

    def on_change(self):
        # see ReplicatedBackend.on_change: dropped repops must not
        # linger in the op tracker
        for st in self._inflight.values():
            self.pg.finish_tracked(st.get("msg"), "reset")
        for st in self._reads.values():
            self.pg.finish_tracked(st.get("msg"), "reset")
        self._inflight.clear()
        self._reads.clear()
        self._rmw.clear()
        self._active_reqids.clear()

    # -- writes ------------------------------------------------------------
    def submit_write(self, msg: M.MOSDOp, reqid: str):
        """EC mutations: write_full/delete/xattr/omap apply directly;
        partial `write` and `append` on an existing object go through
        read-modify-write — gather the stripe (decode from minimum
        shards, reconstructing if degraded), splice the new bytes,
        re-encode, sub-write (reference ``src/osd/ECTransaction.cc``
        + the extent cache, at object granularity)."""
        pg = self.pg
        oid = msg.oid
        active = self._active_reqids
        if reqid in active:
            # a client resend raced the IN-FLIGHT original: with
            # primary-applies-last the log entry (and so the dup
            # check) only lands at ack time, so without this the
            # resend would queue behind the RMW gate and APPLY AGAIN
            # (double append).  Drop it — the original replies with
            # the same tid (reference: in-progress repop dup check).
            return
        active.add(reqid)
        if oid in self._rmw:
            # an RMW is mid-flight on this object: EVERY write to it
            # queues behind it (a write_full/delete slipping past
            # would be clobbered when the RMW's splice commits)
            self._rmw[oid].append(
                lambda: self._resubmit_queued(msg, reqid))
            return
        # serialize ALL writes per object, not just RMWs: the primary
        # now applies locally at ACK time (primary-applies-last), so
        # two in-flight ops on one object could complete out of order
        # and leave the primary's shard at the older bytes
        self._rmw[oid] = []
        try:
            self._submit_gated(msg, reqid, oid)
        except Exception as e:   # noqa: BLE001 — a poisoned op (bad
            # op kind, encode failure) must release the gate and fail
            # the op, not wedge every later write to this object —
            # and must clear its half-registered inflight state
            self._inflight.pop(reqid, None)
            active.discard(reqid)
            self._release_rmw(oid)
            pg._reply(msg, -22, f"write failed: {e!r}")

    def _submit_gated(self, msg: M.MOSDOp, reqid: str, oid: str):
        pg = self.pg
        exists = self._read_local_meta(oid) is not None
        kinds = [op.get("op") for op in msg.ops]
        needs_old = exists and any(k in ("write", "append", "truncate")
                                   for k in kinds)
        if needs_old:
            fake = M.MOSDOp(tid=0, client="rmw", pgid=str(pg.pgid),
                            oid=oid, epoch=pg.daemon.osdmap.epoch,
                            ops=[], flags=0)
            fake.connection = None

            def on_chunks(decoded, meta):
                size = int(meta.get("size", 0))
                k = self.engine.k
                stored = (int(meta.get("stored", size))
                          if "comp" in meta else size)
                raw = b"".join(
                    decoded[i].tobytes() for i in range(k))[:stored]
                old = pg.unseal_payload(raw, meta)
                try:
                    self._apply_ops(msg, reqid, old)
                except Exception as e:   # noqa: BLE001 — same
                    # poisoned-op handling as the synchronous path:
                    # release the gate + reqid mark + inflight state
                    # and FAIL the op, or every later write to this
                    # object wedges (and a stale inflight entry could
                    # ack a future resend early off late sub-replies)
                    self._inflight.pop(reqid, None)
                    self._active_reqids.discard(reqid)
                    self._release_rmw(oid)
                    pg._reply(msg, -22, f"write failed: {e!r}")
                # gate NOT released on success: it holds until the
                # op acks (primary-applies-last ordering)

            def on_fail():
                self._active_reqids.discard(reqid)
                self._release_rmw(oid)
                pg._reply(msg, -5, "rmw read failed")

            self._start_data_read(fake, on_chunks=on_chunks,
                                  on_fail=on_fail)
            return
        self._apply_ops(msg, reqid, b"" if not exists else None)

    def _resubmit_queued(self, msg, reqid: str):
        """Re-enter submit for a write that waited behind the RMW
        gate (clearing its active mark so the re-entry isn't treated
        as its own duplicate)."""
        self._active_reqids.discard(reqid)
        self.submit_write(msg, reqid)

    def _release_rmw(self, oid: str):
        waiters = self._rmw.pop(oid, [])
        for fn in waiters:
            fn()

    def _apply_ops(self, msg: M.MOSDOp, reqid: str,
                   old: bytes | None):
        """Build the new object payload from `old` (b"" for a fresh
        object, None when no data op needs it) and fan out."""
        pg = self.pg
        oid = msg.oid
        version = pg.next_version()
        prior = self._object_version(oid)
        data = None
        cur = old
        delete = False
        attr_ops = []
        results = []
        for op in msg.ops:
            kind = op.get("op")
            if kind in _NOOP_OPS:
                results.append({})
            elif kind == "write_full":
                cur = bytes.fromhex(op["data"])
                data = cur
                results.append({})
            elif kind == "write":
                buf = bytes.fromhex(op["data"])
                off = int(op.get("off", 0))
                base = bytearray(cur or b"")
                if len(base) < off:
                    base.extend(b"\x00" * (off - len(base)))
                base[off:off + len(buf)] = buf
                cur = bytes(base)
                data = cur
                results.append({})
            elif kind == "append":
                cur = (cur or b"") + bytes.fromhex(op["data"])
                data = cur
                results.append({})
            elif kind == "truncate":
                size = int(op["size"])
                base = (cur or b"")
                cur = (base[:size] if size <= len(base)
                       else base + b"\x00" * (size - len(base)))
                data = cur
                results.append({})
            elif kind == "delete":
                delete = True
                results.append({})
            elif kind in ("setxattr", "rmxattr", "omap_set", "omap_rm"):
                attr_ops.append(op)
                results.append({})
            else:
                raise ValueError(f"unknown write op {kind!r}")
        entry = LogEntry(op=DELETE if delete else MODIFY, oid=oid,
                         version=version, prior_version=prior,
                         reqid=reqid, mtime=time.time())
        daemon = pg.daemon
        # encode once; per-shard transactions.  The fused GF encode +
        # CRC digest is the device kernel of the write path — it goes
        # through the per-OSD batch engine, which coalesces concurrent
        # writes (across PGs and op types) into megabatch launches and
        # completes each member with its shard bytes AND per-shard
        # hinfo digests.  The fan-out continues in the completion
        # callback; with the default immediate flush this runs
        # synchronously before submit_encode returns (the old
        # semantics, bit-identically), while a deadline window makes
        # it a true async data plane.  Traced as a child of the OSD op
        # span; the engine links it to its megabatch flush span.
        if data is not None:
            k, m = self.engine.k, self.engine.m
            _ospan = getattr(getattr(msg, "tracked", None), "span",
                             None)
            span = daemon.tracer.start_span(
                "gf_encode", parent=_ospan, tags={
                    "layer": "device", "kernel": "gf_encode",
                    "bytes": len(data), "k": k, "m": m})

            def _fail(e):
                self._inflight.pop(reqid, None)
                self._active_reqids.discard(reqid)
                self._release_rmw(oid)
                pg._reply(msg, -22, f"write failed: {e!r}")

            def _encoded(comp, _extra, _dlen=len(data)):
                with daemon.lock:
                    if span is not None:
                        if comp.info:
                            span.set_tag("batch_rows",
                                         comp.info.get("rows"))
                            span.set_tag("batch_members",
                                         comp.info.get("members"))
                        span.finish()
                    if reqid not in self._active_reqids:
                        return      # op reset (on_change) mid-encode
                    if comp.error is not None:
                        _fail(comp.error)
                        return
                    shard_chunks, hinfos = comp.value
                    try:
                        self._finish_apply(
                            msg, reqid, oid, entry, version, results,
                            shard_chunks, hinfos, delete, attr_ops,
                            _dlen, extra=_extra)
                    except Exception as e:   # noqa: BLE001 — poisoned
                        # op past encode: same cleanup as submit_write
                        _fail(e)

            def _encode(payload, extra):
                with daemon.profiler.bind():
                    daemon.batch_engine.submit_encode(
                        self.engine, payload, span=span,
                        callback=lambda comp: _encoded(comp, extra))

            if pg.compression_on:
                # inline compression before the erasure code: the
                # SEALED payload is what shards into chunks — hinfo
                # CRCs stay consistent across replicas, scrub and
                # recovery move sealed bytes, reads truncate the
                # decoded concat to `stored` then expand
                def _sealed(err, stored, extra, _ingest):
                    with daemon.lock:
                        if reqid not in self._active_reqids:
                            return
                        if err is not None:
                            _fail(err)
                            return
                        try:
                            _encode(stored, extra)
                        except Exception as e:   # noqa: BLE001
                            _fail(e)

                pg.seal_payload(data, span, _sealed)
            else:
                _encode(data, None)
            return
        self._finish_apply(msg, reqid, oid, entry, version, results,
                           None, None, delete, attr_ops, None)

    def _finish_apply(self, msg: M.MOSDOp, reqid: str, oid: str,
                      entry, version, results, shard_chunks, hinfos,
                      delete: bool, attr_ops, logical_size,
                      extra=None):
        """The post-encode half of a write: min_size gate, per-shard
        transactions, primary-applies-last fan-out.  Runs inline for
        data-less ops and as the batch engine's completion for
        encoded ones (under the daemon lock either way)."""
        pg = self.pg
        daemon = pg.daemon
        live = []
        for s, o in enumerate(pg.acting):
            if o == CRUSH_ITEM_NONE or not daemon.osdmap.is_up(o):
                continue
            if o != daemon.whoami and \
                    not pg.backfill_gate(o, oid, is_delete=delete):
                continue
            live.append((s, o))
        if len(live) < max(pg.pool.min_size, self.engine.k) \
                and not delete:
            # durability floor (reference: EC PGs don't go active —
            # and writes don't ack — below min_size): acking after
            # landing on fewer shards can leave a stripe that a single
            # later failure makes unrecoverable.  EAGAIN; the client
            # retries until enough members take the write.  Deletes
            # are exempt: they remove state and replay from the log.
            pg._reply(msg, -11, "degraded below min_size")
            self._active_reqids.discard(reqid)
            self._release_rmw(oid)
            return
        # PRIMARY APPLIES LAST (write-ahead ordering): the local txn +
        # log entry are deferred until every live peer acked its
        # sub-write.  An op interrupted mid-fan-out then leaves NO
        # trace on the primary — the client's resend re-executes at a
        # fresh version and full-replace fan-out heals any peer
        # orphans.  The old order (primary first) could strand the
        # only copy of a stripe on the primary's single shard — m
        # losses of redundancy in one step and unrecoverable with
        # k > 1 (the reference avoids this with per-entry rollback
        # records in the EC log; deferring the primary is the
        # rollback-free equivalent at our op granularity).
        local = [(s, o) for s, o in live if o == daemon.whoami]
        remote = [(s, o) for s, o in live if o != daemon.whoami]
        local_txns = [self._shard_txn(s, oid, shard_chunks, delete,
                                      attr_ops, version,
                                      logical_size, hinfos=hinfos,
                                      extra=extra)
                      for s, _ in local]
        state = {"waiting": {s for s, _ in remote}, "msg": msg,
                 "version": version, "results": results,
                 "local_txns": local_txns, "entry": entry,
                 "oid": oid}
        self._inflight[reqid] = state
        span = getattr(getattr(msg, "tracked", None), "span", None)
        trace = span.ctx() if span is not None \
            else getattr(msg, "trace", None)
        for s, o in remote:
            txn = self._shard_txn(s, oid, shard_chunks, delete,
                                  attr_ops, version, logical_size,
                                  hinfos=hinfos, extra=extra)
            daemon.send_to_osd(o, M.MOSDECSubOpWrite(
                reqid=reqid, pgid=str(pg.pgid), shard=s,
                epoch=daemon.osdmap.epoch, txn=txn.to_dict(),
                version=list(version),
                log_entries=[entry.to_dict()],
                pg_info=pg.info.to_dict(), trace=trace))
        self._maybe_ack(reqid)

    def _shard_txn(self, shard: int, oid: str, chunks, delete: bool,
                   attr_ops, version, logical_size,
                   hinfos=None, extra=None) -> Transaction:
        pg = self.pg
        cid = pg.cid_for_shard(shard)
        t = Transaction()
        if delete:
            t.remove(cid, oid)
            return t
        if chunks is not None:
            chunk = chunks[shard]
            # hinfo normally arrives precomputed from the batch
            # engine's fused digest (identical by construction to the
            # host crc — asserted in tests); the host path is the
            # fallback for callers without one
            hinfo = (hinfos[shard] if hinfos is not None
                     else crc32c(chunk))
            t.truncate(cid, oid, 0)
            t.write(cid, oid, 0, chunk)
            t.setattrs(cid, oid, {"_": _obj_meta(
                version, logical_size, hinfo=hinfo, extra=extra)})
        # attr-only mutations leave "_" untouched: it carries the
        # shard's data hinfo, which an attr update must not clobber
        # (the log entry alone records the new version)
        for op in attr_ops:
            kind = op["op"]
            if kind == "setxattr":
                t.setattrs(cid, oid,
                           {op["name"]: bytes.fromhex(op["data"])})
            elif kind == "rmxattr":
                t.rmattr(cid, oid, op["name"])
            elif kind == "omap_set":
                t.omap_setkeys(cid, oid, {
                    k: bytes.fromhex(v) for k, v in op["kv"].items()})
            elif kind == "omap_rm":
                t.omap_rmkeys(cid, oid, list(op["keys"]))
        return t

    def _apply_shard_txn(self, txn: Transaction, entries,
                         on_commit=None):
        pg = self.pg
        for e in entries:
            # the applied txn supersedes any pending recovery for this
            # object even when the entry itself is a dup (an activation
            # log that raced this sub-write may have queued it missing)
            pg.missing.pop(e.oid, None)
            if e.version > pg.log.head:
                pg.log.add(e)
                pg.info.last_update = e.version
        pg._maybe_trim_log()
        pg._persist_meta(txn)
        pg.daemon.store.queue_transaction(txn, on_commit)

    def apply_sub_write(self, msg: M.MOSDECSubOpWrite):
        pg, daemon = self.pg, self.pg.daemon
        daemon.perf.inc("subop")
        txn = Transaction.from_dict(msg.txn)
        entries = [LogEntry.from_dict(e) for e in msg.log_entries or []]
        reply = M.MOSDECSubOpWriteReply(
            reqid=msg.reqid, pgid=msg.pgid, shard=msg.shard,
            epoch=daemon.osdmap.epoch, rc=0, from_osd=daemon.whoami)

        def _committed():
            # the shard ack is a commit promise: it leaves only after
            # the sub-write is WAL-durable on this OSD
            with daemon.lock:
                daemon.send_to_osd(pg.primary, reply)
        self._apply_shard_txn(txn, entries, _committed)
        pg._note_local_object_write()

    def handle_sub_write_reply(self, msg: M.MOSDECSubOpWriteReply):
        st = self._inflight.get(msg.reqid)
        if st is None:
            return
        st["waiting"].discard(msg.shard)
        self._maybe_ack(msg.reqid)

    def _maybe_ack(self, reqid: str):
        st = self._inflight.get(reqid)
        if st is None or st["waiting"]:
            return
        pg = self.pg
        daemon = pg.daemon
        if not st.get("committing"):
            # every live peer committed: NOW apply locally + log
            # (primary-applies-last -- see submit_write).  The client
            # ack additionally waits for the local shard txns and the
            # meta txn to be WAL-durable: phase two below.
            st["committing"] = True
            txns = list(st.get("local_txns") or ())
            entry = st.get("entry")
            if entry is not None:
                pg.missing.pop(st.get("oid"), None)
                pg.log.add(entry)
                pg.info.last_update = entry.version
                txns.append(pg._persist_meta())
            st["pending_commits"] = len(txns)

            def _committed():
                with daemon.lock:
                    cur = self._inflight.get(reqid)
                    if cur is not st:
                        return      # interval change swept the state
                    st["pending_commits"] -= 1
                    self._maybe_ack(reqid)
            for txn in txns:
                daemon.store.queue_transaction(txn, _committed)
        if st.get("pending_commits", 0) > 0:
            return
        del self._inflight[reqid]
        pg._reply(st["msg"], 0, "", results=st["results"],
                  version=st["version"])
        self._active_reqids.discard(reqid)
        if st.get("oid") is not None:
            self._release_rmw(st["oid"])

    # -- object meta helpers ----------------------------------------------
    def _object_version(self, oid: str) -> tuple:
        meta = self._read_local_meta(oid)
        return tuple(meta["version"]) if meta else ZERO

    def _read_local_meta(self, oid: str) -> dict | None:
        try:
            return json.loads(bytes(self.pg.daemon.store.getattr(
                self.pg.cid, oid, "_")))
        except KeyError:
            return None


    # -- reads -------------------------------------------------------------
    def do_reads(self, msg: M.MOSDOp):
        """EC reads may fan out; returns None (async) unless every
        wanted op is locally answerable."""
        pg = self.pg
        oid = msg.oid
        meta = self._read_local_meta(oid)
        simple = []
        needs_data = False
        for op in msg.ops:
            kind = op.get("op")
            if op.get("snapid"):
                raise ValueError(
                    "pool snapshots are not supported on EC pools")
            if kind in _NOOP_OPS:
                simple.append({})
            elif kind in ("read",):
                needs_data = True
            elif kind == "stat":
                if meta is None:
                    raise KeyError(oid)
                simple.append({"size": meta["size"],
                               "version": tuple(meta["version"])})
            elif kind == "getxattr":
                simple.append({"data": self.pg.daemon.store.getattr(
                    pg.cid, oid, op["name"]).hex()})
            elif kind == "getxattrs":
                simple.append({"attrs": {
                    k: v.hex() for k, v in
                    self.pg.daemon.store.getattrs(pg.cid, oid).items()
                    if k != "_"}})
            elif kind == "omap_get":
                simple.append(_omap_read_result(
                    self.pg.daemon.store.omap_get(pg.cid, oid), op))
            elif kind == "pgls":
                simple.append({"objects": pg._list_objects()})
            else:
                raise ValueError(f"unknown read op {kind!r}")
        if not needs_data:
            return simple
        if meta is None:
            raise KeyError(oid)
        self._start_data_read(msg)
        return None

    def _available_shards(self) -> dict[int, int]:
        """shard → osd by acting position, live members only."""
        pg, m = self.pg, self.pg.daemon.osdmap
        return {s: o for s, o in enumerate(pg.acting)
                if o != CRUSH_ITEM_NONE and m.is_up(o)}

    def _holders_by_shard(self) -> dict[int, list[int]]:
        """shard → acting members whose shard-s COLLECTION holds data,
        from peering-time shards_held advertisements (split /
        re-placement leftovers).  Alternates for when the assigned
        member lacks an object — sub-reads are collection-addressed,
        so any holder can serve."""
        pg, m = self.pg, self.pg.daemon.osdmap
        held_by: dict[int, list[int]] = {}
        for s in pg._held_shards():
            held_by.setdefault(s, []).append(pg.daemon.whoami)
        for o, pi in pg.peer_info.items():
            if o in pg.acting and m.is_up(o) and pi.shards_held:
                for s in pi.shards_held:
                    held_by.setdefault(s, []).append(o)
        return held_by

    def _start_data_read(self, msg: M.MOSDOp, want=None, on_chunks=None,
                         exclude: set[int] | None = None, on_fail=None):
        """Gather minimum_to_decode shards, then decode+reply (or hand
        chunks to `on_chunks` for recovery reconstruction).  `exclude`
        drops shards known not to hold the object (recovery targets,
        peers still missing it).  Every failure path fires `on_fail`
        so recovery callers can release their pull registration and
        retry later instead of wedging."""
        pg, daemon = self.pg, self.pg.daemon
        oid = msg.oid if msg is not None else None
        k = self.engine.k
        avail = self._available_shards()
        for s in exclude or ():
            avail.pop(s, None)
        holders = self._holders_by_shard()
        # assigned-member-first with alternate-holder fallback: a
        # member that still misses this object (recovery in flight, or
        # its shard collection moved in a split/re-placement) is
        # swapped for an acting member that actually HOLDS the shard
        # collection; a later -ENOENT sub-read reply retries the
        # remaining alternates (handle_sub_read_reply)
        alts: dict[int, list[int]] = {}
        demoted: dict[int, int] = {}
        for s, o in list(avail.items()):
            alts[s] = [h for h in holders.get(s, []) if h != o]
            pm = pg.peer_missing.get(o)
            misses = (pm is not None and oid in pm) or \
                (o == daemon.whoami and oid in pg.missing)
            if misses:
                if alts[s]:
                    avail[s] = alts[s].pop(0)
                else:
                    # believed-missing with no alternate holder: a
                    # LAST-RESORT probe target, not a hard exclusion —
                    # the missing belief can be stale (a peer-reported
                    # set from before its recovery completed), and a
                    # probe that truly ENOENTs is handled by the
                    # extension path; dropping it outright can leave
                    # fewer than k chunks and wedge recovery
                    demoted[s] = avail.pop(s)
        want = set(range(k)) if want is None else set(want)
        try:
            need = self.engine.minimum_to_decode(want, set(avail))
        except Exception:
            if demoted:
                avail.update(demoted)
                try:
                    need = self.engine.minimum_to_decode(
                        want, set(avail))
                except Exception:
                    need = None
            else:
                need = None
        if need is None:
            if on_fail is not None:
                on_fail()
            if msg is not None:
                if on_chunks is None and oid is not None and \
                        pg.is_degraded_object(oid):
                    # too few consistent shards ONLY because recovery
                    # is still restoring some member's copy: queue the
                    # op until the object recovers instead of failing
                    # it (reference waiting_for_degraded_object)
                    pg.wait_for_object(oid, lambda: pg.do_op(msg))
                    return
                pg._reply(msg, -5, "not enough shards to read")  # EIO
            return
        self._read_tid += 1
        tid = self._read_tid
        st = {"msg": msg, "need": set(need), "chunks": {},
              "want": want, "on_chunks": on_chunks, "oid": oid,
              "on_fail": on_fail, "alts": alts}
        self._reads[tid] = st
        for s in need:
            if not self._issue_shard_read(tid, s, avail[s]):
                return
        self._maybe_finish_read(tid)

    def _issue_shard_read(self, tid: int, s: int, o: int) -> bool:
        """Fetch shard s of st's object from osd o (local or remote).
        → False when the read aborted (state already cleaned up)."""
        pg, daemon = self.pg, self.pg.daemon
        st = self._reads.get(tid)
        if st is None:
            return False
        oid = st["oid"]
        if o != daemon.whoami:
            daemon.send_to_osd(o, M.MOSDECSubOpRead(
                tid=tid, pgid=str(pg.pgid), shard=s,
                epoch=daemon.osdmap.epoch, oid=oid, attrs=True))
            return True
        cid = pg.cid_for_shard(s)
        try:
            chunk = daemon.store.read(cid, oid)
        except KeyError:
            nxt = st["alts"].get(s)
            if nxt:
                return self._issue_shard_read(tid, s, nxt.pop(0))
            if self._shard_unfetchable(tid, s):
                return True     # read continues on other shards
            del self._reads[tid]
            if st.get("on_fail") is not None:
                st["on_fail"]()
            if st["msg"] is not None:
                pg._reply(st["msg"], -2, "no such object")
            return False
        st["chunks"][s] = chunk
        try:
            meta = json.loads(bytes(daemon.store.getattr(cid, oid,
                                                         "_")))
            # the mixed-version guard must see LOCAL chunks too — a
            # stale local shard collection is exactly as dangerous as
            # a remote one
            st.setdefault("metas", {})[s] = meta
        except KeyError:
            pass
        return True

    def handle_sub_read(self, msg: M.MOSDECSubOpRead):
        pg, daemon = self.pg, self.pg.daemon
        cid = pg.cid_for_shard(msg.shard)
        try:
            data = daemon.store.read(cid, msg.oid)
            meta = daemon.store.getattr(cid, msg.oid, "_")
            rc = 0
        except KeyError:
            data, meta, rc = b"", b"{}", -2
        daemon.send_to_osd(pg.primary, M.MOSDECSubOpReadReply(
            tid=msg.tid, pgid=msg.pgid, shard=msg.shard,
            epoch=daemon.osdmap.epoch, rc=rc, data=data.hex(),
            attrs={"_": meta.hex()}, from_osd=daemon.whoami))

    _MIXED_RETRIES = 8
    _MIXED_RETRY_DELAY = 0.25

    def _retry_read_later(self, msg: M.MOSDOp) -> bool:
        """Requeue a client read whose shard set is transiently
        inconsistent (stray holders mid-re-placement).  Bounded: after
        _MIXED_RETRIES the caller fails the op for real."""
        tries = getattr(msg, "_mixed_retries", 0)
        if tries >= self._MIXED_RETRIES:
            return False
        msg._mixed_retries = tries + 1
        pg = self.pg
        pg.daemon.timer.add_event_after(
            self._MIXED_RETRY_DELAY,
            lambda: pg.daemon.op_queue.enqueue("client", msg))
        return True

    def handle_sub_read_reply(self, msg: M.MOSDECSubOpReadReply):
        st = self._reads.get(msg.tid)
        if st is None:
            return
        if msg.rc != 0:
            # the assigned member may simply not hold this object's
            # chunk yet (split / re-placement): try the remaining
            # holders of the shard collection before failing
            nxt = (st.get("alts") or {}).get(msg.shard)
            if msg.rc == -2 and nxt:
                self._issue_shard_read(msg.tid, msg.shard, nxt.pop(0))
                self._maybe_finish_read(msg.tid)
                return
            if msg.rc == -2 and self._shard_unfetchable(msg.tid,
                                                        msg.shard):
                return          # read continues on other shards
            del self._reads[msg.tid]
            if st.get("on_fail") is not None:
                st["on_fail"]()
            if st["msg"] is not None:
                self.pg._reply(st["msg"], msg.rc, "shard read failed")
            return
        chunk = bytes.fromhex(msg.data)
        # verify the per-chunk checksum before trusting it (reference
        # HashInfo crc verification on sub-read)
        meta = json.loads(bytes.fromhex(msg.attrs["_"]))
        hinfo = meta.get("hinfo")
        if hinfo is not None and crc32c(chunk) != hinfo:
            del self._reads[msg.tid]
            if st.get("on_fail") is not None:
                st["on_fail"]()
            if st["msg"] is not None:
                self.pg._reply(st["msg"], -5, "chunk crc mismatch")
            return
        st["chunks"][msg.shard] = chunk
        st.setdefault("metas", {})[msg.shard] = meta
        self._maybe_finish_read(msg.tid)

    def _shard_unfetchable(self, tid: int, s: int) -> bool:
        """Shard s ENOENTed with no alternates: drop it from the read
        set and extend to other shards if decode stays feasible.
        → True when the read survives (caller must not tear down)."""
        st = self._reads.get(tid)
        if st is None:
            return True
        st["need"].discard(s)
        st.setdefault("attempted", set()).add(s)
        try:
            feasible_now = set(self.engine.minimum_to_decode(
                st["want"], set(st["need"]))) <= set(st["need"])
        except Exception:
            feasible_now = False
        if feasible_now:
            self._maybe_finish_read(tid)
            return True
        return self._extend_read(tid)

    def _extend_read(self, tid: int):
        """Grow a read's shard set with untried members (preferring
        ones believed to hold the object; believed-missing members are
        last-resort probes — the belief can be stale).  → False when no
        extension is possible (state intact, caller fails the read);
        True when handled — extended, completed, or torn down."""
        st = self._reads.get(tid)
        if st is None:
            return True     # state already gone: nothing more to do
        attempted = st.setdefault("attempted", set(st["need"]))
        avail = self._available_shards()
        oid = st.get("oid")
        pg = self.pg
        preferred, fallback = [], []
        for s, o in avail.items():
            if s in st["chunks"] or s in attempted:
                continue
            misses = (o == pg.daemon.whoami
                      and oid in pg.missing) or \
                (oid in (pg.peer_missing.get(o) or ()))
            (fallback if misses else preferred).append(s)
        extra = preferred or fallback
        if not extra:
            return False            # no extension possible; state intact
        for s in extra:
            attempted.add(s)
            st["need"].add(s)
            if not self._issue_shard_read(tid, s, avail[s]):
                return True         # read state torn down
        if set(st["chunks"]) >= st["need"]:
            self._maybe_finish_read(tid)
        return True                 # handled (completed or awaiting)

    def _maybe_finish_read(self, tid: int):
        st = self._reads.get(tid)
        if st is None or set(st["chunks"]) < st["need"]:
            return
        # a stale stray shard collection (pre-re-placement leftover)
        # must never be decoded against fresh chunks; but mixed
        # versions are NORMAL under thrash — a shard that was down
        # during the write still holds the old object until recovery
        # pushes it.  Decode from the shards at the NEWEST version
        # when they still satisfy the code (reference ECBackend
        # get_min_avail_to_read_shards consults the missing set to
        # the same effect); fail only when they cannot.
        metas = st.get("metas") or {}
        vers_map = {s: tuple(m.get("version", ZERO))
                    for s, m in metas.items()}
        vers = set(vers_map.values())
        if len(vers) > 1:
            # choose the NEWEST version the gathered chunks can
            # actually decode.  An un-acked interrupted write can
            # leave a newer version on a MINORITY of shards (fewer
            # than k) — that version was never acknowledged, so
            # falling back to the previous feasible one IS the
            # correct outcome (the reference reaches the same result
            # via per-entry rollback of uncommitted EC log entries).
            # never fall below the version the PRIMARY's log carries:
            # with primary-applies-last, a logged version IS an acked
            # version, and serving anything older would be silent
            # rollback of an acknowledged write
            oid = st.get("oid")
            committed = ZERO
            if oid is not None:
                for e in reversed(self.pg.log.entries):
                    if e.oid == oid:
                        committed = e.version
                        break
            fresh = None
            for cand in sorted(vers, reverse=True):
                if cand < committed:
                    break
                cset = {s: c for s, c in st["chunks"].items()
                        if vers_map.get(s) == cand}
                try:
                    need = self.engine.minimum_to_decode(
                        st["want"], set(cset))
                    if set(need) <= set(cset):
                        fresh = cset
                        newest = cand
                        break
                except Exception:
                    continue
            if fresh is None:
                # no gathered version decodes: EXTEND the read to
                # shards not yet tried before giving up (reference:
                # ECBackend re-issues to remaining shards on errors)
                if self._extend_read(tid):
                    return
                del self._reads[tid]
                if st.get("on_fail") is not None:
                    st["on_fail"]()
                msg = st["msg"]
                if msg is not None:
                    oid = st.get("oid")
                    if st.get("on_chunks") is None and oid and \
                            self.pg.is_degraded_object(oid):
                        # stale shards will be overwritten by the
                        # in-flight recovery: retry after it lands
                        self.pg.wait_for_object(
                            oid, lambda: self.pg.do_op(msg))
                        return
                    if st.get("on_chunks") is None and \
                            self._retry_read_later(msg):
                        # a stale STRAY holder answered (its copy
                        # predates a re-placement) and not enough
                        # acting shards agree yet — recovery isn't
                        # tracking strays, so back off briefly and
                        # re-target; fail only when it persists
                        return
                    self.pg._reply(msg, -5,
                                   "mixed-version shard chunks")
                return
            st["chunks"] = fresh
            metas = {s: m for s, m in metas.items()
                     if vers_map.get(s) == newest}
        st["meta"] = next(iter(metas.values()), {})
        del self._reads[tid]
        chunks = {s: np.frombuffer(c, dtype=np.uint8)
                  for s, c in st["chunks"].items()}
        self._submit_decode(st, chunks)

    def _submit_decode(self, st: dict, chunks: dict):
        """The decode half of a gathered read, split submit/completion
        through the batch engine's reconstruct lane (mirroring the
        write path's ``_finish_apply`` split): degraded client reads,
        ``recover_primary_object`` reconstructs, and backfill/repair
        pushes — from every PG on this OSD — coalesce into fused
        per-(code, erasure-pattern, size-bucket) megabatch launches.
        With the lane disabled or ``recon_flush_ms=0`` the completion
        fires synchronously before submit returns, preserving the old
        one-decode-at-a-time semantics exactly."""
        pg = self.pg
        daemon = pg.daemon
        epoch = pg.interval_epoch
        span = daemon.tracer.start_span(
            "gf_decode", tags={
                "layer": "device", "kernel": "gf_decode",
                "pgid": str(pg.pgid), "shards": len(chunks),
                "want": len(st["want"])})

        def _decoded(comp):
            with daemon.lock:
                if span is not None:
                    if comp.info:
                        span.set_tag("batch_rows",
                                     comp.info.get("rows"))
                        span.set_tag("batch_members",
                                     comp.info.get("members"))
                    span.finish()
                if pg.interval_epoch != epoch:
                    # the interval changed while the decode was in
                    # flight: on_change already reset the read/pull
                    # world this completion would touch — drop it
                    # (clients resend, recovery re-peers)
                    if st.get("on_fail") is not None:
                        st["on_fail"]()
                    return
                if comp.error is not None:
                    if st.get("on_fail") is not None:
                        st["on_fail"]()
                    if st["msg"] is not None and \
                            st.get("on_chunks") is None:
                        pg._reply(st["msg"], -5,
                                  f"decode failed: {comp.error!r}")
                    return
                self._finish_decoded(st, comp.value)

        with daemon.profiler.bind():
            daemon.batch_engine.submit_reconstruct(
                self.engine, chunks, want=st["want"], span=span,
                callback=_decoded)

    def _finish_decoded(self, st: dict, decoded: dict):
        """Completion half: assemble the client reply (or hand the
        decoded chunks to the recovery continuation).  Runs under the
        daemon lock either way — inline for immediate mode, on the
        engine's FIFO completion worker for batched mode."""
        if st["on_chunks"] is not None:
            st["on_chunks"](decoded, st.get("meta") or {})
            return
        meta = st.get("meta") or {}
        size = int(meta.get("size", 0))
        stored = (int(meta.get("stored", size))
                  if "comp" in meta else size)
        raw = np.concatenate(
            [decoded[i] for i in sorted(st["want"])]).tobytes()[:stored]
        payload = self.pg.unseal_payload(raw, meta)
        results = []
        msg = st["msg"]
        for op in msg.ops:
            kind = op.get("op")
            if kind == "read":
                off = int(op.get("off", 0))
                ln = op.get("len")
                end = len(payload) if ln is None else off + int(ln)
                results.append({"data": payload[off:end].hex()})
            elif kind == "stat":
                results.append({"size": size,
                                "version": tuple(meta["version"])})
            else:
                # non-data ops re-run locally for the final answer
                results.append({})
        self.pg._reply(msg, 0, "", results=results,
                       version=tuple(meta.get("version", ZERO)))

    # -- recovery ----------------------------------------------------------
    def push_object(self, peer: int, oid: str, version: tuple):
        """Reconstruct the peer's shard chunk from k survivors and push
        it (reference ECBackend recovery — the §4.3 reconstruct)."""
        pg = self.pg
        shard = pg.acting.index(peer)
        fake = M.MOSDOp(tid=0, client="recovery", pgid=str(pg.pgid),
                        oid=oid, epoch=pg.daemon.osdmap.epoch,
                        ops=[], flags=0)
        fake.connection = None

        def on_chunks(decoded, meta):
            chunk = decoded[shard].tobytes()
            pg.daemon.send_to_osd(peer, M.MOSDPGPush(
                pgid=str(pg.pgid), epoch=pg.daemon.osdmap.epoch,
                oid=oid, data=chunk.hex(),
                attrs={"_": _obj_meta(
                    tuple(meta.get("version", version)),
                    int(meta.get("size", 0)),
                    hinfo=crc32c(chunk),
                    extra=_meta_extra(meta)).hex()},
                omap={}, version=list(version),
                from_osd=pg.daemon.whoami, pull_tid=None))

        self._start_data_read(fake, want={shard}, on_chunks=on_chunks,
                              exclude={shard})

    def recover_primary_object(self, oid: str, version: tuple):
        pg = self.pg
        pull_tid = self._alloc_pull(oid)
        if pull_tid is None:
            return
        shard = pg.shard
        fake = M.MOSDOp(tid=0, client="recovery", pgid=str(pg.pgid),
                        oid=oid, epoch=pg.daemon.osdmap.epoch,
                        ops=[], flags=0)
        fake.connection = None

        def on_chunks(decoded, meta):
            chunk = decoded[shard].tobytes()
            t = Transaction()
            cid = pg.cid
            if not pg.daemon.store.collection_exists(cid):
                t.create_collection(cid)
            t.truncate(cid, oid, 0)
            t.write(cid, oid, 0, chunk)
            t.setattrs(cid, oid, {"_": _obj_meta(
                tuple(meta.get("version", version)),
                int(meta.get("size", 0)), hinfo=crc32c(chunk),
                extra=_meta_extra(meta))})
            pg.daemon.store.queue_transaction(t)
            pg._pulls.pop(pull_tid, None)
            pg.missing.pop(oid, None)
            pg._object_recovered(oid)
            pg._maybe_clean()

        self._start_data_read(fake, want={shard}, on_chunks=on_chunks,
                              exclude={shard},
                              on_fail=lambda: pg._pulls.pop(pull_tid,
                                                            None))

    # -- scrub -------------------------------------------------------------
    def build_scrub_map(self, deep: bool = True) -> dict:
        """oid → {size, crc, version, valid}: each EC shard verifies
        its own chunk against the stored hinfo crc (reference deep
        scrub on EC shards).  Deep maps digest chunks through the
        batched CRC-32C kernel and carry the chunk payload ("data",
        hex) so the primary can re-run the erasure code across shards
        — the parity recheck that catches bit-rot whose hinfo was
        rewritten consistently.  Shallow maps are presence/size only
        (no data read, no self-check)."""
        pg = self.pg
        store, cid = pg.daemon.store, pg.cid
        out = {}
        chunks: dict[str, bytes] = {}
        metas: dict[str, dict] = {}
        for oid in pg._list_objects():
            try:
                meta = json.loads(bytes(store.getattr(cid, oid, "_")))
                if deep:
                    chunks[oid] = bytes(store.read(cid, oid))
                metas[oid] = meta
            except KeyError:
                continue
            out[oid] = {"size": int(meta.get("size", 0)),
                        "version": meta.get("version", list(ZERO)),
                        "valid": True}
        if deep:
            eng = scrub_engine.default_engine()
            span = pg.daemon.tracer.start_span(
                "crc_digest", tags={
                    "layer": "device", "kernel": "crc32c",
                    "pgid": str(pg.pgid), "objects": len(chunks),
                    "bytes": sum(len(b) for b in chunks.values())})
            if span is not None:
                span.add_link(getattr(pg, "_scrub_trace", None))
            with pg.daemon.profiler.bind():
                digests = eng.compute_digests(chunks)
            for oid, digest in digests.items():
                hinfo = metas[oid].get("hinfo")
                out[oid].update(
                    crc=digest, data=chunks[oid].hex(),
                    valid=hinfo is None or digest == hinfo)
            if span is not None:
                span.finish()
            perf = pg.daemon.perf
            perf.inc("scrub_objects_scanned", len(chunks))
            perf.inc("scrub_digest_bytes",
                     sum(len(b) for b in chunks.values()))
        return out

    def scrub_compare(self, maps: dict[int, dict],
                      deep: bool = True) -> int:
        """A shard whose self-check failed (or that is missing an
        object other members have) gets its chunk reconstructed from
        the k survivors — the §4.3 path as repair.

        Deep scrubs additionally re-encode each fully-present stripe
        through the GF(2^8) matmul engine and compare recomputed
        parity against the stored parity shards; an inconsistent
        stripe whose shards all pass their own hinfo self-check is
        attributed by erasure hypothesis testing — singles first,
        then pairs when the code has parity to spare
        (``scrub.engine.isolate_culprits``) — and repaired through
        the same reconstruct path."""
        pg = self.pg
        me = pg.daemon.whoami
        oids = set()
        for m in maps.values():
            oids.update(m)
        errors = 0
        report = []
        shard_of = {osd: i for i, osd in enumerate(pg.acting)
                    if osd != CRUSH_ITEM_NONE}
        versions: dict[str, tuple] = {}
        suspect: set[str] = set()
        for oid in sorted(oids):
            ver = tuple(next(m[oid] for m in maps.values()
                             if oid in m)["version"])
            versions[oid] = ver
            shard_report: dict[tuple, dict] = {}
            obj_errors: set[str] = set()
            for osd, m in maps.items():
                e = m.get(oid)
                if e is not None and e["valid"]:
                    continue
                errors += 1
                suspect.add(oid)
                kind = "missing" if e is None else "data_digest_mismatch"
                obj_errors.add(kind)
                shard_report[osd, shard_of.get(osd, -1)] = {
                    "errors": [kind],
                    **({} if e is None else
                       {"size": e["size"], "digest": e.get("crc")})}
                if osd == me:
                    pg.missing[oid] = ver
                else:
                    pg.peer_missing.setdefault(osd, {})[oid] = ver
            if shard_report:
                report.append(scrub_engine.inconsistent_entry(
                    oid, sorted(obj_errors), shard_report))
        if deep:
            errors += self._parity_recheck(
                maps, oids - suspect, shard_of, versions, report)
        if report:
            pg.inconsistent_objects = report
        return errors

    def _parity_recheck(self, maps: dict[int, dict], oids: set,
                        shard_of: dict[int, int],
                        versions: dict[str, tuple],
                        report: list) -> int:
        """Re-encode fully-present self-consistent stripes; attribute
        and queue repair for any whose stored parity diverges."""
        pg = self.pg
        me = pg.daemon.whoami
        ec = self.engine
        n = ec.k + ec.m
        stripes: dict[str, dict[int, bytes]] = {}
        for oid in oids:
            chunks: dict[int, bytes] = {}
            for osd, m in maps.items():
                e = m.get(oid)
                if e is None or "data" not in e or osd not in shard_of:
                    continue
                chunks[shard_of[osd]] = bytes.fromhex(e["data"])
            if (len(chunks) == n
                    and len({len(c) for c in chunks.values()}) == 1):
                stripes[oid] = chunks
        if not stripes:
            return 0
        eng = scrub_engine.default_engine()
        before = eng.parity_bytes
        span = pg.daemon.tracer.start_span(
            "parity_recheck", tags={
                "layer": "device", "kernel": "gf_encode",
                "pgid": str(pg.pgid), "stripes": len(stripes)})
        if span is not None:
            span.add_link(getattr(pg, "_scrub_trace", None))
        with pg.daemon.profiler.bind():
            verdicts = eng.recheck_parity(
                ec, stripes,
                batch=getattr(pg.daemon, "batch_engine", None))
        if span is not None:
            span.set_tag("bytes", eng.parity_bytes - before)
            span.finish()
        pg.daemon.perf.inc("scrub_parity_recheck_bytes",
                           eng.parity_bytes - before)
        errors = 0
        for oid, inconsistent in sorted(verdicts.items()):
            if not inconsistent:
                continue
            errors += 1
            culprits = scrub_engine.isolate_culprits(ec, stripes[oid])
            osd_by_shard = {s: o for o, s in shard_of.items()}
            shard_report: dict[tuple, dict] = {}
            kinds = ["parity_mismatch"]
            if not culprits:
                # detected but unattributable (m=1 has no
                # discriminating redundancy; ambiguous multi-shard
                # evidence must not pick scapegoats): report only
                for osd, s in shard_of.items():
                    shard_report[osd, s] = {
                        "errors": ["parity_mismatch"]}
            else:
                ver = versions[oid]
                for culprit in culprits:
                    osd = osd_by_shard[culprit]
                    shard_report[osd, culprit] = {
                        "errors": ["parity_mismatch"]}
                    if osd == me:
                        pg.missing[oid] = ver
                    else:
                        pg.peer_missing.setdefault(osd, {})[oid] = ver
            report.append(scrub_engine.inconsistent_entry(
                oid, kinds, shard_report))
        return errors

    def answer_pull(self, msg: M.MOSDPGPull):
        # EC primaries reconstruct rather than pull whole objects
        pass

    def apply_push(self, msg: M.MOSDPGPush):
        pg = self.pg
        cid = pg.cid
        if _push_is_stale(pg.daemon.store, cid, msg):
            pg.missing.pop(msg.oid, None)   # bytes already present:
            return                          # not missing (see above)
        t = Transaction()
        if not pg.daemon.store.collection_exists(cid):
            t.create_collection(cid)
        t.remove(cid, msg.oid)
        t.write(cid, msg.oid, 0, bytes.fromhex(msg.data))
        if msg.attrs:
            t.setattrs(cid, msg.oid,
                       {k: bytes.fromhex(v) for k, v in msg.attrs.items()})
        pg.missing.pop(msg.oid, None)
        pg._note_local_object_write()
        pg._persist_meta(t)
        pg.daemon.store.queue_transaction(t)

"""Op schedulers — priority dequeue of OSD work.

Reference behavior re-created (``src/osd/scheduler/OpScheduler.h``,
``src/osd/scheduler/mClockScheduler.cc`` + ``src/dmclock/``,
``src/common/WeightedPriorityQueue.h``; SURVEY.md §3.5): incoming work
is classified (client ops, peer sub-ops, recovery, scrub, background)
and drained by a scheduler that keeps recovery storms from burying
client I/O.  Two flavors behind ``osd_op_queue``:

- **wpq** (`WeightedPriorityQueue`): deterministic weighted
  round-robin — each class accrues credit += weight per dequeue
  round, the non-empty class with the most credit is served and pays
  cost 1.  Within a class, FIFO.

- **mclock** (`MClockScheduler`): dmclock-style QoS.  Every op gets
  three tags at arrival — reservation (spaced 1/res apart: the
  guaranteed minimum rate), proportional (spaced 1/weight: the excess
  share), limit (spaced 1/lim: the cap).  Dequeue serves, in order:
  any op whose reservation tag is due (earliest first — this is what
  makes the minimum unconditionally hold under adverse load), else
  the earliest proportional tag among classes not past their limit.
  Peering traffic bypasses QoS entirely (the control plane IS the
  failure detector's dependency; the reference gives it
  ``op_scheduler_class::immediate``).
"""

from __future__ import annotations

import collections
import threading
import time

# priority classes (reference op_scheduler_class)
CLIENT = "client"          # MOSDOp
SUBOP = "subop"            # replication / EC sub-writes + reads
PEERING = "peering"        # maps/queries/notifies/logs — never starved
RECOVERY = "recovery"      # pushes/pulls/backfill
SCRUB = "scrub"            # scrub maps

DEFAULT_WEIGHTS = {
    PEERING: 1000,          # control plane preempts everything
    CLIENT: 63,
    SUBOP: 63,
    RECOVERY: 5,
    SCRUB: 2,
}


class WeightedPriorityQueue:
    """Blocking multi-class queue with weighted fair dequeue."""

    def __init__(self, weights: dict[str, int] | None = None):
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self._queues: dict[str, collections.deque] = {
            c: collections.deque() for c in self.weights}
        self._credit: dict[str, float] = {c: 0.0 for c in self.weights}
        self._cv = threading.Condition()
        self._closed = False

    def enqueue(self, klass: str, item, **_dmc_ignored):
        with self._cv:
            if klass not in self._queues:
                self._queues[klass] = collections.deque()
                self._credit[klass] = 0.0
                self.weights.setdefault(klass, 1)
            self._queues[klass].append(item)
            self._cv.notify()

    def dequeue(self, timeout: float | None = None):
        """→ (class, item) or None on timeout/close."""
        with self._cv:
            while True:
                nonempty = [c for c, q in self._queues.items() if q]
                if nonempty:
                    for c in nonempty:
                        self._credit[c] += self.weights[c]
                    best = max(nonempty, key=lambda c: self._credit[c])
                    self._credit[best] -= sum(
                        self.weights[c] for c in nonempty)
                    return best, self._queues[best].popleft()
                if self._closed:
                    return None
                if not self._cv.wait(timeout):
                    return None

    def account(self, klass: str, cost: float = 1.0):
        """Charge out-of-band work to a class (the batch engine's
        reconstruct-lane flushes bypass the queue — the device work
        already happened — but must still debit the class's fair
        share so subsequent queued work of that class defers)."""
        with self._cv:
            if klass not in self._credit:
                self._queues.setdefault(klass, collections.deque())
                self.weights.setdefault(klass, 1)
                self._credit[klass] = 0.0
            self._credit[klass] -= float(cost)

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def __len__(self):
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def depths(self) -> dict[str, int]:
        with self._cv:
            return {c: len(q) for c, q in self._queues.items() if q}


_MCLOCK_FALLBACK = (0.0, 1.0, 0.0)      # unknown classes: weight-only
_INF = float("inf")


def default_mclock_profiles() -> dict[str, tuple[float, float,
                                                 float]]:
    """The balanced profile, read from the option-table defaults so
    there is exactly ONE source of truth for the per-class
    (res ops/s, weight, limit ops/s) triples (0 ⇒ no reservation /
    no limit): client and replication sub-ops share the bulk,
    recovery gets a floor so it always makes progress but a ceiling
    so a storm cannot take over, scrub is best-effort."""
    from ..core.config import ConfigProxy
    from ..core.options import build_options
    return profiles_from_config(ConfigProxy(build_options()))


class MClockScheduler:
    """dmclock single-server scheduler with the same blocking-queue
    surface as `WeightedPriorityQueue` (enqueue/dequeue/close/len/
    depths), so the OSD op worker is scheduler-agnostic.

    `clock` is injectable so tests drive virtual time and assert the
    reservation/limit behavior deterministically.
    """

    @staticmethod
    def _normalize(profiles):
        """dmclock invariant: reservation ≤ limit.  The reservation
        path serves whenever its tag is due, bypassing the limit
        check, so res > lim would silently void the cap — clamp to
        keep the operator's ceiling authoritative."""
        out = {}
        for klass, (res, wgt, lim) in profiles.items():
            if lim > 0:
                res = min(res, lim)
            out[klass] = (res, wgt, lim)
        return out

    def __init__(self,
                 profiles: dict[str, tuple[float, float, float]]
                 | None = None,
                 clock=time.monotonic,
                 client_qos: dict[str, tuple[float, float, float]]
                 | None = None):
        self.profiles = self._normalize(
            profiles or default_mclock_profiles())
        # per-tenant overrides inside the CLIENT class (reference
        # dmclock's per-client ClientInfo, exposed upstream through
        # rgw qos / the mclock client profiles): a tenant named here
        # gets its own (res, wgt, lim) — including a PRIVATE limit
        # stream, so capping an aggressor tenant never throttles the
        # victim sharing the class
        self.client_qos = self._normalize(dict(client_qos or {}))
        self._client_lim_prev: dict[str, float] = {}
        self.clock = clock
        # per (class, client): deque of (r_tag, p_tag, l_tag, item)
        # — distributed dmclock tracks R/P tags per client within a
        # class (reference dmclock ClientRec); client None = the
        # class-wide anonymous stream (sub-ops, recovery, scrub).
        # The LIMIT stream stays per CLASS: the operator's ceiling is
        # a class budget and must not multiply with client count.
        self._queues: dict[tuple, collections.deque] = {}
        self._prev: dict[tuple, tuple[float, float]] = {}
        self._lim_prev: dict[str, float] = {}
        self._last_seen: dict[tuple, float] = {}
        self._peering: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._closed = False

    # idle per-client state is erased after this long (reference
    # dmclock ClientRec idle/erase ages) — without it, every client
    # entity ever seen leaves a tag tuple and an empty deque behind
    IDLE_PURGE_S = 60.0

    def enqueue(self, klass: str, item, client=None, delta: int = 1,
                rho: int = 1):
        """`delta`/`rho` are the distributed-dmclock feedback: how
        many of this client's requests completed ANYWHERE (delta) /
        under reservation anywhere (rho) since its last request to
        this server.  The tags advance by rho/res and delta/weight —
        a client already getting its reservation from other servers
        progresses its reservation tag here faster, so the aggregate
        reserved rate across servers stays ≈ res instead of res × N
        (reference src/dmclock TagCalc)."""
        with self._cv:
            if klass == PEERING:
                self._peering.append(item)
                self._cv.notify()
                return
            now = self.clock()
            res, wgt, lim = self.profiles.get(klass, _MCLOCK_FALLBACK)
            override = (klass == CLIENT
                        and client in self.client_qos)
            if override:
                res, wgt, lim = self.client_qos[client]
            key = (klass, client)
            pr, pp = self._prev.get(key, (-_INF, -_INF))
            pl = (self._client_lim_prev.get(client, -_INF) if override
                  else self._lim_prev.get(klass, -_INF))
            delta = max(int(delta), 1)
            rho = max(int(rho), 1)
            r = max(now, pr + rho / res) if res > 0 else _INF
            p = max(now, pp + delta / max(wgt, 1e-9))
            lt = max(now, pl + 1.0 / lim) if lim > 0 else 0.0
            self._prev[key] = (r if res > 0 else pr, p)
            if override:
                self._client_lim_prev[client] = lt
            else:
                self._lim_prev[klass] = lt
            self._last_seen[key] = now
            self._queues.setdefault(key,
                                    collections.deque()).append(
                (r, p, lt, item))
            self._cv.notify()

    def _pick(self, now: float):
        """→ (klass, item) to serve now, or (None, wake_at)."""
        if self._peering:
            return PEERING, self._peering.popleft()
        best_r = best_p = None
        wake = _INF
        stale = []
        for key, q in self._queues.items():
            if not q:
                if now - self._last_seen.get(key, now) \
                        > self.IDLE_PURGE_S:
                    stale.append(key)
                continue
            r_tag, p_tag, l_tag, _ = q[0]
            # the class-wide limit gates BOTH phases: per-client
            # reservations must not aggregate past the operator's
            # class ceiling (deviation from pure dmclock, where the
            # reservation bypasses the limit — there the limit is
            # per-client too)
            if l_tag <= now and r_tag <= now:
                if best_r is None or r_tag < best_r[0]:
                    best_r = (r_tag, key)
            elif r_tag < _INF:
                wake = min(wake, max(r_tag, min(l_tag, _INF)))
            if l_tag <= now:
                if best_p is None or p_tag < best_p[0]:
                    best_p = (p_tag, key)
            else:
                wake = min(wake, l_tag)
        for key in stale:       # erase idle per-client state
            del self._queues[key]
            self._prev.pop(key, None)
            self._last_seen.pop(key, None)
            if key[0] == CLIENT:
                self._client_lim_prev.pop(key[1], None)
        choice = best_r or best_p
        if choice is None:
            return None, wake
        key = choice[1]
        _, _, _, item = self._queues[key].popleft()
        self._last_seen[key] = now
        # report which phase served the op (reference PhaseType in
        # the dmclock response): the client tracker turns it into rho
        try:
            item._dmc_phase = ("reservation" if choice is best_r
                               else "priority")
        except AttributeError:
            pass        # plain tuples/ints in unit tests
        return key[0], item

    def dequeue(self, timeout: float | None = None):
        """→ (class, item) or None on timeout/close."""
        deadline = (None if timeout is None
                    else self.clock() + timeout)
        with self._cv:
            while True:
                now = self.clock()
                klass, item_or_wake = self._pick(now)
                if klass is not None:
                    return klass, item_or_wake
                if self._closed and not len(self):
                    return None
                if deadline is not None and now >= deadline:
                    return None
                # sleep until the earliest due tag, the deadline, or a
                # new arrival — whichever first (wake > now holds: any
                # due tag would have been picked above)
                waits = [w - now for w in (item_or_wake, deadline)
                         if w is not None and w < _INF]
                self._cv.wait(min(waits) if waits else None)

    def account(self, klass: str, cost: float = 1.0):
        """Charge ``cost`` completed-elsewhere ops to a class's QoS
        streams (reference: dmclock's delta/rho feedback, here fed by
        the batch engine's reconstruct-lane flushes).  The class limit
        tag and the anonymous stream's reservation/proportional tags
        advance by cost/rate, so NEW arrivals of that class space out
        as if the lane's megabatch had been served from the queue —
        already-queued items keep the tags they got at enqueue."""
        with self._cv:
            if klass == PEERING or cost <= 0:
                return
            now = self.clock()
            res, wgt, lim = self.profiles.get(klass, _MCLOCK_FALLBACK)
            if lim > 0:
                pl = self._lim_prev.get(klass, -_INF)
                self._lim_prev[klass] = max(now, pl) + cost / lim
            key = (klass, None)
            pr, pp = self._prev.get(key, (-_INF, -_INF))
            if res > 0:
                pr = max(now, pr) + cost / res
            pp = max(now, pp) + cost / max(wgt, 1e-9)
            self._prev[key] = (pr, pp)
            self._last_seen[key] = now
            self._cv.notify_all()

    def reload_profiles(self, profiles: dict[str, tuple[float, float,
                                                        float]]):
        """Apply new QoS triples to a LIVE scheduler (runtime
        `config set osd_mclock_scheduler_*`).  Already-queued ops
        keep their tags; new arrivals use the new spacing (max(now,
        prev+1/rate) re-converges immediately)."""
        with self._cv:
            self.profiles.update(self._normalize(profiles))
            self._cv.notify_all()

    def set_client_qos(self, client_qos: dict[str, tuple[float, float,
                                                         float]]):
        """Replace the per-tenant override map on a live scheduler
        (runtime `config set osd_mclock_scheduler_client_qos`).
        Tenants dropped from the map fall back to the class-wide
        triple; their private limit stream is forgotten."""
        with self._cv:
            self.client_qos = self._normalize(dict(client_qos))
            for c in list(self._client_lim_prev):
                if c not in self.client_qos:
                    del self._client_lim_prev[c]
            self._cv.notify_all()

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def __len__(self):
        with self._cv:
            return (len(self._peering)
                    + sum(len(q) for q in self._queues.values()))

    def depths(self) -> dict[str, int]:
        with self._cv:
            d: dict[str, int] = {}
            for (c, _client), q in self._queues.items():
                if q:
                    d[c] = d.get(c, 0) + len(q)
            if self._peering:
                d[PEERING] = len(self._peering)
            return d


def profiles_from_config(config) -> dict[str, tuple[float, float,
                                                    float]]:
    """Read the osd_mclock_scheduler_* option family."""
    out = {}
    for klass, opt in ((CLIENT, "client"), (SUBOP, "subop"),
                       (RECOVERY, "recovery"), (SCRUB, "scrub")):
        out[klass] = (
            float(config.get(f"osd_mclock_scheduler_{opt}_res")),
            float(config.get(f"osd_mclock_scheduler_{opt}_wgt")),
            float(config.get(f"osd_mclock_scheduler_{opt}_lim")))
    return out


def client_qos_from_config(config) -> dict[str, tuple[float, float,
                                                      float]]:
    """Parse osd_mclock_scheduler_client_qos: JSON
    ``{tenant: [res, wgt, lim]}``.  Untrusted operator input —
    malformed JSON or triples degrade to no overrides / skip the
    entry rather than killing the daemon."""
    import json
    text = str(config.get("osd_mclock_scheduler_client_qos") or "")
    if not text.strip():
        return {}
    try:
        raw = json.loads(text)
    except ValueError:
        return {}
    out = {}
    if isinstance(raw, dict):
        for tenant, triple in raw.items():
            try:
                res, wgt, lim = (float(triple[0]), float(triple[1]),
                                 float(triple[2]))
            except (TypeError, ValueError, IndexError, KeyError):
                continue
            out[str(tenant)] = (res, wgt, lim)
    return out


def make_op_queue(config):
    """The `osd_op_queue` seam (reference OpScheduler::make_scheduler):
    the option enum is honest — "mclock" builds the QoS scheduler,
    and the osd_mclock_scheduler_* knobs stay live via config
    observers (a `config set` on a running daemon retunes the queue,
    matching the reference's runtime-adjustable dmclock options)."""
    kind = config.get("osd_op_queue")
    if kind == "mclock":
        q = MClockScheduler(profiles_from_config(config),
                            client_qos=client_qos_from_config(config))

        def _retune(_name, _val):
            q.reload_profiles(profiles_from_config(config))

        def _retune_qos(_name, _val):
            q.set_client_qos(client_qos_from_config(config))

        for opt in ("client", "subop", "recovery", "scrub"):
            for suffix in ("res", "wgt", "lim"):
                config.add_observer(
                    f"osd_mclock_scheduler_{opt}_{suffix}", _retune)
        config.add_observer("osd_mclock_scheduler_client_qos",
                            _retune_qos)
        return q
    return WeightedPriorityQueue()

"""Op scheduler — weighted-priority dequeue of OSD work.

Reference behavior re-created (``src/osd/scheduler/OpScheduler.h`` /
``src/common/WeightedPriorityQueue.h``; SURVEY.md §3.5): incoming work
is classified (client ops, peer sub-ops, recovery, scrub, background)
and drained by a scheduler that picks among non-empty priority classes
with probability proportional to weight — strict priority for the
highest class would starve recovery; pure FIFO would let recovery
storms bury client I/O.  This is the WPQ flavor; the reference's
mClock QoS scheduler is a possible future refinement.

Deterministic weighted round-robin (no RNG): each class accrues
credit += weight on every dequeue round; the non-empty class with the
most credit is served and pays cost 1.  Within a class, FIFO.
"""

from __future__ import annotations

import collections
import threading

# priority classes (reference op_scheduler_class)
CLIENT = "client"          # MOSDOp
SUBOP = "subop"            # replication / EC sub-writes + reads
PEERING = "peering"        # maps/queries/notifies/logs — never starved
RECOVERY = "recovery"      # pushes/pulls/backfill
SCRUB = "scrub"            # scrub maps

DEFAULT_WEIGHTS = {
    PEERING: 1000,          # control plane preempts everything
    CLIENT: 63,
    SUBOP: 63,
    RECOVERY: 5,
    SCRUB: 2,
}


class WeightedPriorityQueue:
    """Blocking multi-class queue with weighted fair dequeue."""

    def __init__(self, weights: dict[str, int] | None = None):
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self._queues: dict[str, collections.deque] = {
            c: collections.deque() for c in self.weights}
        self._credit: dict[str, float] = {c: 0.0 for c in self.weights}
        self._cv = threading.Condition()
        self._closed = False

    def enqueue(self, klass: str, item):
        with self._cv:
            if klass not in self._queues:
                self._queues[klass] = collections.deque()
                self._credit[klass] = 0.0
                self.weights.setdefault(klass, 1)
            self._queues[klass].append(item)
            self._cv.notify()

    def dequeue(self, timeout: float | None = None):
        """→ (class, item) or None on timeout/close."""
        with self._cv:
            while True:
                nonempty = [c for c, q in self._queues.items() if q]
                if nonempty:
                    for c in nonempty:
                        self._credit[c] += self.weights[c]
                    best = max(nonempty, key=lambda c: self._credit[c])
                    self._credit[best] -= sum(
                        self.weights[c] for c in nonempty)
                    return best, self._queues[best].popleft()
                if self._closed:
                    return None
                if not self._cv.wait(timeout):
                    return None

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def __len__(self):
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    def depths(self) -> dict[str, int]:
        with self._cv:
            return {c: len(q) for c, q in self._queues.items() if q}

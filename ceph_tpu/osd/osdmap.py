"""OSDMap — epoch-versioned cluster state and the PG→OSD mapping spine.

Reference behavior re-created: ``src/osd/OSDMap.{h,cc}`` and the pool
type ``pg_pool_t`` from ``src/osd/osd_types.{h,cc}`` (SURVEY.md §3.4):

- pools (size, min_size, pg_num, crush_rule, EC profile, flags) keyed by
  id, with the ``HASHPSPOOL`` placement-seed mixing;
- per-OSD state: exists/up flags, CRUSH reweight (16.16), addresses
  elided (the messenger layer binds names, not this map);
- the mapping pipeline ``object_locator_to_pg -> raw_pg_to_pg ->
  pg_to_raw_osds -> (upmap overrides) -> up -> (pg_temp/primary_temp)
  -> acting`` — the exact call chain of
  ``OSDMap::pg_to_up_acting_osds``;
- ``Incremental`` deltas applied in epoch order.

The CRUSH walk itself runs on the scalar oracle for single lookups and
on `ceph_tpu.crush.jax_mapper.BatchMapper` for PG-batch workloads
(osdmaptool, balancer) — same results, bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..crush.hash import ceph_str_hash_rjenkins, crush_hash32_2
from ..crush.map import CRUSH_ITEM_NONE, CrushMap, build_flat_map
from ..crush.mapper import do_rule

# pool types (reference pg_pool_t::TYPE_*)
TYPE_REPLICATED = 1
TYPE_ERASURE = 3

# pool flags (subset)
FLAG_HASHPSPOOL = 1 << 0

# cluster-wide OSDMap flags (reference CEPH_OSDMAP_*): operator
# switches set via `ceph osd set <flag>`
CLUSTER_FLAGS = {
    "pause": 1 << 0,     # block client I/O (pauserd|pausewr)
    "nodown": 1 << 1,    # suppress marking OSDs down
    "noout": 1 << 2,     # suppress auto-out (stored; nothing
                         # auto-outs at this scale yet)
    "noscrub": 1 << 3,   # suppress scheduled (shallow) scrubs
    "nodeep-scrub": 1 << 4,  # suppress scheduled deep scrubs
}

# osd state bits (reference CEPH_OSD_EXISTS/UP)
EXISTS = 1
UP = 2


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """The pg_num folding function (reference ``ceph_stable_mod`` in
    ``src/include/ceph_hash.h``): stable under pg_num growth — a pg only
    moves when its own bit splits."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _calc_bits_of(n: int) -> int:
    return max(0, (n - 1)).bit_length() if n > 0 else 0


@dataclass(frozen=True, order=True)
class PGid:
    pool: int
    seed: int

    def __str__(self):
        return f"{self.pool}.{self.seed:x}"

    @classmethod
    def parse(cls, s: str) -> "PGid":
        pool, seed = s.split(".")
        return cls(int(pool), int(seed, 16))


@dataclass
class PGPool:
    """pg_pool_t analog."""
    id: int
    name: str
    type: int = TYPE_REPLICATED
    size: int = 3
    min_size: int = 2
    pg_num: int = 32
    pgp_num: int = 0                 # 0 ⇒ follows pg_num
    crush_rule: int = 0
    object_hash: str = "rjenkins"
    flags: int = FLAG_HASHPSPOOL
    erasure_code_profile: str = ""
    last_change: int = 0             # epoch of last modification
    # pool snapshots (reference pg_pool_t::snap_seq/snaps): clients
    # stamp writes with the pool SnapContext; OSDs clone-on-write
    snap_seq: int = 0
    snaps: dict = field(default_factory=dict)   # id → name
    # quotas (reference pg_pool_t quota_max_objects/bytes): 0 = none.
    # `full` is set by the mon when PGMap usage exceeds a quota;
    # OSDs reply -EDQUOT to writes while it holds.
    quota_max_objects: int = 0
    quota_max_bytes: int = 0
    full: bool = False
    # cache tiering (reference pg_pool_t tier fields): a cache pool
    # has tier_of = base pool id; the BASE pool's read/write_tier
    # point at the cache once the overlay is set, redirecting client
    # ops there (the Objecter honors this like the reference).
    tier_of: int = -1
    read_tier: int = -1
    write_tier: int = -1
    cache_mode: str = "none"         # none | writeback
    tiers: list = field(default_factory=list)
    # stretch pools (reference pg_pool_t peering-crush stretch set):
    # replicas span the datacenter buckets; on site loss the mon drops
    # min_size to 1 (degraded stretch mode) and restores
    # `stretch_min_size` once both sites are back.
    is_stretch: bool = False
    stretch_min_size: int = 0        # healthy min_size to restore
    # storage efficiency (reference pg_pool_t compression_* options +
    # dedup tiering): mode none|passive|aggressive|force gates the
    # OSD's inline compression lane; dedup is replicated-pool-only
    # and mutually exclusive with pool snapshots (mon-enforced).
    compression_mode: str = "none"
    compression_algorithm: str = ""
    dedup_enable: bool = False

    def __post_init__(self):
        if self.pgp_num == 0:
            self.pgp_num = self.pg_num

    @property
    def pg_num_mask(self) -> int:
        return (1 << _calc_bits_of(self.pg_num)) - 1

    @property
    def pgp_num_mask(self) -> int:
        return (1 << _calc_bits_of(self.pgp_num)) - 1

    def is_erasure(self) -> bool:
        return self.type == TYPE_ERASURE

    def raw_pg_to_pg(self, seed: int) -> int:
        return ceph_stable_mod(seed, self.pg_num, self.pg_num_mask)

    def raw_pg_to_pps(self, seed: int) -> int:
        """Placement seed handed to CRUSH (``pg_pool_t::raw_pg_to_pps``).
        HASHPSPOOL mixes the pool id in so co-sized pools diverge."""
        if self.flags & FLAG_HASHPSPOOL:
            return int(crush_hash32_2(
                ceph_stable_mod(seed, self.pgp_num, self.pgp_num_mask),
                self.id & 0xFFFFFFFF))
        return (ceph_stable_mod(seed, self.pgp_num, self.pgp_num_mask)
                + self.id)

    def raw_pg_to_pps_batch(self, seeds):
        """Vectorized twin of `raw_pg_to_pps` over a uint32 seed array —
        the osdmaptool/balancer batch path.  Same math, one definition
        site; tests assert elementwise equality with the scalar form."""
        import numpy as np
        seeds = np.asarray(seeds, dtype=np.uint32)
        masked = np.where(
            (seeds & self.pgp_num_mask) < self.pgp_num,
            seeds & self.pgp_num_mask, seeds & (self.pgp_num_mask >> 1))
        if self.flags & FLAG_HASHPSPOOL:
            return crush_hash32_2(masked.astype(np.uint32),
                                  np.uint32(self.id & 0xFFFFFFFF))
        return (masked + self.id).astype(np.uint32)


@dataclass
class Incremental:
    """OSDMap::Incremental analog — one epoch's delta."""
    epoch: int
    new_pools: dict[int, PGPool] = field(default_factory=dict)
    old_pools: list[int] = field(default_factory=list)
    new_max_osd: int | None = None
    new_state: dict[int, int] = field(default_factory=dict)   # xor'd bits
    new_weight: dict[int, int] = field(default_factory=dict)
    new_pg_temp: dict[PGid, list[int]] = field(default_factory=dict)
    new_primary_temp: dict[PGid, int] = field(default_factory=dict)
    new_pg_upmap: dict[PGid, list[int]] = field(default_factory=dict)
    old_pg_upmap: list[PGid] = field(default_factory=list)
    new_pg_upmap_items: dict[PGid, list[tuple[int, int]]] = \
        field(default_factory=dict)
    old_pg_upmap_items: list[PGid] = field(default_factory=list)
    new_crush: CrushMap | None = None
    # stretch-mode state delta: {field: value} over the OSDMap stretch
    # attributes (stretch_mode_enabled, stretch_sites, ...)
    new_stretch: dict | None = None


class OSDMap:
    def __init__(self, crush: CrushMap | None = None, max_osd: int = 0):
        self.epoch = 0
        self.crush = crush if crush is not None else CrushMap()
        self.max_osd = max_osd
        self.osd_state = [0] * max_osd
        self.osd_weight = [0x10000] * max_osd     # reweight, 16.16
        # newest epoch through which each OSD confirmed aliveness as a
        # would-be primary (reference osd_info_t::up_thru, bumped by
        # MOSDAlive before a primary activates): an interval whose
        # primary never bumped up_thru into it provably accepted no
        # writes, which is what keeps dead-primary intervals from
        # blocking peering forever
        self.osd_up_thru = [0] * max_osd
        self.pools: dict[int, PGPool] = {}
        self.pool_name: dict[str, int] = {}
        self.pg_temp: dict[PGid, list[int]] = {}
        self.primary_temp: dict[PGid, int] = {}
        self.pg_upmap: dict[PGid, list[int]] = {}
        self.pg_upmap_items: dict[PGid, list[tuple[int, int]]] = {}
        self.erasure_code_profiles: dict[str, dict[str, str]] = {}
        self.flags = 0
        # daemon addresses, "host:port" — the Objecter's routing table
        # (reference OSDMap::get_addrs)
        self.osd_addrs: dict[int, str] = {}
        # (rule_id, result_max) → BatchMapper, reused across epochs:
        # a weight-only CRUSH change rebinds via set_weights (zero
        # recompiles), everything else falls back to a fresh build
        self._mappers: dict = {}
        # stretch mode (reference OSDMap::stretch_mode_enabled et al.):
        # site-aware placement + surviving-site degraded operation
        self.stretch_mode_enabled = False
        self.stretch_bucket_type = 0             # crush type id (datacenter)
        self.stretch_sites: dict[str, list[int]] = {}   # site → osd ids
        self.stretch_tiebreaker = ""             # tiebreaker mon name
        self.degraded_stretch_mode = False       # a site is down
        self.recovering_stretch_mode = False     # healed, recovery pending
        self.stretch_degraded_site = ""          # which site died

    def batch_mapper(self, rule_id: int, result_max: int,
                     tracer=None, **kwargs):
        """Cached `crush.jax_mapper.BatchMapper` for (rule, size).

        The reweight fast path of the mapping spine: balancer rounds
        and repeated osdmaptool sweeps hit the same compiled
        executable; after `apply_incremental` swaps in a weight-only
        `new_crush`, the mapper rebinds through
        `BatchMapper.set_weights` instead of recompiling.  Topology /
        rule / tunables changes rebuild (and the compiled program may
        still warm-start from the on-disk export cache).

        ``tracer``: optional ``core.tracer.Tracer`` — the acquisition
        is recorded as a device span tagged with how it was satisfied
        (mapper reuse / weight rebind / fresh build, and whether a
        fresh build warm-started from the AOT compile cache)."""
        from ..crush.jax_mapper import BatchMapper
        span = None if tracer is None else tracer.start_span(
            "crush_batch_mapper", tags={
                "layer": "device", "kernel": "crush",
                "rule": rule_id, "result_max": result_max})
        key = (rule_id, result_max, tuple(sorted(kwargs.items())))
        bm = self._mappers.get(key)
        if bm is not None:
            rebound = bm.cmap is not self.crush
            if rebound:
                try:
                    bm.set_weights(self.crush)
                except (ValueError, NotImplementedError):
                    bm = None
            if bm is not None:
                if span is not None:
                    span.set_tag("cache_hit", True)
                    span.set_tag("how",
                                 "rebind" if rebound else "reuse")
                    span.finish()
                return bm
        bm = BatchMapper(self.crush, rule_id, result_max=result_max,
                         **kwargs)
        self._mappers[key] = bm
        if span is not None:
            # bm.cache_hit: the fresh build warm-started from the
            # persistent AOT executable cache (no XLA recompile)
            span.set_tag("cache_hit", bool(bm.cache_hit))
            span.set_tag("how", "build")
            span.finish()
        return bm

    # -- construction ------------------------------------------------------
    @classmethod
    def build_simple(cls, n_osds: int, pg_bits: int = 6,
                     pool_type: int = TYPE_REPLICATED) -> "OSDMap":
        """osdmaptool --createsimple analog: flat straw2 map, all osds
        up+in, one pool 'rbd' with n_osds << pg_bits PGs (replicated by
        default; TYPE_ERASURE gets an indep rule and positional holes)."""
        from ..crush.map import Rule, Step
        crush = build_flat_map(n_osds)
        crush.rules.append(Rule(id=1, name="erasure_rule", type="erasure",
                                steps=[Step("take", -1),
                                       Step("choose_indep", 0, 0),
                                       Step("emit")]))
        m = cls(crush=crush, max_osd=n_osds)
        m.epoch = 1
        for o in range(n_osds):
            m.osd_state[o] = EXISTS | UP
        m.create_pool("rbd", pg_num=max(1, n_osds << pg_bits),
                      type=pool_type,
                      crush_rule=1 if pool_type == TYPE_ERASURE else 0)
        return m

    def create_pool(self, name: str, pg_num: int = 32, *, size: int = 3,
                    min_size: int | None = None, crush_rule: int = 0,
                    type: int = TYPE_REPLICATED,
                    erasure_code_profile: str = "") -> PGPool:
        pid = max(self.pools, default=-1) + 1
        if min_size is None:
            min_size = size - size // 2 if type == TYPE_REPLICATED else size
        pool = PGPool(id=pid, name=name, type=type, size=size,
                      min_size=min_size, pg_num=pg_num,
                      crush_rule=crush_rule, last_change=self.epoch,
                      erasure_code_profile=erasure_code_profile)
        self.pools[pid] = pool
        self.pool_name[name] = pid
        return pool

    # -- osd state ---------------------------------------------------------
    def is_up(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_state[osd] & UP)

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_state[osd] & EXISTS)

    def is_out(self, osd: int) -> bool:
        return self.osd_weight[osd] == 0

    def up_thru(self, osd: int) -> int:
        return self.osd_up_thru[osd] if 0 <= osd < self.max_osd else 0

    def mark_down(self, osd: int):
        self.osd_state[osd] &= ~UP

    def mark_out(self, osd: int):
        self.osd_weight[osd] = 0

    # -- the mapping spine -------------------------------------------------
    def object_locator_to_pg(self, oid: str, pool: int,
                             key: str = "") -> PGid:
        """Objecter's first hop (reference
        ``OSDMap::object_locator_to_pg``): hash the object name (or
        locator key) to a raw placement seed."""
        p = self.pools[pool]
        name = key or oid
        if p.object_hash != "rjenkins":
            raise ValueError(f"unsupported object_hash {p.object_hash!r}")
        return PGid(pool, int(ceph_str_hash_rjenkins(name.encode())))

    def raw_pg_to_pg(self, pgid: PGid) -> PGid:
        p = self.pools[pgid.pool]
        return PGid(pgid.pool, p.raw_pg_to_pg(pgid.seed))

    def pg_to_raw_osds(self, pgid: PGid) -> list[int]:
        """CRUSH mapping, no overrides (``OSDMap::_pg_to_raw_osds``)."""
        pool = self.pools[pgid.pool]
        pps = pool.raw_pg_to_pps(pgid.seed)
        raw = do_rule(self.crush, self.crush.rule_by_id(pool.crush_rule),
                      pps, pool.size, self.osd_weight)
        return [o if (o == CRUSH_ITEM_NONE or self.exists(o)) else
                CRUSH_ITEM_NONE for o in raw]

    def _apply_upmap(self, pgid: PGid, raw: list[int]) -> list[int]:
        """pg_upmap (full replacement) and pg_upmap_items (pairwise)
        overrides — ``OSDMap::_apply_upmap``."""
        pm = self.pg_upmap.get(pgid)
        if pm:
            if all(self.exists(o) and not self.is_out(o) for o in pm):
                return list(pm)
        items = self.pg_upmap_items.get(pgid)
        if items:
            raw = list(raw)
            for frm, to in items:
                if (frm in raw and to not in raw and self.exists(to)
                        and not self.is_out(to)):
                    raw[raw.index(frm)] = to
        return raw

    def _raw_to_up_osds(self, pool: PGPool,
                        raw: list[int]) -> tuple[list[int], int]:
        """Strip down OSDs: replicated pools compact, EC pools keep
        positional NONE holes (shard identity matters)."""
        if pool.is_erasure():
            up = [o if (o != CRUSH_ITEM_NONE and self.is_up(o))
                  else CRUSH_ITEM_NONE for o in raw]
        else:
            up = [o for o in raw
                  if o != CRUSH_ITEM_NONE and self.is_up(o)]
        primary = next((o for o in up if o != CRUSH_ITEM_NONE), -1)
        return up, primary

    def pg_to_up_acting_osds(
            self, pgid: PGid,
    ) -> tuple[list[int], int, list[int], int]:
        """→ (up, up_primary, acting, acting_primary), the full override
        chain of the reference method of the same name."""
        pgid = self.raw_pg_to_pg(pgid)
        pool = self.pools[pgid.pool]
        raw = self.pg_to_raw_osds(pgid)
        raw = self._apply_upmap(pgid, raw)
        up, up_primary = self._raw_to_up_osds(pool, raw)
        acting = self.pg_temp.get(pgid)
        if acting is None:
            acting = list(up)
            acting_primary = up_primary
        else:
            acting = list(acting)
            acting_primary = next(
                (o for o in acting if o != CRUSH_ITEM_NONE), -1)
        tp = self.primary_temp.get(pgid)
        if tp is not None and tp in acting:
            acting_primary = tp
        return up, up_primary, acting, acting_primary

    # -- incrementals ------------------------------------------------------
    def apply_incremental(self, inc: Incremental):
        if inc.epoch != self.epoch + 1:
            raise ValueError(
                f"incremental epoch {inc.epoch} != {self.epoch}+1")
        self.epoch = inc.epoch
        if inc.new_crush is not None:
            self.crush = inc.new_crush
            # weight-only fast path: rebind every cached batch mapper
            # onto the new map now (`remap()` under the hood — zero
            # recompiles); a mapper that rejects the rebind saw a
            # topology/tunables change and is evicted so the next
            # `batch_mapper` call rebuilds it.
            for key, bm in list(self._mappers.items()):
                try:
                    bm.set_weights(self.crush)
                except (ValueError, NotImplementedError):
                    del self._mappers[key]
        if inc.new_stretch is not None:
            for k, v in inc.new_stretch.items():
                if not hasattr(self, k):
                    raise ValueError(f"unknown stretch field {k!r}")
                setattr(self, k, v)
        if inc.new_max_osd is not None:
            old = self.max_osd
            self.max_osd = inc.new_max_osd
            if self.max_osd > old:
                self.osd_state += [0] * (self.max_osd - old)
                self.osd_weight += [0x10000] * (self.max_osd - old)
                self.osd_up_thru += [0] * (self.max_osd - old)
            else:
                del self.osd_state[self.max_osd:]
                del self.osd_weight[self.max_osd:]
                del self.osd_up_thru[self.max_osd:]
        for pid, pool in inc.new_pools.items():
            pool.last_change = inc.epoch
            self.pools[pid] = pool
            self.pool_name[pool.name] = pid
        for pid in inc.old_pools:
            pool = self.pools.pop(pid, None)
            if pool:
                self.pool_name.pop(pool.name, None)
        for osd, bits in inc.new_state.items():
            self.osd_state[osd] ^= bits
        for osd, w in inc.new_weight.items():
            self.osd_weight[osd] = w
        for pgid, osds in inc.new_pg_temp.items():
            if osds:
                self.pg_temp[pgid] = list(osds)
            else:
                self.pg_temp.pop(pgid, None)
        for pgid, osd in inc.new_primary_temp.items():
            if osd >= 0:
                self.primary_temp[pgid] = osd
            else:
                self.primary_temp.pop(pgid, None)
        self.pg_upmap.update(inc.new_pg_upmap)
        for pgid in inc.old_pg_upmap:
            self.pg_upmap.pop(pgid, None)
        self.pg_upmap_items.update(inc.new_pg_upmap_items)
        for pgid in inc.old_pg_upmap_items:
            self.pg_upmap_items.pop(pgid, None)

    # -- stretch mode ------------------------------------------------------
    def stretch_site_up(self, site: str) -> bool:
        """A site counts as up while any of its OSDs is up."""
        return any(self.is_up(o) for o in self.stretch_sites.get(site, []))

    def stretch_down_sites(self) -> list[str]:
        return [s for s in sorted(self.stretch_sites)
                if not self.stretch_site_up(s)]

    # -- stats -------------------------------------------------------------
    def num_up_osds(self) -> int:
        return sum(1 for s in self.osd_state if s & UP)

    def num_in_osds(self) -> int:
        return sum(1 for o in range(self.max_osd)
                   if self.exists(o) and not self.is_out(o))

"""GF(2^8) arithmetic — the field under every Reed-Solomon erasure code.

Reference behavior being re-created (not ported): jerasure/gf-complete's
``w=8`` Galois field with primitive polynomial ``0x11d``
(x^8 + x^4 + x^3 + x^2 + 1), as used by Ceph's jerasure and ISA-L erasure
code plugins (reference: ``src/erasure-code/jerasure/``, bundled
``gf-complete``; see SURVEY.md §3.6).

This module is the NumPy **oracle**: simple, table-driven, scalar-faithful.
The TPU path (`ceph_tpu.ops.gf_jax`) must agree with it byte-for-byte.

Representations provided:

- log/antilog tables (`GF_LOG`, `GF_EXP`) and a full 256x256 product table
  (`GF_MUL_TABLE`) for gather-based multiply;
- the *bitmatrix* form: each field element ``a`` maps to an 8x8 GF(2)
  matrix ``M(a)`` over bit-vectors such that ``a*b`` = ``M(a) @ bits(b)``
  mod 2.  This turns GF matmul into int8 matmul + parity — the MXU-friendly
  formulation used by the Pallas kernels.
"""

from __future__ import annotations

import functools

import numpy as np

# Primitive polynomial for GF(2^8): x^8+x^4+x^3+x^2+1 — the gf-complete
# default for w=8 (0x11d with the implicit x^8 term).
GF_POLY = 0x11D
GF_GENERATOR = 2  # x is primitive for 0x11d


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    # duplicate so exp[log a + log b] needs no mod
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    log[0] = 0  # by convention; callers must special-case 0
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a, b):
    """Elementwise GF(2^8) product of uint8 arrays/scalars."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    out = GF_EXP[GF_LOG[a] + GF_LOG[b]]
    return np.where((a == 0) | (b == 0), np.uint8(0), out)


def _build_mul_table() -> np.ndarray:
    a = np.arange(256, dtype=np.uint8)[:, None]
    b = np.arange(256, dtype=np.uint8)[None, :]
    return gf_mul(np.broadcast_to(a, (256, 256)), np.broadcast_to(b, (256, 256)))


GF_MUL_TABLE = _build_mul_table()


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_div(a, b):
    """Elementwise a / b in GF(2^8); b must be nonzero."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    if np.any(b == 0):
        raise ZeroDivisionError("GF(2^8) division by 0")
    out = GF_EXP[GF_LOG[a] - GF_LOG[b] + 255]
    return np.where(a == 0, np.uint8(0), out)


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_matmul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product: XOR-accumulate of per-element products.

    A: [n, k] uint8, B: [k, m] uint8 -> [n, m] uint8.  This is the oracle
    for both encode (coding_matrix @ data_chunks) and decode
    (inverse_submatrix @ surviving_chunks).
    """
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    # products[i, j, l] = A[i, l] * B[l, j]; XOR-reduce over l
    prod = GF_MUL_TABLE[A[:, None, :], B.T[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=2)


def gf_mat_inv(A: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination."""
    A = np.array(A, dtype=np.uint8)
    n = A.shape[0]
    if A.shape != (n, n):
        raise ValueError(f"square matrix required, got {A.shape}")
    aug = np.concatenate([A, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv = gf_inv(int(aug[col, col]))
        aug[col] = gf_mul(aug[col], inv)
        for row in range(n):
            if row != col and aug[row, col] != 0:
                aug[row] ^= gf_mul(aug[row, col], aug[col])
    return aug[:, n:].copy()


# ---------------------------------------------------------------------------
# Bitmatrix formulation (the MXU-friendly form)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _bitmatrix_table() -> np.ndarray:
    """BITMAT[a] is the 8x8 GF(2) matrix of 'multiply by a'.

    Convention: bits(b)[j] = (b >> j) & 1 (LSB first).  Column j of
    BITMAT[a] is bits(a * x^j), i.e. ``a * (1<<j)``.  Then
    bits(a*b) = BITMAT[a] @ bits(b) mod 2.
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for a in range(256):
        for j in range(8):
            col = gf_mul(a, 1 << j)
            for i in range(8):
                out[a, i, j] = (int(col) >> i) & 1
    return out


def gf_bitmatrix(a) -> np.ndarray:
    """8x8 GF(2) bit-matrix (uint8 0/1 entries) for multiplication by ``a``.

    For a coefficient matrix C [m, k], `expand_bitmatrix(C)` gives the
    [8m, 8k] GF(2) matrix whose mod-2 matmul with bit-decomposed data equals
    the GF(2^8) matmul — jerasure's ``jerasure_matrix_to_bitmatrix``
    equivalent, and the form the TPU MXU consumes as int8 matmul + parity.
    """
    return _bitmatrix_table()[np.asarray(a, dtype=np.uint8)]


def expand_bitmatrix(C: np.ndarray) -> np.ndarray:
    """[m, k] uint8 coefficient matrix -> [8m, 8k] 0/1 bitmatrix."""
    C = np.asarray(C, dtype=np.uint8)
    m, k = C.shape
    bm = gf_bitmatrix(C)  # [m, k, 8, 8]
    return bm.transpose(0, 2, 1, 3).reshape(8 * m, 8 * k)


def bytes_to_bits(x: np.ndarray) -> np.ndarray:
    """[..., n] uint8 -> [..., 8n] bits, LSB-first per byte (matches
    `gf_bitmatrix`'s convention)."""
    x = np.asarray(x, dtype=np.uint8)
    bits = np.unpackbits(x[..., None], axis=-1, bitorder="little")
    return bits.reshape(*x.shape[:-1], x.shape[-1] * 8)


def bits_to_bytes(b: np.ndarray) -> np.ndarray:
    b = np.asarray(b, dtype=np.uint8)
    n8 = b.shape[-1]
    assert n8 % 8 == 0
    return np.packbits(b.reshape(*b.shape[:-1], n8 // 8, 8), axis=-1,
                       bitorder="little").reshape(*b.shape[:-1], n8 // 8)

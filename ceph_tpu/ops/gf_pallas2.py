"""Fused GF(2^8) matmul Pallas kernel v2 — bit-sliced i32 lanes.

Why a v2: the v1 kernel (`gf_pallas.py`) measured ~7.5 GB/s on v5e and
was flat across stripe grouping — its bottleneck was never the MXU
(~9% contraction fill) but the VPU expand/pack work and the layout:
every uint8 array with k/m sublanes pays (32, 128) tiling padding, and
int8 elementwise ops occupy full 32-bit VPU lanes anyway.  v2 keeps
the same math (GF(2^8) multiply-accumulate == GF(2) bitmatrix matmul,
the reference's ``galois_w08_region_multiply`` region loop behind
``src/erasure-code/jerasure``; SURVEY.md §4.2) but restructures the
data movement:

    bytes are processed 4-per-lane as int32 words
      data tile  [k, TN/4] int32            (native (8,128) i32 tiling)
      -> expand  [32k, TN/4] int8 planes    (bit j of word = byte j//8,
                                             bit j%8 — 2 VPU ops/plane)
      -> GF(2) matmul on the MXU            ([32m, 32k] x [32k, TN/4],
                                             256-deep contraction @k=8:
                                             2x the MXU's native depth,
                                             vs 64 = 50% stalls in v1)
      -> mask + weighted re-pack            ([m, TN/4] int32 words)

    so every array in the pipeline has a 32-bit or sublane-aligned
    int8 layout — no uint8 relayouts — and HBM still moves only data
    once in, parity once out.

The GF(2) matrix is the v1 bitmatrix block-diagonalized 4x over byte
position: byte b of a word only ever multiplies into byte b of the
parity word, so block b maps plane rows [b*8k, (b+1)*8k) to output
rows [b*8m, (b+1)*8m).  Word-internal byte order therefore cancels:
whatever order `lax.bitcast_convert_type` packs bytes into a word, the
same order unpacks the parity word, and GF acts bytewise.

Mosaic constraints honored from v1's production runs: no vector
shifts on sub-32-bit ints — bit extraction is AND + compare, packing
is multiply-add (weights wrap through int32, bit 31 included); traced
under `jax.enable_x64(False)`.

Byte-exactness: `tests/test_gf_pallas2.py` (interpret mode vs the
NumPy oracle and the XLA path); on real TPU, `bench.py` verifies
parity bytes before any timing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jaxcompat import enable_x64, tpu_compiler_params

_LANES = 128
_WORD = 4                      # bytes per i32 lane
# lanes per tile (i32 words); 2048 words = 8 KiB rows; VMEM per tile at
# k=8,m=3: data 64 KiB + planes 512 KiB int8 + acc 768 KiB i32 < 2 MiB
_MAX_TNW = 2048

# int32 multiply weights for bit j of a word, wrapping at bit 31
_BIT_W = [int(np.int32(np.uint32(1 << j))) for j in range(32)]
_BIT_MASK = [int(np.int32(np.uint32(1 << j))) for j in range(32)]


def block_diag4(bitmat: np.ndarray) -> np.ndarray:
    """v1 bit-layout matrix [8m, 8k] -> word-sliced [32m, 32k] int8:
    one identical block per in-word byte position."""
    m8, k8 = bitmat.shape
    out = np.zeros((4 * m8, 4 * k8), dtype=np.int8)
    for b in range(4):
        out[b * m8:(b + 1) * m8, b * k8:(b + 1) * k8] = bitmat
    return out


def _gf_kernel2(bdmat_ref, data_ref, out_ref, *, k: int, m: int):
    """One (stripe, word-tile): expand -> 256-deep matmul -> pack."""
    w = data_ref[0]                                   # [k, TNW] int32
    planes = []
    for j in range(32):                               # row b*8k + s*k + i
        mask = jnp.int32(_BIT_MASK[j])
        planes.append(((w & mask) != 0).astype(jnp.int8))
    bits = jnp.concatenate(planes, axis=0)            # [32k, TNW] int8
    acc = jax.lax.dot_general(
        bdmat_ref[...], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)             # [32m, TNW] int32
    acc = acc & 1
    # out word bit (8b+r) of parity j = acc row b*8m + r*m + j; the
    # weighted sum wraps through int32 (bit 31 = the negative weight)
    word = acc[0:m] * jnp.int32(_BIT_W[0])
    for j in range(1, 32):
        word = word + acc[j * m:(j + 1) * m] * jnp.int32(_BIT_W[j])
    out_ref[0] = word


def _pick_tile(nw: int) -> int:
    for tnw in (_MAX_TNW, 1024, 512, 256, _LANES):
        if tnw <= nw and nw % tnw == 0:
            return tnw
    return nw           # nw < 128: single undersized tile


@functools.partial(jax.jit, static_argnames=("k", "m", "interpret"))
def _gf_apply_pallas2(bdmat, words, *, k: int, m: int,
                      interpret: bool = False):
    """bdmat [32m, 32k] int8, words [B, k, nw] int32 -> [B, m, nw]."""
    b, _, nw = words.shape
    tnw = _pick_tile(nw)
    grid = (b, nw // tnw)
    return pl.pallas_call(
        functools.partial(_gf_kernel2, k=k, m=m),
        out_shape=jax.ShapeDtypeStruct((b, m, nw), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((4 * 8 * m, 4 * 8 * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, tnw), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, m, tnw), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bdmat, words)


def gf_matmul_pallas2(bitmat: jnp.ndarray, data: jnp.ndarray, m: int,
                      interpret: bool = False,
                      bdmats: dict | None = None) -> jnp.ndarray:
    """Fused GF(2^8) matmul, v2.  data [..., k, n] uint8 -> [..., m, n].

    Accepts unbatched [k, n] and arbitrary leading batch dims; lane
    extents not divisible by 512 bytes (128 i32 words) are zero-padded
    (GF-linear maps send zero bytes to zero bytes).

    bdmats: optional cache dict (GFLinear passes one) holding the
    device [32m, 32k] matrix under key "v2".
    """
    k8 = bitmat.shape[1]
    k = k8 // 8
    lead = data.shape[:-2]
    n = data.shape[-1]
    x = data.reshape((-1, k, n))
    bsz = x.shape[0]
    npad = -n % (_LANES * _WORD)
    if npad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, npad)))
    nw = (n + npad) // _WORD
    bdmat = (bdmats or {}).get("v2")
    if bdmat is None:
        bdmat = jnp.asarray(block_diag4(np.asarray(bitmat)))
        if bdmats is not None:
            bdmats["v2"] = bdmat
    with enable_x64(False):
        words = jax.lax.bitcast_convert_type(
            x.reshape(bsz, k, nw, _WORD), jnp.int32)
        out = _gf_apply_pallas2(bdmat, words, k=k, m=m,
                                interpret=interpret)
        outb = jax.lax.bitcast_convert_type(out, jnp.uint8)
    outb = outb.reshape(bsz, m, nw * _WORD)[:, :, :n]
    return outb.reshape(*lead, m, n)


# -- word-native path: i32 in, i32 out, no byte<->word relayout ------------
#
# Round-5 discovery (measured on v5e): the fused byte-API kernel above
# tops out ~21 GB/s not because of expand/matmul/pack — an empty
# kernel with the same BlockSpecs runs just as slow — but because of
# the data movement AROUND it: (a) `bitcast_convert_type` u8->i32 is a
# real relayout pass on TPU ((32,128) int8 tiles -> (8,128) i32
# tiles), re-paid every call, and (b) a [B, k, n] uint8 operand with
# k=8 sublanes pays 4x (32,128)-tile padding on every HBM read.
# Feeding the SAME kernel i32 words end-to-end measures 66 GB/s raw /
# ~84 GB/s net of the relay's ~64 ms dispatch floor — 10x the
# host's gf-complete-strength native baseline (the SURVEY §7 target).
#
# Chunk payloads should therefore live as i32 words on device for
# their whole lifetime; `np.ndarray.view("<i4")` converts on the host
# for free (GF(2^8) acts bytewise, so word endianness cancels between
# pack and unpack — same argument as the block-diagonal layout above).

_MAX_TNW_WORDS = 8192


def _pick_tile_words(nw: int, k: int) -> int:
    # VMEM per tile scales with 32k rows; 8192 lanes measured best for
    # k=8 and stays within budget up to clay-sized k
    for tnw in (_MAX_TNW_WORDS, 4096, 2048, 1024, 512, 256, _LANES):
        if tnw <= nw and nw % tnw == 0:
            return tnw
    return nw


@functools.partial(jax.jit, static_argnames=("k", "m", "interpret"))
def _gf_apply_words(bdmat, mrow, words, *, k: int, m: int,
                    interpret: bool = False):
    """bdmat [32m, 32k] int8, mrow [32k, 1] i32, words [B, k, nw] i32
    -> [B, m, nw] i32."""
    b, _, nw = words.shape
    tnw = _pick_tile_words(nw, k)

    def kern(bd_ref, mrow_ref, data_ref, out_ref):
        w = data_ref[0]                               # [k, TNW] i32
        tiled = jnp.tile(w, (32, 1))                  # [32k, TNW]
        bits = ((tiled & mrow_ref[...]) != 0).astype(jnp.int8)
        acc = jax.lax.dot_general(
            bd_ref[...], bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32) & 1
        word = acc[0:m] * jnp.int32(_BIT_W[0])
        for j in range(1, 32):
            word = word + acc[j * m:(j + 1) * m] * jnp.int32(_BIT_W[j])
        out_ref[0] = word

    return pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((b, m, nw), jnp.int32),
        grid=(b, nw // tnw),
        in_specs=[
            pl.BlockSpec((4 * 8 * m, 4 * 8 * k), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4 * 8 * k, 1), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k, tnw), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, m, tnw), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel")),
        interpret=interpret,
    )(bdmat, mrow, words)


def _word_operands(bitmat, k: int, bdmats: dict | None):
    """Device [32m, 32k] matrix + [32k, 1] per-row bit masks, cached."""
    cached = (bdmats or {}).get("words")
    if cached is not None:
        return cached
    bdmat = jnp.asarray(block_diag4(np.asarray(bitmat)))
    mrow = jnp.asarray(np.array(
        [_BIT_MASK[r // k] for r in range(32 * k)],
        dtype=np.int32).reshape(32 * k, 1))
    # don't poison the cache with tracers if a caller hands us a
    # traced bitmat from inside its own jit (np.asarray above raises
    # for tracers, but be explicit about the concrete-only contract)
    if bdmats is not None and not isinstance(bdmat, jax.core.Tracer):
        bdmats["words"] = (bdmat, mrow)
    return bdmat, mrow


def gf_matmul_words(bitmat: jnp.ndarray, words: jnp.ndarray, m: int,
                    interpret: bool = False,
                    bdmats: dict | None = None) -> jnp.ndarray:
    """Fused GF(2^8) matmul over word-resident chunks.

    words: [..., k, nw] int32 — each lane holds 4 consecutive payload
    bytes (host view ``bytes.view("<i4")``).  Returns [..., m, nw]
    int32 parity words.  nw not divisible by the tile is zero-padded
    (zero bytes map to zero bytes under any GF-linear map).
    """
    k8 = bitmat.shape[1]
    k = k8 // 8
    lead = words.shape[:-2]
    nw = words.shape[-1]
    x = words.reshape((-1, k, nw))
    npad = -nw % _LANES
    if npad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, npad)))
    nwp = nw + npad
    bdmat, mrow = _word_operands(bitmat, k, bdmats)
    with enable_x64(False):
        b = x.shape[0]
        if nwp <= 2048 and b > 1 and b * nwp >= 2048:
            # small-stripe fold: at <=64 KiB stripes the grid
            # degenerates into b narrow steps whose per-tile overhead
            # dominates (measured: 4 KiB 14.9->63.8, 64 KiB
            # 46->62 GB/s; at 128 KiB+ the fold's two transposes turn
            # into a slight net loss, hence the nwp <= 2048 cut).
            # GF acts per lane-column, so fold the stripe batch into
            # the lane axis — one transpose each way buys full-width
            # tiles.
            xt = jnp.moveaxis(x, 0, 1).reshape(1, k, b * nwp)
            out = _gf_apply_words(bdmat, mrow, xt, k=k, m=m,
                                  interpret=interpret)
            out = jnp.moveaxis(out.reshape(m, b, nwp), 1, 0)
        else:
            out = _gf_apply_words(bdmat, mrow, x, k=k, m=m,
                                  interpret=interpret)
    out = out[:, :, :nw]
    return out.reshape(*lead, m, nw)


# -- resident bit-planes: expand once, multiply many -----------------------
#
# Recovery and scrub re-multiply the SAME surviving chunks by several
# decode matrices (multi-target reconstruct, verify-then-repair).  The
# fused kernel above re-expands per call because its input is bytes;
# these entry points keep the expansion in device memory across calls
# (VERDICT r4 #1: "expand once per buffer lifetime").

@functools.partial(jax.jit, static_argnames=())
def gf_expand_words(data: jnp.ndarray) -> jnp.ndarray:
    """[..., k, n] uint8 (n % 512 == 0) -> [..., 32k, n/4] int8 planes
    in the v2 word-sliced layout."""
    *lead, k, n = data.shape
    nw = n // _WORD
    with enable_x64(False):
        words = jax.lax.bitcast_convert_type(
            data.reshape(*lead, k, nw, _WORD), jnp.int32)
        planes = []
        for j in range(32):
            mask = jnp.int32(_BIT_MASK[j])
            planes.append(((words & mask) != 0).astype(jnp.int8))
        # stack as [32, ..., k, nw] then fold (j, k) -> rows b*8k+s*k+i
        bits = jnp.stack(planes, axis=0)
        bits = jnp.moveaxis(bits, 0, -3)          # [..., 32, k, nw]
    return bits.reshape(*lead, 32 * k, nw)


def _gf_planes_kernel(bdmat_ref, planes_ref, out_ref, *, m: int):
    acc = jax.lax.dot_general(
        bdmat_ref[...], planes_ref[0],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    acc = acc & 1
    word = acc[0:m] * jnp.int32(_BIT_W[0])
    for j in range(1, 32):
        word = word + acc[j * m:(j + 1) * m] * jnp.int32(_BIT_W[j])
    out_ref[0] = word


@functools.partial(jax.jit, static_argnames=("m", "interpret"))
def _gf_apply_planes(bdmat, planes, *, m: int,
                     interpret: bool = False):
    bsz, k32, nw = planes.shape
    tnw = _pick_tile(nw)
    return pl.pallas_call(
        functools.partial(_gf_planes_kernel, m=m),
        out_shape=jax.ShapeDtypeStruct((bsz, m, nw), jnp.int32),
        grid=(bsz, nw // tnw),
        in_specs=[
            pl.BlockSpec((4 * 8 * m, k32), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k32, tnw), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, m, tnw), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bdmat, planes)


def gf_matmul_planes(bitmat: jnp.ndarray, planes: jnp.ndarray, m: int,
                     interpret: bool = False,
                     bdmats: dict | None = None) -> jnp.ndarray:
    """Multiply pre-expanded planes ([..., 32k, nw] int8 from
    `gf_expand_words`) -> [..., m, 4*nw] uint8 parity bytes.

    bdmats: optional cache dict shared with `gf_matmul_pallas2` (same
    "v2" key, same matrix) so the multiply-many loop neither rebuilds
    nor re-uploads the device matrix, and the jitted wrapper reuses
    its compiled executable across calls."""
    k32 = planes.shape[-2]
    nw = planes.shape[-1]
    lead = planes.shape[:-2]
    x = planes.reshape((-1, k32, nw))
    bdmat = (bdmats or {}).get("v2")
    if bdmat is None:
        bdmat = jnp.asarray(block_diag4(np.asarray(bitmat)))
        if bdmats is not None:
            bdmats["v2"] = bdmat
    with enable_x64(False):
        out = _gf_apply_planes(bdmat, x, m=m, interpret=interpret)
        outb = jax.lax.bitcast_convert_type(out, jnp.uint8)
    return outb.reshape(*lead, m, nw * _WORD)


class ResidentPlanes:
    """Expand-once/multiply-many survivor planes, resident on device.

    ``gf_matmul_words`` re-expands its byte input into bit-planes on
    every call, but a recovery sweep multiplies the SAME survivor
    batch by several GF(2^8) matrices: the decode matrix for erased
    data rows, the composed coding∘decode matrix for erased parity
    rows, one matrix per hypothesis in scrub culprit attribution.
    This holder runs :func:`gf_expand_words` once and serves any
    number of :meth:`multiply` calls against the resident planes.

    ``mats`` is an optional shared per-matrix operand cache
    ({matrix bytes: bdmats dict}); hand the same dict to every
    ``ResidentPlanes`` of a sweep and the block-diagonal device
    matrices upload once for the whole sweep instead of once per
    batch (the "held across a whole recovery sweep" half of the
    contract — planes live per batch, matrices per sweep).

    ``mesh`` (a jax Mesh) shards a 3-D batch over the batch axis:
    the planes expand once *sharded* and every :meth:`multiply` is a
    ``shard_map`` of the local Pallas kernel — each device multiplies
    only its resident plane slice, matrices replicated as closure
    constants.  Batches that aren't 3-D or don't divide ``mesh.size``
    silently stay single-device (same results, one chip).
    """

    __slots__ = ("planes", "n", "interpret", "_mats", "mesh", "_spec")

    # gf_expand_words tile contract: byte length % 512 == 0 so the
    # word planes split into whole 128-lane tiles
    _ALIGN = 512

    def __init__(self, data, interpret: bool = False,
                 mats: dict | None = None, mesh=None):
        data = jnp.asarray(data, dtype=jnp.uint8)
        n = int(data.shape[-1])
        pad = -n % self._ALIGN
        if pad:
            width = [(0, 0)] * (data.ndim - 1) + [(0, pad)]
            data = jnp.pad(data, width)
        self.n = n
        self.interpret = interpret
        self._mats = mats if mats is not None else {}
        if mesh is not None and (data.ndim != 3
                                 or data.shape[0] % mesh.size):
            mesh = None
        self.mesh = mesh
        self._spec = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._spec = PartitionSpec(tuple(mesh.axis_names),
                                       None, None)
            data = jax.device_put(data, NamedSharding(mesh, self._spec))
        self.planes = gf_expand_words(data)

    def multiply(self, matrix: np.ndarray) -> jnp.ndarray:
        """GF(2^8) matrix [m, k] × resident planes → [..., m, n]
        uint8 (device value, pad stripped; zero padding is exact:
        zero bytes map to zero bytes under any GF-linear map)."""
        from .gf_jax import _bit_layout_matrix
        mat = np.ascontiguousarray(matrix, dtype=np.uint8)
        bdmats = self._mats.setdefault(mat.tobytes(), {})
        bits = _bit_layout_matrix(mat)
        if self.mesh is not None:
            return self._multiply_mesh(bits, mat.shape[0],
                                       bdmats)[..., : self.n]
        out = gf_matmul_planes(bits, self.planes, mat.shape[0],
                               interpret=self.interpret, bdmats=bdmats)
        return out[..., : self.n]

    def _multiply_mesh(self, bits, m: int, bdmats: dict) -> jnp.ndarray:
        """shard_map of the local planes kernel over the batch axis —
        a sharded operand fed straight to the jitted pallas_call would
        be gathered to one device, so the kernel runs *inside* the
        per-device program instead."""
        from ..utils.jaxcompat import shard_map
        bdmat = bdmats.get("v2")
        if bdmat is None:
            bdmat = bdmats["v2"] = jnp.asarray(
                block_diag4(np.asarray(bits)))
        interpret = self.interpret

        def local_fn(planes):           # [Bl, 32k, nw] this device
            out = _gf_apply_planes(bdmat, planes, m=m,
                                   interpret=interpret)
            return jax.lax.bitcast_convert_type(out, jnp.uint8).reshape(
                planes.shape[0], m, -1)

        with enable_x64(False):
            return shard_map(local_fn, mesh=self.mesh,
                             in_specs=self._spec, out_specs=self._spec,
                             check_vma=False)(self.planes)

"""GF(2^8) linear algebra in JAX — the TPU execution path for erasure codes.

Two formulations, both byte-exact against the NumPy oracle in
``ceph_tpu.ops.gf``:

1. **bitmatrix matmul** (`gf_matmul_bits`): the GF(2^8) coefficient matrix
   C [m, k] expands to a GF(2) matrix; data bytes expand to bit-planes; the
   product is an int8 matmul with int32 accumulation followed by a mod-2
   parity and bit re-packing.  This keeps the hot loop on the MXU, which is
   exactly why this framework exists (reference hot loop:
   ``gf-complete``'s ``galois_w08_region_multiply`` SIMD inner loop behind
   ``src/erasure-code/jerasure``; SURVEY.md §4.2).
2. **table gather** (`gf_matmul_gather`): 256x256 product-table lookup +
   XOR reduce.  Simpler, used for cross-checking and small shapes.

Layout convention for the bitmatrix path (chosen to avoid intra-lane
shuffles on TPU):

- data bit-planes are stacked along the contraction axis in (bit, chunk)
  order: plane row ``s*k + i`` holds bit ``s`` of data chunk ``i``;
- output bit rows are produced in (bit, parity) order: row ``r*m + j`` is
  bit ``r`` of parity chunk ``j``;
- re-packing bytes is then 8 strided row-slices combined with shifts —
  pure elementwise ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .gf import GF_MUL_TABLE, gf_bitmatrix


def _bit_layout_matrix(coding: np.ndarray) -> np.ndarray:
    """[m, k] uint8 -> [8m, 8k] 0/1 int8 bitmatrix in (bit, chunk) layout.

    Row r*m+j, column s*k+i = BM(coding[j, i])[r, s].
    """
    coding = np.asarray(coding, dtype=np.uint8)
    m, k = coding.shape
    bm = gf_bitmatrix(coding)            # [m, k, 8, 8] (j, i, r, s)
    bm = bm.transpose(2, 0, 3, 1)        # [8(r), m(j), 8(s), k(i)]
    return bm.reshape(8 * m, 8 * k).astype(np.int8)


def _expand_bits(data: jnp.ndarray) -> jnp.ndarray:
    """[..., k, n] uint8 -> [..., 8k, n] int8 bit-planes in (bit, chunk) order."""
    k = data.shape[-2]
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(8, 1, 1)
    bits = (data[..., None, :, :] >> shifts) & jnp.uint8(1)   # [..., 8, k, n]
    return bits.reshape(*data.shape[:-2], 8 * k, data.shape[-1]).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray, m: int) -> jnp.ndarray:
    """[..., 8m, n] int32 0/1 in (bit, parity) order -> [..., m, n] uint8."""
    b = bits.reshape(*bits.shape[:-2], 8, m, bits.shape[-1])
    shifts = jnp.arange(8, dtype=jnp.int32).reshape(8, 1, 1)
    return jnp.sum(b << shifts, axis=-3).astype(jnp.uint8)


def gf_matmul_bits(bitmat: jnp.ndarray, data: jnp.ndarray, m: int) -> jnp.ndarray:
    """GF(2^8) matmul via GF(2) int8 matmul on the MXU.

    bitmat: [8m, 8k] int8 from `_bit_layout_matrix`.
    data:   [..., k, n] uint8.
    Returns [..., m, n] uint8.
    """
    dbits = _expand_bits(data)
    acc = jax.lax.dot_general(
        bitmat, dbits,
        dimension_numbers=(((1,), (dbits.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    # dot_general output: [8m, ..., n] — move the row axis back
    if dbits.ndim > 2:
        acc = jnp.moveaxis(acc, 0, -2)
    return _pack_bits(acc & 1, m)


def gf_matmul_gather(coding: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """GF(2^8) matmul via product-table gather + XOR reduce.

    coding: [m, k] uint8; data: [..., k, n] uint8 -> [..., m, n] uint8.
    """
    table = jnp.asarray(GF_MUL_TABLE.reshape(-1))
    idx = (coding.astype(jnp.int32)[:, :, None] * 256
           + data.astype(jnp.int32)[..., None, :, :])
    prods = table[idx]                       # [..., m, k, n]
    return jax.lax.reduce(
        prods, np.uint8(0), jax.lax.bitwise_xor, dimensions=(prods.ndim - 2,))


class GFLinear:
    """A compiled GF(2^8) linear map (encode or decode step) over batches.

    Wraps a fixed coefficient matrix [m, k]; calling it on data
    [batch..., k, n] uint8 returns [batch..., m, n] uint8.

    Backends:
    - ``"pallas"`` — the fused VMEM kernel v2
      (`ceph_tpu.ops.gf_pallas2`), the TPU production path: bytes
      processed 4-per-lane as i32 words, bit-planes expanded in VMEM,
      a 256-deep (at k=8) GF(2) matmul on the MXU, parity packed back
      to words — one HBM read of the data, one write of the parity;
    - ``"pallas-v1"`` — the original uint8-layout fused kernel
      (`ceph_tpu.ops.gf_pallas`), kept for the old-vs-new roofline
      comparison in bench.py;
    - ``"xla"`` — the dot_general bitmatrix composition above (works on
      any backend; what CPU tests run);
    - ``"auto"`` (default) — pallas (v2) on TPU, xla elsewhere.
    ``*-interpret`` variants run the pallas kernels in interpret mode
    for CPU byte-exactness tests.
    """

    def __init__(self, coding: np.ndarray, use_bits: bool = True,
                 backend: str = "auto"):
        self.coding = np.asarray(coding, dtype=np.uint8)
        self.m, self.k = self.coding.shape
        self.use_bits = use_bits
        if backend == "auto":
            backend = ("pallas" if jax.default_backend() == "tpu"
                       and use_bits else "xla")
        self.backend = backend
        if use_bits:
            self._mat = jnp.asarray(_bit_layout_matrix(self.coding))
        else:
            self._mat = jnp.asarray(self.coding)
        # the pallas path jits internally (and interpret mode under an
        # outer jit miscompiles on the CPU backend); jit only the
        # XLA-composed paths here
        if self.backend.startswith("pallas") and use_bits is False:
            raise ValueError("pallas backends are bitmatrix-only")
        self._fn = (self._apply if self.backend.startswith("pallas")
                    else jax.jit(self._apply))
        # persistent warm start (XLA path only): per input shape, the
        # lowered program round-trips through the export cache exactly
        # like the CRUSH mapper's — a fresh process deserializes
        # instead of re-tracing the encode/decode programs
        self._shape_fns: dict[tuple, object] = {}
        self.export_hits: dict[tuple, bool] = {}

    def _fn_for_shape(self, shape: tuple):
        fn = self._shape_fns.get(shape)
        if fn is not None:
            return fn
        fn, hit = self._warm_start(shape)
        self._shape_fns[shape] = fn
        self.export_hits[shape] = hit
        return fn

    def _warm_start(self, shape: tuple):
        from ..native.aot import CompileCache, cached_export
        if CompileCache.default() is None:
            return self._fn, False
        import hashlib
        key = {"kind": "gf_linear", "jax": jax.__version__,
               "x64": bool(jax.config.jax_enable_x64),
               "backend": jax.default_backend(),
               "use_bits": self.use_bits, "m": self.m, "k": self.k,
               "mat": hashlib.sha256(self.coding.tobytes()).hexdigest(),
               "shape": list(shape)}
        try:
            exported, hit = cached_export(
                "ec", key, lambda: jax.jit(self._apply),
                (jax.ShapeDtypeStruct(shape, jnp.uint8),))
            return jax.jit(exported.call), hit
        except Exception:
            return self._fn, False

    def _apply(self, data: jnp.ndarray) -> jnp.ndarray:
        if self.backend in ("pallas", "pallas-interpret"):
            from .gf_pallas2 import gf_matmul_pallas2
            if not hasattr(self, "_bdmats"):
                self._bdmats = {}
            return gf_matmul_pallas2(
                self._mat, data, self.m,
                interpret=self.backend == "pallas-interpret",
                bdmats=self._bdmats)
        if self.backend in ("pallas-v1", "pallas-v1-interpret"):
            from .gf_pallas import gf_matmul_pallas
            if not hasattr(self, "_bdmats"):
                self._bdmats = {}
            return gf_matmul_pallas(
                self._mat, data, self.m,
                interpret=self.backend == "pallas-v1-interpret",
                bdmats=self._bdmats)
        if self.use_bits:
            return gf_matmul_bits(self._mat, data, self.m)
        return gf_matmul_gather(self._mat, data)

    def __call__(self, data) -> jax.Array:
        from ..core.device_profiler import DeviceProfiler
        arr = jnp.asarray(data, dtype=jnp.uint8)
        rows = int(arr.shape[0]) if arr.ndim else 0
        ln = DeviceProfiler.active().start(
            "gf_encode", bytes_in=arr.nbytes, rows=rows,
            cache_hit=self.export_hits.get(arr.shape, False),
            backend=self.backend)
        try:
            if self.backend == "xla":
                out = self._fn_for_shape(arr.shape)(arr)
            else:
                out = self._fn(arr)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.finish(out=out, bytes_out=out.nbytes,
                      cache_hit=self.export_hits.get(arr.shape, False))
        return out


class GFLinearWords:
    """Word-native GF(2^8) linear map: [..., k, nw] int32 -> [..., m, nw].

    The 10x-over-native production encode path (see
    `gf_pallas2.gf_matmul_words` for the measured rationale): chunk
    payloads stay int32 for their whole device lifetime, so no call
    pays the u8<->i32 relayout or the uint8 sublane-padding tax.
    Host-side conversion is a free ``bytes.view("<i4")``.

    Mirrors the reference's region-multiply entry
    (``galois_w08_region_multiply`` behind src/erasure-code/jerasure —
    SURVEY.md §4.2) at word granularity; byte-exactness vs the scalar
    oracle is asserted in tests/test_gf_pallas2.py and before any
    bench timing.
    """

    def __init__(self, coding: np.ndarray, interpret: bool | None = None):
        self.coding = np.asarray(coding, dtype=np.uint8)
        self.m, self.k = self.coding.shape
        # Mosaic only lowers on TPU; elsewhere run the kernel in
        # interpret mode (the CPU test/fallback path)
        self.interpret = (jax.default_backend() != "tpu"
                          if interpret is None else interpret)
        self._mat = jnp.asarray(_bit_layout_matrix(self.coding))
        self._bdmats: dict = {}

    def __call__(self, words) -> jax.Array:
        from .gf_pallas2 import gf_matmul_words
        from ..core.device_profiler import DeviceProfiler
        warr = jnp.asarray(words)
        ln = DeviceProfiler.active().start(
            "gf_encode", bytes_in=warr.nbytes,
            rows=int(warr.shape[0]) if warr.ndim else 0,
            backend="words")
        try:
            out = gf_matmul_words(self._mat, warr, self.m,
                                  interpret=self.interpret,
                                  bdmats=self._bdmats)
        except Exception:
            if ln is not None:
                ln.abort()
            raise
        if ln is not None:
            ln.finish(out=out, bytes_out=out.nbytes)
        return out

    @staticmethod
    def to_words(data: np.ndarray) -> np.ndarray:
        """Host bytes [..., n] uint8 (n % 4 == 0) -> [..., n/4] int32."""
        return np.ascontiguousarray(data).view("<i4")

    @staticmethod
    def to_bytes(words: np.ndarray) -> np.ndarray:
        """Host words [..., nw] int32 -> [..., 4*nw] uint8."""
        return np.ascontiguousarray(words).view("<u1")


class GFEncodeDigest:
    """Fused EC encode + CRC-32C digest — one launch per megabatch.

    The batch engine's device program: ``[B, k, L]`` uint8 stripes in,
    ``([B, m, L]`` uint8 parity, ``[B, k+m]`` uint32 shard digests)
    out.  Parity is the GF(2) bitmatrix matmul above; the digest
    reuses ``scrub.crc32c_jax``'s contribution-matrix construction so
    every data *and* parity shard leaves the device already CRC'd —
    the write path's per-shard hinfo costs no second pass.

    One jitted program per ``(B, L)`` — callers bucket both to powers
    of two so the live set stays O(log B · log L).  On TPU the staged
    batch is donated (``donate_argnums``): the input buffer's HBM is
    reusable the moment the launch consumes it, which is what lets
    the engine double-buffer host↔device staging without 2x peak
    memory.  CPU (CI) skips donation — XLA:CPU can't alias them and
    would warn on every launch.

    ``mesh`` shards the megabatch over the batch axis across every
    mesh device (the bitmatrix and the CRC contribution matrix are
    replicated closure constants, so the program is embarrassingly
    data-parallel) — one OSD host drives all chips per launch.  Shapes
    whose batch doesn't divide ``mesh.size`` fall back to the
    single-device program, and the sharded variant skips the export
    cache (serialized programs don't carry shardings); it still
    amortizes through the in-process per-shape table.
    """

    def __init__(self, coding: np.ndarray, donate: bool | None = None,
                 mesh=None):
        self.coding = np.asarray(coding, dtype=np.uint8)
        self.m, self.k = self.coding.shape
        self._mat = jnp.asarray(_bit_layout_matrix(self.coding))
        self.donate = (jax.default_backend() == "tpu"
                       if donate is None else bool(donate))
        self.mesh = mesh
        self._shape_fns: dict[tuple, object] = {}
        self.export_hits: dict[tuple, bool] = {}
        self.mesh_hits: dict[tuple, bool] = {}

    def _make(self, batch: int, length: int):
        from ..scrub.crc32c_jax import _contrib
        k, m = self.k, self.m
        k_dense, a_dense = _contrib(length)
        kt = jnp.asarray(k_dense.T.astype(np.int8))       # [8L, 32]
        ones = np.ones(32, dtype=np.uint8)
        const_row = jnp.asarray((((a_dense @ ones) % 2) ^ ones)
                                .astype(np.int32))
        mat = self._mat

        def run(data):                                    # [B, k, L] u8
            parity = gf_matmul_bits(mat, data, m)         # [B, m, L]
            shards = jnp.concatenate([data, parity], axis=1)
            flat = shards.reshape(batch * (k + m), length)
            shifts = jnp.arange(8, dtype=jnp.uint8)
            bits = ((flat[:, :, None] >> shifts) & jnp.uint8(1))
            bits = bits.reshape(batch * (k + m),
                                8 * length).astype(jnp.int8)
            acc = jax.lax.dot_general(
                bits, kt, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out_bits = ((acc + const_row) & 1).astype(jnp.uint32)
            crcs = jnp.sum(out_bits << jnp.arange(32, dtype=jnp.uint32),
                           axis=-1, dtype=jnp.uint32)
            return parity, crcs.reshape(batch, k + m)

        return run

    def _fn_for_shape(self, shape: tuple):
        fn = self._shape_fns.get(shape)
        if fn is not None:
            return fn
        batch, _k, length = shape
        run = self._make(batch, length)
        donate = (0,) if self.donate else ()
        if self.mesh is not None and batch % self.mesh.size == 0:
            from jax.sharding import NamedSharding, PartitionSpec
            spec = PartitionSpec(tuple(self.mesh.axis_names), None, None)
            fn = jax.jit(run, donate_argnums=donate,
                         in_shardings=(NamedSharding(self.mesh, spec),))
            self._shape_fns[shape] = fn
            self.export_hits[shape] = False
            self.mesh_hits[shape] = True
            return fn
        fn, hit = jax.jit(run, donate_argnums=donate), False
        from ..native.aot import CompileCache, cached_export
        if CompileCache.default() is not None:
            import hashlib
            key = {"kind": "gf_encode_digest", "jax": jax.__version__,
                   "x64": bool(jax.config.jax_enable_x64),
                   "backend": jax.default_backend(),
                   "m": self.m, "k": self.k,
                   "mat": hashlib.sha256(
                       self.coding.tobytes()).hexdigest(),
                   "shape": list(shape)}
            try:
                exported, hit = cached_export(
                    "ec", key, lambda: jax.jit(run),
                    (jax.ShapeDtypeStruct(shape, jnp.uint8),))
                fn = jax.jit(exported.call, donate_argnums=donate)
            except Exception:
                pass            # non-exportable on this jax: plain jit
        self._shape_fns[shape] = fn
        self.export_hits[shape] = hit
        self.mesh_hits[shape] = False
        return fn

    def __call__(self, data) -> tuple[jax.Array, jax.Array]:
        """[B, k, L] uint8 → (parity [B, m, L], crcs [B, k+m]).

        Returns *device* values un-fenced — the caller (the engine's
        flight queue) decides when to materialise, which is the whole
        double-buffering point.  Not profiler-instrumented: the engine
        brackets each flight itself with rows/bytes occupancy."""
        arr = jnp.asarray(data, dtype=jnp.uint8)
        if arr.ndim != 3 or arr.shape[1] != self.k:
            raise ValueError(
                f"GFEncodeDigest wants [B, {self.k}, L], got {arr.shape}")
        return self._fn_for_shape(arr.shape)(arr)

"""Reed-Solomon / Cauchy generator-matrix construction.

Re-creates (independently, from the published algorithms) the coding-matrix
constructions used by the reference's erasure-code plugins:

- jerasure ``reed_sol_van``: extended-Vandermonde matrix made systematic by
  column elimination (reference behavior: ``src/erasure-code/jerasure``,
  bundled ``jerasure/src/reed_sol.c: reed_sol_vandermonde_coding_matrix``;
  SURVEY.md §3.6).
- jerasure ``reed_sol_r6_op``: the RAID-6 special case (row of ones + row of
  powers of 2).
- jerasure ``cauchy_orig`` / ``cauchy_good``: Cauchy matrices, with
  ``cauchy_good`` applying the ones-minimising column/row scaling
  (``jerasure/src/cauchy.c: cauchy_improve_coding_matrix``).
- ISA-L ``reed_sol_van`` / ``cauchy``: ISA-L's ``gf_gen_rs_matrix`` /
  ``gf_gen_cauchy1_matrix`` variants (reference behavior:
  ``src/erasure-code/isa/ErasureCodeIsa.cc`` over the isa-l submodule).
  Note the documented upstream caveat that ISA-L's Vandermonde construction
  is not MDS for every (k, m); we reproduce the construction, not a fix.

All matrices are the *coding* rows only: shape [m, k] uint8.  The full
generator is ``[I_k; C]``.

Provenance: the reference mount was empty (SURVEY.md §0), so byte-exactness
is asserted against these independently re-derived constructions plus
algebraic invariants (systematic, MDS where expected), not against captured
reference bytes.
"""

from __future__ import annotations

import numpy as np

from .gf import gf_div, gf_inv, gf_mul, gf_pow, gf_mat_inv, gf_matmul, gf_bitmatrix


def _gf_mul_int(a: int, b: int) -> int:
    return int(gf_mul(a, b))


def extended_vandermonde_matrix(rows: int, cols: int) -> np.ndarray:
    """jerasure ``reed_sol_extended_vandermonde_matrix`` (w=8).

    Row 0 is e_0, row rows-1 is e_{cols-1}; interior row i is
    [1, i, i^2, ... i^(cols-1)] in GF(2^8).
    """
    if rows < cols:
        raise ValueError("rows < cols")
    vdm = np.zeros((rows, cols), dtype=np.uint8)
    vdm[0, 0] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            vdm[i, j] = acc
            acc = _gf_mul_int(acc, i)
    vdm[rows - 1, cols - 1] = 1
    return vdm


def big_vandermonde_distribution_matrix(rows: int, cols: int) -> np.ndarray:
    """jerasure ``reed_sol_big_vandermonde_distribution_matrix``.

    Column-eliminates the extended Vandermonde matrix so the top cols x cols
    block is the identity; elimination order and operations follow the
    upstream algorithm exactly (pivot search downward, column scaling,
    column elimination from row i down).
    """
    if cols >= rows:
        raise ValueError("cols >= rows")
    dist = extended_vandermonde_matrix(rows, cols)
    for i in range(1, cols):
        # find a row at/below i with a nonzero entry in column i
        j = i
        while j < rows and dist[j, i] == 0:
            j += 1
        if j >= rows:
            raise ValueError("bad rows/cols for distribution matrix")
        if j != i:
            tmp = dist[j].copy()
            dist[j] = dist[i]
            dist[i] = tmp
        # scale column i so dist[i, i] == 1
        if dist[i, i] != 1:
            inv = gf_inv(int(dist[i, i]))
            dist[:, i] = gf_mul(dist[:, i], inv)
        # eliminate the rest of row i with column operations (rows >= i only;
        # rows above already form the identity pattern and have 0 in col i)
        for j2 in range(cols):
            tmp_v = int(dist[i, j2])
            if j2 != i and tmp_v != 0:
                dist[i:, j2] ^= gf_mul(dist[i:, i], tmp_v)
    return dist


def reed_sol_van_matrix(k: int, m: int) -> np.ndarray:
    """jerasure ``reed_sol_vandermonde_coding_matrix``: bottom m rows of the
    big Vandermonde distribution matrix. Shape [m, k]."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    dist = big_vandermonde_distribution_matrix(k + m, k)
    return dist[k:, :].copy()


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """jerasure ``reed_sol_r6_coding_matrix`` (m == 2): ones row + powers of 2."""
    mat = np.zeros((2, k), dtype=np.uint8)
    mat[0, :] = 1
    acc = 1
    for j in range(k):
        mat[1, j] = acc
        acc = _gf_mul_int(acc, 2)
    return mat


def cauchy_n_ones(n: int) -> int:
    """Number of ones in the 8x8 bitmatrix of multiplication by ``n``."""
    return int(gf_bitmatrix(n).sum())


def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """jerasure ``cauchy_original_coding_matrix``: entry (i, j) = 1/(i ^ (m+j))."""
    if k + m > 256:
        raise ValueError("k+m must be <= 256 for w=8")
    mat = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            mat[i, j] = gf_inv(i ^ (m + j))
    return mat


def cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """jerasure ``cauchy_good_general_coding_matrix``: the Cauchy matrix with
    the ones-minimising improvement from ``cauchy_improve_coding_matrix``."""
    if k == 1 and m == 2:
        return np.array([[1], [1]], dtype=np.uint8)
    mat = cauchy_orig_matrix(k, m)
    # divide each column by its first-row element (row 0 becomes all ones)
    for j in range(k):
        if mat[0, j] != 1:
            mat[:, j] = gf_div(mat[:, j], int(mat[0, j]))
    # for each later row, find the division that minimises bitmatrix ones
    for i in range(1, m):
        best = sum(cauchy_n_ones(int(v)) for v in mat[i])
        best_j = -1
        for j in range(k):
            if mat[i, j] != 1:
                inv = gf_inv(int(mat[i, j]))
                total = sum(
                    cauchy_n_ones(_gf_mul_int(int(v), inv)) for v in mat[i])
                if total < best:
                    best = total
                    best_j = j
        if best_j != -1:
            mat[i, :] = gf_div(mat[i, :], int(mat[i, best_j]))
    return mat


def isa_rs_van_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L ``gf_gen_rs_matrix`` coding rows: row r = powers of 2^r."""
    mat = np.zeros((m, k), dtype=np.uint8)
    gen = 1
    for r in range(m):
        p = 1
        for j in range(k):
            mat[r, j] = p
            p = _gf_mul_int(p, gen)
        gen = _gf_mul_int(gen, 2)
    return mat


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L ``gf_gen_cauchy1_matrix`` coding rows: entry = 1/((k+r) ^ j)."""
    mat = np.zeros((m, k), dtype=np.uint8)
    for r in range(m):
        for j in range(k):
            mat[r, j] = gf_inv((k + r) ^ j)
    return mat


def decode_matrix(coding: np.ndarray, k: int, erasures: list[int]) -> np.ndarray:
    """Build the k x k decode matrix for recovering the data chunks.

    ``coding`` is [m, k]; chunk ids are 0..k-1 (data) then k..k+m-1 (parity).
    ``erasures`` lists the erased chunk ids.  Returns D [k, k_surviving=k]
    such that data = D @ survivors, where survivors are the first k
    non-erased chunks in id order — the same survivor-selection rule as
    jerasure ``jerasure_matrix_decode``.
    """
    m = coding.shape[0]
    erased = set(erasures)
    survivors = [i for i in range(k + m) if i not in erased][:k]
    if len(survivors) < k:
        raise ValueError("not enough surviving chunks to decode")
    gen = np.concatenate([np.eye(k, dtype=np.uint8), np.asarray(coding, dtype=np.uint8)])
    sub = gen[survivors, :]  # [k, k]
    return gf_mat_inv(sub)


def solve_gf_system(A: np.ndarray, b: np.ndarray) -> np.ndarray | None:
    """Solve A @ x = b over GF(2^8) by Gaussian elimination.

    A: [neq, nunk] uint8; b: [neq, width] uint8.  Returns x [nunk, width]
    if the system determines every unknown uniquely, else None.  Used by
    the non-MDS codes (SHEC) and as the LRC fallback solver.
    """
    A = np.array(A, dtype=np.uint8)
    b = np.array(b, dtype=np.uint8)
    neq, nunk = A.shape
    row = 0
    pivots = []
    for col in range(nunk):
        pivot = None
        for r in range(row, neq):
            if A[r, col] != 0:
                pivot = r
                break
        if pivot is None:
            return None  # unknown col not determined
        if pivot != row:
            A[[row, pivot]] = A[[pivot, row]]
            b[[row, pivot]] = b[[pivot, row]]
        inv = gf_inv(int(A[row, col]))
        A[row] = gf_mul(A[row], inv)
        b[row] = gf_mul(b[row], inv)
        for r in range(neq):
            if r != row and A[r, col] != 0:
                factor = int(A[r, col])
                A[r] ^= gf_mul(A[row], factor)
                b[r] ^= gf_mul(b[row], factor)
        pivots.append(row)
        row += 1
    return b[pivots]


def encode_oracle(coding: np.ndarray, data: np.ndarray) -> np.ndarray:
    """NumPy oracle encode: data [k, chunk] uint8 -> parity [m, chunk]."""
    return gf_matmul(coding, data)


def decode_oracle(coding: np.ndarray, k: int, chunks: dict[int, np.ndarray],
                  chunk_size: int) -> dict[int, np.ndarray]:
    """NumPy oracle decode: recover ALL chunks from any k survivors.

    ``chunks`` maps chunk id -> bytes for available chunks.  Returns a dict
    with every chunk id 0..k+m-1 filled in.
    """
    m = coding.shape[0]
    erasures = [i for i in range(k + m) if i not in chunks]
    survivors = [i for i in range(k + m) if i in chunks][:k]
    dm = decode_matrix(coding, k, erasures)
    surv = np.stack([np.asarray(chunks[i], dtype=np.uint8) for i in survivors])
    data = gf_matmul(dm, surv)
    out = {i: data[i] for i in range(k)}
    parity = gf_matmul(np.asarray(coding, dtype=np.uint8), data)
    for j in range(m):
        out[k + j] = parity[j]
    for i, buf in chunks.items():
        out[i] = np.asarray(buf, dtype=np.uint8)
    return out

"""Fused GF(2^8) matmul Pallas kernel — the TPU hot loop for erasure codes.

The XLA bitmatrix path (`ceph_tpu.ops.gf_jax.gf_matmul_bits`) materializes
the 8x bit-plane expansion and the 32x int32 accumulator in HBM between
ops; at EC shapes (k<=20 rows) that elementwise HBM traffic dominates the
matmul.  This kernel fuses the whole pipeline per tile in VMEM:

    read data tile [G, k, TN] uint8          (HBM read: 1 byte/byte)
      -> bit-plane expand   [G*8k, TN] int8    (VPU, VMEM only)
      -> GF(2) matmul on the MXU -> [G*8m, TN] int32
      -> mask + bit re-pack -> [G, m, TN] uint8 (VPU, VMEM only)
    write parity tile [G, m, TN] uint8       (HBM write: m/k byte/byte)

so HBM moves only the data once in and the parity once out — the same
shape as the reference's ``galois_w08_region_multiply`` region loop
(gf-complete behind ``src/erasure-code/jerasure``; SURVEY.md §4.2), but
batched across stripes and fed to a 128x128 systolic array.

G stripes are packed block-diagonally into one matmul so the MXU's
128-deep contraction actually fills: a single k=8 stripe contracts over
only 8k=64 of 128 MXU rows (~9% utilization, measured 7.5 GB/s on
v5e); G=2 makes the contraction exactly 128 deep (measured ~2x).

Bit layouts extend `gf_jax._bit_layout_matrix` per diagonal block:
contraction row g*8k + s*k + i is bit s of chunk i of stripe g; output
row g*8m + r*m + j is bit r of parity j of stripe g.  Byte-exactness
against the NumPy oracle is asserted in ``tests/test_gf_pallas.py``
(interpret mode) and on real TPU by ``bench.py``'s pre-timing verify.

Mosaic notes: no vector shifts on narrow ints (shrui/shli fail to
legalize) — bit extraction is AND + compare, packing is multiply-add;
the kernel traces under `jax.enable_x64(False)` because i64 grid
arithmetic (from the CRUSH-required global x64 mode) also fails to
legalize.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.jaxcompat import enable_x64

# lane width is 128 on all TPU generations; tiles are multiples of it
_LANES = 128
_MAX_TN = 4096          # per-tile lane extent (VMEM budget ~1 MB/tile)
# stripes per matmul: 2 fills the 128-deep contraction for k=8, but
# measured v5e throughput is flat across G=1/2/4 (the expand/pack VPU
# work and DMA granularity dominate, not the MXU) — keep it simple
_GROUP = 1


def _gf_kernel(bitmat_ref, data_ref, out_ref, *, k: int, m: int, g: int):
    """One (stripe-group, lane-tile): fused expand -> matmul -> pack."""
    planes = []
    for gi in range(g):
        d = data_ref[gi]                              # [k, TN] uint8
        for s in range(8):
            planes.append(((d & jnp.uint8(1 << s)) != 0).astype(jnp.int8))
    bits = jnp.concatenate(planes, axis=0)            # [g*8k, TN] int8
    acc = jax.lax.dot_general(
        bitmat_ref[...], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)             # [g*8m, TN] int32
    acc = acc & 1
    for gi in range(g):
        base = gi * 8 * m
        packed = acc[base:base + m]
        for r in range(1, 8):
            packed = packed + acc[base + r * m:base + (r + 1) * m] \
                * (1 << r)
        out_ref[gi] = packed.astype(jnp.uint8)


def block_diag_bitmat(bitmat: np.ndarray, g: int) -> np.ndarray:
    """[8m, 8k] -> block-diagonal [g*8m, g*8k] int8."""
    m8, k8 = bitmat.shape
    out = np.zeros((g * m8, g * k8), dtype=np.int8)
    for gi in range(g):
        out[gi * m8:(gi + 1) * m8, gi * k8:(gi + 1) * k8] = bitmat
    return out


def _pick_tile(n: int) -> int:
    for tn in (_MAX_TN, 2048, 1024, 512, 256, _LANES):
        if tn <= n and n % tn == 0:
            return tn
    return n            # n < 128: single undersized tile (padded by Mosaic)


@functools.partial(jax.jit, static_argnames=("k", "m", "g", "interpret"))
def _gf_apply_pallas(bdmat, data, *, k: int, m: int, g: int,
                     interpret: bool = False):
    """bdmat [g*8m, g*8k] int8, data [B, k, n] uint8 (B % g == 0)
    -> [B, m, n] uint8."""
    b, _, n = data.shape
    tn = _pick_tile(n)
    grid = (b // g, n // tn)
    return pl.pallas_call(
        functools.partial(_gf_kernel, k=k, m=m, g=g),
        out_shape=jax.ShapeDtypeStruct((b, m, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m * g, 8 * k * g), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g, k, tn), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g, m, tn), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(bdmat, data)


def gf_matmul_pallas(bitmat: jnp.ndarray, data: jnp.ndarray, m: int,
                     interpret: bool = False, bdmats=None) -> jnp.ndarray:
    """Fused GF(2^8) matmul.  data [..., k, n] uint8 -> [..., m, n].

    Accepts unbatched [k, n] and arbitrary leading batch dims; lane
    extents not divisible by 128 and batches not divisible by the
    stripe group are zero-padded (GF-linear maps send zero bytes to
    zero bytes, so padding never corrupts parity).

    bdmats: optional {g: device block-diag matrix} cache (GFLinear
    precomputes it so the hot path never rebuilds/re-uploads it).
    """
    k8 = bitmat.shape[1]
    k = k8 // 8
    lead = data.shape[:-2]
    n = data.shape[-1]
    x = data.reshape((-1, k, n))
    b = x.shape[0]
    g = _GROUP if b >= _GROUP else 1
    npad = -n % _LANES
    bpad = -b % g
    if npad or bpad:
        x = jnp.pad(x, ((0, bpad), (0, 0), (0, npad)))
    bdmat = (bdmats or {}).get(g)
    if bdmat is None:
        bdmat = jnp.asarray(block_diag_bitmat(np.asarray(bitmat), g))
        if bdmats is not None:
            bdmats[g] = bdmat
    # trace in 32-bit mode: under jax_enable_x64 (required by CRUSH)
    # the grid/index arithmetic becomes i64, which Mosaic rejects
    with enable_x64(False):
        out = _gf_apply_pallas(bdmat, x, k=k, m=m, g=g,
                               interpret=interpret)
    out = out[:b, :, :n]
    return out.reshape(*lead, m, n)

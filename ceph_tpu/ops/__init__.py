"""Numeric cores: GF(2^8), Reed-Solomon matrices, rjenkins hashing, crush_ln.

Every core has two forms:

- a NumPy *oracle* (scalar-faithful to the published upstream algorithm) —
  the bit-exactness standard used by tests; and
- a JAX form (vectorised/batched, jit/vmap/shard_map-friendly) — the TPU
  execution path.
"""

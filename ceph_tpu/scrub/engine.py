"""Batched deep-scrub planner: on-device digests + EC parity recheck.

The scrub data path (reference ``src/osd/scrubber/ScrubStore`` +
``be_compare_scrubmaps``) has two integrity layers:

1. **Digests** — every shard payload is CRC-32C'd.  Payloads are
   bucketed by exact length and digested as ``[n, L]`` batches through
   :func:`..scrub.crc32c_jax.crc32c_batch` (one MXU matmul per
   bucket); small/ragged buckets fall back to the host scalar —
   identical digests either way.
2. **Parity recheck** (EC pools only) — per-shard digests can only
   prove a shard matches *its own* stored hinfo; if a shard and its
   hinfo were rewritten consistently (or rotted together), only
   re-running the code catches it.  Stripes are stacked
   ``[B, k, chunk]`` and re-encoded through the existing
   ``ops/gf_jax`` matmul path; recomputed parity is byte-compared
   against the stored parity shards.

For an inconsistent stripe, :func:`isolate_culprit` identifies the
bad shard by hypothesis testing: for each candidate shard c, decode c
from the others and accept the hypothesis whose repaired stripe is
self-consistent — exactly the repair the EC reconstruct path then
performs.  Reports use the ``rados list-inconsistent-obj`` shape.
"""

from __future__ import annotations

import os

import numpy as np

from .crc32c_jax import crc32c, crc32c_batch, crc32c_combine


class ScrubEngine:
    """Stateless-ish digest/parity planner; counters accumulate so the
    OSD perf counters and bench can report scanned bytes."""

    def __init__(self, device_min_rows: int = 4,
                 device_min_bytes: int = 1 << 16,
                 segment_bytes: int | None = None,
                 use_mesh: bool | None = None):
        mode = os.environ.get("CEPH_TPU_SCRUB_DEVICE", "auto").lower()
        self.mode = mode if mode in ("auto", "always", "never") else "auto"
        self.device_min_rows = device_min_rows
        self.device_min_bytes = device_min_bytes
        # multichip digest scan: shard the CRC batch over the cluster
        # mesh (off by default — standalone scrubs outside an engine
        # keep seed single-chip behavior unless opted in)
        if use_mesh is None:
            use_mesh = os.environ.get(
                "CEPH_TPU_SCRUB_MESH", "0").lower() in ("1", "true",
                                                        "yes", "on")
        self.use_mesh = bool(use_mesh)
        self._mesh = None
        # streaming-digest granularity: objects larger than one
        # device buffer are digested as equal segments and folded
        # with crc32c_combine (GF(2) matrix exponentiation) — the
        # device batch shape stays bounded no matter the object size
        if segment_bytes is None:
            segment_bytes = int(os.environ.get(
                "CEPH_TPU_SCRUB_SEGMENT_BYTES", 4 << 20))
        self.segment_bytes = max(1, int(segment_bytes))
        self.objects_scanned = 0
        self.segmented_objects = 0
        self.digest_bytes = 0
        self.device_digest_bytes = 0
        self.parity_bytes = 0

    # ------------------------------------------------------- digests

    def _digest_mesh(self):
        """The cluster mesh for the digest scan, or None (mesh off or
        a single visible device)."""
        if not self.use_mesh:
            return None
        if self._mesh is None:
            import jax
            if len(jax.devices()) <= 1:
                return None
            from ..parallel.mesh import cluster_mesh
            self._mesh = cluster_mesh()
        return self._mesh

    def _use_device(self, rows: int, length: int) -> bool:
        if self.mode == "always":
            return length > 0
        if self.mode == "never" or length == 0:
            return False
        return (rows >= self.device_min_rows
                or rows * length >= self.device_min_bytes)

    def compute_digests(self, payloads: dict) -> dict:
        """{key: bytes-like} → {key: crc32c int}, batching same-length
        payloads through the device kernel.

        Payloads larger than ``segment_bytes`` are digested as a
        stream of equal-size segments (which land in one shared
        length bucket, so they batch with *each other* across
        objects) and folded back into one per-object digest with
        :func:`crc32c_combine` — bit-identical to digesting the
        whole buffer at once, but the device batch never exceeds
        ``segment_bytes`` per row.
        """
        seg = self.segment_bytes
        parts: dict = {}        # key -> [(part_key, part_len), ...]
        expanded: dict = {}     # part_key/key -> bytes
        for key, buf in payloads.items():
            b = bytes(buf)
            if len(b) > seg:
                self.segmented_objects += 1
                pieces = parts[key] = []
                for i, off in enumerate(range(0, len(b), seg)):
                    pk = ("_seg", key, i)
                    expanded[pk] = b[off:off + seg]
                    pieces.append((pk, len(expanded[pk])))
            else:
                expanded[key] = b
        digests = self._digest_exact(expanded)
        out: dict = {}
        for key in payloads:
            if key in parts:
                crc = 0
                for pk, plen in parts[key]:
                    crc = crc32c_combine(crc, digests[pk], plen)
                out[key] = crc
            else:
                out[key] = digests[key]
        self.objects_scanned += len(payloads)
        return out

    def _digest_exact(self, payloads: dict) -> dict:
        """Digest already-materialised byte payloads, bucketed by
        exact length (no segmentation — compute_digests handles it)."""
        by_len: dict[int, list] = {}
        for key, b in payloads.items():
            by_len.setdefault(len(b), []).append((key, b))
        out: dict = {}
        for length, group in by_len.items():
            self.digest_bytes += length * len(group)
            if self._use_device(len(group), length):
                from ..core.device_profiler import DeviceProfiler
                mesh = self._digest_mesh()
                devices = None
                if mesh is not None:
                    from ..parallel.mesh import mesh_device_labels
                    devices = mesh_device_labels(mesh)
                batch = np.frombuffer(
                    b"".join(b for _, b in group), dtype=np.uint8
                ).reshape(len(group), length)
                ln = DeviceProfiler.active().start(
                    "crc_digest", bytes_in=batch.nbytes,
                    rows=len(group), devices=devices)
                try:
                    crcs = crc32c_batch(batch, mesh=mesh)
                except Exception:
                    if ln is not None:
                        ln.abort()
                    raise
                if ln is not None:
                    ln.finish(bytes_out=crcs.nbytes)
                self.device_digest_bytes += length * len(group)
                for (key, _), c in zip(group, crcs):
                    out[key] = int(c)
            else:
                for key, b in group:
                    out[key] = crc32c(b)
        return out

    # ------------------------------------------------- parity recheck

    def recheck_parity(self, ec, stripes: dict, batch=None) -> dict:
        """{oid: {shard_index: uint8 chunk}} → {oid: inconsistent bool}.

        `ec` is an ``ErasureCodeInterface`` plugin (k data + m parity
        shards, shard i ≥ k is parity row i-k).  Every stripe must
        carry all k+m equal-length shards.  Re-encodes data shards in
        per-chunk-size batches and byte-compares recomputed parity
        against the stored parity shards.

        ``batch`` (a ``BatchEngine``) routes the re-encodes through
        the engine's reconstruct lane instead of launching standalone,
        so scrub rechecks coalesce with in-flight recovery
        reconstructs; any lane failure falls back wholesale to the
        standalone path below (identical results either way).
        """
        if (batch is not None and getattr(batch, "enabled", False)
                and getattr(batch, "recon_enabled", False)):
            out = self._recheck_batched(ec, stripes, batch)
            if out is not None:
                return out
        k, m = ec.k, ec.m
        by_size: dict[int, list] = {}
        for oid, shards in stripes.items():
            chunk = len(shards[0])
            by_size.setdefault(chunk, []).append((oid, shards))
        out: dict = {}
        for chunk, group in by_size.items():
            data = np.stack([
                np.stack([np.frombuffer(memoryview(shards[i]), np.uint8)
                          for i in range(k)])
                for _, shards in group])                 # [B, k, chunk]
            self.parity_bytes += data.size
            from ..core.device_profiler import DeviceProfiler
            ln = DeviceProfiler.active().start(
                "parity_recheck", bytes_in=data.nbytes,
                rows=len(group))
            try:
                parity = np.asarray(ec._encode_chunks(data))  # [B, m, chunk]
            except Exception:
                # engine without batch support: stripe at a time
                try:
                    parity = np.stack([np.asarray(ec._encode_chunks(d))
                                       for d in data])
                except Exception:
                    if ln is not None:
                        ln.abort()
                    raise
            if ln is not None:
                ln.finish(bytes_out=parity.nbytes)
            for (oid, shards), par in zip(group, parity):
                stored = np.stack([
                    np.frombuffer(memoryview(shards[k + j]), np.uint8)
                    for j in range(m)])
                out[oid] = not np.array_equal(par, stored)
        return out

    def _recheck_batched(self, ec, stripes: dict, batch) -> dict | None:
        """Submit every stripe's re-encode to the reconstruct lane and
        flush it synchronously (inline completion on this thread — the
        scrub may hold the daemon lock, so it must not wait behind the
        engine's completion worker).  Returns None to signal wholesale
        fallback to the standalone path."""
        k, m = ec.k, ec.m
        comps = {}
        added = 0
        try:
            for oid, shards in stripes.items():
                data = np.stack([
                    np.frombuffer(memoryview(shards[i]), np.uint8)
                    for i in range(k)])
                added += data.size
                self.parity_bytes += data.size
                comps[oid] = batch.submit_recheck(ec, data)
            batch.flush_sync("recon", reason="scrub")
            out: dict = {}
            for oid, comp in comps.items():
                par = np.asarray(comp.result(timeout=60.0))
                shards = stripes[oid]
                stored = np.stack([
                    np.frombuffer(memoryview(shards[k + j]), np.uint8)
                    for j in range(m)])
                out[oid] = not np.array_equal(par, stored)
            return out
        except Exception:       # noqa: BLE001 — lane unusable for
            # this code/engine combination: undo the provisional byte
            # accounting and let the standalone path redo everything
            self.parity_bytes -= added
            return None


def isolate_culprit(ec, shards: dict) -> int | None:
    """Given one inconsistent stripe {shard_index: uint8 chunk} with
    all k+m shards present, return the single shard index whose
    reconstruction-from-the-others restores stripe consistency, or
    None when no single-shard hypothesis explains the mismatch.

    Needs m >= 2 to attribute: with a single parity row every
    one-erasure decode trivially re-satisfies that row, so each
    hypothesis looks consistent and None is returned — the caller
    should then fall back to per-shard digest evidence (hinfo)."""
    k, m = ec.k, ec.m
    n = k + m
    arrs = {i: np.frombuffer(memoryview(shards[i]), np.uint8)
            for i in range(n)}
    candidates = []
    for c in range(n):
        survivors = {i: arrs[i] for i in range(n) if i != c}
        try:
            rebuilt = ec.decode({c}, survivors)[c]
        except Exception:
            continue
        if np.array_equal(rebuilt, arrs[c]):
            continue            # hypothesis changes nothing — not it
        fixed = dict(arrs)
        fixed[c] = rebuilt
        parity = np.asarray(ec._encode_chunks(
            np.stack([fixed[i] for i in range(k)])))
        if all(np.array_equal(parity[j], fixed[k + j]) for j in range(m)):
            candidates.append(c)
    # only a UNIQUE consistent hypothesis is an attribution (with m=1
    # every hypothesis passes; ambiguity must not pick a scapegoat)
    return candidates[0] if len(candidates) == 1 else None


def isolate_culprits(ec, shards: dict,
                     max_erasures: int = 2) -> tuple[int, ...]:
    """Multi-shard culprit attribution for one inconsistent stripe
    with all k+m shards present: try single-erasure hypotheses first
    (:func:`isolate_culprit`), then search erasure PAIRS when no
    single shard explains the mismatch and the code has parity to
    spare.  Returns the attributed shard indices, or ``()`` when the
    stripe is unattributable or the evidence is ambiguous.

    Pair attribution needs m >= 3 in general: decoding a pair from
    the n-2 survivors leaves m-2 surviving parity rows *beyond* the
    decode basis as witnesses, and with m=2 there are none — every
    pair hypothesis re-satisfies the code, so all pairs tie and ()
    is returned (ambiguity must not pick scapegoats)."""
    import itertools

    k, m = ec.k, ec.m
    n = k + m
    single = isolate_culprit(ec, shards)
    if single is not None:
        return (single,)
    if m < 2 or max_erasures < 2:
        return ()
    arrs = {i: np.frombuffer(memoryview(shards[i]), np.uint8)
            for i in range(n)}
    candidates = []
    for pair in itertools.combinations(range(n), 2):
        survivors = {i: arrs[i] for i in range(n) if i not in pair}
        try:
            rebuilt = ec.decode(set(pair), survivors)
        except Exception:       # noqa: BLE001 — undecodable pattern
            continue
        if all(np.array_equal(np.asarray(rebuilt[c]), arrs[c])
               for c in pair):
            continue            # hypothesis changes nothing — not it
        fixed = dict(arrs)
        for c in pair:
            fixed[c] = np.asarray(rebuilt[c], dtype=np.uint8)
        parity = np.asarray(ec._encode_chunks(
            np.stack([fixed[i] for i in range(k)])))
        if all(np.array_equal(parity[j], fixed[k + j])
               for j in range(m)):
            candidates.append(pair)
    return tuple(candidates[0]) if len(candidates) == 1 else ()


def inconsistent_entry(oid: str, errors: list[str],
                       shards: dict) -> dict:
    """One ``rados list-inconsistent-obj``-shaped report entry.

    `shards`: {(osd, shard_index): {size, digest?, errors: [...]}}."""
    union: set[str] = set()
    shard_list = []
    for (osd, shard), info in sorted(shards.items()):
        union |= set(info.get("errors", ()))
        shard_list.append({"osd": osd, "shard": shard, **info})
    return {"object": {"name": oid},
            "errors": sorted(errors),
            "union_shard_errors": sorted(union),
            "shards": shard_list}


_DEFAULT: ScrubEngine | None = None


def default_engine() -> ScrubEngine:
    """Process-wide engine (shared digest counters across PGs)."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = ScrubEngine()
    return _DEFAULT

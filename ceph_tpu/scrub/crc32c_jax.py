"""CRC32C (Castagnoli) as GF(2) linear algebra — host scalar + JAX batch.

The reference's ``ceph_crc32c`` (``src/common/crc32c*``) is CRC-32C:
polynomial ``0x1EDC6F41``, reflected (LSB-first) register, init and
final xor ``0xFFFFFFFF`` — the iSCSI/RFC 3720 CRC, *not* zlib's
ISO-HDLC CRC-32.  Three entry points, all byte-exact against the RFC
3720 golden vectors:

- :func:`crc32c` — host scalar, slice-by-8 table-driven; the drop-in
  for ``zlib.crc32``-shaped call sites (``crc32c(data, seed)``).
- :func:`crc32c_combine` — ``crc(A||B)`` from ``crc(A)``, ``crc(B)``
  and ``len(B)`` via GF(2) matrix exponentiation (the zlib
  ``crc32_combine`` construction, Castagnoli matrices): chunked CRCs
  merge exactly like the reference's CRC over a buffer chain.
- :func:`crc32c_batch` — the device kernel: one fused matmul digests
  a whole ``[n_objects, chunk]`` uint8 batch.

Why a matmul: the CRC register update is linear over GF(2).  With
``r`` the raw (conditioned) register and ``b`` a data byte,

    r' = A·r ⊕ B·bits(b)

where ``A`` is the 32x32 shift-a-zero-byte matrix and ``B`` maps the 8
data bits through the CRC table (the table is additive:
``T[x^y] = T[x]^T[y]``).  Unrolled over a chunk of L bytes,

    crc_out = A^L·crc_in ⊕ (A^L·F ⊕ F) ⊕ K·bits(data),   F = 0xFFFFFFFF

with ``K = [A^(L-1)·B | A^(L-2)·B | ... | B]`` the ``[32, 8L]``
contribution matrix.  ``K`` is built host-side by doubling (log L
GF(2) matmuls) and cached per length; the device then digests n
objects as one ``[n, 8L] x [8L, 32]`` int8 matmul with int32
accumulation, mod-2 parity and a 32-bit repack — the same MXU
bit-matrix idiom as ``ops.gf_jax``.
"""

from __future__ import annotations

import functools

import numpy as np

CRC32C_POLY = 0x1EDC6F41        # Castagnoli, normal form
_POLY = 0x82F63B78              # reflected (LSB-first register)
_MASK = 0xFFFFFFFF


# ---------------------------------------------------------------- tables

def _make_table() -> list[int]:
    tab = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        tab.append(c)
    return tab


_TABLE = _make_table()

# slice-by-8: T8[0] consumes the most-significant of 8 bytes in flight
_T8: list[list[int]] = [_TABLE]
for _k in range(1, 8):
    _prev = _T8[-1]
    _T8.append([(_prev[i] >> 8) ^ _TABLE[_prev[i] & 0xFF]
                for i in range(256)])
_T8.reverse()   # _T8[j] shifts its byte past 7-j later bytes


def _as_bytes(data) -> bytes:
    if isinstance(data, (bytes, bytearray)):
        return bytes(data)
    if isinstance(data, memoryview):
        return data.tobytes()
    arr = np.asarray(data)
    if arr.dtype != np.uint8:
        raise TypeError(f"crc32c wants bytes/uint8, got {arr.dtype}")
    return arr.tobytes()


def crc32c(data, crc: int = 0) -> int:
    """CRC-32C of `data`, chaining from `crc` (``crc32c(b"") == 0``;
    ``crc32c(b, crc32c(a)) == crc32c(a + b)``)."""
    b = _as_bytes(data)
    c = (int(crc) ^ _MASK) & _MASK
    n8 = len(b) & ~7
    t0, t1, t2, t3, t4, t5, t6, t7 = _T8
    for off in range(0, n8, 8):
        lo = c ^ int.from_bytes(b[off:off + 4], "little")
        hi = int.from_bytes(b[off + 4:off + 8], "little")
        c = (t0[lo & 0xFF] ^ t1[(lo >> 8) & 0xFF]
             ^ t2[(lo >> 16) & 0xFF] ^ t3[lo >> 24]
             ^ t4[hi & 0xFF] ^ t5[(hi >> 8) & 0xFF]
             ^ t6[(hi >> 16) & 0xFF] ^ t7[hi >> 24])
    for byte in b[n8:]:
        c = (c >> 8) ^ _TABLE[(c ^ byte) & 0xFF]
    return (c ^ _MASK) & _MASK


# ------------------------------------------------- GF(2) matrix algebra
#
# A 32x32 GF(2) matrix is a list of 32 uint32 columns: col[i] = M·e_i.

def _matrix_times(mat: list[int], vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _matrix_square(mat: list[int]) -> list[int]:
    return [_matrix_times(mat, col) for col in mat]


def _shift_byte_matrix() -> list[int]:
    """A: the raw-register operator for one zero *byte*:
    ``A(r) = (r >> 8) ^ T[r & 0xFF]``."""
    return [((1 << i) >> 8) ^ _TABLE[(1 << i) & 0xFF] for i in range(32)]


_A_COLS = _shift_byte_matrix()


def crc32c_shift(crc: int, nbytes: int) -> int:
    """Apply ``A^nbytes`` (append `nbytes` zero bytes to the *raw*
    register) to a 32-bit value, by square-and-multiply."""
    c = int(crc) & _MASK
    n = int(nbytes)
    if n < 0:
        raise ValueError("negative length")
    mat = _A_COLS
    while n:
        if n & 1:
            c = _matrix_times(mat, c)
        n >>= 1
        if n:
            mat = _matrix_square(mat)
    return c


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """``crc32c(A || B)`` from ``crc32c(A)``, ``crc32c(B)``, ``len(B)``.

    Follows from linearity: conditioning cancels, leaving
    ``crc(A||B) = A^len_b · crc(A) ⊕ crc(B)``.
    """
    if len_b == 0:
        return int(crc_a) & _MASK
    return crc32c_shift(crc_a, len_b) ^ (int(crc_b) & _MASK)


def _matrix_inverse(cols: list[int]) -> list[int]:
    """Invert a 32x32 GF(2) matrix (column-of-uint32 form) by
    Gauss-Jordan elimination.  A is invertible because the CRC
    polynomial has a nonzero constant term (x^0), so the byte-shift
    operator is a bijection on register states."""
    n = len(cols)
    dense = _dense(cols, n).astype(np.uint8)
    aug = np.concatenate([dense, np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = col + int(np.argmax(aug[col:, col]))
        if aug[piv, col] == 0:
            raise ValueError("singular GF(2) matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        for row in np.nonzero(aug[:, col])[0]:
            if row != col:
                aug[row] ^= aug[col]
    inv = aug[:, n:]
    return [int(sum(int(inv[r, i]) << r for r in range(n)))
            for i in range(n)]


@functools.lru_cache(maxsize=1)
def _a_inv_cols() -> list[int]:
    return _matrix_inverse(_A_COLS)


def crc32c_unshift(crc: int, nbytes: int) -> int:
    """Inverse of :func:`crc32c_shift`: apply ``A^-nbytes`` (remove
    `nbytes` trailing zero bytes from the *raw* register), by
    square-and-multiply over the inverted shift matrix."""
    c = int(crc) & _MASK
    n = int(nbytes)
    if n < 0:
        raise ValueError("negative length")
    mat = _a_inv_cols()
    while n:
        if n & 1:
            c = _matrix_times(mat, c)
        n >>= 1
        if n:
            mat = _matrix_square(mat)
    return c


@functools.lru_cache(maxsize=None)
def crc32c_zeros(nbytes: int) -> int:
    """``crc32c(b"\\x00" * nbytes)`` without touching the bytes:
    conditioning in, ``A^n``, conditioning out."""
    if nbytes == 0:
        return 0
    return (crc32c_shift(_MASK, nbytes) ^ _MASK) & _MASK


def crc32c_zero_unpad(crc: int, pad: int) -> int:
    """``crc32c(A)`` from ``crc32c(A || 0^pad)`` — strip `pad`
    trailing zero bytes from a digest.

    The batch engine right-pads every member payload to its size
    bucket with zeros before the fused device digest; by
    ``crc(A||0^n) = A^n·crc(A) ⊕ crc(0^n)`` the true digest is
    recovered host-side with two 32-bit GF(2) matrix applications —
    no second pass over the data."""
    if pad == 0:
        return int(crc) & _MASK
    return crc32c_unshift((int(crc) ^ crc32c_zeros(pad)) & _MASK, pad)


# ------------------------------------------------------- batch kernel

def _dense(cols: list[int], rows: int = 32) -> np.ndarray:
    """uint32 columns -> dense 0/1 uint8 matrix [rows, len(cols)]."""
    c = np.asarray(cols, dtype=np.uint64)
    return ((c[None, :] >> np.arange(rows, dtype=np.uint64)[:, None])
            & 1).astype(np.uint8)


_A_DENSE = _dense(_A_COLS)
# B: data-byte injection, column s = T[1<<s] (table additivity)
_B_DENSE = _dense([_TABLE[1 << s] for s in range(8)])


@functools.lru_cache(maxsize=None)
def _contrib(length: int) -> tuple[np.ndarray, np.ndarray]:
    """→ (K [32, 8L] with column 8j+s = A^(L-1-j)·B·e_s, A^L [32, 32]),
    built by doubling: K_2n = [A^n·K_n | K_n]."""
    if length == 1:
        return _B_DENSE, _A_DENSE
    if length % 2:
        k1, a1 = _contrib(length - 1)
        head = (a1 @ _B_DENSE) % 2
        return (np.concatenate([head, k1], axis=1).astype(np.uint8),
                ((_A_DENSE @ a1) % 2).astype(np.uint8))
    kh, ah = _contrib(length // 2)
    return (np.concatenate([(ah @ kh) % 2, kh], axis=1).astype(np.uint8),
            ((ah @ ah) % 2).astype(np.uint8))


@functools.lru_cache(maxsize=64)
def _batch_kernel(length: int, mesh=None):
    """Jitted ``([n, L] u8 data, [n] u32 seeds) -> [n] u32 crcs``.

    With ``mesh`` (hashable — jax Mesh instances are) the batch is
    sharded over the row axis across every mesh device: each row's
    digest is an independent matmul against the replicated
    contribution matrix, so the scrub digest scan is pure data
    parallelism."""
    import jax
    import jax.numpy as jnp

    k_dense, a_dense = _contrib(length)
    kt = jnp.asarray(k_dense.T.astype(np.int8))       # [8L, 32]
    at = jnp.asarray(a_dense.T.astype(np.int8))       # [32, 32]
    # conditioned constant (A^L·F ⊕ F) as a 0/1 row
    ones = np.ones(32, dtype=np.uint8)
    const_row = jnp.asarray((((a_dense @ ones) % 2) ^ ones)
                            .astype(np.int32))

    def run(data, seeds):
        n = data.shape[0]
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((data[:, :, None] >> shifts) & jnp.uint8(1))
        bits = bits.reshape(n, 8 * length).astype(jnp.int8)
        acc = jax.lax.dot_general(
            bits, kt, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        sbits = ((seeds[:, None] >> jnp.arange(32, dtype=jnp.uint32))
                 & jnp.uint32(1)).astype(jnp.int8)
        acc = acc + jax.lax.dot_general(
            sbits, at, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out_bits = ((acc + const_row) & 1).astype(jnp.uint32)
        return jnp.sum(out_bits << jnp.arange(32, dtype=jnp.uint32),
                       axis=-1, dtype=jnp.uint32)

    if mesh is None:
        return jax.jit(run)
    from jax.sharding import NamedSharding, PartitionSpec
    axes = tuple(mesh.axis_names)
    rows2d = NamedSharding(mesh, PartitionSpec(axes, None))
    rows1d = NamedSharding(mesh, PartitionSpec(axes))
    return jax.jit(run, in_shardings=(rows2d, rows1d),
                   out_shardings=rows1d)


def crc32c_batch(data, seeds=None, mesh=None) -> np.ndarray:
    """CRC-32C of every row of a ``[n, L]`` uint8 batch → ``[n]`` uint32.

    `seeds` (optional ``[n]`` uint32) chains each row from a prior CRC,
    exactly like the `crc` argument of :func:`crc32c`.  `mesh` shards
    the scan data-parallel over the row axis (rows zero-pad up to a
    device-count multiple; pad digests are discarded) — bit-identical
    to the single-device kernel per row.
    """
    import jax.numpy as jnp

    arr = jnp.asarray(data, dtype=jnp.uint8)
    if arr.ndim != 2:
        raise ValueError(f"crc32c_batch wants [n, L], got {arr.shape}")
    n, length = arr.shape
    if length == 0:
        base = np.zeros(n, dtype=np.uint32)
        if seeds is not None:
            base |= np.asarray(seeds, dtype=np.uint32)
        return base
    if seeds is None:
        s = jnp.zeros(n, dtype=jnp.uint32)
    else:
        s = jnp.asarray(seeds, dtype=jnp.uint32)
    if mesh is not None and mesh.size > 1:
        pad = -n % mesh.size
        if pad:
            arr = jnp.pad(arr, ((0, pad), (0, 0)))
            s = jnp.pad(s, (0, pad))
    else:
        mesh = None
    from ..core.device_profiler import DeviceProfiler
    devices = None
    if mesh is not None:
        from ..parallel.mesh import mesh_device_labels
        devices = mesh_device_labels(mesh)
    misses = _batch_kernel.cache_info().misses
    ln = DeviceProfiler.active().start(
        "crc32c", bytes_in=arr.nbytes, rows=int(arr.shape[0]),
        rows_used=n, devices=devices)
    try:
        out = _batch_kernel(length, mesh)(arr, s)
    except Exception:
        if ln is not None:
            ln.abort()
        raise
    res = np.asarray(out, dtype=np.uint32)[:n]
    if ln is not None:
        ln.finish(bytes_out=res.nbytes,
                  cache_hit=_batch_kernel.cache_info().misses == misses)
    return res
